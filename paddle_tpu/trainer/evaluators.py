"""Evaluator runtime.

Reference: /root/reference/paddle/gserver/evaluators/Evaluator.cpp
(ClassificationErrorEvaluator:41, SumEvaluator:151, ColumnSumEvaluator:243,
AucEvaluator Evaluator.h:155, PrecisionRecallEvaluator:234, printers
:870-1235), ChunkEvaluator.cpp, CTCErrorEvaluator.cpp.

Evaluators accumulate over batches on the host (numpy) from layer outputs —
they're observability, not part of the jitted step.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.graph.argument import Argument
from paddle_tpu.proto import EvaluatorConfig, ModelConfig
from paddle_tpu.utils.registry import Registry

evaluator_registry: Registry[type] = Registry("evaluator")


def register_evaluator(*names):
    return evaluator_registry.register(*names)


class Evaluator:
    def __init__(self, cfg: EvaluatorConfig):
        self.cfg = cfg
        self.start()

    def start(self) -> None:
        raise NotImplementedError

    def eval_batch(self, args: List[Argument]) -> None:
        raise NotImplementedError

    def result(self) -> Dict[str, float]:
        raise NotImplementedError

    def summary(self) -> str:
        return " ".join(f"{k}={v:.6g}" for k, v in self.result().items())

    # -- distributed merging (the reference's Evaluator::getState /
    # mergeState split, Evaluator.h:81-82: trainers ship a SMALL
    # accumulated state once per period instead of raw activations
    # per batch)

    def merge_state(self) -> Optional[np.ndarray]:
        """Flat float64 vector of accumulated state that merges across
        processes by SUMMATION, or None if this evaluator cannot merge
        that way (raw-record evaluators, printers) — those eval gathered
        full outputs per batch instead."""
        return None

    def load_state(self, vec: np.ndarray) -> None:
        """Inverse of merge_state: replace accumulators with vec."""
        raise NotImplementedError(type(self).__name__)

    # -- helpers

    @staticmethod
    def _rows(arg: Argument) -> np.ndarray:
        """Flatten an output to valid rows [N, D] (masking padding)."""
        v = np.asarray(arg.value) if arg.value is not None else None
        if v is None:
            ids = np.asarray(arg.ids)
            v = ids.reshape(ids.shape + (1,)).astype(np.float32)
        if arg.sub_seq_lengths is not None:
            lens = np.asarray(arg.sub_seq_lengths)
            rows = [
                v[b, s, :t]
                for b in range(v.shape[0])
                for s, t in enumerate(lens[b])
                if t > 0
            ]
            return np.concatenate(rows, axis=0) if rows else v.reshape(0, v.shape[-1])
        if arg.seq_lengths is not None:
            lens = np.asarray(arg.seq_lengths)
            rows = [v[b, : lens[b]] for b in range(v.shape[0])]
            return np.concatenate(rows, axis=0) if rows else v.reshape(0, v.shape[-1])
        return v

    @staticmethod
    def _label_rows(arg: Argument) -> np.ndarray:
        if arg.ids is not None:
            ids = np.asarray(arg.ids)
            if arg.seq_lengths is not None and ids.ndim >= 2:
                lens = np.asarray(arg.seq_lengths)
                return np.concatenate([ids[b, : lens[b]].reshape(-1) for b in range(ids.shape[0])])
            return ids.reshape(-1)
        return np.argmax(Evaluator._rows(arg), axis=-1)


@register_evaluator("classification_error")
class ClassificationErrorEvaluator(Evaluator):
    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def eval_batch(self, args):
        out, label = args[0], args[1]
        probs = self._rows(out)
        labels = self._label_rows(label)
        if self.cfg.classification_threshold > 0 and probs.shape[-1] == 1:
            pred = (probs[:, 0] > self.cfg.classification_threshold).astype(np.int64)
        else:
            pred = np.argmax(probs, axis=-1)
        n = min(len(pred), len(labels))
        self.wrong += float(np.sum(pred[:n] != labels[:n]))
        self.total += n

    def result(self):
        return {"classification_error": self.wrong / max(self.total, 1.0)}

    def merge_state(self):
        return np.array([self.wrong, self.total], np.float64)

    def load_state(self, vec):
        self.wrong, self.total = float(vec[0]), float(vec[1])


@register_evaluator("sum")
class SumEvaluator(Evaluator):
    def start(self):
        self.sum = 0.0
        self.total = 0.0

    def eval_batch(self, args):
        rows = self._rows(args[0])
        self.sum += float(rows.sum())
        self.total += rows.shape[0]

    def result(self):
        return {"sum": self.sum, "mean": self.sum / max(self.total, 1.0)}

    def merge_state(self):
        return np.array([self.sum, self.total], np.float64)

    def load_state(self, vec):
        self.sum, self.total = float(vec[0]), float(vec[1])


@register_evaluator("last-column-sum")
class ColumnSumEvaluator(Evaluator):
    def start(self):
        self.sum = 0.0
        self.total = 0.0

    def eval_batch(self, args):
        rows = self._rows(args[0])
        self.sum += float(rows[:, -1].sum())
        self.total += rows.shape[0]

    def result(self):
        return {"column_sum": self.sum, "column_mean": self.sum / max(self.total, 1.0)}

    def merge_state(self):
        return np.array([self.sum, self.total], np.float64)

    def load_state(self, vec):
        self.sum, self.total = float(vec[0]), float(vec[1])


@register_evaluator("last-column-auc")
class AucEvaluator(Evaluator):
    """Histogram AUC like the reference (AucEvaluator, Evaluator.h:155)."""

    BINS = 4096

    def start(self):
        self.pos = np.zeros(self.BINS)
        self.neg = np.zeros(self.BINS)

    def eval_batch(self, args):
        out, label = args[0], args[1]
        scores = self._rows(out)[:, -1]
        labels = self._label_rows(label)
        # optional third input: per-sample weight (adds w to the bin,
        # reference Evaluator.cpp statPos_/statNeg_ += w)
        w = (self._rows(args[2])[:, -1] if len(args) > 2
             else np.ones_like(scores, np.float64))
        idx = np.clip((scores * (self.BINS - 1)).astype(np.int64), 0, self.BINS - 1)
        np.add.at(self.pos, idx[labels == 1], w[labels == 1])
        np.add.at(self.neg, idx[labels != 1], w[labels != 1])

    def result(self):
        # trapezoidal over descending threshold
        tp = np.cumsum(self.pos[::-1])
        fp = np.cumsum(self.neg[::-1])
        tot_p, tot_n = tp[-1] if len(tp) else 0.0, fp[-1] if len(fp) else 0.0
        if tot_p == 0 or tot_n == 0:
            return {"auc": 0.0}
        tpr = np.concatenate([[0.0], tp / tot_p])
        fpr = np.concatenate([[0.0], fp / tot_n])
        auc = float(np.trapezoid(tpr, fpr))
        return {"auc": auc}

    def merge_state(self):
        return np.concatenate([self.pos, self.neg]).astype(np.float64)

    def load_state(self, vec):
        self.pos = np.asarray(vec[: self.BINS], np.float64)
        self.neg = np.asarray(vec[self.BINS :], np.float64)


@register_evaluator("seq_classification_error")
class SequenceClassificationErrorEvaluator(Evaluator):
    """Per-sequence error (ref: SequenceClassificationErrorEvaluator,
    Evaluator.cpp:111): a sequence counts as wrong if ANY valid frame's
    argmax disagrees with the label."""

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def eval_batch(self, args):
        out, label = args[0], args[1]
        v = np.asarray(out.value)                       # [B, T, C]
        pred = np.argmax(v, axis=-1)
        labels = np.asarray(label.ids)
        lens = (
            np.asarray(out.seq_lengths)
            if out.seq_lengths is not None
            else np.full((v.shape[0],), v.shape[1], np.int64)
        )
        for b in range(v.shape[0]):
            t = int(lens[b])
            lb = labels[b] if labels.ndim > 1 else np.full((t,), labels[b])
            self.wrong += float(np.any(pred[b, :t] != lb[:t]))
            self.total += 1.0

    def result(self):
        return {"seq_classification_error": self.wrong / max(self.total, 1.0)}

    def merge_state(self):
        return np.array([self.wrong, self.total], np.float64)

    def load_state(self, vec):
        self.wrong, self.total = float(vec[0]), float(vec[1])


@register_evaluator("rank-auc")
class RankAucEvaluator(Evaluator):
    """AUC over rank-model scores (ref: RankAucEvaluator, Evaluator.h:202):
    inputs = output score, click (label), optional pv (weight). Exact AUC
    over the accumulated (score, click, pv) triples."""

    def start(self):
        self.scores = []
        self.clicks = []
        self.pvs = []

    def eval_batch(self, args):
        out = self._rows(args[0])[:, -1]
        click = self._rows(args[1])[:, -1]
        pv = self._rows(args[2])[:, -1] if len(args) > 2 else np.ones_like(click)
        self.scores.append(out)
        self.clicks.append(click)
        self.pvs.append(pv)

    def result(self):
        if not self.scores:
            return {"rank_auc": 0.0}
        s = np.concatenate(self.scores)
        click = np.concatenate(self.clicks)
        pv = np.concatenate(self.pvs)
        # group by unique score so tied pos/neg pairs count 0.5 each
        # (order-independent AUC)
        uniq, inv = np.unique(s, return_inverse=True)
        pos_g = np.bincount(inv, weights=click, minlength=len(uniq))
        neg_g = np.bincount(inv, weights=pv - click, minlength=len(uniq))
        cum_neg_below = np.cumsum(neg_g) - neg_g   # strictly lower scores
        pairs_correct = float(np.sum(pos_g * (cum_neg_below + 0.5 * neg_g)))
        total_pairs = float(pos_g.sum() * neg_g.sum())
        return {"rank_auc": pairs_correct / total_pairs if total_pairs else 0.0}


@register_evaluator("precision_recall")
class PrecisionRecallEvaluator(Evaluator):
    def start(self):
        self.tp: Dict[int, float] = {}
        self.fp: Dict[int, float] = {}
        self.fn: Dict[int, float] = {}

    def eval_batch(self, args):
        out, label = args[0], args[1]
        probs = self._rows(out)
        labels = self._label_rows(label)
        pred = np.argmax(probs, axis=-1)
        for p, l in zip(pred, labels):
            p, l = int(p), int(l)
            if p == l:
                self.tp[l] = self.tp.get(l, 0) + 1
            else:
                self.fp[p] = self.fp.get(p, 0) + 1
                self.fn[l] = self.fn.get(l, 0) + 1

    def result(self):
        classes = set(self.tp) | set(self.fp) | set(self.fn)
        if self.cfg.positive_label >= 0:
            classes = {self.cfg.positive_label}
        precs, recs = [], []
        for c in classes:
            tp = self.tp.get(c, 0.0)
            fp = self.fp.get(c, 0.0)
            fn = self.fn.get(c, 0.0)
            precs.append(tp / max(tp + fp, 1.0))
            recs.append(tp / max(tp + fn, 1.0))
        p = float(np.mean(precs)) if precs else 0.0
        r = float(np.mean(recs)) if recs else 0.0
        f1 = 2 * p * r / max(p + r, 1e-9)
        return {"precision": p, "recall": r, "F1": f1}


@register_evaluator("pnpair")
class PnpairEvaluator(Evaluator):
    """Positive-negative pair ordering accuracy (ref Evaluator.h:308)."""

    def start(self):
        self.records: List = []

    def eval_batch(self, args):
        out, label = args[0], args[1]
        scores = self._rows(out)[:, -1]
        labels = self._label_rows(label)
        # optional third input: query id for grouping; fourth: weight
        if len(args) > 2:
            qids = self._label_rows(args[2])
        else:
            qids = np.zeros_like(labels)
        w = (self._rows(args[3])[:, -1] if len(args) > 3
             else np.ones_like(scores, np.float64))
        self.records.extend(
            zip(qids.tolist(), labels.tolist(), scores.tolist(), w.tolist()))

    def result(self):
        from collections import defaultdict

        by_q = defaultdict(list)
        for q, l, s, w in self.records:
            by_q[q].append((l, s, w))
        pos_minus_neg = 0.0
        total = 0.0
        CHUNK = 256  # bounds pair-walk temporaries to CHUNK*n entries
        for items in by_q.values():
            # vectorized pair walk in row chunks (semantics identical to
            # the reference's O(n^2) loop, PnpairEvaluator::stat: pair
            # weight = mean of the two samples' weights, ties 0.5) —
            # memory stays O(CHUNK*n) even when every record lands in one
            # group (the no-qid default)
            n = len(items)
            l = np.asarray([it[0] for it in items], np.float64)
            sc = np.asarray([it[1] for it in items], np.float64)
            w = np.asarray([it[2] for it in items], np.float64)
            col = np.arange(n)
            for i0 in range(0, n - 1, CHUNK):
                rows = np.arange(i0, min(i0 + CHUNK, n - 1))
                pair = col[None, :] > rows[:, None]          # j > i
                diff = pair & (l[None, :] != l[rows][:, None])
                if not diff.any():
                    continue
                ri, cj = np.nonzero(diff)
                iu, ju = rows[ri], cj
                pw = (w[iu] + w[ju]) / 2.0
                hi_is_i = l[iu] > l[ju]
                hi = np.where(hi_is_i, sc[iu], sc[ju])
                lo = np.where(hi_is_i, sc[ju], sc[iu])
                total += float(pw.sum())
                pos_minus_neg += float(pw[hi > lo].sum() + 0.5 * pw[hi == lo].sum())
        # raw total as the denominator: max(total, 1) would deflate the
        # metric whenever the total pair weight is < 1
        return {"pnpair_accuracy": pos_minus_neg / total if total > 0 else 0.0}


@register_evaluator("ctc_edit_distance")
class CTCErrorEvaluator(Evaluator):
    """Edit distance between CTC best-path decode and the label sequence
    (ref: CTCErrorEvaluator.cpp)."""

    def start(self):
        self.dist = 0.0
        self.total_labels = 0.0

    @staticmethod
    def _edit_distance(a, b) -> int:
        la, lb = len(a), len(b)
        dp = list(range(lb + 1))
        for i in range(1, la + 1):
            prev = dp[0]
            dp[0] = i
            for j in range(1, lb + 1):
                cur = dp[j]
                dp[j] = min(dp[j] + 1, dp[j - 1] + 1, prev + (a[i - 1] != b[j - 1]))
                prev = cur
        return dp[lb]

    def eval_batch(self, args):
        out, label = args[0], args[1]
        probs = np.asarray(out.value)  # [B, T, C] (blank = C-1)
        lens = np.asarray(out.seq_lengths)
        blank = probs.shape[-1] - 1
        label_ids = np.asarray(label.ids)
        label_lens = np.asarray(label.seq_lengths)
        for b in range(probs.shape[0]):
            path = np.argmax(probs[b, : lens[b]], axis=-1)
            decoded = []
            prev = -1
            for p in path:
                if p != prev and p != blank:
                    decoded.append(int(p))
                prev = p
            target = label_ids[b, : label_lens[b]].tolist()
            self.dist += self._edit_distance(decoded, target)
            self.total_labels += len(target)

    def result(self):
        return {"ctc_error_rate": self.dist / max(self.total_labels, 1.0)}

    def merge_state(self):
        return np.array([self.dist, self.total_labels], np.float64)

    def load_state(self, vec):
        self.dist, self.total_labels = float(vec[0]), float(vec[1])


@register_evaluator("chunk")
class ChunkEvaluator(Evaluator):
    """IOB/IOE/IOBES chunking F1 (ref: ChunkEvaluator.cpp)."""

    def start(self):
        self.correct = 0.0
        self.pred_chunks = 0.0
        self.label_chunks = 0.0

    def _extract_chunks(self, tags: List[int]):
        """tag = type * tagsPerType + posInScheme. IOB: 0=B,1=I; IOE: 0=I,
        1=E; IOBES: 0=B,1=I,2=E,3=S; 'other' = last tag id."""
        scheme = self.cfg.chunk_scheme
        n_types = self.cfg.num_chunk_types
        per = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
        other = n_types * per
        chunks = []
        start = None
        ctype = None
        for i, t in enumerate(tags + [other]):
            if t >= other:
                tt, pos = None, None
            else:
                tt, pos = t // per, t % per
            begin = False
            end_prev = False
            if scheme == "IOB":
                begin = pos == 0
                end_prev = tt is None or (start is not None and (pos == 0 or tt != ctype))
            elif scheme == "IOE":
                begin = start is None and tt is not None
                end_prev = start is not None and (ctype != tt or (i > 0 and tags[i - 1] % per == 1))
            elif scheme == "IOBES":
                begin = pos in (0, 3)
                end_prev = tt is None or (start is not None and (pos in (0, 3) or tt != ctype))
            else:  # plain: every tag is its own chunk type, 'other' closes
                begin = tt is not None and tt != ctype
                end_prev = start is not None and tt != ctype
            if end_prev and start is not None:
                chunks.append((start, i - 1, ctype))
                start = None
            if begin and tt is not None:
                start = i
                ctype = tt
            elif tt is None:
                start = None
                ctype = None
        return set(chunks)

    def eval_batch(self, args):
        out, label = args[0], args[1]
        preds = self._label_rows(out)
        labels = self._label_rows(label)
        pred_chunks = self._extract_chunks([int(x) for x in preds])
        label_chunks = self._extract_chunks([int(x) for x in labels])
        self.correct += len(pred_chunks & label_chunks)
        self.pred_chunks += len(pred_chunks)
        self.label_chunks += len(label_chunks)

    def result(self):
        p = self.correct / max(self.pred_chunks, 1.0)
        r = self.correct / max(self.label_chunks, 1.0)
        return {"precision": p, "recall": r, "F1": 2 * p * r / max(p + r, 1e-9)}


class _PrinterEvaluator(Evaluator):
    def start(self):
        self.lines: List[str] = []

    def result(self):
        return {}

    def summary(self):
        return "\n".join(self.lines[-5:])


@register_evaluator("value_printer")
class ValuePrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, args):
        self.lines.append(str(self._rows(args[0])[:4]))


@register_evaluator("gradient_printer")
class GradientPrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, args):
        self.lines.append("<gradients not captured in functional mode>")


@register_evaluator("max_id_printer")
class MaxIdPrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, args):
        rows = self._rows(args[0])
        self.lines.append(str(np.argsort(-rows, axis=-1)[:4, : max(1, self.cfg.num_results)]))


@register_evaluator("max_frame_printer")
class MaxFramePrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, args):
        rows = self._rows(args[0])
        self.lines.append(str(rows.max(axis=-1)[:4]))


@register_evaluator("seq_text_printer")
class SeqTextPrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, args):
        arg = args[-1]
        ids = np.asarray(arg.ids) if arg.ids is not None else np.argmax(np.asarray(arg.value), -1)
        vocab = None
        if self.cfg.dict_file:
            try:
                with open(self.cfg.dict_file) as f:
                    vocab = [l.rstrip("\n") for l in f]
            except OSError:
                vocab = None
        for row in ids[:4]:
            toks = [vocab[t] if vocab and t < len(vocab) else str(int(t)) for t in np.atleast_1d(row)]
            line = (" " if self.cfg.delimited else "").join(toks)
            self.lines.append(line)
        if self.cfg.result_file:
            with open(self.cfg.result_file, "a") as f:
                for row in ids:
                    toks = [
                        vocab[t] if vocab and t < len(vocab) else str(int(t))
                        for t in np.atleast_1d(row)
                    ]
                    f.write((" " if self.cfg.delimited else "").join(toks) + "\n")


@register_evaluator("classification_error_printer")
class ClassificationErrorPrinterEvaluator(_PrinterEvaluator):
    def eval_batch(self, args):
        out, label = args[0], args[1]
        probs = self._rows(out)
        labels = self._label_rows(label)
        pred = np.argmax(probs, axis=-1)
        err = (pred != labels).astype(np.float32)
        self.lines.append(str(err[:16]))


class EvaluatorChain:
    """All configured evaluators of a model, fed from layer outputs."""

    def __init__(self, model: ModelConfig, names: Optional[List[str]] = None):
        self.model = model
        self.evaluators: List[Evaluator] = []
        # set by the trainer in multi-process runs when evaluators were fed
        # process-local rows: vec -> cross-process SUM of vec. Reading
        # results then merges sufficient statistics once — the reference's
        # distributeEval (Evaluator.h:81-82) — instead of gathering raw
        # activations every batch.
        self.merge_fn = None
        for cfg in model.evaluators:
            if names is not None and cfg.name not in names:
                continue
            if cfg.type in evaluator_registry:
                self.evaluators.append(evaluator_registry.get(cfg.type)(cfg))

    def partition(self):
        """(mergeable, unmergeable) evaluators: mergeable ones carry
        summable state and can accumulate on local rows."""
        merge, gather = [], []
        for e in self.evaluators:
            (merge if e.merge_state() is not None else gather).append(e)
        return merge, gather

    @staticmethod
    def layers_for(evaluators: List[Evaluator]) -> List[str]:
        seen: List[str] = []
        for e in evaluators:
            for n in e.cfg.input_layers:
                if n not in seen:
                    seen.append(n)
        return seen

    def _merged(self, e: Evaluator) -> Evaluator:
        """A view of e with cross-process-merged state (e itself keeps
        accumulating local rows; merging at read time is idempotent)."""
        vec = None if self.merge_fn is None else e.merge_state()
        if vec is None:
            return e
        clone = type(e)(e.cfg)
        clone.load_state(self.merge_fn(vec))
        return clone

    def __bool__(self) -> bool:
        return bool(self.evaluators)

    @property
    def needed_layers(self) -> List[str]:
        """Layer outputs the chain reads — multi-process runs gather only
        these to the host (distributeEval analog, Evaluator.h:81-82)."""
        seen: List[str] = []
        for e in self.evaluators:
            for n in e.cfg.input_layers:
                if n not in seen:
                    seen.append(n)
        return seen

    def start(self):
        for e in self.evaluators:
            e.start()

    def eval_batch(self, outputs: Dict[str, Argument], only: Optional[List[Evaluator]] = None):
        for e in (self.evaluators if only is None else only):
            args = [outputs[n] for n in e.cfg.input_layers if n in outputs]
            if len(args) == len(e.cfg.input_layers):
                e.eval_batch(args)

    def summary(self) -> str:
        parts = []
        for e in self.evaluators:
            s = self._merged(e).summary()
            if s:
                parts.append(f"{e.cfg.name}: {s}")
        return "  ".join(parts)

    def results(self) -> Dict[str, float]:
        out = {}
        for e in self.evaluators:
            for k, v in self._merged(e).result().items():
                out[f"{e.cfg.name}.{k}"] = v
        return out
