"""Contiguous row-range math for row-sharded embedding tables.

Pure integer arithmetic, deliberately jax-free: ``cluster_launch``
calls ``row_budget_error`` before relaunch rounds (no jax in the
supervisor), and ``paddle check-checkpoint`` calls
``coverage_problems`` on shard indexes from cold disk.

The sharding model is the simplest one that composes with the
PR-6 durable shard protocol: host ``i`` of ``n`` owns the contiguous
interval ``[i*nrows//n, (i+1)*nrows//n)``.  Balanced to within one
row, order-preserving (resharding moves whole sub-intervals, never
permutes rows), and a shard record needs only ``row_range=[lo, hi)``
to be self-describing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


def partition_rows(nrows: int, num_hosts: int) -> List[Tuple[int, int]]:
    """Contiguous, balanced row ranges: one ``(lo, hi)`` per host.

    Ranges tile ``[0, nrows)`` exactly; sizes differ by at most one
    row.  ``nrows < num_hosts`` leaves trailing hosts with empty
    ranges (``lo == hi``) rather than failing — a 3-row table on 4
    hosts is legal, just wasteful.
    """
    if nrows < 0:
        raise ValueError(f"nrows must be >= 0, got {nrows}")
    if num_hosts <= 0:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    return [
        (i * nrows // num_hosts, (i + 1) * nrows // num_hosts)
        for i in range(num_hosts)
    ]


def rows_per_host(nrows: int, num_hosts: int) -> int:
    """Largest per-host row count under ``partition_rows`` (= ceil)."""
    if num_hosts <= 0:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    return -(-max(nrows, 0) // num_hosts)


def row_budget_error(tables: Dict[str, int], num_hosts: int,
                     budget: int) -> Optional[str]:
    """Refusal message when ``num_hosts`` hosts cannot hold every
    table within ``budget`` rows/host, else None.

    ``budget <= 0`` means unlimited (the flag's default).  The message
    names the offending table, its row count, the host count, and the
    per-host need — the string ``cluster_launch`` refuses a relaunch
    round with, so it must carry enough to act on.
    """
    if budget <= 0 or not tables:
        return None
    if num_hosts <= 0:
        return f"no hosts left to hold {len(tables)} sparse table(s)"
    for name, nrows in sorted(tables.items()):
        need = rows_per_host(int(nrows), num_hosts)
        if need > budget:
            label = f"sparse table '{name}'" if name else "sparse table"
            return (
                f"{label} of {int(nrows)} rows does not fit "
                f"{num_hosts} host(s) within --sparse_row_budget={budget} "
                f"rows/host (needs {need})"
            )
    return None


def reshard_plan(old_ranges: Sequence[Tuple[int, int]],
                 new_ranges: Sequence[Tuple[int, int]],
                 ) -> List[List[Tuple[int, int, int]]]:
    """Per-new-host fetch plan: which old hosts' sub-intervals
    assemble each new range.

    Returns one list per new host of ``(src_host, lo, hi)`` triples
    (``[lo, hi)`` in table coordinates), in row order.  A new host's
    triples tile its range exactly when the old ranges tile the table.
    """
    plan: List[List[Tuple[int, int, int]]] = []
    for nlo, nhi in new_ranges:
        parts: List[Tuple[int, int, int]] = []
        for src, (olo, ohi) in enumerate(old_ranges):
            lo, hi = max(nlo, olo), min(nhi, ohi)
            if lo < hi:
                parts.append((src, lo, hi))
        parts.sort(key=lambda t: t[1])
        plan.append(parts)
    return plan


def coverage_problems(nrows: int,
                      ranges: Sequence[Tuple[int, int, object]],
                      ) -> List[str]:
    """Named holes/overlaps in a claimed row coverage of ``[0, nrows)``.

    ``ranges`` is ``(lo, hi, host)`` per shard record.  Every problem
    is a full sentence naming the exact interval and the responsible
    host(s) — ``paddle check-checkpoint`` surfaces these verbatim, and
    "rows [4, 8) missing" must be actionable without opening the
    index by hand.
    """
    problems: List[str] = []
    clean: List[Tuple[int, int, object]] = []
    for lo, hi, host in ranges:
        lo, hi = int(lo), int(hi)
        if lo < 0 or hi > nrows or lo > hi:
            problems.append(
                f"rows [{lo}, {hi}) (host {host}) outside table of "
                f"{nrows} rows"
            )
            continue
        if lo < hi:
            clean.append((lo, hi, host))
    clean.sort(key=lambda t: (t[0], t[1]))
    cursor = 0
    covered_to = 0  # furthest hi seen — overlap detection under sort order
    for lo, hi, host in clean:
        if lo > cursor:
            problems.append(
                f"rows [{cursor}, {lo}) of {nrows} uncovered "
                f"(no host's shard record claims them)"
            )
        if lo < covered_to:
            others = sorted(
                {str(h) for l2, h2, h in clean
                 if (l2, h2, h) != (lo, hi, host) and l2 < hi and h2 > lo}
            )
            problems.append(
                f"rows [{lo}, {min(hi, covered_to)}) covered more than "
                f"once (host {host} overlaps host(s) {', '.join(others)})"
            )
        cursor = max(cursor, hi)
        covered_to = max(covered_to, hi)
    if cursor < nrows:
        problems.append(
            f"rows [{cursor}, {nrows}) of {nrows} uncovered "
            f"(no host's shard record claims them)"
        )
    return problems
