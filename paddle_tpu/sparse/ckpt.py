"""Row-range reads of durable checkpoints (the reshard's disk half).

Two consumers:

* relaunch restore — :func:`load_table_rows` assembles an arbitrary
  row slice of a table from a COMMITTED pass directory's merged
  index, via the threaded :class:`~paddle_tpu.sparse.reshard.
  ReshardLoader` (any hole or double-write raises, naming the
  interval);
* ``paddle check-checkpoint`` — :func:`partial_row_holes` names, for
  a torn ``pass-N.tmp``, exactly which row intervals of which tables
  never reached disk and which hosts did commit theirs.

Shard records written since this PR carry an explicit
``row_range=[lo, hi)``; older records for dim-0-sharded params are
equivalent to ``[start[0], start[0] + shape[0])`` and
:func:`load_table_rows` accepts the derived form, so pre-sparse
checkpoints stay loadable.  :func:`partial_row_holes` trusts only the
explicit stamp — deriving there would claim phantom full-row coverage
for column-sharded dense params.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.sparse import rowshard
from paddle_tpu.sparse.reshard import ReshardLoader

_SHARD_HOST_RE = re.compile(r"\.shard(\d{5})\.npz$")
_PARTIAL_IDX_RE = re.compile(r"^(?P<base>.+)\.index\.(?P<pid>\d{5})\.json$")


def shard_row_range(rec: Dict[str, Any]) -> Optional[Tuple[int, int]]:
    """The row interval a shard record claims: explicit ``row_range``
    when stamped, else derived from ``start[0]``/``shape[0]`` (the
    pre-sparse record form for dim-0 shardings)."""
    rr = rec.get("row_range")
    if rr:
        return int(rr[0]), int(rr[1])
    start, shape = rec.get("start"), rec.get("shape")
    if start and shape:
        return int(start[0]), int(start[0]) + int(shape[0])
    return None


def _shard_host(fname: str) -> str:
    m = _SHARD_HOST_RE.search(fname)
    return str(int(m.group(1))) if m else "?"


def load_table_rows(pass_dir: str, name: str, lo: int, hi: int,
                    base: str = "params", workers: int = 4) -> np.ndarray:
    """Rows ``[lo, hi)`` of table ``name`` from a committed pass dir.

    Reads only the shard files whose ``row_range`` overlaps the
    request — a survivor loading its post-reshard slice never touches
    the rest of the table.  Raises :class:`~paddle_tpu.sparse.
    reshard.ReshardError` (naming the interval) on any coverage hole.
    """
    index_path = os.path.join(pass_dir, f"{base}.index.json")
    with open(index_path) as f:
        index = json.load(f)
    entry = index.get(name)
    if entry is None:
        raise KeyError(f"no entry for {name!r} in {index_path}")
    records = []
    for rec in entry.get("shards", []):
        rr = shard_row_range(rec)
        if rr is None:
            continue
        records.append(dict(rec, row_range=[rr[0], rr[1]]))

    def read_fn(rec: Dict[str, Any]) -> np.ndarray:
        with np.load(os.path.join(pass_dir, rec["file"])) as z:
            return np.asarray(z[rec["key"]])

    return ReshardLoader(records, read_fn, workers=workers).load(lo, hi)


def partial_row_holes(tmp_dir: str,
                      tables: Optional[Dict[str, int]] = None) -> List[str]:
    """Named row holes in a TORN pass tmp dir's per-host partial
    indexes — the evidence ``paddle check-checkpoint`` prints for a
    pass that never committed.

    ``tables`` restricts the check to known sparse tables
    (``{name: nrows}``); by default every entry whose partial records
    carry a row extent is checked against its global ``shape[0]``.
    Each message names the table, the missing interval, and which
    hosts DID land their partial index (the absent host is the
    responsible one).
    """
    by_name: Dict[Tuple[str, str], Dict[str, Any]] = {}
    hosts_present: List[str] = []
    try:
        listing = sorted(os.listdir(tmp_dir))
    except OSError:
        return []
    for fn in listing:
        m = _PARTIAL_IDX_RE.match(fn)
        if not m:
            continue
        hosts_present.append(str(int(m.group("pid"))))
        try:
            with open(os.path.join(tmp_dir, fn)) as f:
                partial = json.load(f)
        except (OSError, ValueError):
            continue
        for name, entry in partial.items():
            slot = by_name.setdefault(
                (m.group("base"), name),
                {"shape": entry.get("shape"), "ranges": []},
            )
            for rec in entry.get("shards", []):
                # EXPLICIT row_range only: deriving from start/shape here
                # would claim full-row coverage for column-sharded dense
                # params and report phantom overlaps
                rr = rec.get("row_range")
                if rr:
                    slot["ranges"].append(
                        (int(rr[0]), int(rr[1]),
                         _shard_host(rec.get("file", "")))
                    )
    holes: List[str] = []
    present = ", ".join(sorted(set(hosts_present), key=int)) or "none"
    for (base, name), slot in sorted(by_name.items()):
        shape = slot["shape"]
        if not shape or not slot["ranges"]:
            continue
        if tables is not None and name.split("/", 1)[0] not in tables:
            continue
        nrows = int(shape[0])
        for msg in rowshard.coverage_problems(nrows, slot["ranges"]):
            holes.append(
                f"{base}/{name}: {msg} — partial index present from "
                f"host(s) {present}"
            )
    return holes
