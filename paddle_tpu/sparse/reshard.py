"""Threaded reassembly of one host's new row range from shard records.

A relaunch round after a host loss re-slices every sparse table's row
ranges over the surviving host set (``rowshard.partition_rows``);
each survivor then loads its new slice from the last committed
checkpoint's ``row_range``-stamped shard records.  That load is the
reshard: possibly several source files per destination range, read
concurrently, each row landing exactly once.

All threading goes through the ``utils/concurrency`` seam so ``paddle
race`` can virtualise the schedule (tests/race_specs/
spec_sparse_reshard.py asserts no lost/duplicate row across the
reshard).  numpy-only — callers hand in a ``read_fn`` so the loader
never touches disk formats itself.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

import numpy as np

from paddle_tpu.utils import concurrency as cc


class ReshardError(RuntimeError):
    """A destination row range could not be assembled exactly once."""


class ReshardLoader:
    """Assemble rows ``[lo, hi)`` of one table from shard records.

    ``records`` are shard-index entries carrying ``row_range=[rlo,
    rhi)``; ``read_fn(record)`` returns that record's rows as a numpy
    array of shape ``(rhi - rlo, *row_shape)``.  ``load`` fans the
    overlapping records out over ``workers`` threads and fails loudly
    — naming the interval — on any row left unfilled or filled twice.
    """

    def __init__(self, records: Sequence[Dict[str, Any]],
                 read_fn: Callable[[Dict[str, Any]], np.ndarray],
                 workers: int = 4):
        self._records = list(records)
        self._read_fn = read_fn
        self._workers = max(1, int(workers))

    def load(self, lo: int, hi: int) -> np.ndarray:
        lo, hi = int(lo), int(hi)
        if hi < lo:
            raise ValueError(f"bad row range [{lo}, {hi})")
        overlapping = []
        for rec in self._records:
            rr = rec.get("row_range")
            if not rr:
                continue
            rlo, rhi = int(rr[0]), int(rr[1])
            clo, chi = max(lo, rlo), min(hi, rhi)
            if clo < chi:
                overlapping.append((rec, rlo, clo, chi))
        out: List[np.ndarray] = [None]  # allocated on first read
        fill = np.zeros(hi - lo, dtype=np.int32)  # per-row write count
        lock = cc.Lock()
        work = cc.Queue()
        for item in overlapping:
            work.put(item)
        errors: List[BaseException] = []

        def worker() -> None:
            while True:
                try:
                    rec, rlo, clo, chi = work.get_nowait()
                except Exception:
                    return
                try:
                    rows = np.asarray(self._read_fn(rec))
                    want = int(rec["row_range"][1]) - rlo
                    if rows.shape[0] != want:
                        raise ReshardError(
                            f"shard {rec.get('file', '?')} claims rows "
                            f"[{rlo}, {rlo + want}) but holds "
                            f"{rows.shape[0]} row(s)"
                        )
                    piece = rows[clo - rlo:chi - rlo]
                    with lock:
                        if out[0] is None:
                            out[0] = np.zeros(
                                (hi - lo,) + piece.shape[1:],
                                dtype=piece.dtype,
                            )
                        out[0][clo - lo:chi - lo] = piece
                        fill[clo - lo:chi - lo] += 1
                except BaseException as e:  # surfaced after join
                    with lock:
                        errors.append(e)

        threads = [
            cc.Thread(target=worker, name=f"reshard-{i}", daemon=True)
            for i in range(min(self._workers, max(1, len(overlapping))))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        problems = _fill_problems(lo, hi, fill)
        if problems:
            raise ReshardError("; ".join(problems))
        if out[0] is None:
            # hi == lo (empty destination range) is the only clean way here
            return np.zeros((0,), dtype=np.float32)
        return out[0]


def _fill_problems(lo: int, hi: int, fill: np.ndarray) -> List[str]:
    """Human-named intervals where fill count != 1."""
    problems: List[str] = []
    for want, word in ((0, "missing from every shard record"),
                       (2, "written more than once")):
        mask = (fill == 0) if want == 0 else (fill > 1)
        if not mask.any():
            continue
        idx = np.flatnonzero(mask)
        start = prev = int(idx[0])
        runs: List[Tuple[int, int]] = []
        for i in idx[1:]:
            i = int(i)
            if i != prev + 1:
                runs.append((start, prev + 1))
                start = i
            prev = i
        runs.append((start, prev + 1))
        for a, b in runs:
            problems.append(f"rows [{lo + a}, {lo + b}) {word}")
    return problems
