"""Row-sharded sparse-parameter training (doc/sparse.md).

Role of the reference's parameter-server sparse path
(`SparseRowMatrix.h`, sparse remote updaters): embedding tables whose
rows are too large for one host live row-sharded across hosts, each
batch gathers/scatters only the touched rows, per-row optimizer state
rides the same row sharding, and the PR 1-6 durability stack is
extended with explicit ``row_range`` shard records so a host loss
reshards the surviving table instead of silently zero-initialising it.

Submodules stay import-light: ``rowshard`` and ``runtime`` are
jax-free (usable from ``cluster_launch`` and ``paddle
check-checkpoint``); ``reshard`` needs only numpy + the
``utils/concurrency`` seam; ``ckpt`` reads checkpoint indexes.
"""

from paddle_tpu.sparse.rowshard import (  # noqa: F401
    coverage_problems,
    partition_rows,
    reshard_plan,
    row_budget_error,
)
from paddle_tpu.sparse.runtime import (  # noqa: F401
    SparseStats,
    clear_tables,
    register_tables,
    registered_tables,
)
