"""Process-global sparse-table registry + per-pass touched-row stats.

The registry is how the layers below the trainer learn which params
are row-sharded sparse tables without threading a config through
every call site: the trainer registers ``{param_name: nrows}`` at
construction, and ``checkpoint.snapshot_owned_trees`` (a jax-free
module that must not import the trainer) looks names up here to stamp
``row_range`` into shard records.  Registration is idempotent and
cleared per-trainer — tests call :func:`clear_tables` in teardown.

``SparseStats`` is the accounting half of the ``kind=sparse``
telemetry record: occurrence/unique touched-row counts and
gather/scatter byte estimates per pass, plus reshard events observed
at restore time.  numpy-only, no jax.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_TABLES: Dict[str, int] = {}


def register_tables(tables: Dict[str, int]) -> None:
    """Declare row-sharded sparse tables: ``{param_name: nrows}``."""
    for name, nrows in tables.items():
        _TABLES[str(name)] = int(nrows)


def clear_tables() -> None:
    _TABLES.clear()


def registered_tables() -> Dict[str, int]:
    """Snapshot of the registry (copy — callers may not mutate it)."""
    return dict(_TABLES)


class SparseStats:
    """Per-pass touched-row accounting for one trainer.

    ``row_bytes`` maps table param name -> bytes per row (width *
    itemsize), fixed at construction so byte estimates don't need the
    arrays.  Gather bytes count every occurrence (the prefetch
    fetches per-id); scatter bytes count unique rows (the updater
    dedupes before writing back).
    """

    def __init__(self, row_bytes: Dict[str, int]):
        self.row_bytes = {str(k): int(v) for k, v in row_bytes.items()}
        self.reshard_events: List[Dict[str, int]] = []
        self._reset_pass()

    def _reset_pass(self) -> None:
        self.rows_touched = 0
        self.gather_bytes = 0
        self.scatter_bytes = 0
        self._unique: Dict[str, set] = {}

    def note_batch(self, plan: List[Tuple[str, str]],
                   host_batch: Dict[str, Any]) -> None:
        """Account one batch: ``plan`` is the trainer's
        ``sparse_prefetch_plan()`` ([(param_name, data_layer_name)]),
        ``host_batch`` the per-launch host arg dict whose entries
        carry integer ``.ids``."""
        for pn, dname in plan:
            arg = host_batch.get(dname)
            ids = getattr(arg, "ids", None)
            if ids is None:
                continue
            ids = np.asarray(ids).reshape(-1)
            if ids.size == 0:
                continue
            rb = self.row_bytes.get(pn, 0)
            uniq = np.unique(ids)
            self.rows_touched += int(ids.size)
            self.gather_bytes += int(ids.size) * rb
            self.scatter_bytes += int(uniq.size) * rb
            self._unique.setdefault(pn, set()).update(int(i) for i in uniq)

    def note_reshard(self, old_hosts: int, new_hosts: int) -> None:
        """Record one restore-time resharding (host-count change)."""
        self.reshard_events.append(
            {"old_hosts": int(old_hosts), "new_hosts": int(new_hosts)}
        )

    def unique_rows(self) -> int:
        return sum(len(s) for s in self._unique.values())

    def pass_record(self, duration_s: Optional[float] = None,
                    ) -> Dict[str, Any]:
        """The ``kind=sparse`` payload for the pass just finished;
        resets per-pass counters (reshard events are per-run and
        persist)."""
        uniq = self.unique_rows()
        rec: Dict[str, Any] = {
            "rows_touched": int(self.rows_touched),
            "unique_rows": int(uniq),
            "unique_row_rate": (
                float(uniq) / float(self.rows_touched)
                if self.rows_touched else 0.0
            ),
            "gather_bytes": int(self.gather_bytes),
            "scatter_bytes": int(self.scatter_bytes),
            "reshard_events": len(self.reshard_events),
        }
        if duration_s is not None and duration_s > 0:
            rec["sparse_rows_per_sec"] = self.rows_touched / duration_s
        self._reset_pass()
        return rec
