"""`paddle lint` — jax-aware static analysis for the framework's own
invariants.

Eight PRs of resilience/observability/perf work rest on invariants that
previously lived only in commit messages: no wall-clock in hot paths,
no host syncs inside the step loop, recompile-stable launch signatures,
flush-before-exit for crash evidence, locked shared state on daemon
threads, and documented record kinds / fault sites. This package turns
each into a mechanical AST check with a stable rule ID (PTL001-PTL007,
catalog in doc/static_analysis.md), a mandatory-reason suppression
syntax (``# lint: disable=PTL00x -- reason``), and a checked-in JSON
baseline so the CI gate is "zero NEW findings", not "zero findings".

Everything here is stdlib-only (``ast`` + ``re`` + ``json``) and never
imports jax — ``paddle lint`` must run on a dev laptop, in CI before
the accelerator runtime exists, and over a tree copied off a pod.
"""

from paddle_tpu.analysis.core import (  # noqa: F401
    ALL_RULES,
    Finding,
    LintResult,
    run_lint,
)
from paddle_tpu.analysis.baseline import (  # noqa: F401
    load_baseline,
    write_baseline,
)
