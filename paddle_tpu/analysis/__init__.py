"""`paddle lint` / `paddle race` — the framework's own analysis stack.

Eight PRs of resilience/observability/perf work rest on invariants that
previously lived only in commit messages: no wall-clock in hot paths,
no host syncs inside the step loop, recompile-stable launch signatures,
flush-before-exit for crash evidence, locked shared state on daemon
threads, bounded daemon-thread waits, and documented record kinds /
fault sites. This package turns each into a mechanical AST check with
a stable rule ID (PTL001-PTL008, catalog in doc/static_analysis.md), a
mandatory-reason suppression syntax (``# lint: disable=PTL00x --
reason``), and a checked-in JSON baseline so the CI gate is "zero NEW
findings", not "zero findings".

The ``dynamic`` subpackage is the other half: `paddle race` runs the
REAL daemon-thread code under a deterministic, seeded schedule
explorer and proves (or clears) what the AST rules can only suspect —
torn reads, lock-order inversions, lost wakeups (doc/static_analysis.md
"Dynamic analysis").

Everything here is stdlib-only (``ast`` + ``re`` + ``json`` +
``threading`` for the explorer's gated threads) and never imports jax
— both gates must run on a dev laptop, in CI before the accelerator
runtime exists, and over a tree copied off a pod.
"""

from paddle_tpu.analysis.core import (  # noqa: F401
    ALL_RULES,
    Finding,
    LintResult,
    run_lint,
)
from paddle_tpu.analysis.baseline import (  # noqa: F401
    load_baseline,
    write_baseline,
)
