"""Checked-in baseline: grandfathered findings, so adopting a new rule
never requires fixing (or loudly suppressing) every historical hit at
once. The CI gate is "zero NEW findings"; the baseline is the honest,
reviewable list of what was grandfathered and why that was acceptable.

Format (``.paddle_lint_baseline.json`` at the repo root)::

    {
      "version": 1,
      "findings": [
        {"rule": "PTL00x", "path": "...", "fingerprint": "...",
         "message": "..."},
        ...
      ]
    }

Only ``fingerprint`` is matched (rule + path + source line content —
line-number independent, see core._fingerprint); ``rule``/``path``/
``message`` ride along for reviewability. ``paddle lint
--write-baseline`` regenerates the file from the current findings;
entries that no longer match anything are reported as stale so the
baseline shrinks monotonically instead of fossilizing.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

BASELINE_VERSION = 1
BASELINE_NAME = ".paddle_lint_baseline.json"


def default_baseline_path(repo_root: str) -> Optional[str]:
    """The conventional location, when it exists."""
    path = os.path.join(repo_root, BASELINE_NAME)
    return path if os.path.isfile(path) else None


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: not a v{BASELINE_VERSION} lint baseline "
            f"(got version={doc.get('version') if isinstance(doc, dict) else None!r})"
        )
    if not isinstance(doc.get("findings"), list):
        raise ValueError(f"{path}: baseline 'findings' must be a list")
    return doc


def write_baseline(path: str, findings: Sequence,
                   keep_entries: Sequence[Dict[str, Any]] = ()) -> Dict[str, Any]:
    """Serialize ``findings`` (core.Finding objects) as a baseline doc,
    written atomically (tmp + replace) so a killed run never leaves a
    torn baseline for the next one to trust. ``keep_entries`` are raw
    prior-baseline entries carried over verbatim — the write path for a
    SUBSET scan, whose non-scanned files' grandfathered entries must
    not be dropped just because this run couldn't see them."""
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "fingerprint": f.fingerprint,
            "message": f.message,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    entries.extend(keep_entries)
    entries.sort(key=lambda e: (e.get("path", ""), e.get("rule", ""),
                                e.get("fingerprint", "")))
    doc = {
        "version": BASELINE_VERSION,
        "findings": entries,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return doc
