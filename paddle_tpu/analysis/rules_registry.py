"""PTL007 — registry drift: every emitted record kind must be
registered in ``KIND_REQUIRED`` and documented in
doc/observability.md's "Record kinds" table; every planted fault site
must be in ``SITE_DOCS`` (and vice versa). The generalization of the
doc-flags consistency test: the registries ARE the documentation, so
drift between code, registry, and doc is mechanical to catch.

Everything is read statically (AST of metrics.py / faultinject.py,
regex over the doc) — no imports, so the check runs on any tree.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from paddle_tpu.analysis.core import (
    Finding,
    LintContext,
    SourceFile,
    const_strings,
    dotted,
    rule,
    str_arg0,
)

_DOC_REL = os.path.join("doc", "observability.md")


def _module_assign(sf: SourceFile, name: str) -> Optional[ast.Assign]:
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            return node
    return None


def _dict_keys(node: Optional[ast.Assign]) -> Set[str]:
    if node is None or not isinstance(node.value, ast.Dict):
        return set()
    return {
        k.value
        for k in node.value.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    }


def _doc_kinds(repo_root: str) -> Optional[Set[str]]:
    """First-column backticked names of the "Record kinds" table in
    doc/observability.md (section-scoped: the envelope table's `v`/`t`
    rows must not count as kinds). None = doc not found (fixture trees
    without docs skip the doc half)."""
    path = os.path.join(repo_root, _DOC_REL)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    m = re.search(r"^#+\s*Record kinds\s*$", text, re.MULTILINE)
    if m is None:
        return None
    section = text[m.end():]
    nxt = re.search(r"^#+\s", section, re.MULTILINE)
    if nxt:
        section = section[: nxt.start()]
    return set(re.findall(r"^\|\s*`(\w+)`", section, re.MULTILINE))


def _emit_sites(ctx: LintContext) -> List[Tuple[SourceFile, ast.Call, str]]:
    out = []
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d == "emit" or d.endswith(".emit"):
                kind = str_arg0(node)
                if kind:
                    out.append((sf, node, kind))
    return out


def _fault_sites(ctx: LintContext) -> List[Tuple[SourceFile, ast.Call, str]]:
    out = []
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d == "fault_point" or d.endswith(".fault_point"):
                site = str_arg0(node)
                if site:
                    out.append((sf, node, site))
    return out


@rule(
    "PTL007",
    "registry drift: emitted kind without KIND_REQUIRED entry / doc "
    "schema row, or fault site missing from SITE_DOCS",
    project=True,
)
def check_registry_drift(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []

    metrics_sf = ctx.find("observability/metrics.py")
    fault_sf = ctx.find("resilience/faultinject.py")
    doc_kinds = _doc_kinds(ctx.repo_root)

    # ---------------- record kinds
    if metrics_sf is not None:
        kr_assign = _module_assign(metrics_sf, "KIND_REQUIRED")
        fk_assign = _module_assign(metrics_sf, "FLUSH_KINDS")
        kind_required = _dict_keys(kr_assign)
        flush_kinds = set(
            const_strings(fk_assign.value)
        ) if fk_assign is not None else set()
        emitted: Dict[str, Tuple[SourceFile, ast.Call]] = {}
        for sf, node, kind in _emit_sites(ctx):
            emitted.setdefault(kind, (sf, node))
        for kind, (sf, node) in sorted(emitted.items()):
            if kind_required and kind not in kind_required:
                out.append(Finding(
                    rule="PTL007", path=sf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"record kind `{kind}` is emitted but has no "
                        "KIND_REQUIRED entry in observability/metrics.py — "
                        "register its required fields (may be ()) so "
                        "validate_record covers it"
                    ),
                    snippet=sf.snippet(node.lineno),
                ))
            if doc_kinds is not None and kind not in doc_kinds:
                out.append(Finding(
                    rule="PTL007", path=sf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"record kind `{kind}` is emitted but undocumented "
                        f"— add its row to {_DOC_REL} \"Record kinds\""
                    ),
                    snippet=sf.snippet(node.lineno),
                ))
        # reverse direction: registry entries no code/doc backs. Only
        # meaningful on a scan that includes the emitters — a kind that
        # is documented counts as backed (bench.py emits `bench` from
        # outside the package; `paddle lint --json` emits the lint kinds
        # without going through MetricsWriter).
        anchor_line = kr_assign.lineno if kr_assign is not None else 1
        for kind in sorted(kind_required):
            if kind not in emitted and doc_kinds is not None \
                    and kind not in doc_kinds:
                out.append(Finding(
                    rule="PTL007", path=metrics_sf.rel, line=anchor_line,
                    col=0,
                    message=(
                        f"KIND_REQUIRED entry `{kind}` is neither emitted "
                        "anywhere in the scanned tree nor documented — "
                        "dead registry entry?"
                    ),
                    snippet=metrics_sf.snippet(anchor_line),
                ))
        fk_line = fk_assign.lineno if fk_assign is not None else 1
        # same doc-gating as the KIND_REQUIRED reverse check: with no
        # doc in the tree (copied off a pod) documentation status is
        # unknowable, so don't guess "dead"
        for kind in sorted(flush_kinds):
            if kind not in emitted and doc_kinds is not None \
                    and kind not in doc_kinds:
                out.append(Finding(
                    rule="PTL007", path=metrics_sf.rel, line=fk_line, col=0,
                    message=(
                        f"FLUSH_KINDS names `{kind}`, which is neither "
                        "emitted anywhere in the scanned tree nor "
                        "documented — dead flush kind?"
                    ),
                    snippet=metrics_sf.snippet(fk_line),
                ))

    # ---------------- fault sites
    if fault_sf is not None:
        site_docs = _dict_keys(_module_assign(fault_sf, "SITE_DOCS"))
        planted: Dict[str, Tuple[SourceFile, ast.Call]] = {}
        for sf, node, site in _fault_sites(ctx):
            planted.setdefault(site, (sf, node))
        for site, (sf, node) in sorted(planted.items()):
            if site_docs and site not in site_docs:
                out.append(Finding(
                    rule="PTL007", path=sf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"fault site `{site}` is planted but missing from "
                        "SITE_DOCS — `paddle faults` and chaos-spec authors "
                        "can't see it"
                    ),
                    snippet=sf.snippet(node.lineno),
                ))
        sd_assign = _module_assign(fault_sf, "SITE_DOCS")
        sd_line = sd_assign.lineno if sd_assign is not None else 1
        # reverse direction only when the scan includes SOME planting
        # layer: a subset scan (e.g. resilience/ alone) sees SITE_DOCS
        # but none of the trainer/feeder/checkpoint call sites, and
        # must not report every documented site as unplanted
        for site in sorted(site_docs) if planted else ():
            if site not in planted:
                out.append(Finding(
                    rule="PTL007", path=fault_sf.rel, line=sd_line, col=0,
                    message=(
                        f"SITE_DOCS documents fault site `{site}` but no "
                        "fault_point() in the scanned tree plants it — "
                        "chaos specs naming it would silently never fire"
                    ),
                    snippet=fault_sf.snippet(sd_line),
                ))
    return out
