"""PTL005 (daemon-thread shared-state writes without a lock), PTL006
(exit paths not dominated by a metrics flush), and PTL008 (unbounded
blocking primitives on daemon-thread paths) — the concurrency and
crash-evidence invariants from the async-checkpoint / hangwatch /
heartbeat work. Also home of :func:`thread_shared_attrs`, the static
seed for `paddle race`'s dynamic watch lists.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from paddle_tpu.analysis.core import (
    Finding,
    LintContext,
    SourceFile,
    dotted,
    rule,
    str_arg0,
)

# ------------------------------------------------------------- PTL005


class _FileIndex:
    """Functions by qualname + the class that owns each method."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.module_funcs: Dict[str, ast.AST] = {}
        self.methods: Dict[Tuple[str, str], ast.AST] = {}  # (class, name)
        self.class_of: Dict[ast.AST, str] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.methods[(node.name, sub.name)] = sub
                        self.class_of[sub] = node.name
        # nested defs (closures handed to Thread(target=...))
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if (
                        sub is not node
                        and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and sub.name not in self.module_funcs
                    ):
                        self.module_funcs.setdefault(sub.name, sub)

    def resolve(self, ref: ast.AST) -> List[ast.AST]:
        """Function nodes a callable reference might mean: a bare name
        (module or nested def) or ``self.method`` (any class defining
        that method — conservative when several do)."""
        if isinstance(ref, ast.Name):
            fn = self.module_funcs.get(ref.id)
            return [fn] if fn is not None else []
        if (
            isinstance(ref, ast.Attribute)
            and isinstance(ref.value, ast.Name)
            and ref.value.id == "self"
        ):
            # every same-named method in the file, deliberately: a
            # subclass override of a method the base's thread loop calls
            # (`ShardedAsyncCheckpointer._write` via the inherited
            # `_run`) is also thread-side
            return [
                fn for (cls, name), fn in self.methods.items()
                if name == ref.attr
            ]
        return []


def _thread_entry_refs(sf: SourceFile) -> List[ast.AST]:
    """The callable ref of every Thread(target=...), Timer(..., fn),
    and pool.submit(fn, ...) in the file."""
    out: List[ast.AST] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d.endswith("Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    out.append(kw.value)
        elif d.endswith("Timer"):
            for kw in node.keywords:
                if kw.arg == "function":
                    out.append(kw.value)
            if len(node.args) >= 2:
                out.append(node.args[1])
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "submit":
            if node.args:
                out.append(node.args[0])
    return out


def _reachable_functions(sf: SourceFile,
                         entries: List[ast.AST]) -> List[ast.AST]:
    """Every function reachable from the given entry refs by the
    in-file transitive call walk — the ONE worklist shared by PTL005,
    PTL008, and the dynamic analyzer's watch-list seeding (a fix to
    call resolution lands everywhere at once)."""
    if not entries:
        return []
    idx = _FileIndex(sf)
    out: List[ast.AST] = []
    seen: Set[int] = set()
    work: List[ast.AST] = []
    for ref in entries:
        work.extend(idx.resolve(ref))
    while work:
        fn = work.pop()
        if fn is None or id(fn) in seen:
            continue
        seen.add(id(fn))
        out.append(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                work.extend(idx.resolve(node.func))
    return out


def _thread_side_functions(sf: SourceFile) -> List[ast.AST]:
    """Functions reachable from ANY thread entry (PTL005's scope)."""
    return _reachable_functions(sf, _thread_entry_refs(sf))


def thread_shared_attrs(text: str, filename: str = "<mem>") -> Set[str]:
    """Self-attributes referenced (read OR written) on a thread-run
    path of ``text`` — the static seed for `paddle race`'s dynamic
    watch lists: PTL005's walk finds the fields, minus the lock filter
    (whether the synchronization is sufficient is exactly what the
    schedule explorer judges dynamically)."""
    try:
        sf = SourceFile(filename, "mem.py", text)
    except SyntaxError:
        return set()
    attrs: Set[str] = set()
    for fn in _thread_side_functions(sf):
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                attrs.add(node.attr)
    return attrs


def _locked_lines(fn: ast.AST, lock_re: re.Pattern) -> Set[int]:
    """Line numbers lexically inside a ``with <something-lockish>:``."""
    lines: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            if any(
                lock_re.search(ast.unparse(item.context_expr))
                for item in node.items
            ):
                end = getattr(node, "end_lineno", node.lineno)
                lines.update(range(node.lineno, (end or node.lineno) + 1))
    return lines


@rule(
    "PTL005",
    "self-attribute written on a daemon-thread code path without an "
    "enclosing lock",
)
def check_unlocked_thread_writes(sf: SourceFile, ctx: LintContext) -> Iterable[Finding]:
    """State shared between a worker thread and the step loop (the
    async-ckpt writer's progress counters, the heartbeat sequence, the
    hangwatch fired-flag) must be written under the object's lock —
    torn read-modify-writes there turn into lost progress pings, double
    saves, or double hang reports. The walk: thread entry points
    (``Thread(target=...)``/``Timer``/``pool.submit``) plus everything
    they transitively call in-file; any ``self.attr = ...`` /
    ``self.attr += ...`` there must sit inside a ``with <lock>:``."""
    thread_side = _thread_side_functions(sf)
    if not thread_side:
        return []
    lock_re = re.compile(ctx.config["lock_name_re"], re.IGNORECASE)
    out: List[Finding] = []
    reported: Set[Tuple[int, int]] = set()
    for fn in thread_side:
        locked = _locked_lines(fn, lock_re)
        for node in ast.walk(fn):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            for t in targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                if node.lineno in locked:
                    continue
                key = (node.lineno, node.col_offset)
                if key in reported:
                    continue
                reported.add(key)
                out.append(Finding(
                    rule="PTL005", path=sf.rel, line=node.lineno,
                    col=node.col_offset,
                    end_line=getattr(node, "end_lineno", 0) or 0,
                    message=(
                        f"`self.{t.attr}` written on the "
                        f"thread-run path `{getattr(fn, 'name', '?')}` "
                        "without an enclosing lock — wrap the write in "
                        "`with <lock>:` (shared with the thread's readers)"
                    ),
                    snippet=sf.snippet(node.lineno),
                ))
    return out


# ------------------------------------------------------------- PTL008


def _daemon_entry_refs(sf: SourceFile) -> List[ast.AST]:
    """Callable refs of thread entries that run as DAEMONS: explicit
    ``Thread(..., daemon=True)`` targets and every ``Timer`` function
    (the codebase's timers are hang-defense backstops, daemonized by
    attribute). Non-daemon threads and pool workers are excluded — the
    interpreter joins them at exit, so an unbounded wait there is an
    ordinary (diagnosable) hang, not a silent zombie."""
    out: List[ast.AST] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d.endswith("Thread"):
            is_daemon = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            if is_daemon:
                for kw in node.keywords:
                    if kw.arg == "target":
                        out.append(kw.value)
        elif d.endswith("Timer"):
            for kw in node.keywords:
                if kw.arg == "function":
                    out.append(kw.value)
            if len(node.args) >= 2:
                out.append(node.args[1])
    return out


#: method -> (what it is, the bounded idiom to suggest)
_UNBOUNDED_BLOCKERS = {
    "acquire": ("Lock.acquire()", "acquire(timeout=...)"),
    "wait": ("Condition/Event.wait()", "wait(timeout=...) in a loop"),
    "get": ("Queue.get()", "get(timeout=...) in a loop"),
}


def _call_is_bounded(name: str, call: ast.Call) -> bool:
    """True when the blocking call carries a bound (or cannot block):
    per-method argument semantics, conservative (an unknown expression
    in a blocking/timeout position passes — never guess a finding)."""
    args = call.args
    kwargs = {kw.arg: kw.value for kw in call.keywords}
    timeout = kwargs.get("timeout")
    if timeout is not None and not (
        isinstance(timeout, ast.Constant) and timeout.value is None
    ):
        return True
    if name == "wait":
        # wait(timeout) — the first positional IS the bound
        return bool(args)
    if name == "get":
        if len(args) >= 2:
            return True  # get(block, timeout)
        if args:
            a = args[0]
            if isinstance(a, ast.Constant):
                # get(False) cannot block; get(True) blocks unbounded.
                # Any other constant first arg is a dict.get(key) — not
                # a queue at all
                return a.value is not True
            return True  # get(<expr>): dict.get(key) shape, pass
        blk = kwargs.get("block")
        if blk is not None and not (
            isinstance(blk, ast.Constant) and blk.value is True
        ):
            return True  # block=False (or unknown) cannot be pinned
        return False
    if name == "acquire":
        if len(args) >= 2:
            return True  # acquire(blocking, timeout)
        blocking = args[0] if args else kwargs.get("blocking")
        if blocking is not None:
            if isinstance(blocking, ast.Constant):
                # acquire(False) is a try-lock (cannot block);
                # acquire(True) blocks unbounded
                return blocking.value is not True
            return True  # unknown expression: don't guess
        return False
    return True


@rule(
    "PTL008",
    "unbounded blocking primitive (acquire()/wait()/get() without a "
    "timeout) on a daemon-thread code path",
)
def check_unbounded_daemon_blocking(sf: SourceFile,
                                    ctx: LintContext) -> Iterable[Finding]:
    """The hang-defense stack (PR 4) can only forensically report a
    thread that eventually RUNS: a daemon parked forever on an
    uninstrumented primitive never dumps a stack, never pings, and
    survives as a silent zombie past every watchdog. On code reachable
    from a daemon-thread target, ``lock.acquire()`` /
    ``cv.wait()`` / ``queue.get()`` must carry a timeout and re-check
    their predicate (a spurious wake re-loop is free; an unreportable
    block is not)."""
    daemon_side = _reachable_functions(sf, _daemon_entry_refs(sf))
    out: List[Finding] = []
    for fn in daemon_side:
        for call in ast.walk(fn):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)):
                continue
            name = call.func.attr
            if name not in _UNBOUNDED_BLOCKERS:
                continue
            if _call_is_bounded(name, call):
                continue
            what, idiom = _UNBOUNDED_BLOCKERS[name]
            out.append(Finding(
                rule="PTL008", path=sf.rel, line=call.lineno,
                col=call.col_offset,
                end_line=getattr(call, "end_lineno", 0) or 0,
                message=(
                    f"unbounded `{dotted(call.func) or '.' + name}()` "
                    f"({what}) on the daemon-thread path "
                    f"`{getattr(fn, 'name', '?')}` — a daemon parked "
                    "forever on an uninstrumented primitive is invisible "
                    f"to the hang-defense stack; use `{idiom}` and "
                    "re-check the predicate"
                ),
                snippet=sf.snippet(call.lineno),
            ))
    return out


# ------------------------------------------------------------- PTL006

_EXIT_CALLS = {"os._exit", "sys.exit", "exit"}


def _is_instrumented(sf: SourceFile) -> bool:
    """A module that writes telemetry records: any ``*.emit("kind")`` /
    ``emit("kind")`` call with a literal kind."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if (d == "emit" or d.endswith(".emit")) and str_arg0(node):
                return True
    return False


def _in_main_guard(sf: SourceFile, lineno: int) -> bool:
    """Inside ``if __name__ == "__main__":`` — the process-entry idiom
    where ``sys.exit(main())`` runs atexit (and so the metrics flush
    hook) normally."""
    for node in sf.tree.body:
        if isinstance(node, ast.If):
            src = ast.unparse(node.test)
            if "__name__" in src and "__main__" in src:
                end = getattr(node, "end_lineno", node.lineno) or node.lineno
                if node.lineno <= lineno <= end:
                    return True
    return False


@rule(
    "PTL006",
    "exit path (os._exit/sys.exit/raise SystemExit) in an instrumented "
    "module without a preceding metrics flush",
)
def check_exit_without_flush(sf: SourceFile, ctx: LintContext) -> Iterable[Finding]:
    """The crash-evidence discipline: a record flushed BEFORE the death
    is the only record that exists after it. ``os._exit`` skips atexit
    entirely; explicit exits in instrumented modules must therefore be
    dominated by a ``flush()`` call in the same function (the pattern
    the fault injector and hangwatch established)."""
    if not _is_instrumented(sf):
        return []
    # function node -> flush-call line numbers, exit nodes
    out: List[Finding] = []
    funcs = [
        n for n in ast.walk(sf.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    owner: Dict[int, ast.AST] = {}
    for fn in funcs:
        for sub in ast.walk(fn):
            owner[id(sub)] = fn  # innermost wins (walk order outer->inner)
    for node in ast.walk(sf.tree):
        exit_desc = None
        if isinstance(node, ast.Call) and dotted(node.func) in _EXIT_CALLS:
            exit_desc = f"{dotted(node.func)}()"
        elif isinstance(node, ast.Raise) and node.exc is not None:
            d = dotted(node.exc) or (
                dotted(node.exc.func) if isinstance(node.exc, ast.Call) else ""
            )
            if d == "SystemExit":
                exit_desc = "raise SystemExit"
        if exit_desc is None:
            continue
        if _in_main_guard(sf, node.lineno):
            continue
        fn = owner.get(id(node))
        flushed = False
        if fn is not None:
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Call)
                    and dotted(sub.func).split(".")[-1] == "flush"
                    and sub.lineno < node.lineno
                ):
                    flushed = True
                    break
        if not flushed:
            out.append(Finding(
                rule="PTL006", path=sf.rel, line=node.lineno,
                col=node.col_offset,
                end_line=getattr(node, "end_lineno", 0) or 0,
                message=(
                    f"`{exit_desc}` in an instrumented module without a "
                    "preceding metrics flush — flush the evidence BEFORE "
                    "the death (fault records survive their own exit)"
                ),
                snippet=sf.snippet(node.lineno),
            ))
    return out
