"""PTL001 (wall-clock in hot paths) and PTL002 (host syncs in hot
loops) — the timing and overlap invariants from the telemetry and
zero-stall-host work.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set, Tuple

from paddle_tpu.analysis.core import (
    Finding,
    LintContext,
    SourceFile,
    dotted,
    path_matches,
    rule,
)

# ------------------------------------------------------------- PTL001

# every reading of civil time the stdlib offers under two module names.
# time.monotonic()/perf_counter() are the sanctioned clocks.
_WALL_CLOCK = {
    "time.time",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.date.today",
    "date.today",
}


@rule(
    "PTL001",
    "wall-clock read (time.time/datetime.now) in a hot-path module — "
    "records carry monotonic t-offsets",
)
def check_wall_clock(sf: SourceFile, ctx: LintContext) -> Iterable[Finding]:
    """The metrics schema's ``t`` is a monotonic offset from
    ``run_start`` — the ONE sanctioned wall-clock read. Any other wall
    clock in an instrumented hot path (observability/, the feeder, the
    trainer step loop, the async checkpointer) re-introduces the
    NTP-step / clock-skew hazards the offset schema exists to avoid."""
    if not any(
        path_matches(sf.rel, p) for p in ctx.config["hot_path_files"]
    ):
        return []
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and dotted(node.func) in _WALL_CLOCK:
            out.append(Finding(
                rule="PTL001", path=sf.rel, line=node.lineno,
                col=node.col_offset,
                end_line=getattr(node, "end_lineno", 0) or 0,
                message=(
                    f"wall-clock read `{dotted(node.func)}()` in a hot-path "
                    "module — use time.monotonic()/time.perf_counter() "
                    "(the t-offset schema contract, doc/observability.md)"
                ),
                snippet=sf.snippet(node.lineno),
            ))
    return out


# ------------------------------------------------------------- PTL002

# calls that force a device->host sync regardless of argument
_ALWAYS_SYNC = {"jax.device_get", "jax.block_until_ready"}
# calls that sync when applied to a device value (tainted name)
_SYNC_IF_TAINTED = {"float", "np.asarray", "numpy.asarray", "np.array",
                    "numpy.array"}


def _call_names(call: ast.Call) -> Tuple[str, str]:
    d = dotted(call.func)
    attr = call.func.attr if isinstance(call.func, ast.Attribute) else ""
    return d, attr


def _assigned_names(target: ast.AST) -> List[str]:
    """Top-level bound names only: ``a, (b, *c) = ...`` -> a, b, c.
    Attribute/subscript targets bind no local name (``self.x = ...``
    must not taint ``self``)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Starred):
        return _assigned_names(target.value)
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in target.elts:
            out.extend(_assigned_names(e))
        return out
    return []


class _HotLoopVisitor(ast.NodeVisitor):
    """Taint walk over ONE hot-loop function: names assigned from
    device-producing calls (``*.call``, ``*_step``, ``launch_fn``) are
    device values; reading one back on host (`float()`, `.item()`,
    `np.asarray()`, `jax.device_get`, `block_until_ready`) inside a
    for/while body is a per-step stall and gets flagged. A flagged sync
    un-taints its assignment targets (the value is host-side after)."""

    def __init__(self, sf: SourceFile, source_res: List[re.Pattern]):
        self.sf = sf
        self.source_res = source_res
        self.tainted: Set[str] = set()
        self.loop_depth = 0
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[int, int]] = set()  # loops re-scan their test

    # ---- helpers

    def _is_device_source(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d, _ = _call_names(node)
        return bool(d) and any(r.search(d) for r in self.source_res)

    def _has_tainted_name(self, node: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Name) and n.id in self.tainted
            for n in ast.walk(node)
        )

    def _flag(self, node: ast.AST, what: str) -> None:
        key = (node.lineno, node.col_offset)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(
            rule="PTL002", path=self.sf.rel, line=node.lineno,
            col=node.col_offset,
            end_line=getattr(node, "end_lineno", 0) or 0,
            message=(
                f"host sync `{what}` inside the hot loop — every launch "
                "stalls on it; hoist it to a window boundary or keep the "
                "value on device"
            ),
            snippet=self.sf.snippet(node.lineno),
        ))

    def _scan_syncs(self, node: ast.AST) -> bool:
        """Flag sync calls under ``node`` (when inside a loop). Returns
        True when one was found (the statement's value is host-side)."""
        found = False
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            d, attr = _call_names(call)
            sync = None
            if d in _ALWAYS_SYNC or attr == "block_until_ready":
                sync = d or f".{attr}()"
            elif attr == "item" and call.args == [] and self._has_tainted_name(
                call.func
            ):
                sync = ".item()"
            elif d in _SYNC_IF_TAINTED and call.args and self._has_tainted_name(
                call.args[0]
            ):
                sync = f"{d}()"
            if sync is not None:
                found = True
                if self.loop_depth > 0:
                    self._flag(call, sync)
        return found

    # ---- statements (taint flows through assignments in source order)

    def _handle_assign(self, node, targets, value) -> None:
        names = []
        for t in targets:
            names.extend(_assigned_names(t))
        synced = self._scan_syncs(value)
        if self._is_device_source(value) or (
            isinstance(value, ast.Tuple)
            and any(self._is_device_source(e) for e in value.elts)
        ):
            self.tainted.update(names)
        elif synced or not self._has_tainted_name(value):
            # host-side now (or reassigned from untainted expression)
            self.tainted.difference_update(names)
        else:
            # tainted rhs propagates (e.g. `x = losses[0]`)
            self.tainted.update(names)
        self.generic_visit_stmts(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._handle_assign(node, node.targets, node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._scan_syncs(node.value)

    def visit_Expr(self, node: ast.Expr) -> None:
        self._scan_syncs(node.value)

    def generic_visit_stmts(self, node) -> None:
        pass

    def visit_For(self, node: ast.For) -> None:
        self._scan_syncs(node.iter)
        if self._is_device_source(node.iter):
            self.tainted.update(_assigned_names(node.target))
        self.loop_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        self.loop_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_While(self, node: ast.While) -> None:
        # the test re-evaluates EVERY iteration — scan it at loop depth
        # (unlike a for's iter, which evaluates once at entry), and
        # AGAIN after the body so loop-carried taint (`loss` assigned
        # inside, read by the next iteration's test) is seen; _flag
        # dedupes the doubly-scanned site
        self.loop_depth += 1
        self._scan_syncs(node.test)
        for stmt in node.body:
            self.visit(stmt)
        self._scan_syncs(node.test)
        self.loop_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_FunctionDef(self, node) -> None:
        pass  # nested defs run on their own schedule, not per-step

    visit_AsyncFunctionDef = visit_FunctionDef

    def generic_visit(self, node: ast.AST) -> None:
        # default: scan expressions for syncs, recurse into bodies
        for fname in ("test", "value", "exc"):
            sub = getattr(node, fname, None)
            if isinstance(sub, ast.AST):
                self._scan_syncs(sub)
        for fname in ("body", "orelse", "finalbody", "handlers"):
            for stmt in getattr(node, fname, []) or []:
                if isinstance(stmt, ast.stmt) or isinstance(
                    stmt, ast.excepthandler
                ):
                    self.visit(stmt)


@rule(
    "PTL002",
    "device->host sync (float/.item/np.asarray/device_get/"
    "block_until_ready) inside a hot step/serve loop",
)
def check_host_sync(sf: SourceFile, ctx: LintContext) -> Iterable[Finding]:
    """The zero-stall-host work moved every per-step host cost off the
    critical path; one stray ``float(loss)`` per batch silently undoes
    it. Syncs that are part of the design (the one documented
    device->host transfer per launch, the nonfinite gate) carry
    `# lint: disable=PTL002 -- reason` suppressions at the call site —
    the reason IS the documentation."""
    funcs = [
        name
        for pat, name in ctx.config["hot_loop_funcs"]
        if path_matches(sf.rel, pat)
    ]
    if not funcs:
        return []
    source_res = [re.compile(r) for r in ctx.config["device_source_res"]]
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name in funcs
        ):
            v = _HotLoopVisitor(sf, source_res)
            for stmt in node.body:
                v.visit(stmt)
            out.extend(v.findings)
    return out
