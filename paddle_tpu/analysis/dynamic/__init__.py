"""`paddle race` — deterministic schedule exploration for the
framework's daemon-thread and commit-agreement paths.

PR 9's `paddle lint` (the static half) can say "this write LOOKS
unlocked"; this package runs the REAL code — the async checkpoint
writers, the hangwatch monitor, the heartbeat renewer, the feeder
pool's bounded-queue discipline — under a virtualized threading layer
(`paddle_tpu/utils/concurrency.py` is the seam) and a deterministic,
seeded scheduler, then mechanically detects:

- **torn reads** (``detector=torn_read``): happens-before race
  detection over watched shared attributes — the watch lists are
  seeded from the same analysis PTL005 runs statically, so "static
  finds the fields, dynamic proves the race";
- **lock-order inversions** (``detector=lock_order``): the union
  lock-order graph across every explored schedule, cycle ⇒ potential
  deadlock even if no explored schedule hit it;
- **lost wakeups / deadlocks** (``detector=lost_wakeup`` /
  ``deadlock``): a quiesced schedule with a non-daemon thread parked
  forever on a wait no future notify can reach;
- **schedule-dependent crashes** (``detector=spec_error``): a spec
  assertion or exception that only some interleaving triggers.

Everything is replayable: a finding carries the seed + thread-switch
trace, and re-running ``paddle race --spec NAME --seed N --schedules
K`` reproduces the whole run bit-for-bit. jax-free by construction —
the specs drive the real classes through their injectable seams.
"""

from paddle_tpu.analysis.dynamic.explore import (  # noqa: F401
    DETECTORS,
    Explorer,
    SpecContext,
    load_specs,
)
from paddle_tpu.analysis.dynamic.shim import (  # noqa: F401
    ScheduleAbort,
    Scheduler,
    VirtualProvider,
)
