"""``paddle race [--seed N] [--schedules K] [--json]`` — the CLI.

jax-free like `paddle lint`: the specs drive the real daemon-thread
code through its injectable seams, so the whole run executes before
(and without) the accelerator runtime. Exit codes mirror lint: 0 = no
new (non-baselined) findings, 1 = new findings, 2 = usage/baseline
errors.

``--json`` emits one schema-v1 record per finding
(``kind=race_finding``) plus a closing ``kind=race_summary`` with
per-detector counts — the artifact ``paddle compare`` diffs between
two race runs (growth in any detector ⇒ REGRESSION, exit 1).

Replay: the executed schedule set is a pure function of
``(--seed, --schedules)`` per spec, so re-running the printed command
reproduces any finding bit-for-bit; every finding also prints its
thread-switch trace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from paddle_tpu.analysis import baseline as bl
from paddle_tpu.analysis.core import find_repo_root
from paddle_tpu.analysis.dynamic.explore import (
    DETECTORS,
    Explorer,
    SpecResult,
    load_specs,
)

RACE_BASELINE_NAME = ".paddle_race_baseline.json"
DEFAULT_SEED = 0
DEFAULT_SCHEDULES = 24


def default_specs_dir() -> str:
    root = find_repo_root([os.getcwd()])
    return os.path.join(root, "tests", "race_specs")


def summary_record(results: List[SpecResult], seed: int) -> Dict[str, Any]:
    """kind=race_summary (doc/observability.md): the per-detector count
    surface ``paddle compare`` diffs between two race runs."""
    new = [f for r in results for f in r.findings if not f.baselined]
    base = sum(
        1 for r in results for f in r.findings if f.baselined
    )
    counts: Dict[str, int] = {}
    for f in new:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "v": 1, "kind": "race_summary", "host": 0, "t": 0.0,
        "findings": len(new),
        "baselined": base,
        "counts": counts,
        "detectors": list(DETECTORS),
        "specs": [r.spec for r in results],
        "schedules": sum(r.schedules_run for r in results),
        "exhaustive": [r.spec for r in results if r.exhaustive],
        "truncated": sum(r.truncated for r in results),
        "seed": seed,
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle race",
        description=(
            "deterministic schedule explorer + lock-order/torn-read/"
            "lost-wakeup analyzer for the daemon-thread paths "
            "(doc/static_analysis.md, 'Dynamic analysis')"
        ),
    )
    p.add_argument("--seed", type=int, default=DEFAULT_SEED,
                   help=f"schedule seed (default {DEFAULT_SEED}); the run "
                        "is a pure function of (seed, schedules)")
    p.add_argument("--schedules", type=int, default=DEFAULT_SCHEDULES,
                   help="schedule budget per spec (default "
                        f"{DEFAULT_SCHEDULES}): first half bounded-DFS "
                        "(exhaustive when the tree fits), rest "
                        "seeded-random")
    p.add_argument("--spec", action="append", default=None, metavar="NAME",
                   help="run only the named spec(s) (repeatable)")
    p.add_argument("--specs", default=None, metavar="DIR",
                   help="spec directory (default: tests/race_specs under "
                        "the repo root)")
    p.add_argument("--step-cap", type=int, default=20000, dest="step_cap",
                   help="per-schedule scheduling-point cap (livelock "
                        "backstop; capped schedules are counted as "
                        "truncated in the summary)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit JSONL race_finding/race_summary records "
                        "(validate_record-compatible; feed to "
                        "`paddle compare`)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline JSON of grandfathered findings "
                        f"(default: {RACE_BASELINE_NAME} at the repo "
                        "root, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline (report every finding as new)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline file "
                        "and exit 0 (grandfathering)")
    p.add_argument("--list", action="store_true", dest="list_specs",
                   help="list discovered specs and exit")
    args = p.parse_args(argv)

    specs_dir = args.specs or default_specs_dir()
    try:
        specs = load_specs(specs_dir, names=args.spec)
    except (OSError, KeyError, AssertionError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.list_specs:
        for s in specs:
            doc_lines = (s.__doc__ or "").strip().splitlines()
            head = doc_lines[0] if doc_lines else ""
            print(f"{s.NAME}  ({os.path.basename(s.__file__)}): {head}")
        return 0
    if not specs:
        print(f"error: no spec_*.py under {specs_dir!r}", file=sys.stderr)
        return 2

    repo_root = find_repo_root([specs_dir])
    baseline_path = args.baseline or os.path.join(repo_root,
                                                  RACE_BASELINE_NAME)
    baseline = None
    if (not args.no_baseline and not args.write_baseline
            and os.path.isfile(baseline_path)):
        try:
            baseline = bl.load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"error: cannot load baseline: {e}", file=sys.stderr)
            return 2

    explorer = Explorer(seed=args.seed, schedules=args.schedules,
                        step_cap=args.step_cap)
    results = explorer.run(specs)

    findings = [f for r in results for f in r.findings]
    if baseline:
        allowed: Dict[str, int] = {}
        for ent in baseline.get("findings", []):
            fp = ent.get("fingerprint")
            if fp:
                allowed[fp] = allowed.get(fp, 0) + 1
        for f in findings:
            if allowed.get(f.fingerprint, 0) > 0:
                allowed[f.fingerprint] -= 1
                f.baselined = True

    if args.write_baseline:
        path = args.baseline or os.path.join(repo_root, RACE_BASELINE_NAME)
        bl.write_baseline(path, findings)
        print(f"wrote {len(findings)} finding(s) to {path}", file=sys.stderr)
        return 0

    new = [f for f in findings if not f.baselined]
    if args.as_json:
        for f in findings:
            print(json.dumps(f.record()))
        print(json.dumps(summary_record(results, args.seed)))
    else:
        for f in findings:
            print(f.render())
        for r in results:
            cov = "exhaustive" if r.exhaustive else "budgeted"
            trunc = (f", {r.truncated} truncated at --step-cap"
                     if r.truncated else "")
            print(f"# {r.spec}: {r.schedules_run} schedule(s) [{cov}], "
                  f"{r.steps} scheduling points{trunc}")
        print(
            f"# {len(new)} new finding(s), {len(findings) - len(new)} "
            f"baselined, {len(results)} spec(s), seed={args.seed} — replay "
            f"any finding with: paddle race --seed {args.seed} "
            f"--schedules {args.schedules} --spec <name>"
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
