"""Virtualized concurrency primitives + the deterministic scheduler.

The mechanism: every virtual thread is a REAL thread, but gated — the
scheduler opens exactly one gate at a time, and the running thread
hands control back at every *scheduling point* (lock acquire, cv
wait/notify, event set/wait, queue put/get, sleep, thread start/join,
watched-attribute access). Execution is therefore fully serialized,
and the interleaving is a pure function of the scheduler's choice
sequence — which is how a finding replays from a seed.

Time is virtual: ``monotonic()`` reads the scheduler's clock, and the
clock only advances when nothing is runnable but somebody is blocked
with a deadline (a timed wait, a sleep, a timer) — so a spec that
exercises a 60 s watchdog timeout costs microseconds.

Vector clocks ride along for the happens-before race detector: lock
release/acquire, cv notify, event set, queue put/get, and thread
start/join all transfer clocks, so two accesses to a watched attribute
race exactly when no chain of synchronization orders them — the
detection does NOT need the losing interleaving to actually occur in
the explored schedule.

Everything here is stdlib-only and jax-free.
"""

from __future__ import annotations

import os
import queue as _queue
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

#: real-time ceiling on one grant: if the resumed thread neither pauses
#: nor finishes within this many WALL seconds, it blocked on something
#: the shim cannot see (a real lock held cross-thread, real I/O wedge)
#: — a harness error, reported loudly, never a silent hang of the run
REAL_STALL_S = 20.0

from paddle_tpu.utils import concurrency as _cc

# frames never used to NAME a primitive or an access: the shim itself,
# the seam module (cc.Lock() must be named after ITS caller), the
# explorer driving the spec, and the real threading module hosting the
# gated threads
_SHIM_FILES = (
    os.path.abspath(__file__),
    os.path.abspath(_cc.__file__),
    os.path.abspath(__file__).replace("shim.py", "explore.py"),
    os.path.abspath(threading.__file__),
)


class ScheduleAbort(BaseException):
    """Raised inside a virtual thread to unwind it at schedule end —
    BaseException (like SystemExit) so ``except Exception`` handlers in
    the code under test don't swallow the teardown. Equivalent to the
    daemon-kill at process exit, which is what schedule end models."""


class HarnessError(RuntimeError):
    """The shim was used in a way the scheduler cannot serialize."""


def _vjoin(dst: Dict[int, int], src: Dict[int, int]) -> None:
    for k, v in src.items():
        if v > dst.get(k, 0):
            dst[k] = v


def call_site() -> Tuple[str, int, str]:
    """(filename, lineno, funcname) of the nearest caller frame outside
    the shim and the seam — how primitives and accesses get named
    after the code under test, not after this machinery."""
    skip = set(_SHIM_FILES)
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) not in skip and not fn.startswith("<"):
            return fn, f.f_lineno, f.f_code.co_name
        f = f.f_back
    return "?", 0, "?"


class _VThread:
    __slots__ = (
        "tid", "name", "daemon", "target", "args", "kwargs", "state",
        "block_kind", "block_desc", "block_obj", "deadline", "wake_reason",
        "pending_vc", "vc", "held", "exc", "killed", "finished", "go",
        "paused", "real", "pending_op", "joiners",
    )

    def __init__(self, tid: int, name: str, daemon: bool, target, args, kwargs):
        self.tid = tid
        self.name = name
        self.daemon = daemon
        self.target = target
        self.args = args
        self.kwargs = kwargs
        self.state = "runnable"    # runnable | running | blocked | finished
        self.block_kind = ""
        self.block_desc = ""
        self.block_obj: Any = None
        self.deadline: Optional[float] = None
        self.wake_reason: Optional[str] = None
        self.pending_vc: Optional[Dict[int, int]] = None
        self.vc: Dict[int, int] = {}
        self.held: List["VLock"] = []
        self.exc: Optional[BaseException] = None
        self.killed = False
        self.finished = False
        self.go = threading.Event()
        self.paused = threading.Event()
        self.real: Optional[threading.Thread] = None
        self.pending_op = "spawn"
        self.joiners: List["_VThread"] = []


class Scheduler:
    """One schedule's worth of serialized execution (see module doc).

    ``chooser(k) -> int`` picks among the k runnable threads at every
    branch point (k > 1); the recorded ``choices`` list of (pick, k)
    is the schedule's identity. Detector raw material accumulates in
    ``access_races`` / ``lock_edges`` / ``quiesce`` for explore.py."""

    def __init__(self, chooser: Callable[[int], int], step_cap: int = 20000):
        self.chooser = chooser
        self.step_cap = step_cap
        self.now = 0.0
        self.steps = 0
        self.active = False
        self.truncated = False
        self.harness_stall: Optional[str] = None
        self.threads: List[_VThread] = []
        self.trace: List[Tuple[str, str]] = []
        self.choices: List[Tuple[int, int]] = []
        self._tls = threading.local()
        # detectors' raw material
        self.access_log: Dict[Tuple[int, str], Dict[str, Any]] = {}
        self.access_races: List[Dict[str, Any]] = []
        self.lock_edges: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.quiesce: List[Dict[str, Any]] = []
        self._next_tid = 0

    # ------------------------------------------------------------- plumbing

    def _cur(self) -> Optional[_VThread]:
        return getattr(self._tls, "vt", None)

    def _require(self) -> _VThread:
        vt = self._cur()
        if vt is None:
            raise HarnessError(
                "virtual primitive used from a thread the scheduler does "
                "not manage (create threads via cc.Thread inside the spec)"
            )
        return vt

    def _pause(self, vt: _VThread) -> None:
        vt.paused.set()
        vt.go.wait()
        vt.go.clear()
        if vt.killed:
            raise ScheduleAbort()

    def yield_point(self, op: str) -> None:
        """Declare a scheduling point: hand control to the scheduler,
        resume when granted. No-op outside managed execution and during
        teardown unwind."""
        vt = self._cur()
        if vt is None:
            return
        if vt.killed:
            raise ScheduleAbort()
        self.steps += 1
        vt.state = "runnable"
        vt.pending_op = op
        self._pause(vt)
        vt.state = "running"

    def block(self, kind: str, desc: str,
              deadline: Optional[float] = None, obj: Any = None) -> str:
        """Park the current thread until woken (returns the wake
        reason: the waker's tag, or "timeout"). ``obj`` is the
        primitive INSTANCE being waited on — wake routing matches on
        identity, never on the site-derived display name (two
        primitives constructed at the same source line must not
        cross-wake each other's waiters)."""
        vt = self._require()
        if vt.killed:
            raise ScheduleAbort()
        self.steps += 1
        vt.state = "blocked"
        vt.block_kind = kind
        vt.block_desc = desc
        vt.block_obj = obj
        vt.deadline = deadline
        vt.pending_op = f"{kind}:{desc}"
        self._pause(vt)
        vt.state = "running"
        if vt.pending_vc is not None:
            _vjoin(vt.vc, vt.pending_vc)
            vt.pending_vc = None
        return vt.wake_reason or "timeout"

    def wake(self, vt: _VThread, reason: str,
             vc: Optional[Dict[int, int]] = None) -> None:
        if vt.finished or vt.state != "blocked":
            return
        vt.state = "runnable"
        vt.wake_reason = reason
        vt.deadline = None
        vt.pending_vc = dict(vc) if vc else None

    # -------------------------------------------------------------- threads

    def spawn(self, target, args=(), kwargs=None, name: Optional[str] = None,
              daemon: bool = False) -> _VThread:
        tid = self._next_tid
        self._next_tid += 1
        vt = _VThread(tid, name or f"T{tid}", daemon, target, args,
                      kwargs or {})
        parent = self._cur()
        if parent is not None:
            vt.vc = dict(parent.vc)
            parent.vc[parent.tid] = parent.vc.get(parent.tid, 0) + 1
        vt.vc[tid] = 1
        self.threads.append(vt)

        def _body():
            self._tls.vt = vt
            vt.go.wait()  # lint: disable=PTL008 -- the controller's gate: every grant path either sets it or kills the vthread (killall), and the run() loop cannot exit while a gated thread exists; a bounded wait would busy-wake every parked virtual thread
            vt.go.clear()
            try:
                if not vt.killed:
                    vt.target(*vt.args, **vt.kwargs)
            except ScheduleAbort:
                pass
            except BaseException as e:  # the schedule's evidence
                vt.exc = e
            finally:
                vt.finished = True
                vt.state = "finished"
                vt.paused.set()

        vt.real = threading.Thread(target=_body, name=f"vsched-{vt.name}",
                                   daemon=True)
        vt.real.start()
        return vt

    def join_thread(self, vt: _VThread, timeout: Optional[float]) -> bool:
        me = self._require()
        self.yield_point(f"join {vt.name}")
        if vt.finished:
            _vjoin(me.vc, vt.vc)
            return True
        deadline = None if timeout is None else self.now + timeout
        vt.joiners.append(me)
        reason = self.block("join", vt.name, deadline, obj=vt)
        if me in vt.joiners:
            vt.joiners.remove(me)
        if reason == "timeout" and not vt.finished:
            return False
        _vjoin(me.vc, vt.vc)
        return True

    # ---------------------------------------------------------- controller

    def run(self, main_fn, name: str = "main") -> "ScheduleResult":
        """Execute ``main_fn`` as the root virtual thread, driving the
        schedule to completion (all non-daemon threads finished), a
        quiesce (reported), or the step cap."""
        assert not self.active, "Scheduler.run is one-shot"
        self.active = True
        main = self.spawn(main_fn, name=name, daemon=False)
        try:
            while True:
                if self.steps > self.step_cap:
                    self.truncated = True
                    break
                if not any(not t.daemon and not t.finished
                           for t in self.threads):
                    break
                runnable = [t for t in self.threads if t.state == "runnable"]
                if not runnable:
                    if not self._advance_clock():
                        self._report_quiesce()
                        break
                    continue
                if len(runnable) > 1:
                    idx = self.chooser(len(runnable))
                    self.choices.append((idx, len(runnable)))
                else:
                    idx = 0
                self._grant(runnable[idx])
                if self.harness_stall:
                    break
        finally:
            self._killall()
            self.active = False
        return ScheduleResult(self, main)

    def _grant(self, vt: _VThread) -> None:
        self.trace.append((vt.name, vt.pending_op))
        vt.state = "running"
        vt.go.set()
        if not vt.paused.wait(REAL_STALL_S):
            self.harness_stall = (
                f"thread {vt.name} neither paused nor finished within "
                f"{REAL_STALL_S}s wall time at op {vt.pending_op!r} — it "
                "blocked on something outside the shim"
            )
            return
        vt.paused.clear()
        if vt.finished:
            for j in list(vt.joiners):
                self.wake(j, "join", vt.vc)
            vt.joiners.clear()

    def _advance_clock(self) -> bool:
        timed = [t for t in self.threads
                 if t.state == "blocked" and t.deadline is not None]
        if not timed:
            return False
        self.now = max(self.now, min(t.deadline for t in timed))
        for t in timed:
            if t.deadline is not None and t.deadline <= self.now:
                self.wake(t, "timeout")
        return True

    def _report_quiesce(self) -> None:
        """Nothing runnable, nothing timed: every blocked thread here is
        parked forever. Non-daemon ⇒ a real deadlock / lost wakeup (a
        daemon parked at idle after main finished is normal shutdown —
        that case never reaches here because the run loop exits first)."""
        for t in self.threads:
            if t.state != "blocked":
                continue
            self.quiesce.append({
                "thread": t.name,
                "daemon": t.daemon,
                "kind": t.block_kind,
                "desc": t.block_desc,
            })

    def _killall(self) -> None:
        for vt in self.threads:
            tries = 0
            while not vt.finished and tries < 3:
                tries += 1
                vt.killed = True
                vt.go.set()
                if not vt.paused.wait(REAL_STALL_S):
                    self.harness_stall = self.harness_stall or (
                        f"thread {vt.name} did not unwind at schedule end"
                    )
                    break
                vt.paused.clear()

    # ------------------------------------------------- detector attach points

    def on_lock_acquired(self, lock: "VLock") -> None:
        vt = self._require()
        _vjoin(vt.vc, lock.vc)
        for held in vt.held:
            if held is lock:
                continue
            edge = (held.site, lock.site)
            if edge not in self.lock_edges:
                fn, line, func = call_site()
                self.lock_edges[edge] = {
                    "from": held.name, "to": lock.name,
                    "at": f"{fn}:{line} ({func})", "thread": vt.name,
                }
        vt.held.append(lock)

    def on_lock_released(self, lock: "VLock") -> None:
        vt = self._require()
        lock.vc = dict(vt.vc)
        vt.vc[vt.tid] = vt.vc.get(vt.tid, 0) + 1
        if lock in vt.held:
            vt.held.remove(lock)

    def on_access(self, obj: Any, label: str, attr: str, kind: str) -> None:
        """A watched-attribute access: a scheduling point AND a
        happens-before check against the last write / outstanding reads
        of the same attribute on the same object."""
        vt = self._cur()
        if vt is None or vt.killed or not self.active:
            return
        fn, line, func = call_site()
        self.yield_point(f"{kind} {label}.{attr} @{os.path.basename(fn)}:{line}")
        key = (id(obj), attr)
        cell = self.access_log.setdefault(
            key, {"label": label, "w": None, "r": {}}
        )

        def ordered(tid: int, clk: int) -> bool:
            return tid == vt.tid or clk <= vt.vc.get(tid, 0)

        site = (fn, line, func)
        if kind == "write":
            prior = []
            w = cell["w"]
            if w is not None and not ordered(w[0], w[1]):
                prior.append(("write", w[2], w[3]))
            for tid, (clk, rsite, rname) in cell["r"].items():
                if not ordered(tid, clk):
                    prior.append(("read", rsite, rname))
            for pkind, psite, pname in prior:
                self.access_races.append({
                    "label": label, "attr": attr,
                    "kind": f"{pkind}-write",
                    "prior_site": psite, "prior_thread": pname,
                    "site": site, "thread": vt.name,
                })
            cell["w"] = (vt.tid, vt.vc.get(vt.tid, 0), site, vt.name)
            cell["r"] = {}
        else:
            w = cell["w"]
            if w is not None and not ordered(w[0], w[1]):
                self.access_races.append({
                    "label": label, "attr": attr, "kind": "write-read",
                    "prior_site": w[2], "prior_thread": w[3],
                    "site": site, "thread": vt.name,
                })
            cell["r"][vt.tid] = (vt.vc.get(vt.tid, 0), site, vt.name)

    # ------------------------------------------------------------ virtual time

    def monotonic(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        vt = self._cur()
        if vt is None:
            return
        self.block("sleep", f"{seconds:g}s", self.now + max(0.0, seconds))


class ScheduleResult:
    """What one executed schedule yields to the explorer."""

    def __init__(self, sched: Scheduler, main: _VThread):
        self.trace = list(sched.trace)
        self.choices = list(sched.choices)
        self.steps = sched.steps
        self.truncated = sched.truncated
        self.harness_stall = sched.harness_stall
        self.access_races = list(sched.access_races)
        self.lock_edges = dict(sched.lock_edges)
        self.quiesce = list(sched.quiesce)
        self.thread_excs = [
            (t.name, t.exc) for t in sched.threads if t.exc is not None
        ]
        self.main_exc = main.exc

    def switch_trace(self, limit: int = 14) -> str:
        """Compact thread-switch rendering: consecutive grants to the
        same thread collapse to ``name:count``."""
        out: List[str] = []
        runs: List[Tuple[str, int]] = []
        for name, _op in self.trace:
            if runs and runs[-1][0] == name:
                runs[-1] = (name, runs[-1][1] + 1)
            else:
                runs.append((name, 1))
        for name, n in runs[:limit]:
            out.append(f"{name}:{n}")
        if len(runs) > limit:
            out.append("…")
        return " → ".join(out)


# ---------------------------------------------------------------- primitives


class VLock:
    def __init__(self, sched: Scheduler, reentrant: bool = False):
        self.sched = sched
        self.reentrant = reentrant
        fn, line, _func = call_site()
        self.site = f"{os.path.basename(fn)}:{line}"
        self.name = f"{'RLock' if reentrant else 'Lock'}@{self.site}"
        self.owner: Optional[_VThread] = None
        self.count = 0
        self.vc: Dict[int, int] = {}

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        s = self.sched
        vt = s._cur()
        if vt is None or vt.killed or not s.active:
            # a primitive owned by a FINISHED schedule (cached in a
            # process-global like the metrics registry), or teardown
            # unwind: execution is serialized, take it plainly
            self.owner, self.count = vt, 1
            return True
        s.yield_point(f"acquire {self.name}")
        if self.reentrant and self.owner is vt:
            self.count += 1
            return True
        deadline = None
        if timeout is not None and timeout >= 0:
            deadline = s.now + timeout
        while True:
            if self.owner is None:
                self.owner = vt
                self.count = 1
                s.on_lock_acquired(self)
                return True
            if not blocking:
                return False
            reason = s.block("lock", self.name, deadline, obj=self)
            if reason == "timeout" and self.owner is not None:
                return False

    def release(self) -> None:
        s = self.sched
        vt = s._cur()
        if vt is None or vt.killed or not s.active:
            self.owner, self.count = None, 0
            return
        if self.owner is not vt:
            raise RuntimeError(f"release of un-acquired {self.name}")
        self.count -= 1
        if self.reentrant and self.count > 0:
            return
        s.on_lock_released(self)
        self.owner = None
        self.count = 0
        for t in s.threads:
            if t.state == "blocked" and t.block_kind == "lock" \
                    and t.block_obj is self:
                s.wake(t, "lock_free")

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class VCondition:
    def __init__(self, sched: Scheduler, lock: Optional[VLock] = None):
        self.sched = sched
        self._lock = lock if lock is not None else VLock(sched)
        fn, line, _func = call_site()
        self.site = f"{os.path.basename(fn)}:{line}"
        self.name = f"Condition@{self.site}"
        self._waiters: List[_VThread] = []

    # lock interface delegates
    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        s = self.sched
        vt = s._require()
        if vt.killed:
            raise ScheduleAbort()
        if not s.active:
            raise HarnessError(f"wait on {self.name} after its schedule")
        if self._lock.owner is not vt:
            raise RuntimeError(f"wait on {self.name} without the lock")
        # full release (cv.wait releases even a reentrantly-held lock)
        saved = self._lock.count
        self._lock.count = 1
        self._lock.release()
        self._waiters.append(vt)
        deadline = None if timeout is None else s.now + timeout
        reason = s.block("cond", self.name, deadline, obj=self)
        if vt in self._waiters:  # timeout path: still registered
            self._waiters.remove(vt)
        # reacquire unconditionally (python semantics)
        self._lock.acquire()
        self._lock.count = saved
        return reason == "notify"

    def notify(self, n: int = 1) -> None:
        s = self.sched
        vt = s._cur()
        if vt is None or vt.killed or not s.active:
            return
        if self._lock.owner is not vt:
            raise RuntimeError(f"notify on {self.name} without the lock")
        s.yield_point(f"notify {self.name}")
        woken, self._waiters = self._waiters[:n], self._waiters[n:]
        vt.vc[vt.tid] = vt.vc.get(vt.tid, 0) + 1
        for w in woken:
            s.wake(w, "notify", vt.vc)

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


class VEvent:
    def __init__(self, sched: Scheduler):
        self.sched = sched
        fn, line, _func = call_site()
        self.site = f"{os.path.basename(fn)}:{line}"
        self.name = f"Event@{self.site}"
        self._flag = False
        self.vc: Dict[int, int] = {}

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        s = self.sched
        vt = s._cur()
        self._flag = True
        if vt is None or vt.killed or not s.active:
            return
        s.yield_point(f"set {self.name}")
        vt.vc[vt.tid] = vt.vc.get(vt.tid, 0) + 1
        _vjoin(self.vc, vt.vc)
        for t in s.threads:
            if t.state == "blocked" and t.block_kind == "event" \
                    and t.block_obj is self:
                s.wake(t, "notify", self.vc)

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        s = self.sched
        if not s.active or s._cur() is None:
            return self._flag  # stale-scheduler primitive: no blocking
        vt = s._require()
        if vt.killed:
            raise ScheduleAbort()
        s.yield_point(f"wait {self.name}")
        if self._flag:
            _vjoin(vt.vc, self.vc)
            return True
        deadline = None if timeout is None else s.now + timeout
        s.block("event", self.name, deadline, obj=self)
        if self._flag:
            _vjoin(vt.vc, self.vc)
        return self._flag


class VQueue:
    def __init__(self, sched: Scheduler, maxsize: int = 0):
        self.sched = sched
        self.maxsize = maxsize
        fn, line, _func = call_site()
        self.site = f"{os.path.basename(fn)}:{line}"
        self.name = f"Queue@{self.site}"
        self._items: List[Tuple[Any, Dict[int, int]]] = []

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        s = self.sched
        vt = s._require()
        if vt.killed:  # unwind: best-effort append, no parking
            self._items.append((item, {}))
            return
        s.yield_point(f"put {self.name}")
        deadline = None if timeout is None else s.now + timeout
        while self.full():
            if not block:
                raise _queue.Full()
            reason = s.block("queue_put", self.name, deadline, obj=self)
            if reason == "timeout" and self.full():
                raise _queue.Full()
        vt.vc[vt.tid] = vt.vc.get(vt.tid, 0) + 1
        self._items.append((item, dict(vt.vc)))
        for t in s.threads:
            if t.state == "blocked" and t.block_kind == "queue_get" \
                    and t.block_obj is self:
                s.wake(t, "item")

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        s = self.sched
        vt = s._require()
        if vt.killed:
            raise ScheduleAbort()
        s.yield_point(f"get {self.name}")
        deadline = None if timeout is None else s.now + timeout
        while not self._items:
            if not block:
                raise _queue.Empty()
            reason = s.block("queue_get", self.name, deadline, obj=self)
            if reason == "timeout" and not self._items:
                raise _queue.Empty()
        item, vc = self._items.pop(0)
        _vjoin(vt.vc, vc)
        for t in s.threads:
            if t.state == "blocked" and t.block_kind == "queue_put" \
                    and t.block_obj is self:
                s.wake(t, "space")
        return item

    def get_nowait(self):
        return self.get(block=False)


class VThreadHandle:
    """What ``cc.Thread(...)`` returns under the shim — the
    ``threading.Thread`` surface the framework uses (start/join/
    is_alive/name/daemon)."""

    def __init__(self, sched: Scheduler, group=None, target=None, name=None,
                 args=(), kwargs=None, daemon: Optional[bool] = None):
        assert group is None
        self.sched = sched
        self._target = target
        self.name = name  # None -> named at start() from the spawn tid
        self.daemon = bool(daemon)
        self._args = args
        self._kwargs = kwargs or {}
        self._vt: Optional[_VThread] = None

    def start(self) -> None:
        if self._vt is not None:
            raise RuntimeError("threads can only be started once")
        s = self.sched
        vt = s._cur()
        if vt is not None and not vt.killed:
            s.yield_point(f"start {self.name or 'thread'}")
        self._vt = s.spawn(self._target, self._args, self._kwargs,
                           name=self.name, daemon=self.daemon)
        self.name = self._vt.name

    def join(self, timeout: Optional[float] = None) -> None:
        if self._vt is None:
            raise RuntimeError("cannot join thread before it is started")
        self.sched.join_thread(self._vt, timeout)

    def is_alive(self) -> bool:
        return self._vt is not None and not self._vt.finished

    @property
    def ident(self):
        return self._vt.tid if self._vt is not None else None


class VTimer(VThreadHandle):
    """``threading.Timer`` twin: fires ``function`` after a VIRTUAL
    ``interval`` unless cancelled — the hangwatch forensics backstop's
    contract."""

    def __init__(self, sched: Scheduler, interval: float, function,
                 args=None, kwargs=None):
        super().__init__(sched, target=self._run)
        self.interval = float(interval)
        self.function = function
        self.fn_args = args or ()
        self.fn_kwargs = kwargs or {}
        self._cancel = VEvent(sched)

    def cancel(self) -> None:
        self._cancel.set()

    def _run(self) -> None:
        if not self._cancel.wait(timeout=self.interval):
            if not self._cancel.is_set():
                self.function(*self.fn_args, **self.fn_kwargs)


class VirtualProvider:
    """The ``concurrency.install()`` payload: constructors bound to one
    scheduler. ``current_thread``/``main_thread`` stay REAL — they back
    "am I allowed to install signal handlers" guards, and a virtual
    thread (a real non-main thread) must answer no there."""

    def __init__(self, sched: Scheduler):
        self.sched = sched

    def Thread(self, *args, **kwargs):
        return VThreadHandle(self.sched, *args, **kwargs)

    def Timer(self, interval, function, args=None, kwargs=None):
        return VTimer(self.sched, interval, function, args, kwargs)

    def Lock(self):
        return VLock(self.sched)

    def RLock(self):
        return VLock(self.sched, reentrant=True)

    def Condition(self, lock=None):
        return VCondition(self.sched, lock)

    def Event(self):
        return VEvent(self.sched)

    def Queue(self, maxsize: int = 0):
        return VQueue(self.sched, maxsize)

    def monotonic(self) -> float:
        return self.sched.monotonic()

    def perf_counter(self) -> float:
        return self.sched.monotonic()

    def sleep(self, seconds: float) -> None:
        self.sched.sleep(seconds)

    current_thread = staticmethod(threading.current_thread)
    main_thread = staticmethod(threading.main_thread)
    get_ident = staticmethod(threading.get_ident)
    enumerate_threads = staticmethod(threading.enumerate)


# ------------------------------------------------------- attribute watching


def watch_object(sched: Scheduler, obj: Any, attrs) -> Any:
    """Instrument ``obj`` so every read/write of the named attributes is
    a scheduling point + a happens-before race check. Implemented by
    swapping the instance's class for a generated subclass — works for
    ordinary (non-slots) classes, which all the watched framework
    classes are. Returns ``obj``."""
    attrs = frozenset(attrs)
    base = type(obj)
    label = base.__name__

    class _Watched(base):  # type: ignore[misc,valid-type]
        def __getattribute__(self, name):
            if name in attrs:
                sched.on_access(self, label, name, "read")
            return base.__getattribute__(self, name)

        def __setattr__(self, name, value):
            if name in attrs:
                sched.on_access(self, label, name, "write")
            base.__setattr__(self, name, value)

    _Watched.__name__ = f"Watched{label}"
    _Watched.__qualname__ = _Watched.__name__
    obj.__class__ = _Watched
    return obj
