"""Schedule enumeration + the three race detectors.

Exploration discipline (the PR-1 fault injector's replay-from-seed
rule, applied to interleavings): the set of schedules executed is a
pure function of ``(spec, seed, schedules)`` —

- **exhaustive for small state spaces**: a bounded-DFS over the choice
  tree (a choice point = a moment more than one virtual thread is
  runnable) runs first, up to half the budget; when the tree fits, the
  sweep is *complete* and the summary says so;
- **seeded-random beyond**: the remaining budget runs schedules whose
  every pick comes from ``random.Random(f"{seed}:{spec}:{i}")`` —
  deterministic across processes and platforms.

Detectors:

- ``torn_read`` — happens-before races on watched shared attributes
  (collected by the shim's vector clocks; see shim.py);
- ``lock_order`` — the UNION lock-order graph across all explored
  schedules; a strongly connected component with ≥2 locks (or a
  self-loop) is a potential deadlock even if no explored schedule
  actually deadlocked;
- ``lost_wakeup`` / ``deadlock`` — a quiesced schedule left a
  non-daemon thread parked forever on a wait (cv/event/queue ⇒ lost
  wakeup, lock ⇒ deadlock) that no runnable or timed thread can ever
  satisfy;
- ``spec_error`` — an exception (including a spec's own invariant
  assertion) only some interleaving raises;
- ``harness`` — the shim could not serialize the spec (real blocking
  outside the seam); loud, because coverage silently shrank.
"""

from __future__ import annotations

import hashlib
import inspect
import os
import random
import re
import shutil
import tempfile
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.rules_concurrency import thread_shared_attrs
from paddle_tpu.analysis.dynamic import shim
from paddle_tpu.utils import concurrency as cc

DETECTORS = ("torn_read", "lock_order", "deadlock", "lost_wakeup",
             "spec_error", "harness")

#: drop ``:<line>`` from primitive names when fingerprinting — findings
#: must survive edits that only shift lines (same rule as lint's
#: snippet-hash fingerprints)
_LINE_RE = re.compile(r":\d+")


@dataclass
class RaceFinding:
    """One dynamic finding. Field names mirror analysis.core.Finding
    where they overlap (``rule`` is the detector id) so the PR-9
    baseline machinery (analysis/baseline.py) serializes these
    unchanged."""

    rule: str            # detector id, one of DETECTORS
    spec: str
    message: str
    path: str = ""       # repo-relative primary site
    line: int = 0
    col: int = 0
    snippet: str = ""
    fingerprint: str = ""
    baselined: bool = False
    seed: int = 0
    schedule: str = ""   # e.g. "dfs[1,0]" or "rand#7"
    trace: str = ""      # compact thread-switch trace

    def render(self) -> str:
        loc = f"{self.path}:{self.line}: " if self.path else ""
        base = (f"{loc}{self.rule} [{self.spec}] {self.message}"
                + ("  [baselined]" if self.baselined else ""))
        if self.trace:
            base += (f"\n    replay: seed={self.seed} schedule={self.schedule}"
                     f"  trace: {self.trace}")
        return base

    def record(self) -> Dict[str, Any]:
        """The ``--json`` shape: schema-v1 ``kind=race_finding``
        (doc/observability.md), same discipline as lint_finding."""
        return {
            "v": 1, "kind": "race_finding", "host": 0, "t": 0.0,
            "detector": self.rule, "spec": self.spec, "path": self.path,
            "line": self.line, "message": self.message,
            "snippet": self.snippet, "fingerprint": self.fingerprint,
            "baselined": self.baselined, "seed": self.seed,
            "schedule": self.schedule, "trace": self.trace,
        }


@dataclass
class SpecResult:
    spec: str
    findings: List[RaceFinding] = field(default_factory=list)
    schedules_run: int = 0
    exhaustive: bool = False
    truncated: int = 0   # schedules that hit the step cap
    steps: int = 0


def _fp(*parts: str) -> str:
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


def _rel(path: str) -> str:
    """Repo-relative rendering of a site path (best-effort)."""
    from paddle_tpu.analysis.core import find_repo_root

    root = find_repo_root([os.getcwd()])
    ap = os.path.abspath(path)
    if ap.startswith(root + os.sep):
        return os.path.relpath(ap, root).replace(os.sep, "/")
    return path


def _site_str(site: Tuple[str, int, str]) -> str:
    fn, line, func = site
    return f"{_rel(fn)}:{line} ({func})"


def _stable_site(site: Tuple[str, int, str]) -> str:
    fn, _line, func = site
    return f"{os.path.basename(fn)}:{func}"


class SpecContext:
    """Handed to ``spec.run(ctx)``. The spec constructs the code under
    test as usual (the concurrency seam is already virtualized when
    run() executes), spawns contention via ``cc.Thread``, and registers
    watch lists here."""

    def __init__(self, sched: shim.Scheduler):
        self.sched = sched
        self.cc = cc
        self._tmpdir: Optional[str] = None

    @property
    def tmpdir(self) -> str:
        if self._tmpdir is None:
            self._tmpdir = tempfile.mkdtemp(prefix="paddle_race_")
        return self._tmpdir

    def watch(self, obj: Any, *attrs: str) -> Any:
        """Watch explicit attributes of ``obj`` for torn reads."""
        return shim.watch_object(self.sched, obj, attrs)

    def static_watch(self, obj: Any, extra: Iterable[str] = ()) -> Set[str]:
        """Watch ``obj`` with the PTL005-derived watch list: every
        self-attribute the static analysis sees referenced on a
        thread-run path of the class's module — static finds the
        fields, dynamic proves (or clears) the race."""
        src_file = inspect.getsourcefile(type(obj))
        attrs: Set[str] = set(extra)
        if src_file and os.path.exists(src_file):
            with open(src_file, encoding="utf-8") as f:
                attrs |= thread_shared_attrs(f.read(), src_file)
        if attrs:
            shim.watch_object(self.sched, obj, attrs)
        return attrs

    def _cleanup(self) -> None:
        if self._tmpdir is not None:
            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None


# ------------------------------------------------------------ spec loading


def load_specs(specs_dir: str,
               names: Optional[Sequence[str]] = None) -> List[Any]:
    """Import every ``spec_*.py`` under ``specs_dir`` (sorted — the
    run order is part of determinism). A spec module must define
    ``NAME`` (str) and ``run(ctx)``."""
    import importlib.util

    out = []
    if not os.path.isdir(specs_dir):
        raise FileNotFoundError(f"race specs directory {specs_dir!r} missing")
    for fname in sorted(os.listdir(specs_dir)):
        if not (fname.startswith("spec_") and fname.endswith(".py")):
            continue
        mod_name = f"paddle_race_specs.{fname[:-3]}"
        spec = importlib.util.spec_from_file_location(
            mod_name, os.path.join(specs_dir, fname)
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert hasattr(mod, "NAME") and hasattr(mod, "run"), (
            f"{fname}: a race spec must define NAME and run(ctx)"
        )
        if names and mod.NAME not in names:
            continue
        out.append(mod)
    if names:
        known = {m.NAME for m in out}
        missing = [n for n in names if n not in known]
        if missing:
            raise KeyError(f"unknown spec(s): {', '.join(missing)}")
    return out


# --------------------------------------------------------------- explorer


class Explorer:
    def __init__(self, seed: int = 0, schedules: int = 30,
                 step_cap: int = 20000):
        self.seed = int(seed)
        self.schedules = max(1, int(schedules))
        self.step_cap = step_cap

    # one schedule

    def _execute(self, spec, chooser, sched_id: str,
                 result: SpecResult,
                 edges: Dict[Tuple[str, str], Dict[str, str]]):
        sched = shim.Scheduler(chooser, step_cap=self.step_cap)
        ctx = SpecContext(sched)
        cc.install(shim.VirtualProvider(sched))
        try:
            run = sched.run(lambda: spec.run(ctx))
        finally:
            cc.uninstall()
            ctx._cleanup()
            # metrics the code under test touched were created with
            # THIS schedule's virtual locks (the registry is process-
            # global); drop them so later real-threaded users get fresh
            # counters with real locks — same reset discipline the test
            # suites apply between cases
            from paddle_tpu.observability import metrics as obs

            obs.registry().reset()
        result.schedules_run += 1
        result.steps += run.steps
        if run.truncated:
            result.truncated += 1
        self._harvest(spec.NAME, run, sched_id, result)
        edges.update(run.lock_edges)
        return run

    def _add(self, result: SpecResult, f: RaceFinding) -> None:
        if any(g.fingerprint == f.fingerprint for g in result.findings):
            return
        result.findings.append(f)

    def _harvest(self, name: str, run: shim.ScheduleResult, sched_id: str,
                 result: SpecResult) -> None:
        trace = run.switch_trace()
        if run.harness_stall:
            self._add(result, RaceFinding(
                rule="harness", spec=name,
                message=f"unserializable schedule: {run.harness_stall}",
                fingerprint=_fp("harness", name, run.harness_stall[:64]),
                seed=self.seed, schedule=sched_id, trace=trace,
            ))
        for r in run.access_races:
            prior = _site_str(r["prior_site"])
            cur = _site_str(r["site"])
            self._add(result, RaceFinding(
                rule="torn_read", spec=name,
                message=(
                    f"unsynchronized {r['kind']} of `{r['label']}."
                    f"{r['attr']}`: {r['prior_thread']} at {prior} vs "
                    f"{r['thread']} at {cur} — no happens-before edge "
                    "orders them (torn read-modify-write / stale read)"
                ),
                path=_rel(r["site"][0]), line=r["site"][1],
                fingerprint=_fp("torn_read", name, r["label"], r["attr"],
                                *sorted((_stable_site(r["prior_site"]),
                                         _stable_site(r["site"])))),
                seed=self.seed, schedule=sched_id, trace=trace,
            ))
        blocked_forever = [q for q in run.quiesce if not q["daemon"]]
        if blocked_forever:
            others = ", ".join(
                f"{q['thread']}({'daemon' if q['daemon'] else 'non-daemon'} "
                f"in {q['desc']})" for q in run.quiesce
            )
            for q in blocked_forever:
                det = "deadlock" if q["kind"] == "lock" else "lost_wakeup"
                self._add(result, RaceFinding(
                    rule=det, spec=name,
                    message=(
                        f"thread {q['thread']} parked forever in "
                        f"{q['kind']} wait on {q['desc']} with no "
                        f"possible future wake (all parked: {others})"
                    ),
                    fingerprint=_fp(det, name, q["thread"],
                                    _LINE_RE.sub("", q["desc"])),
                    seed=self.seed, schedule=sched_id, trace=trace,
                ))
        excs = list(run.thread_excs)
        if run.main_exc is not None:
            excs.append(("main", run.main_exc))
        seen_exc = set()
        for tname, exc in excs:
            if id(exc) in seen_exc:
                continue
            seen_exc.add(id(exc))
            tb = traceback.extract_tb(exc.__traceback__)
            last = tb[-1] if tb else None
            where = f"{_rel(last.filename)}:{last.lineno}" if last else "?"
            self._add(result, RaceFinding(
                rule="spec_error", spec=name,
                message=(
                    f"{type(exc).__name__} in thread {tname} at {where}: "
                    f"{exc} (raised only under this interleaving)"
                ),
                path=_rel(last.filename) if last else "",
                line=last.lineno if last else 0,
                fingerprint=_fp("spec_error", name, type(exc).__name__,
                                str(exc)[:120]),
                seed=self.seed, schedule=sched_id, trace=trace,
            ))

    # lock-order cycles (union graph, post-run)

    def _lock_order_findings(self, name: str,
                             edges: Dict[Tuple[str, str], Dict[str, str]],
                             result: SpecResult) -> None:
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for comp in _sccs(graph):
            cyclic = len(comp) > 1 or any(
                (n, n) in edges for n in comp
            )
            if not cyclic:
                continue
            inner = sorted(
                (a, b) for (a, b) in edges if a in comp and b in comp
            )
            detail = "; ".join(
                f"{edges[e]['thread']} took {edges[e]['from']} then "
                f"{edges[e]['to']} at {edges[e]['at']}" for e in inner
            )
            self._add(result, RaceFinding(
                rule="lock_order", spec=name,
                message=(
                    "lock-order cycle over {"
                    + ", ".join(sorted(comp))
                    + f"}} — potential deadlock even though no explored "
                      f"schedule wedged: {detail}"
                ),
                fingerprint=_fp("lock_order", name,
                                *sorted(_LINE_RE.sub("", n) for n in comp)),
                seed=self.seed, schedule="union", trace="",
            ))

    # the budgeted sweep

    def run_spec(self, spec) -> SpecResult:
        result = SpecResult(spec=spec.NAME)
        edges: Dict[Tuple[str, str], Dict[str, str]] = {}
        budget = self.schedules
        dfs_budget = max(1, (budget + 1) // 2)
        stack: List[Tuple[int, ...]] = [()]
        stalled = False
        while stack and result.schedules_run < dfs_budget:
            prefix = stack.pop()
            rec: List[Tuple[int, int]] = []

            def chooser(k: int, _p=prefix, _r=rec) -> int:
                i = len(_r)
                pick = _p[i] if i < len(_p) else 0
                pick = min(pick, k - 1)
                _r.append((pick, k))
                return pick

            sched_id = "dfs[" + ",".join(str(c) for c in prefix) + "]"
            run = self._execute(spec, chooser, sched_id, result, edges)
            if run.harness_stall:
                stalled = True
                break  # every schedule would stall the same way
            # push unexplored alternatives at and beyond this prefix.
            # The child MUST spell out the recorded picks up to i (the
            # picks past len(prefix) were implicit 0s): truncating to
            # prefix[:i] would shift `alt` onto the wrong choice point,
            # skipping branches while re-running others.
            picks = [p for p, _k in rec]
            for i in range(len(rec) - 1, len(prefix) - 1, -1):
                _pick, k = rec[i]
                for alt in range(k - 1, 0, -1):
                    stack.append(tuple(picks[:i] + [alt]))
        result.exhaustive = not stack and not stalled
        # seeded-random tail for trees bigger than the DFS half (a
        # harness stall burns REAL_STALL_S of wall clock per schedule —
        # no tail then: every schedule would stall the same way)
        i = 0
        while (not result.exhaustive and not stalled
               and result.schedules_run < budget):
            rng = random.Random(f"{self.seed}:{spec.NAME}:{i}")
            run = self._execute(
                spec, lambda k, _r=rng: _r.randrange(k), f"rand#{i}",
                result, edges,
            )
            i += 1
            if run.harness_stall:
                break
        self._lock_order_findings(spec.NAME, edges, result)
        result.findings.sort(
            key=lambda f: (DETECTORS.index(f.rule), f.path, f.line,
                           f.fingerprint)
        )
        return result

    def run(self, specs: Sequence[Any]) -> List[SpecResult]:
        return [self.run_spec(s) for s in specs]


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work: List[Tuple[str, List[str]]] = [(root, sorted(graph[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, succs = work[-1]
            if succs:
                nxt = succs.pop(0)
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, sorted(graph[nxt])))
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            else:
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp: Set[str] = set()
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.add(w)
                        if w == node:
                            break
                    out.append(comp)
    return out
