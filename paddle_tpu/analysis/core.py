"""Rule engine: source model, suppressions, fingerprints, the runner.

Design constraints, in order:

1. **jax-free and fast.** Pure ``ast`` over source text — linting the
   whole package must finish in seconds so it rides early in tier-1
   even when the CI window truncates the suite.
2. **Stable IDs, greppable findings.** Every rule has a ``PTLnnn`` id;
   a finding renders as ``path:line:col: PTLnnn message`` and carries a
   line-number-independent fingerprint (rule + path + source line), so
   the checked-in baseline survives unrelated edits above a finding.
3. **Suppressions carry their why.** ``# lint: disable=PTL001 -- reason``
   on the finding's line (or a comment-only line above) suppresses it;
   a suppression WITHOUT a reason suppresses nothing and is itself a
   finding (PTL000) — the reason is the documentation the invariant
   would otherwise lose.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------- model

#: rule id -> one-line description. doc/static_analysis.md and the
#: reverse-consistency test in tests/test_lint.py pin this catalog
#: against the documentation (PTL007's discipline applied to the linter
#: itself).
ALL_RULES: Dict[str, str] = {
    "PTL000": "suppression comment missing its mandatory reason",
}

FILE_RULES: List[Tuple[str, Callable]] = []
PROJECT_RULES: List[Tuple[str, Callable]] = []


def rule(rid: str, desc: str, *, project: bool = False):
    """Register a rule. File rules run as ``fn(sf, ctx)`` per parsed
    file; project rules run once as ``fn(ctx)`` (cross-file checks)."""

    def deco(fn):
        assert rid not in ALL_RULES, f"duplicate rule id {rid}"
        ALL_RULES[rid] = desc
        (PROJECT_RULES if project else FILE_RULES).append((rid, fn))
        fn.rule_id = rid
        return fn

    return deco


@dataclass
class Finding:
    rule: str
    path: str  # repo-root-relative posix path
    line: int
    col: int
    message: str
    snippet: str = ""
    fingerprint: str = ""
    baselined: bool = False
    # last line of the flagged node (0 = same as `line`): a suppression
    # trailing a black-style wrapped call sits on the closing-paren
    # line, and must still govern the finding anchored to line 1 of it
    end_line: int = 0

    def render(self) -> str:
        base = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        return base + ("  [baselined]" if self.baselined else "")

    def record(self) -> Dict[str, Any]:
        """The ``--json`` shape: a schema-v1 record ``validate_record``
        accepts (kind=lint_finding, doc/observability.md), so lint
        output flows through the same jsonl tooling as run telemetry
        (``paddle compare`` diffs two lint runs)."""
        return {
            "v": 1, "kind": "lint_finding", "host": 0, "t": 0.0,
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "snippet": self.snippet, "fingerprint": self.fingerprint,
            "baselined": self.baselined,
        }


@dataclass
class Suppression:
    line: int
    ids: Tuple[str, ...]
    reason: Optional[str]


SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=(?P<ids>PTL\d{3}(?:\s*,\s*PTL\d{3})*)"
    r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$"
)


class SourceFile:
    """One parsed module: text, lines, AST, suppression table."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # line -> Suppression. Regex over raw lines: a '#' inside a
        # string literal could false-match, but only for lines that also
        # spell 'lint: disable=' — an accepted non-risk.
        self.suppressions: Dict[int, Suppression] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                ids = tuple(
                    s.strip() for s in m.group("ids").split(",") if s.strip()
                )
                self.suppressions[i] = Suppression(i, ids, m.group("reason"))
        # line -> end line of the innermost SIMPLE statement spanning it
        # (wrapped calls put the natural trailing comment on the closing
        # paren line — the statement span lets a suppression there still
        # govern a finding anchored to an inner line). Compound
        # statements are excluded: a `for` header's span must not let a
        # suppression deep in the body govern the header.
        self.stmt_end: Dict[int, int] = {}
        compound = (
            ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
            ast.AsyncWith, ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
            ast.ClassDef,
        )
        for node in ast.walk(self.tree):
            if isinstance(node, ast.stmt) and not isinstance(node, compound):
                end = getattr(node, "end_lineno", None) or node.lineno
                for ln in range(node.lineno, end + 1):
                    self.stmt_end[ln] = end

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppression_for(self, rid: str, lineno: int,
                        end_lineno: int = 0) -> Optional[Suppression]:
        """A suppression governs its own (code) line — any line of the
        flagged node's span, so a trailing comment after a wrapped
        call's closing paren counts — or, when written as a comment-only
        line, the next line of code below it."""
        end_lineno = max(end_lineno, self.stmt_end.get(lineno, 0))
        for line in range(lineno, max(lineno, end_lineno) + 1):
            sup = self.suppressions.get(line)
            if sup is not None and rid in sup.ids:
                return sup
        above = self.suppressions.get(lineno - 1)
        if (
            above is not None
            and rid in above.ids
            and self.snippet(lineno - 1).startswith("#")
        ):
            return above
        return None


@dataclass
class LintContext:
    files: List[SourceFile]
    repo_root: str
    config: Dict[str, Any]

    def find(self, suffix: str) -> Optional[SourceFile]:
        for sf in self.files:
            if path_matches(sf.rel, suffix):
                return sf
        return None


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # sorted, incl. baselined
    skipped: List[Tuple[str, str]] = field(default_factory=list)  # (path, why)
    stale_baseline: List[str] = field(default_factory=list)  # unmatched fingerprints
    files_scanned: int = 0
    scanned_paths: List[str] = field(default_factory=list)  # repo-relative

    @property
    def new(self) -> List[Finding]:
        return [f for f in self.findings if not f.baselined]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.new:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def summary_record(self) -> Dict[str, Any]:
        """kind=lint_summary (doc/observability.md): the per-rule count
        surface ``paddle compare`` diffs between two lint runs."""
        return {
            "v": 1, "kind": "lint_summary", "host": 0, "t": 0.0,
            "findings": len(self.new),
            "baselined": len(self.findings) - len(self.new),
            "counts": self.counts(),
            "files_scanned": self.files_scanned,
            # coverage honesty: a consumer gating on --json must be able
            # to see that files went unscanned (their findings CANNOT
            # have been found) or that baseline entries went stale
            "skipped": len(self.skipped),
            "stale_baseline": len(self.stale_baseline),
            "rules": sorted(ALL_RULES),
        }


# ------------------------------------------------- shared config/helpers

#: Per-rule scoping the invariants were stated against (rationale in
#: doc/static_analysis.md). Paths are repo-relative suffix patterns:
#: a trailing '/' means "anywhere under a directory of this name".
DEFAULT_CONFIG: Dict[str, Any] = {
    # PTL001: modules whose records carry the monotonic `t`-offset
    # schema contract — wall-clock reads there break restart merging
    "hot_path_files": (
        "observability/",
        "data/feeder.py",
        "trainer/trainer.py",
        "trainer/async_ckpt.py",
        # the serving engine's scheduler loop is a hot path: per-
        # iteration wall-clock reads would tax every decode launch, and
        # its timestamps must stay seam-virtualizable for `paddle race`
        "serving/",
    ),
    # PTL002: (file pattern, function) pairs that ARE the hot loops
    "hot_loop_funcs": (
        ("trainer/trainer.py", "train_one_pass"),
        ("observability/serving.py", "run_rung"),
        ("serving/engine.py", "_loop"),
        # the dispatch/collect split: both engine loop bodies stay
        # sync-free — the ONE sanctioned readback lives in the
        # backend's collect(), at the collect boundary by design
        ("serving/engine.py", "_loop_pipelined"),
        ("serving/engine.py", "_loop_blocking"),
        # the fleet router's scheduling loop: routes every admitted
        # request, so a device sync or unbounded wait here stalls the
        # whole fleet
        ("serving/fleet.py", "run"),
        # the socket transport's I/O loops: every cross-host frame
        # passes through these — a device sync or unbounded wait here
        # stalls heartbeats and the router's health view with them
        ("serving/transport.py", "_run"),
        ("serving/transport.py", "_read_until_disconnect"),
        ("serving/transport.py", "_accept"),
        ("serving/transport.py", "_serve_conn"),
        ("serving/transport.py", "_pump"),
    ),
    # PTL002: calls whose results live on device (taint sources)
    "device_source_res": (r"\.call$", r"_step$", r"^launch_fn$"),
    # PTL005: a `with` context whose source mentions one of these is
    # treated as a lock
    "lock_name_re": r"lock|cv|cond|mutex",
}


def path_matches(rel: str, pattern: str) -> bool:
    rel = "/" + rel.replace(os.sep, "/")
    if pattern.endswith("/"):
        return f"/{pattern}" in rel + "/"
    return rel.endswith("/" + pattern)


def dotted(node: ast.AST) -> str:
    """``a.b.c`` for a Name/Attribute chain, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_arg0(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def walk_calls(tree: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def const_strings(node: ast.AST) -> List[str]:
    """Every string constant anywhere under ``node``."""
    return [
        n.value
        for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]


# --------------------------------------------------------------- runner


def discover_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for name in sorted(names):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    # de-dupe while keeping order (overlapping path args)
    seen: set = set()
    uniq = []
    for p in out:
        ap = os.path.abspath(p)
        if ap not in seen:
            seen.add(ap)
            uniq.append(p)
    return uniq


def root_is_marked(repo_root: str) -> bool:
    """True when ``repo_root`` is a real project root (pyproject/.git)
    rather than the bare-directory fallback. Baseline entry paths are
    only stable across invocations under a marked root, so deletion
    detection (an entry whose file is gone) is gated on it."""
    return os.path.exists(
        os.path.join(repo_root, "pyproject.toml")
    ) or os.path.exists(os.path.join(repo_root, ".git"))


def find_repo_root(paths: Sequence[str]) -> str:
    """Walk up from the first path to the enclosing repo (pyproject.toml
    or .git); fall back to the first path's directory, so fixture trees
    without project files get self-relative finding paths."""
    if not paths:
        return os.getcwd()
    start = os.path.abspath(paths[0])
    if os.path.isfile(start):
        start = os.path.dirname(start)
    d = start
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")) or os.path.exists(
            os.path.join(d, ".git")
        ):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return start
        d = parent


def _fingerprint(findings: List[Finding]) -> None:
    """Line-number-independent fingerprints: hash of (rule, path,
    stripped source line), with an occurrence suffix so N identical
    lines get N distinct prints. Survives edits that only shift lines."""
    seen: Dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        base = hashlib.sha1(
            f"{f.rule}|{f.path}|{f.snippet}".encode()
        ).hexdigest()[:16]
        n = seen.get(base, 0)
        seen[base] = n + 1
        f.fingerprint = base if n == 0 else f"{base}-{n}"


def run_lint(
    paths: Sequence[str],
    baseline: Optional[Dict[str, Any]] = None,
    config: Optional[Dict[str, Any]] = None,
) -> LintResult:
    """Lint ``paths`` (files or directories). ``baseline`` is a loaded
    baseline document (see baseline.py); matched findings are kept but
    marked ``baselined`` so only NEW findings gate the exit code."""
    cfg = dict(DEFAULT_CONFIG)
    if config:
        cfg.update(config)
    repo_root = find_repo_root(paths)
    result = LintResult()
    files: List[SourceFile] = []
    for path in discover_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError) as e:
            result.skipped.append((path, str(e)))
            continue
        rel = os.path.relpath(os.path.abspath(path), repo_root)
        try:
            files.append(SourceFile(path, rel, text))
        except SyntaxError as e:
            result.skipped.append((path, f"syntax error: {e.msg} (line {e.lineno})"))
    result.files_scanned = len(files)
    result.scanned_paths = [sf.rel for sf in files]
    ctx = LintContext(files=files, repo_root=repo_root, config=cfg)

    raw: List[Finding] = []
    for sf in files:
        for rid, fn in FILE_RULES:
            raw.extend(fn(sf, ctx))
        # PTL000: a reason-less suppression suppresses nothing AND is a
        # finding — otherwise "# lint: disable" becomes a free pass
        for sup in sf.suppressions.values():
            if sup.reason is None:
                raw.append(Finding(
                    rule="PTL000", path=sf.rel, line=sup.line, col=0,
                    message=(
                        "suppression missing its mandatory reason — use "
                        "`# lint: disable="
                        + ",".join(sup.ids)
                        + " -- <why this is safe>`"
                    ),
                    snippet=sf.snippet(sup.line),
                ))
    for rid, fn in PROJECT_RULES:
        raw.extend(fn(ctx))

    # suppression pass (PTL000 itself is not suppressible)
    by_rel = {sf.rel: sf for sf in files}
    kept: List[Finding] = []
    for f in raw:
        sf = by_rel.get(f.path)
        if f.rule != "PTL000" and sf is not None:
            sup = sf.suppression_for(f.rule, f.line, f.end_line)
            if sup is not None and sup.reason:
                continue
        if not f.snippet and sf is not None:
            f.snippet = sf.snippet(f.line)
        kept.append(f)

    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    _fingerprint(kept)

    if baseline:
        allowed: Dict[str, int] = {}
        ent_path: Dict[str, str] = {}
        for ent in baseline.get("findings", []):
            fp = ent.get("fingerprint")
            if fp:
                allowed[fp] = allowed.get(fp, 0) + 1
                ent_path[fp] = ent.get("path", "")
        for f in kept:
            if allowed.get(f.fingerprint, 0) > 0:
                allowed[f.fingerprint] -= 1
                f.baselined = True
        # staleness is only judged for entries whose file was IN this
        # scan — a subset run must not call the full tree's grandfathered
        # entries stale (and tempt a --write-baseline that drops them) —
        # EXCEPT entries whose file no longer exists at all: a deleted/
        # renamed module's entries would otherwise be immortal (never
        # scanned, never flagged, carried over by every regeneration)
        scanned = {sf.rel for sf in files}
        marked = root_is_marked(repo_root)
        result.stale_baseline = sorted(
            fp for fp, n in allowed.items()
            if n > 0 and (
                ent_path.get(fp, "") in scanned
                or (marked and not os.path.exists(
                    os.path.join(repo_root, ent_path.get(fp, ""))
                ))
            )
        )
    result.findings = kept
    return result


# rule modules self-register via the @rule decorator; imported last so
# the decorators above exist. noqa: the imports ARE the side effect.
from paddle_tpu.analysis import rules_hotpath  # noqa: E402,F401
from paddle_tpu.analysis import rules_jax  # noqa: E402,F401
from paddle_tpu.analysis import rules_concurrency  # noqa: E402,F401
from paddle_tpu.analysis import rules_registry  # noqa: E402,F401
