"""PTL003 (use-after-donate) and PTL004 (recompile hazards) — the
jax-semantics invariants behind donated step buffers and
recompile-stable launch signatures.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from paddle_tpu.analysis.core import (
    Finding,
    LintContext,
    SourceFile,
    dotted,
    rule,
)

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit"}


def _donated_positions(call: ast.Call) -> Optional[List[int]]:
    """The donate_argnums of a ``jax.jit(f, donate_argnums=...)`` call,
    when statically readable."""
    if dotted(call.func) not in _JIT_NAMES:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.append(e.value)
                return out
    return None


def _scopes(tree: ast.Module):
    """Every function scope (and the module itself) — donation tracking
    is per-scope, matching "read afterwards in the same scope"."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _walk_scope(scope):
    """The nodes of ONE scope: descends expressions and control flow
    but not nested function/class bodies (those are their own scopes —
    walking them twice double-reports)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


@rule(
    "PTL003",
    "a buffer passed through a donate_argnums jit call is read after "
    "the call (use-after-donate)",
)
def check_use_after_donate(sf: SourceFile, ctx: LintContext) -> Iterable[Finding]:
    """``donate_argnums`` hands the input buffer to XLA — after the
    call the Python name still exists but its device buffer is deleted;
    touching it raises (best case) or silently reads garbage on some
    backends. The step functions donate params/opt_state, so the
    correct idiom is the immediate rebind
    (``params, opt = step(params, opt, ...)``)."""
    out: List[Finding] = []
    for scope in _scopes(sf.tree):
        # 1) names bound to donating jitted callables in this scope
        donators: Dict[str, List[int]] = {}
        for node in _walk_scope(scope):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                pos = _donated_positions(node.value)
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            donators[t.id] = pos
        if not donators:
            continue
        # 2) line-ordered events. Within one line, evaluation order is
        # loads (0), then the donating call (1), then stores (2) — an
        # assignment's target column precedes its rhs lexically but the
        # store happens LAST (`params = step(params, ...)` is the safe
        # rebind idiom and must not read as store-then-donate).
        donated_at: Dict[str, int] = {}  # name -> donation line
        events: List[Tuple[int, int, int, str, str]] = []
        for node in _walk_scope(scope):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id in donators:
                for p in donators[node.func.id]:
                    if p < len(node.args) and isinstance(node.args[p], ast.Name):
                        events.append(
                            (node.lineno, 1, node.col_offset, "donate",
                             node.args[p].id)
                        )
            elif isinstance(node, ast.Name):
                store = isinstance(node.ctx, (ast.Store, ast.Del))
                events.append((
                    node.lineno, 2 if store else 0, node.col_offset,
                    "store" if store else "load", node.id,
                ))
        flagged: Set[str] = set()
        for line, _prio, col, op, name in sorted(events):
            if op == "donate":
                donated_at[name] = line
            elif op == "store":
                # rebinding (including the canonical same-statement
                # `x, y = f(x, y)`) makes the name safe again
                if name in donated_at and line >= donated_at[name]:
                    donated_at.pop(name, None)
            elif op == "load" and name in donated_at and name not in flagged:
                if line > donated_at[name]:
                    flagged.add(name)
                    out.append(Finding(
                        rule="PTL003", path=sf.rel, line=line, col=col,
                        message=(
                            f"`{name}` was donated to a jit call with "
                            f"donate_argnums (line {donated_at[name]}) and is "
                            "read afterwards — the buffer is gone; rebind "
                            "the name from the call's result"
                        ),
                        snippet=sf.snippet(line),
                    ))
    return out


# ------------------------------------------------------------- PTL004

_DICT_ITER_ATTRS = {"keys", "values", "items"}


def _is_dict_iter_call(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _DICT_ITER_ATTRS
        and not node.args
        and not node.keywords
    ):
        return node.func.attr
    return None


def _jit_decorated(node) -> bool:
    """``@jit`` / ``@jax.jit`` / ``@partial(jax.jit, ...)``."""
    for dec in node.decorator_list:
        d = dotted(dec)
        if d in _JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            if dotted(dec.func) in _JIT_NAMES:
                return True
            if dotted(dec.func) in ("partial", "functools.partial") and (
                dec.args and dotted(dec.args[0]) in _JIT_NAMES
            ):
                return True
    return False


def _local_names(fn) -> Set[str]:
    names: Set[str] = set()
    a = fn.args
    for arg in (
        list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        + ([a.vararg] if a.vararg else []) + ([a.kwarg] if a.kwarg else [])
    ):
        names.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node is not fn
        ):
            names.add(node.name)
    return names


@rule(
    "PTL004",
    "recompile hazard: jit'd closure over a mutable Python value, or a "
    "signature built from dict iteration order",
)
def check_recompile_hazards(sf: SourceFile, ctx: LintContext) -> Iterable[Finding]:
    """Two ways launch signatures go unstable (the compile-telemetry
    work made recompiles observable; this keeps them from appearing):

    - a ``@jit`` function closing over a module-level **mutable**
      value (list/dict/set, lowercase name — UPPERCASE is the constant
      convention): jax traces it once and never again, so a later
      mutation changes numerics WITHOUT a retrace, or forces
      per-call retraces when used as a shape;
    - a cache key / signature built from **dict iteration order**
      (``tuple(d.items())`` et al. without ``sorted``): two processes
      (or one process after a restart with different insertion order)
      disagree on the same logical signature, defeating the persistent
      compile cache and the recompile accounting.
    """
    out: List[Finding] = []
    # module-level mutable bindings (lowercase only: UPPER_CASE module
    # constants-by-convention are exempt)
    mutable_mod: Set[str] = set()
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                         ast.SetComp)
        ):
            for t in node.targets:
                if isinstance(t, ast.Name) and not t.id.isupper():
                    mutable_mod.add(t.id)

    for node in ast.walk(sf.tree):
        # (a) jit closure capture
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            _jit_decorated(node)
        ):
            local = _local_names(node)
            seen: Set[str] = set()
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                    and sub.id in mutable_mod
                    and sub.id not in local
                    and sub.id not in seen
                ):
                    seen.add(sub.id)
                    out.append(Finding(
                        rule="PTL004", path=sf.rel, line=sub.lineno,
                        col=sub.col_offset,
                        message=(
                            f"jit'd `{node.name}` captures mutable "
                            f"module value `{sub.id}` — traced once, "
                            "mutations never retrigger compilation; pass "
                            "it as an argument or freeze it (tuple/"
                            "frozenset, UPPER_CASE constant)"
                        ),
                        snippet=sf.snippet(sub.lineno),
                    ))
        # (b) dict-iteration-order signatures
        if isinstance(node, ast.Call):
            attr = None
            d = dotted(node.func)
            if d == "tuple" and node.args:
                attr = _is_dict_iter_call(node.args[0])
                site = node.args[0]
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and node.args
            ):
                attr = _is_dict_iter_call(node.args[0])
                site = node.args[0]
            else:
                continue
            if attr:
                out.append(Finding(
                    rule="PTL004", path=sf.rel, line=site.lineno,
                    col=site.col_offset,
                    end_line=getattr(node, "end_lineno", 0) or 0,
                    message=(
                        f"signature component built from `.{attr}()` "
                        "iteration order — wrap in sorted(...) so the "
                        "launch signature is stable across processes and "
                        "restarts (persistent compile cache contract)"
                    ),
                    snippet=sf.snippet(site.lineno),
                ))
    return out
