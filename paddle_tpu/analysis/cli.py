"""``paddle lint [paths] [--json] [--baseline FILE]`` — the CLI.

jax-free like the other analyzers: `paddle lint` is the CI gate and
must run before the accelerator runtime exists. Exit codes: 0 = no new
(non-baselined) findings, 1 = new findings, 2 = usage/baseline errors.

``--json`` emits one schema-v1 JSONL record per finding
(``kind=lint_finding``) plus a closing ``kind=lint_summary`` with
per-rule counts — the artifact ``paddle compare`` diffs between two
lint runs (doc/observability.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from paddle_tpu.analysis import baseline as bl
from paddle_tpu.analysis.core import ALL_RULES, find_repo_root, run_lint


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle lint",
        description=(
            "jax-aware static analysis for the framework's hot-path, "
            "concurrency, and telemetry invariants (rule catalog: "
            "doc/static_analysis.md)"
        ),
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: paddle_tpu "
                        "under the current directory, else '.')")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit JSONL lint_finding/lint_summary records "
                        "(validate_record-compatible; feed to "
                        "`paddle compare`)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline JSON of grandfathered findings "
                        f"(default: {bl.BASELINE_NAME} at the repo root, "
                        "when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline (report every finding as new)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline file "
                        "and exit 0 (grandfathering)")
    p.add_argument("--rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.rules:
        for rid in sorted(ALL_RULES):
            print(f"{rid}  {ALL_RULES[rid]}")
        return 0

    paths = args.paths or (
        ["paddle_tpu"] if os.path.isdir("paddle_tpu") else ["."]
    )
    for path in paths:
        if not os.path.exists(path):
            print(f"error: {path!r} does not exist", file=sys.stderr)
            return 2

    repo_root = find_repo_root(paths)
    baseline_path = args.baseline or bl.default_baseline_path(repo_root)
    baseline = None
    if baseline_path and not args.no_baseline and not args.write_baseline:
        try:
            baseline = bl.load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"error: cannot load baseline: {e}", file=sys.stderr)
            return 2

    result = run_lint(paths, baseline=baseline)

    if args.write_baseline:
        path = args.baseline or os.path.join(repo_root, bl.BASELINE_NAME)
        # a SUBSET scan regenerates only what it could see: prior
        # entries for files outside this scan are carried over, never
        # silently dropped (they'd resurface as "new" on the next full
        # run and break the gate)
        keep = []
        if os.path.isfile(path) and not args.no_baseline:
            try:
                from paddle_tpu.analysis.core import root_is_marked

                scanned = set(result.scanned_paths)
                marked = root_is_marked(repo_root)
                keep = [
                    ent for ent in bl.load_baseline(path).get("findings", [])
                    if ent.get("path") not in scanned
                    # entries for deleted/renamed files are dropped, not
                    # carried forward forever (only judged under a real
                    # repo root, where entry paths are stable)
                    and (not marked or os.path.exists(
                        os.path.join(repo_root, ent.get("path", ""))
                    ))
                ]
            except (OSError, ValueError) as e:
                print(f"error: cannot merge existing baseline: {e}",
                      file=sys.stderr)
                return 2
        bl.write_baseline(path, result.findings, keep_entries=keep)
        print(
            f"wrote {len(result.findings)} finding(s) to {path}"
            + (f" (kept {len(keep)} out-of-scope "
               f"entr{'y' if len(keep) == 1 else 'ies'})" if keep else ""),
            file=sys.stderr,
        )
        return 0

    # diagnostics go to stderr in BOTH modes: a CI gate reading --json
    # stdout still sees shrunken coverage and staleness in its log
    for path, why in result.skipped:
        print(f"# skipped {path}: {why}", file=sys.stderr)
    if result.stale_baseline:
        print(
            f"# {len(result.stale_baseline)} stale baseline entr"
            f"{'y' if len(result.stale_baseline) == 1 else 'ies'} no "
            "longer match anything — regenerate with --write-baseline: "
            + ", ".join(result.stale_baseline),
            file=sys.stderr,
        )
    if args.as_json:
        for f in result.findings:
            print(json.dumps(f.record()))
        print(json.dumps(result.summary_record()))
    else:
        for f in result.findings:
            print(f.render())
        n_new = len(result.new)
        n_base = len(result.findings) - n_new
        print(
            f"# {n_new} new finding(s), {n_base} baselined, "
            f"{result.files_scanned} file(s) scanned"
        )
    return 1 if result.new else 0


if __name__ == "__main__":
    sys.exit(main())
