"""The production decode backend: donated slot state on device, one
jitted launch per engine iteration — dispatched and collected as two
halves so the device never waits for the host.

Device state is a single pytree of fixed-shape ``[B, ...]`` buffers for
``B = --serve_slots`` concurrent sequences — the captured static-link
conditioning (seqToseq: encoder projection/values per slot), the decoder
memory carries (the GRU hidden the fused attention-GRU path steps), the
previous token, per-slot step counts, done flags and token budgets.
Both launch fns take the state with ``donate_argnums``, so every
iteration updates it in place (no per-step HBM churn), and both are
routed through the PR-7 :class:`CompileRegistry`:

- launch group ``serve_prefill`` — ONE ``[B, T]`` signature: the full
  graph forward in gen-capture mode (graph/decode_step.py) over a
  padded admission batch, scattered into the named slots (sentinel
  indices drop, so partial admissions reuse the same signature). In
  pipelined mode the admission launch is dispatch-only — the PR-12
  ``block_until_ready`` is gone, so admitting never stalls an in-flight
  decode; its device time surfaces inside the next decode collect span.
- launch group ``serve_decode`` — ONE ``[B, ...]`` signature for the
  WHOLE decode-block ladder: the block size ``u`` is a traced scalar
  bound on the device ``fori_loop`` (token/live buffers are sized to
  the ladder's top rung), so every rung shares one compiled executable
  and recompiles stay 0 across the ladder by construction — stronger
  than one pre-warmed signature per rung, which would show up as
  ``recompiles>0`` group churn in the compile telemetry.
- launch group ``serve_verify`` (PR 20, ``--serve_spec_tokens``) — the
  speculative draft-and-verify launch: ONE ``[K, B]`` signature for the
  whole speculation ladder (draft length ``k`` is a traced scalar, the
  draft buffer is sized to the ladder's top rung, same discipline as
  ``serve_decode``). Each step scores the next position with the real
  model; a slot keeps advancing while its drafted token matches the
  model's own greedy argmax, and the first mismatching step's token is
  the model's CORRECTION — it commits too, riding free. Every emitted
  token is therefore the model's own greedy output: exact parity with
  plain decode, unconditionally. Slots whose draft misses at once (or
  that proposed nothing) advance exactly one plain step.

Reduced-precision slot state (PR 20, ``--serve_slot_dtype=bf16``):
float slot buffers (captured statics + GRU carries) are STORED in
bfloat16 — halving per-slot HBM so ``--serve_slots`` doubles at fixed
footprint — while every step still COMPUTES in f32: statics upcast once
per launch outside the fori_loop, carries upcast before and downcast
after EVERY micro-step inside it. Rounding once per micro-step (not
once per launch) keeps the token stream identical across decode-block
rungs, so the cross-rung golden tests still hold under bf16.

``dispatch()`` enqueues the decode launch and immediately starts
``copy_to_host_async`` on its token/live/finished outputs — the PR-5
snapshot discipline: every transfer is on the wire before the first
``collect()`` blocks. ``collect()`` gathers the oldest in-flight
launch; exec time is attributed THERE, as the union of dispatch→done
spans (overlapping spans must not double-count device seconds), and a
launch fault also surfaces there — exactly where the engine's
cohort-error path expects it.

Evicted-but-unreplaced slots need no device call: a finished (or
abandoned) row's flag freezes it, an abandoned live row self-terminates
at its bounded budget, and the next admission overwrites the slot
wholesale.
"""

from __future__ import annotations

import collections
from typing import Any, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu.serving.backend import (
    DraftBatch,
    StepOut,
    parse_decode_blocks,
    parse_slot_dtype,
    parse_spec_tokens,
)
from paddle_tpu.utils import concurrency as cc


class UnsupportedModelError(RuntimeError):
    """The generation graph cannot be slot-decoded (see plan_of gates);
    the static path (`SequenceGenerator`, PR-8 driver) still works."""


class JaxDecodeBackend:
    GROUP_DECODE = "serve_decode"
    GROUP_PREFILL = "serve_prefill"
    GROUP_VERIFY = "serve_verify"

    def __init__(self, machine, params, slots: int, prompt_tokens: int,
                 max_length: Optional[int] = None,
                 decode_block: Union[int, str, Sequence[int]] = 1,
                 registry=None, feed_name: Optional[str] = None,
                 pipeline: bool = True, fused_step: bool = False,
                 spec_tokens: Union[int, str, Sequence[int], None] = None,
                 slot_dtype: str = "f32"):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.graph.decode_step import (
            capture_prefill, make_greedy_step, plan_fused_step, plan_of,
            plan_slot_dtype,
        )

        self._jax, self._jnp = jax, jnp
        plan, reason = plan_of(machine)
        if plan is None:
            raise UnsupportedModelError(reason)
        self._plan = plan
        self._machine = machine
        self.params = params
        self.slots = int(slots)
        self.prompt_tokens = int(prompt_tokens)
        self.max_length = min(int(max_length or plan.max_length),
                              plan.max_length)
        self.decode_blocks = parse_decode_blocks(decode_block)
        self.max_block = self.decode_blocks[-1]
        self.spec_blocks = parse_spec_tokens(spec_tokens)
        self.max_spec = self.spec_blocks[-1] if self.spec_blocks else 0
        self.slot_dtype = parse_slot_dtype(slot_dtype)
        slot_plan, why = plan_slot_dtype(self.slot_dtype)
        if slot_plan is None:
            raise UnsupportedModelError(why)
        self._store_dtype = (jnp.dtype(slot_plan["store_dtype"])
                             if slot_plan["store_dtype"] else None)
        self.parity_tol = float(slot_plan["parity_tol"])
        self.pipeline = bool(pipeline)
        self._registry = registry
        # exec attribution gate: warmup flips it on; callers measuring
        # calibration passes may toggle it off so those launches stay
        # out of the serve roofline (the static leg's serving_now rule)
        self.serving = False
        self._warmed = False
        names = list(machine.network.input_layer_names)
        if feed_name is None:
            if len(names) != 1:
                raise UnsupportedModelError(
                    f"model has {len(names)} input layers {names} — pass "
                    "feed_name to choose the prompt sequence input"
                )
            feed_name = names[0]
        self._feed_name = feed_name
        self._capture = capture_prefill
        fused_plan = None
        if fused_step:
            fused_plan, why = plan_fused_step(machine, plan,
                                              slot_dtype=self.slot_dtype)
            if fused_plan is None:
                raise UnsupportedModelError(
                    f"--serve_fused_step: {why} (the unfused per-step "
                    "decoder still serves this model)"
                )
        self.fused_step = fused_plan is not None
        self._step = make_greedy_step(machine, plan, fused_plan=fused_plan)
        self._prefill_jit = jax.jit(self._prefill_write, donate_argnums=(1,))
        self._decode_jit = jax.jit(self._decode, donate_argnums=(1,))
        self._verify_jit = (jax.jit(self._verify, donate_argnums=(1,))
                            if self.max_spec else None)
        self._state = self._fresh_state()
        # dispatched-but-uncollected decode launches: (device arrays
        # with host copies in flight, block, dispatch wall time)
        self._inflight: collections.deque = collections.deque()
        # union-of-spans anchor: exec seconds must not double-count
        # overlapping dispatch->done spans (doc/performance.md
        # "Pipelined decode")
        self._exec_anchor = cc.perf_counter()

    # ------------------------------------------------------- jitted fns

    def _feed(self, ids, lens):
        from paddle_tpu.graph import make_seq

        return {self._feed_name: make_seq(None, lens, ids=ids)}

    def _prefill_write(self, params, state, ids, lens, slot_idx, budgets):
        """Admission launch: full-graph capture forward over the padded
        [B, T] admission batch, scattered into the slot rows named by
        ``slot_idx`` (sentinel ``B`` rows drop — one signature for every
        admission size)."""
        jnp = self._jnp
        statics, boots = self._capture(
            self._machine, self._plan, params, self._feed(ids, lens)
        )

        def scatter(dst, src):
            return dst.at[slot_idx].set(src.astype(dst.dtype), mode="drop")

        new_statics = {
            name: {f: scatter(state["statics"][name][f], statics[name][f])
                   for f in state["statics"][name]}
            for name in state["statics"]
        }
        new_carries = tuple(
            scatter(old, boot) for old, boot in zip(state["carries"], boots)
        )
        return {
            "statics": new_statics,
            "carries": new_carries,
            "prev_tok": state["prev_tok"].at[slot_idx].set(
                self._plan.bos, mode="drop"),
            "finished": state["finished"].at[slot_idx].set(False, mode="drop"),
            "steps": state["steps"].at[slot_idx].set(0, mode="drop"),
            "budget": state["budget"].at[slot_idx].set(
                budgets.astype(jnp.int32), mode="drop"),
        }

    # --------------------------------------- reduced-precision slot state
    # Under --serve_slot_dtype=bf16 the slot buffers are STORED in bf16
    # but every step COMPUTES in f32: statics upcast once per launch
    # (outside the fori_loop), carries upcast before / downcast after
    # every micro-step inside it — the per-micro-step rounding point
    # keeps token streams identical across decode-block rungs. Under
    # f32 all three helpers are identity (same jaxpr as PR 12).

    def _statics_compute(self, statics):
        if self._store_dtype is None:
            return statics
        jax, jnp = self._jax, self._jnp
        up = lambda x: (x.astype(jnp.float32)
                        if x.dtype == self._store_dtype else x)
        return jax.tree_util.tree_map(up, statics)

    def _carries_compute(self, carries):
        if self._store_dtype is None:
            return carries
        jnp = self._jnp
        return tuple(c.astype(jnp.float32)
                     if c.dtype == self._store_dtype else c for c in carries)

    def _carries_store(self, like, carries):
        if self._store_dtype is None:
            return carries
        return tuple(c.astype(o.dtype) if c.dtype != o.dtype else c
                     for o, c in zip(like, carries))

    def _decode(self, params, state, u):
        """One iteration: ``u`` greedy micro-steps over all slots,
        EOS/budget termination on device. ``u`` is a TRACED scalar: the
        ladder's rungs all run through this one compiled executable
        (buffers sized to the top rung; rows past ``u`` stay dead)."""
        jax, jnp = self._jax, self._jnp
        um, B = self.max_block, self.slots
        budget = state["budget"]
        statics = self._statics_compute(state["statics"])

        def body(i, acc):
            carries, prev, fin, steps, toks, lives = acc
            live = ~fin
            cf = self._carries_compute(carries)
            cf, tok, fin = self._step(params, statics, cf, prev, fin)
            carries = self._carries_store(carries, cf)
            steps = steps + live.astype(jnp.int32)
            fin = fin | (steps >= budget)
            return (carries, tok, fin, steps,
                    toks.at[i].set(tok), lives.at[i].set(live))

        init = (state["carries"], state["prev_tok"], state["finished"],
                state["steps"], jnp.zeros((um, B), jnp.int32),
                jnp.zeros((um, B), bool))
        carries, prev, fin, steps, toks, lives = jax.lax.fori_loop(
            0, jnp.minimum(u, um), body, init)
        new_state = dict(state, carries=carries, prev_tok=prev,
                         finished=fin, steps=steps)
        return new_state, toks, lives, fin

    def _verify(self, params, state, draft, dlen, k):
        """The speculative verify launch: up to ``k`` greedy micro-steps
        per slot, where a slot stays live only while its drafted token
        keeps matching the model's own argmax (the first mismatching
        step emits the model's corrected token, then the slot freezes
        for the rest of the launch). ``k`` is a TRACED scalar bound like
        ``_decode``'s ``u`` — the whole speculation ladder shares one
        compiled executable (draft buffer sized to the top rung).

        ``draft [K, B]`` int32, ``dlen [B]`` int32 (0 = no proposal: the
        slot takes exactly one plain greedy step). Every emitted token
        is the model's own greedy output — exact parity with plain
        decode. ``prev_tok`` must end as the last token each slot truly
        COMMITTED, so it is tracked separately from the step feed (a
        frozen row's eos emission must not pollute it)."""
        jax, jnp = self._jax, self._jnp
        km, B = self.max_spec, self.slots
        budget = state["budget"]
        statics = self._statics_compute(state["statics"])

        def body(i, acc):
            (carries, prev, committed, fin, steps, accepting,
             toks, lives) = acc
            # a slot is dead for this launch once finished OR once its
            # draft diverged (the correction already committed)
            dead = fin | ~accepting
            live = ~dead
            cf = self._carries_compute(carries)
            cf, tok, _sf = self._step(params, statics, cf, prev, dead)
            carries = self._carries_store(carries, cf)
            steps = steps + live.astype(jnp.int32)
            # real termination comes only from live rows: eos emission
            # or the budget bound (frozen rows emit eos score-free)
            fin = fin | (live & (tok == self._plan.eos)) | (steps >= budget)
            committed = jnp.where(live, tok, committed)
            accepting = live & (i < dlen) & (tok == draft[i])
            return (carries, tok, committed, fin, steps, accepting,
                    toks.at[i].set(tok), lives.at[i].set(live))

        init = (state["carries"], state["prev_tok"], state["prev_tok"],
                state["finished"], state["steps"],
                jnp.ones((B,), bool),
                jnp.zeros((km, B), jnp.int32), jnp.zeros((km, B), bool))
        (carries, _prev, committed, fin, steps, _acc, toks,
         lives) = jax.lax.fori_loop(
            0, jnp.minimum(jnp.maximum(k, 1), km), body, init)
        new_state = dict(state, carries=carries, prev_tok=committed,
                         finished=fin, steps=steps)
        return new_state, toks, lives, fin

    # ------------------------------------------------------- fresh state

    def _fresh_state(self):
        """Zeroed slot buffers, every slot finished (frozen). Shapes come
        from eval_shape of the capture — no compile, no launch. Float
        buffers land in the slot storage dtype (bf16 halves them; the
        prefill scatter's ``astype(dst.dtype)`` downcasts admissions)."""
        jax, jnp = self._jax, self._jnp
        B, T = self.slots, self.prompt_tokens
        ids = jnp.zeros((B, T), jnp.int32)
        lens = jnp.ones((B,), jnp.int32)
        statics_sd, boots_sd = jax.eval_shape(
            lambda p, i, l: self._capture(self._machine, self._plan, p,
                                          self._feed(i, l)),
            self.params, ids, lens,
        )
        store = self._store_dtype

        def zeros(sd):
            dt = sd.dtype
            if store is not None and jnp.issubdtype(dt, jnp.floating):
                dt = store
            return jnp.zeros(sd.shape, dt)

        return {
            "statics": jax.tree_util.tree_map(zeros, statics_sd),
            "carries": tuple(zeros(sd) for sd in boots_sd),
            "prev_tok": jnp.full((B,), self._plan.bos, jnp.int32),
            "finished": jnp.ones((B,), bool),
            "steps": jnp.zeros((B,), jnp.int32),
            "budget": jnp.zeros((B,), jnp.int32),
        }

    # ------------------------------------------------------------- seam

    def warmup(self) -> None:
        """Pay both compiles before serving: a no-slot prefill (all
        sentinel indices) and one decode launch PER LADDER RUNG over the
        all-finished state — zero slot effects. The block bound is a
        traced scalar, so the rung launches all hit the one compiled
        ``serve_decode`` signature: compile records land with
        ``recompiles=0`` and serving never recompiles, whatever rung
        the adaptive policy picks. Idempotent: a second call (bench
        warms the backend itself before ``Engine.start()`` re-runs it,
        possibly with ``serving`` already flipped on) is a no-op — the
        rung launches must never land in the serve roofline as real
        exec."""
        if self._warmed:
            self.serving = True
            return
        self.serving = False
        B, T = self.slots, self.prompt_tokens
        self._admit_call(
            np.zeros((B, T), np.int32), np.ones((B,), np.int32),
            np.full((B,), B, np.int32), np.zeros((B,), np.int32),
        )
        for u in self.decode_blocks:
            self.step(block=u)
        # the speculation ladder warms through the SAME one serve_verify
        # signature (traced k bound): every rung launches once over the
        # all-finished state — zero slot effects, recompiles=0 after
        for kk in self.spec_blocks:
            self.step(draft={0: [0] * kk})
        if self._registry is not None:
            # warmup launches never reach note_exec (serving is off), so
            # the registry's pending compile-cost deduction would zero
            # the FIRST real launch's exec time instead — discard it
            self._registry.drop_pending(self.GROUP_PREFILL, self._sig_prefill())
            self._registry.drop_pending(self.GROUP_DECODE, self._sig_decode())
            if self.spec_blocks:
                self._registry.drop_pending(self.GROUP_VERIFY,
                                            self._sig_verify())
        self._warmed = True
        self.serving = True

    def reset(self) -> None:
        self._state = self._fresh_state()
        self._inflight.clear()
        # a post-fault epoch must not union its first collect span
        # against the dead epoch's anchor
        self._exec_anchor = cc.perf_counter()

    def reload(self, params: Any) -> None:
        """Hot weight swap at an iteration boundary (the engine's
        ``_apply_reload_locked`` is the only caller). Params are the
        NON-donated first argument of both launch fns — dispatched
        launches already captured the old reference, so this reference
        replacement cannot tear them; same shapes/dtypes hit the same
        jit cache, so the swap costs no recompile."""
        self.params = params

    def _sig_prefill(self):
        return (self.slots, self.prompt_tokens)

    def _sig_decode(self):
        return (self.slots, self.prompt_tokens, self.max_block)

    def _sig_verify(self):
        return (self.slots, self.prompt_tokens, self.max_spec)

    def slot_state_bytes(self) -> int:
        """Stored decode-state bytes per slot (captured statics, GRU
        carries, the scalar rows) — the weights-free numerator behind
        the ``slot_bytes`` bench stamp. Cross-checked against
        ``memory_analysis()`` argument bytes in tests: halving this is
        what lets ``--serve_slots`` double at fixed footprint."""
        leaves = self._jax.tree_util.tree_leaves(self._state)
        total = sum(int(l.size) * int(l.dtype.itemsize) for l in leaves)
        return total // self.slots

    def admit(self, slot_ids: Sequence[int], requests: Sequence[Any],
              budgets: Sequence[int]) -> None:
        B, T = self.slots, self.prompt_tokens
        ids = np.zeros((B, T), np.int32)
        lens = np.ones((B,), np.int32)
        idx = np.full((B,), B, np.int32)      # sentinel: row writes nothing
        budg = np.zeros((B,), np.int32)
        for j, (slot, req) in enumerate(zip(slot_ids, requests)):
            p = np.asarray(list(req.prompt or ()), np.int32)[:T]
            if p.size:
                ids[j, : p.size] = p
            lens[j] = max(int(p.size), 1)
            idx[j] = int(slot)
            budg[j] = min(int(budgets[j]), self.max_length)
        self._admit_call(ids, lens, idx, budg)

    def _admit_call(self, ids, lens, idx, budg) -> None:
        jnp = self._jnp
        t0 = cc.perf_counter()
        args = (self.params, self._state, jnp.asarray(ids),
                jnp.asarray(lens), jnp.asarray(idx), jnp.asarray(budg))
        key = self._sig_prefill()
        if self._registry is not None:
            self._state = self._registry.call(
                self.GROUP_PREFILL, key, self._prefill_jit, *args)
        else:
            self._state = self._prefill_jit(*args)
        if not self.pipeline:
            # the PR-12 serial path: admission waits for the prefill, so
            # its measured span IS device time. Pipelined mode never
            # syncs here — the admission must not stall an in-flight
            # decode; the prefill's device time surfaces inside the next
            # decode collect span instead (doc/serving.md)
            self._jax.block_until_ready(self._state["steps"])
        if self._registry is not None and self.serving:
            self._registry.note_exec(self.GROUP_PREFILL, key,
                                     cc.perf_counter() - t0)

    def dispatch(self, block: Optional[int] = None,
                 draft: Optional[DraftBatch] = None) -> None:
        """Enqueue one decode launch and start the device->host copies
        of its outputs — no waiting. Every output's copy is on the wire
        before anyone collects (the PR-5 all-dispatch-then-collect
        snapshot discipline). With ``draft`` (slot -> proposed tokens)
        the launch is the ``serve_verify`` draft-and-verify step instead
        of a plain decode block."""
        jnp = self._jnp
        t0 = cc.perf_counter()
        if draft:
            if not self.max_spec:
                raise RuntimeError(
                    "draft dispatch on a backend with no speculation "
                    "ladder (spec_tokens unset)")
            km, B = self.max_spec, self.slots
            d = np.zeros((km, B), np.int32)
            dl = np.zeros((B,), np.int32)
            for b, toks in draft.items():
                t = [int(x) for x in toks][:km]
                if t:
                    dl[int(b)] = len(t)
                    d[:len(t), int(b)] = t
            k = max(int(dl.max()), 1)
            group, key, fn = self.GROUP_VERIFY, self._sig_verify(), \
                self._verify_jit
            args = (self.params, self._state, jnp.asarray(d),
                    jnp.asarray(dl), jnp.asarray(k, jnp.int32))
            u = k
        else:
            u = int(block) if block else self.max_block
            group, key, fn = self.GROUP_DECODE, self._sig_decode(), \
                self._decode_jit
            args = (self.params, self._state, jnp.asarray(u, jnp.int32))
        if self._registry is not None:
            out = self._registry.call(group, key, fn, *args)
        else:
            out = fn(*args)
        self._state, toks, lives, fin = out
        for arr in (toks, lives, fin):
            try:
                arr.copy_to_host_async()
            except AttributeError:  # non-PJRT array stand-ins (tests)
                break
        self._inflight.append((toks, lives, fin, u, t0, group, key))

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def collect(self) -> StepOut:
        """Gather the oldest in-flight launch. The np.asarray readbacks
        are the one sanctioned device sync of the serve loop: the
        emitted tokens ARE the scheduler's input (EOS eviction, TTFT
        stamping), and exec/TTFT attribution happens at THIS boundary —
        the only honest place under overlap."""
        if not self._inflight:
            # a scheduler bug, not a device fault: fail loudly with the
            # state instead of an opaque IndexError from popleft
            raise RuntimeError(
                "serve_decode collect() with no launch in flight "
                "(dispatch/collect pairing broken)"
            )
        toks, lives, fin, u, t_disp, group, key = self._inflight.popleft()
        t_rb0 = cc.perf_counter()
        toks_np = np.asarray(toks)
        lives_np = np.asarray(lives)
        fin_np = np.asarray(fin)
        t_done = cc.perf_counter()
        # device→host readback cost of THIS collect (the np.asarray
        # syncs above) — the engine turns it into an engine.readback
        # span for traced requests; a duration, not a timestamp, so
        # the perf_counter vs monotonic timebase mismatch cannot leak
        self.last_readback_s = t_done - t_rb0
        if self._registry is not None and self.serving:
            # union of dispatch->done spans: launch N+1 was dispatched
            # while N ran, so anchoring at max(dispatch, previous done)
            # keeps summed exec seconds <= wall seconds
            span = max(t_done - max(t_disp, self._exec_anchor), 0.0)
            self._registry.note_exec(group, key, span, batches=u)
        self._exec_anchor = max(self._exec_anchor, t_done)
        return StepOut(tokens=toks_np, live=lives_np, finished=fin_np)

    def step(self, block: Optional[int] = None,
             draft: Optional[DraftBatch] = None) -> StepOut:
        self.dispatch(block=block, draft=draft)
        return self.collect()
