"""The production decode backend: donated slot state on device, one
jitted launch per engine iteration — dispatched and collected as two
halves so the device never waits for the host.

Device state is a single pytree of fixed-shape ``[B, ...]`` buffers for
``B = --serve_slots`` concurrent sequences — the captured static-link
conditioning (seqToseq: encoder projection/values per slot), the decoder
memory carries (the GRU hidden the fused attention-GRU path steps), the
previous token, per-slot step counts, done flags and token budgets.
Both launch fns take the state with ``donate_argnums``, so every
iteration updates it in place (no per-step HBM churn), and both are
routed through the PR-7 :class:`CompileRegistry`:

- launch group ``serve_prefill`` — ONE ``[B, T]`` signature: the full
  graph forward in gen-capture mode (graph/decode_step.py) over a
  padded admission batch, scattered into the named slots (sentinel
  indices drop, so partial admissions reuse the same signature). In
  pipelined mode the admission launch is dispatch-only — the PR-12
  ``block_until_ready`` is gone, so admitting never stalls an in-flight
  decode; its device time surfaces inside the next decode collect span.
- launch group ``serve_decode`` — ONE ``[B, ...]`` signature for the
  WHOLE decode-block ladder: the block size ``u`` is a traced scalar
  bound on the device ``fori_loop`` (token/live buffers are sized to
  the ladder's top rung), so every rung shares one compiled executable
  and recompiles stay 0 across the ladder by construction — stronger
  than one pre-warmed signature per rung, which would show up as
  ``recompiles>0`` group churn in the compile telemetry.

``dispatch()`` enqueues the decode launch and immediately starts
``copy_to_host_async`` on its token/live/finished outputs — the PR-5
snapshot discipline: every transfer is on the wire before the first
``collect()`` blocks. ``collect()`` gathers the oldest in-flight
launch; exec time is attributed THERE, as the union of dispatch→done
spans (overlapping spans must not double-count device seconds), and a
launch fault also surfaces there — exactly where the engine's
cohort-error path expects it.

Evicted-but-unreplaced slots need no device call: a finished (or
abandoned) row's flag freezes it, an abandoned live row self-terminates
at its bounded budget, and the next admission overwrites the slot
wholesale.
"""

from __future__ import annotations

import collections
from typing import Any, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu.serving.backend import StepOut, parse_decode_blocks
from paddle_tpu.utils import concurrency as cc


class UnsupportedModelError(RuntimeError):
    """The generation graph cannot be slot-decoded (see plan_of gates);
    the static path (`SequenceGenerator`, PR-8 driver) still works."""


class JaxDecodeBackend:
    GROUP_DECODE = "serve_decode"
    GROUP_PREFILL = "serve_prefill"

    def __init__(self, machine, params, slots: int, prompt_tokens: int,
                 max_length: Optional[int] = None,
                 decode_block: Union[int, str, Sequence[int]] = 1,
                 registry=None, feed_name: Optional[str] = None,
                 pipeline: bool = True, fused_step: bool = False):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.graph.decode_step import (
            capture_prefill, make_greedy_step, plan_fused_step, plan_of,
        )

        self._jax, self._jnp = jax, jnp
        plan, reason = plan_of(machine)
        if plan is None:
            raise UnsupportedModelError(reason)
        self._plan = plan
        self._machine = machine
        self.params = params
        self.slots = int(slots)
        self.prompt_tokens = int(prompt_tokens)
        self.max_length = min(int(max_length or plan.max_length),
                              plan.max_length)
        self.decode_blocks = parse_decode_blocks(decode_block)
        self.max_block = self.decode_blocks[-1]
        self.pipeline = bool(pipeline)
        self._registry = registry
        # exec attribution gate: warmup flips it on; callers measuring
        # calibration passes may toggle it off so those launches stay
        # out of the serve roofline (the static leg's serving_now rule)
        self.serving = False
        self._warmed = False
        names = list(machine.network.input_layer_names)
        if feed_name is None:
            if len(names) != 1:
                raise UnsupportedModelError(
                    f"model has {len(names)} input layers {names} — pass "
                    "feed_name to choose the prompt sequence input"
                )
            feed_name = names[0]
        self._feed_name = feed_name
        self._capture = capture_prefill
        fused_plan = None
        if fused_step:
            fused_plan, why = plan_fused_step(machine, plan)
            if fused_plan is None:
                raise UnsupportedModelError(
                    f"--serve_fused_step: {why} (the unfused per-step "
                    "decoder still serves this model)"
                )
        self.fused_step = fused_plan is not None
        self._step = make_greedy_step(machine, plan, fused_plan=fused_plan)
        self._prefill_jit = jax.jit(self._prefill_write, donate_argnums=(1,))
        self._decode_jit = jax.jit(self._decode, donate_argnums=(1,))
        self._state = self._fresh_state()
        # dispatched-but-uncollected decode launches: (device arrays
        # with host copies in flight, block, dispatch wall time)
        self._inflight: collections.deque = collections.deque()
        # union-of-spans anchor: exec seconds must not double-count
        # overlapping dispatch->done spans (doc/performance.md
        # "Pipelined decode")
        self._exec_anchor = cc.perf_counter()

    # ------------------------------------------------------- jitted fns

    def _feed(self, ids, lens):
        from paddle_tpu.graph import make_seq

        return {self._feed_name: make_seq(None, lens, ids=ids)}

    def _prefill_write(self, params, state, ids, lens, slot_idx, budgets):
        """Admission launch: full-graph capture forward over the padded
        [B, T] admission batch, scattered into the slot rows named by
        ``slot_idx`` (sentinel ``B`` rows drop — one signature for every
        admission size)."""
        jnp = self._jnp
        statics, boots = self._capture(
            self._machine, self._plan, params, self._feed(ids, lens)
        )

        def scatter(dst, src):
            return dst.at[slot_idx].set(src.astype(dst.dtype), mode="drop")

        new_statics = {
            name: {f: scatter(state["statics"][name][f], statics[name][f])
                   for f in state["statics"][name]}
            for name in state["statics"]
        }
        new_carries = tuple(
            scatter(old, boot) for old, boot in zip(state["carries"], boots)
        )
        return {
            "statics": new_statics,
            "carries": new_carries,
            "prev_tok": state["prev_tok"].at[slot_idx].set(
                self._plan.bos, mode="drop"),
            "finished": state["finished"].at[slot_idx].set(False, mode="drop"),
            "steps": state["steps"].at[slot_idx].set(0, mode="drop"),
            "budget": state["budget"].at[slot_idx].set(
                budgets.astype(jnp.int32), mode="drop"),
        }

    def _decode(self, params, state, u):
        """One iteration: ``u`` greedy micro-steps over all slots,
        EOS/budget termination on device. ``u`` is a TRACED scalar: the
        ladder's rungs all run through this one compiled executable
        (buffers sized to the top rung; rows past ``u`` stay dead)."""
        jax, jnp = self._jax, self._jnp
        um, B = self.max_block, self.slots
        budget = state["budget"]

        def body(i, acc):
            carries, prev, fin, steps, toks, lives = acc
            live = ~fin
            carries, tok, fin = self._step(params, state["statics"], carries,
                                           prev, fin)
            steps = steps + live.astype(jnp.int32)
            fin = fin | (steps >= budget)
            return (carries, tok, fin, steps,
                    toks.at[i].set(tok), lives.at[i].set(live))

        init = (state["carries"], state["prev_tok"], state["finished"],
                state["steps"], jnp.zeros((um, B), jnp.int32),
                jnp.zeros((um, B), bool))
        carries, prev, fin, steps, toks, lives = jax.lax.fori_loop(
            0, jnp.minimum(u, um), body, init)
        new_state = dict(state, carries=carries, prev_tok=prev,
                         finished=fin, steps=steps)
        return new_state, toks, lives, fin

    # ------------------------------------------------------- fresh state

    def _fresh_state(self):
        """Zeroed slot buffers, every slot finished (frozen). Shapes come
        from eval_shape of the capture — no compile, no launch."""
        jax, jnp = self._jax, self._jnp
        B, T = self.slots, self.prompt_tokens
        ids = jnp.zeros((B, T), jnp.int32)
        lens = jnp.ones((B,), jnp.int32)
        statics_sd, boots_sd = jax.eval_shape(
            lambda p, i, l: self._capture(self._machine, self._plan, p,
                                          self._feed(i, l)),
            self.params, ids, lens,
        )
        zeros = lambda sd: jnp.zeros(sd.shape, sd.dtype)
        return {
            "statics": jax.tree_util.tree_map(zeros, statics_sd),
            "carries": tuple(zeros(sd) for sd in boots_sd),
            "prev_tok": jnp.full((B,), self._plan.bos, jnp.int32),
            "finished": jnp.ones((B,), bool),
            "steps": jnp.zeros((B,), jnp.int32),
            "budget": jnp.zeros((B,), jnp.int32),
        }

    # ------------------------------------------------------------- seam

    def warmup(self) -> None:
        """Pay both compiles before serving: a no-slot prefill (all
        sentinel indices) and one decode launch PER LADDER RUNG over the
        all-finished state — zero slot effects. The block bound is a
        traced scalar, so the rung launches all hit the one compiled
        ``serve_decode`` signature: compile records land with
        ``recompiles=0`` and serving never recompiles, whatever rung
        the adaptive policy picks. Idempotent: a second call (bench
        warms the backend itself before ``Engine.start()`` re-runs it,
        possibly with ``serving`` already flipped on) is a no-op — the
        rung launches must never land in the serve roofline as real
        exec."""
        if self._warmed:
            self.serving = True
            return
        self.serving = False
        B, T = self.slots, self.prompt_tokens
        self._admit_call(
            np.zeros((B, T), np.int32), np.ones((B,), np.int32),
            np.full((B,), B, np.int32), np.zeros((B,), np.int32),
        )
        for u in self.decode_blocks:
            self.step(block=u)
        if self._registry is not None:
            # warmup launches never reach note_exec (serving is off), so
            # the registry's pending compile-cost deduction would zero
            # the FIRST real launch's exec time instead — discard it
            self._registry.drop_pending(self.GROUP_PREFILL, self._sig_prefill())
            self._registry.drop_pending(self.GROUP_DECODE, self._sig_decode())
        self._warmed = True
        self.serving = True

    def reset(self) -> None:
        self._state = self._fresh_state()
        self._inflight.clear()
        # a post-fault epoch must not union its first collect span
        # against the dead epoch's anchor
        self._exec_anchor = cc.perf_counter()

    def reload(self, params: Any) -> None:
        """Hot weight swap at an iteration boundary (the engine's
        ``_apply_reload_locked`` is the only caller). Params are the
        NON-donated first argument of both launch fns — dispatched
        launches already captured the old reference, so this reference
        replacement cannot tear them; same shapes/dtypes hit the same
        jit cache, so the swap costs no recompile."""
        self.params = params

    def _sig_prefill(self):
        return (self.slots, self.prompt_tokens)

    def _sig_decode(self):
        return (self.slots, self.prompt_tokens, self.max_block)

    def admit(self, slot_ids: Sequence[int], requests: Sequence[Any],
              budgets: Sequence[int]) -> None:
        B, T = self.slots, self.prompt_tokens
        ids = np.zeros((B, T), np.int32)
        lens = np.ones((B,), np.int32)
        idx = np.full((B,), B, np.int32)      # sentinel: row writes nothing
        budg = np.zeros((B,), np.int32)
        for j, (slot, req) in enumerate(zip(slot_ids, requests)):
            p = np.asarray(list(req.prompt or ()), np.int32)[:T]
            if p.size:
                ids[j, : p.size] = p
            lens[j] = max(int(p.size), 1)
            idx[j] = int(slot)
            budg[j] = min(int(budgets[j]), self.max_length)
        self._admit_call(ids, lens, idx, budg)

    def _admit_call(self, ids, lens, idx, budg) -> None:
        jnp = self._jnp
        t0 = cc.perf_counter()
        args = (self.params, self._state, jnp.asarray(ids),
                jnp.asarray(lens), jnp.asarray(idx), jnp.asarray(budg))
        key = self._sig_prefill()
        if self._registry is not None:
            self._state = self._registry.call(
                self.GROUP_PREFILL, key, self._prefill_jit, *args)
        else:
            self._state = self._prefill_jit(*args)
        if not self.pipeline:
            # the PR-12 serial path: admission waits for the prefill, so
            # its measured span IS device time. Pipelined mode never
            # syncs here — the admission must not stall an in-flight
            # decode; the prefill's device time surfaces inside the next
            # decode collect span instead (doc/serving.md)
            self._jax.block_until_ready(self._state["steps"])
        if self._registry is not None and self.serving:
            self._registry.note_exec(self.GROUP_PREFILL, key,
                                     cc.perf_counter() - t0)

    def dispatch(self, block: Optional[int] = None) -> None:
        """Enqueue one decode launch and start the device->host copies
        of its outputs — no waiting. Every output's copy is on the wire
        before anyone collects (the PR-5 all-dispatch-then-collect
        snapshot discipline)."""
        jnp = self._jnp
        u = int(block) if block else self.max_block
        t0 = cc.perf_counter()
        args = (self.params, self._state, jnp.asarray(u, jnp.int32))
        if self._registry is not None:
            out = self._registry.call(
                self.GROUP_DECODE, self._sig_decode(), self._decode_jit,
                *args)
        else:
            out = self._decode_jit(*args)
        self._state, toks, lives, fin = out
        for arr in (toks, lives, fin):
            try:
                arr.copy_to_host_async()
            except AttributeError:  # non-PJRT array stand-ins (tests)
                break
        self._inflight.append((toks, lives, fin, u, t0))

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def collect(self) -> StepOut:
        """Gather the oldest in-flight launch. The np.asarray readbacks
        are the one sanctioned device sync of the serve loop: the
        emitted tokens ARE the scheduler's input (EOS eviction, TTFT
        stamping), and exec/TTFT attribution happens at THIS boundary —
        the only honest place under overlap."""
        if not self._inflight:
            # a scheduler bug, not a device fault: fail loudly with the
            # state instead of an opaque IndexError from popleft
            raise RuntimeError(
                "serve_decode collect() with no launch in flight "
                "(dispatch/collect pairing broken)"
            )
        toks, lives, fin, u, t_disp = self._inflight.popleft()
        t_rb0 = cc.perf_counter()
        toks_np = np.asarray(toks)
        lives_np = np.asarray(lives)
        fin_np = np.asarray(fin)
        t_done = cc.perf_counter()
        # device→host readback cost of THIS collect (the np.asarray
        # syncs above) — the engine turns it into an engine.readback
        # span for traced requests; a duration, not a timestamp, so
        # the perf_counter vs monotonic timebase mismatch cannot leak
        self.last_readback_s = t_done - t_rb0
        if self._registry is not None and self.serving:
            # union of dispatch->done spans: launch N+1 was dispatched
            # while N ran, so anchoring at max(dispatch, previous done)
            # keeps summed exec seconds <= wall seconds
            span = max(t_done - max(t_disp, self._exec_anchor), 0.0)
            self._registry.note_exec(self.GROUP_DECODE, self._sig_decode(),
                                     span, batches=u)
        self._exec_anchor = max(self._exec_anchor, t_done)
        return StepOut(tokens=toks_np, live=lives_np, finished=fin_np)

    def step(self, block: Optional[int] = None) -> StepOut:
        self.dispatch(block=block)
        return self.collect()
