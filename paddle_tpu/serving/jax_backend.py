"""The production decode backend: donated slot state on device, one
jitted launch per engine iteration.

Device state is a single pytree of fixed-shape ``[B, ...]`` buffers for
``B = --serve_slots`` concurrent sequences — the captured static-link
conditioning (seqToseq: encoder projection/values per slot), the decoder
memory carries (the GRU hidden the fused attention-GRU path steps), the
previous token, per-slot step counts, done flags and token budgets.
Both launch fns take the state with ``donate_argnums``, so every
iteration updates it in place (no per-step HBM churn), and both are
routed through the PR-7 :class:`CompileRegistry`:

- launch group ``serve_prefill`` — ONE ``[B, T]`` signature: the full
  graph forward in gen-capture mode (graph/decode_step.py) over a
  padded admission batch, scattered into the named slots (sentinel
  indices drop, so partial admissions reuse the same signature).
- launch group ``serve_decode`` — ONE ``[B, ...]`` signature: a
  ``decode_block``-step ``fori_loop`` of the greedy per-step decoder,
  with EOS / budget termination folded into the device ``finished``
  flags. Zero recompiles after warmup is acceptance-checked like PR 8's
  ``serve_gen``.

Evicted-but-unreplaced slots need no device call: a finished (or
abandoned) row's flag freezes it, an abandoned live row self-terminates
at its bounded budget, and the next admission overwrites the slot
wholesale.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from paddle_tpu.serving.backend import StepOut
from paddle_tpu.utils import concurrency as cc


class UnsupportedModelError(RuntimeError):
    """The generation graph cannot be slot-decoded (see plan_of gates);
    the static path (`SequenceGenerator`, PR-8 driver) still works."""


class JaxDecodeBackend:
    GROUP_DECODE = "serve_decode"
    GROUP_PREFILL = "serve_prefill"

    def __init__(self, machine, params, slots: int, prompt_tokens: int,
                 max_length: Optional[int] = None, decode_block: int = 1,
                 registry=None, feed_name: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.graph.decode_step import (
            capture_prefill, make_greedy_step, plan_of,
        )

        self._jax, self._jnp = jax, jnp
        plan, reason = plan_of(machine)
        if plan is None:
            raise UnsupportedModelError(reason)
        self._plan = plan
        self._machine = machine
        self.params = params
        self.slots = int(slots)
        self.prompt_tokens = int(prompt_tokens)
        self.max_length = min(int(max_length or plan.max_length),
                              plan.max_length)
        self.decode_block = max(int(decode_block), 1)
        self._registry = registry
        # exec attribution gate: warmup flips it on; callers measuring
        # calibration passes may toggle it off so those launches stay
        # out of the serve roofline (the static leg's serving_now rule)
        self.serving = False
        names = list(machine.network.input_layer_names)
        if feed_name is None:
            if len(names) != 1:
                raise UnsupportedModelError(
                    f"model has {len(names)} input layers {names} — pass "
                    "feed_name to choose the prompt sequence input"
                )
            feed_name = names[0]
        self._feed_name = feed_name
        self._capture = capture_prefill
        self._step = make_greedy_step(machine, plan)
        self._prefill_jit = jax.jit(self._prefill_write, donate_argnums=(1,))
        self._decode_jit = jax.jit(self._decode, donate_argnums=(1,))
        self._state = self._fresh_state()

    # ------------------------------------------------------- jitted fns

    def _feed(self, ids, lens):
        from paddle_tpu.graph import make_seq

        return {self._feed_name: make_seq(None, lens, ids=ids)}

    def _prefill_write(self, params, state, ids, lens, slot_idx, budgets):
        """Admission launch: full-graph capture forward over the padded
        [B, T] admission batch, scattered into the slot rows named by
        ``slot_idx`` (sentinel ``B`` rows drop — one signature for every
        admission size)."""
        jnp = self._jnp
        statics, boots = self._capture(
            self._machine, self._plan, params, self._feed(ids, lens)
        )

        def scatter(dst, src):
            return dst.at[slot_idx].set(src.astype(dst.dtype), mode="drop")

        new_statics = {
            name: {f: scatter(state["statics"][name][f], statics[name][f])
                   for f in state["statics"][name]}
            for name in state["statics"]
        }
        new_carries = tuple(
            scatter(old, boot) for old, boot in zip(state["carries"], boots)
        )
        return {
            "statics": new_statics,
            "carries": new_carries,
            "prev_tok": state["prev_tok"].at[slot_idx].set(
                self._plan.bos, mode="drop"),
            "finished": state["finished"].at[slot_idx].set(False, mode="drop"),
            "steps": state["steps"].at[slot_idx].set(0, mode="drop"),
            "budget": state["budget"].at[slot_idx].set(
                budgets.astype(jnp.int32), mode="drop"),
        }

    def _decode(self, params, state):
        """One iteration: ``decode_block`` greedy micro-steps over all
        slots, EOS/budget termination on device."""
        jax, jnp = self._jax, self._jnp
        u, B = self.decode_block, self.slots
        statics, budget = state["statics"], state["budget"]

        def body(i, acc):
            carries, prev, fin, steps, toks, lives = acc
            live = ~fin
            carries, tok, fin = self._step(params, statics, carries, prev, fin)
            steps = steps + live.astype(jnp.int32)
            fin = fin | (steps >= budget)
            return (carries, tok, fin, steps,
                    toks.at[i].set(tok), lives.at[i].set(live))

        init = (state["carries"], state["prev_tok"], state["finished"],
                state["steps"], jnp.zeros((u, B), jnp.int32),
                jnp.zeros((u, B), bool))
        carries, prev, fin, steps, toks, lives = jax.lax.fori_loop(
            0, u, body, init)
        new_state = dict(state, carries=carries, prev_tok=prev,
                         finished=fin, steps=steps)
        return new_state, toks, lives, fin

    # ------------------------------------------------------- fresh state

    def _fresh_state(self):
        """Zeroed slot buffers, every slot finished (frozen). Shapes come
        from eval_shape of the capture — no compile, no launch."""
        jax, jnp = self._jax, self._jnp
        B, T = self.slots, self.prompt_tokens
        ids = jnp.zeros((B, T), jnp.int32)
        lens = jnp.ones((B,), jnp.int32)
        statics_sd, boots_sd = jax.eval_shape(
            lambda p, i, l: self._capture(self._machine, self._plan, p,
                                          self._feed(i, l)),
            self.params, ids, lens,
        )
        zeros = lambda sd: jnp.zeros(sd.shape, sd.dtype)
        return {
            "statics": jax.tree_util.tree_map(zeros, statics_sd),
            "carries": tuple(zeros(sd) for sd in boots_sd),
            "prev_tok": jnp.full((B,), self._plan.bos, jnp.int32),
            "finished": jnp.ones((B,), bool),
            "steps": jnp.zeros((B,), jnp.int32),
            "budget": jnp.zeros((B,), jnp.int32),
        }

    # ------------------------------------------------------------- seam

    def warmup(self) -> None:
        """Pay both compiles before serving: a no-slot prefill (all
        sentinel indices) and one decode launch over the all-finished
        state — zero slot effects, so compile records land with
        ``recompiles=0`` and serving never recompiles."""
        jnp = self._jnp
        B, T = self.slots, self.prompt_tokens
        self._admit_call(
            np.zeros((B, T), np.int32), np.ones((B,), np.int32),
            np.full((B,), B, np.int32), np.zeros((B,), np.int32),
        )
        self._step_call()
        self.serving = True

    def reset(self) -> None:
        self._state = self._fresh_state()

    def admit(self, slot_ids: Sequence[int], requests: Sequence[Any],
              budgets: Sequence[int]) -> None:
        B, T = self.slots, self.prompt_tokens
        ids = np.zeros((B, T), np.int32)
        lens = np.ones((B,), np.int32)
        idx = np.full((B,), B, np.int32)      # sentinel: row writes nothing
        budg = np.zeros((B,), np.int32)
        for j, (slot, req) in enumerate(zip(slot_ids, requests)):
            p = np.asarray(list(req.prompt or ()), np.int32)[:T]
            if p.size:
                ids[j, : p.size] = p
            lens[j] = max(int(p.size), 1)
            idx[j] = int(slot)
            budg[j] = min(int(budgets[j]), self.max_length)
        self._admit_call(ids, lens, idx, budg)

    def _admit_call(self, ids, lens, idx, budg) -> None:
        jnp = self._jnp
        t0 = cc.perf_counter()
        args = (self.params, self._state, jnp.asarray(ids),
                jnp.asarray(lens), jnp.asarray(idx), jnp.asarray(budg))
        key = (self.slots, self.prompt_tokens)
        if self._registry is not None:
            self._state = self._registry.call(
                self.GROUP_PREFILL, key, self._prefill_jit, *args)
        else:
            self._state = self._prefill_jit(*args)
        self._jax.block_until_ready(self._state["steps"])
        if self._registry is not None and self.serving:
            self._registry.note_exec(self.GROUP_PREFILL, key,
                                     cc.perf_counter() - t0)

    def step(self) -> StepOut:
        return self._step_call()

    def _step_call(self) -> StepOut:
        t0 = cc.perf_counter()
        key = (self.slots, self.prompt_tokens, self.decode_block)
        if self._registry is not None:
            out = self._registry.call(
                self.GROUP_DECODE, key, self._decode_jit,
                self.params, self._state)
        else:
            out = self._decode_jit(self.params, self._state)
        self._state, toks, lives, fin = out
        # the one per-iteration device sync: the emitted tokens ARE the
        # scheduler's input (EOS eviction, TTFT stamping)
        toks_np = np.asarray(toks)
        lives_np = np.asarray(lives)
        fin_np = np.asarray(fin)
        if self._registry is not None and self.serving:
            self._registry.note_exec(self.GROUP_DECODE, key,
                                     cc.perf_counter() - t0,
                                     batches=self.decode_block)
        return StepOut(tokens=toks_np, live=lives_np, finished=fin_np)
