"""Decode-backend seam for the continuous-batching engine.

The engine (engine.py) is jax-free and schedules *slots*; everything
device-shaped hides behind this protocol:

- ``slots`` / ``max_length`` — capacity and the decode-step bound.
- ``admit(slot_ids, requests, budgets)`` — prefill: write the named
  requests' decode state into the named slots (overwriting whatever a
  previous occupant left there — eviction needs no separate call).
- ``step() -> StepOut`` — ONE iteration: advance every slot by the
  backend's decode block (``u`` micro-steps per launch, default 1) and
  return the emitted tokens plus per-slot done flags.
- ``warmup()`` — pay compiles before serving (so compile telemetry
  shows recompiles=0 afterwards); ``reset()`` — discard all device
  state after a failed launch (the engine errors the in-flight cohort
  and keeps serving).

:class:`FakeBackend` is the deterministic jax-free implementation the
unit tests and ``tests/race_specs/spec_serve_engine.py`` drive the REAL
engine with; :class:`~paddle_tpu.serving.jax_backend.JaxDecodeBackend`
is the production one.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from paddle_tpu.utils import concurrency as cc


@dataclasses.dataclass
class StepOut:
    """One iteration's device readback.

    ``tokens [u, B]`` int — the block's emitted tokens per slot;
    ``live [u, B]`` bool — whether the slot was still generating at that
    micro-step (False rows are frozen padding, not output);
    ``finished [B]`` bool — slot hit EOS or its token budget and is free
    for re-admission."""

    tokens: np.ndarray
    live: np.ndarray
    finished: np.ndarray


class FakeBackend:
    """Deterministic, jax-free decode backend.

    ``token_fn(rid, step_index)`` scripts the "model": it returns the
    token the request emits at its ``step_index``-th decode step
    (default: a stable hash — never EOS, so budgets do the finishing).
    ``chunk`` mirrors the jax backend's decode block. ``step_delay_s``
    burns (virtual, under the race shim) clock per launch.
    ``fail_at_launch`` makes the N-th ``step()`` call raise — the chaos
    seam for the engine's error path."""

    def __init__(self, slots: int = 4, max_length: int = 8, eos: int = 1,
                 token_fn: Optional[Callable[[str, int], int]] = None,
                 chunk: int = 1, step_delay_s: float = 0.0,
                 fail_at_launch: Optional[int] = None):
        self.slots = int(slots)
        self.max_length = int(max_length)
        self.eos = int(eos)
        self.chunk = max(int(chunk), 1)
        self.step_delay_s = float(step_delay_s)
        self.fail_at_launch = fail_at_launch
        self.token_fn = token_fn or (
            lambda rid, i: 2 + (hash((rid, i)) % 97)
        )
        self.launches = 0
        self.admits: List[List[str]] = []   # admission waves, for tests
        self._rows: List[Optional[dict]] = [None] * self.slots

    # ------------------------------------------------------------ seam

    def warmup(self) -> None:
        pass

    def reset(self) -> None:
        self._rows = [None] * self.slots

    def admit(self, slot_ids: Sequence[int], requests: Sequence[Any],
              budgets: Sequence[int]) -> None:
        self.admits.append([r.rid for r in requests])
        for b, req, budget in zip(slot_ids, requests, budgets):
            self._rows[b] = {
                "rid": req.rid,
                "budget": min(int(budget), self.max_length),
                "emitted": 0,
                "done": int(budget) <= 0,
            }

    def step(self) -> StepOut:
        self.launches += 1
        if self.fail_at_launch is not None and self.launches == self.fail_at_launch:
            raise RuntimeError(f"injected decode fault at launch {self.launches}")
        if self.step_delay_s:
            cc.sleep(self.step_delay_s)
        u, B = self.chunk, self.slots
        tokens = np.zeros((u, B), np.int64)
        live = np.zeros((u, B), bool)
        finished = np.zeros((B,), bool)
        for b, row in enumerate(self._rows):
            if row is None:
                continue
            for i in range(u):
                if row["done"]:
                    break
                tok = int(self.token_fn(row["rid"], row["emitted"]))
                tokens[i, b] = tok
                live[i, b] = True
                row["emitted"] += 1
                if tok == self.eos or row["emitted"] >= row["budget"]:
                    row["done"] = True
            finished[b] = row["done"]
        return StepOut(tokens=tokens, live=live, finished=finished)
