"""Decode-backend seam for the continuous-batching engine.

The engine (engine.py) is jax-free and schedules *slots*; everything
device-shaped hides behind this protocol:

- ``slots`` / ``max_length`` — capacity and the decode-step bound.
- ``decode_blocks`` — the pre-warmed decode-block ladder (micro-steps
  per launch the engine's :func:`~paddle_tpu.serving.engine.pick_block`
  policy may choose from).
- ``admit(slot_ids, requests, budgets)`` — prefill: write the named
  requests' decode state into the named slots (overwriting whatever a
  previous occupant left there — eviction needs no separate call).
- ``dispatch(block=None)`` — enqueue ONE decode launch advancing every
  slot by ``block`` micro-steps, without waiting for its results (the
  pipelined loop's first half); ``collect() -> StepOut`` — gather the
  OLDEST in-flight launch's results (blocking); ``inflight`` — how many
  launches are dispatched-but-uncollected.
- ``spec_blocks`` — the pre-warmed speculation ladder (draft lengths K
  the engine's ``pick_spec_k`` policy may choose from); empty = the
  backend takes no drafts. When non-empty, ``dispatch``/``step`` accept
  ``draft={slot: [tokens...]}``: ONE verify launch scores every drafted
  position, commits the longest matching prefix per slot and, on the
  first mismatch, the model's corrected token rides free. Slots without
  a draft (or whose draft misses immediately) advance exactly one plain
  greedy step — exact greedy output is preserved unconditionally.
- ``step(block=None) -> StepOut`` — dispatch + collect in one call (the
  blocking loop and one-shot callers).
- ``warmup()`` — pay compiles before serving (so compile telemetry
  shows recompiles=0 afterwards); ``reset()`` — discard all device
  state AND the in-flight queue after a failed launch (the engine
  errors every in-flight cohort and keeps serving).
- ``reload(params)`` — hot weight swap, called by the engine ONLY at
  an iteration boundary (between a collect and the next dispatch): an
  O(1) reference replacement of the weights the next launch reads.
  Same shapes → no recompile; already-dispatched launches snapshotted
  the old reference and are unaffected.

:class:`FakeBackend` is the deterministic jax-free implementation the
unit tests and ``tests/race_specs/spec_serve_engine.py`` drive the REAL
engine with. It models the in-flight pipeline faithfully: ``dispatch``
advances the scripted rows immediately but parks the ``StepOut`` (or
the injected fault) in a FIFO that only ``collect`` drains — matching
jax async dispatch, where results AND errors surface at readback.
:class:`~paddle_tpu.serving.jax_backend.JaxDecodeBackend` is the
production implementation.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from paddle_tpu.utils import concurrency as cc

# A draft batch: slot index -> proposed next tokens (host ints). The
# engine builds it under its lock from the DraftTable; the backend
# snapshots it at dispatch (the pipelined loop carries it alongside the
# slot->request cohort snapshot).
DraftBatch = Dict[int, List[int]]


def parse_decode_blocks(spec: Union[int, str, Sequence[int], None]) -> Tuple[int, ...]:
    """The decode-block ladder from its flag/env spelling: an int, an
    int sequence, or a comma list like ``"1,2,4,8"`` — sorted, deduped,
    every rung >= 1. The single-int form is the PR-12 flag unchanged (a
    one-rung ladder)."""
    if spec is None:
        return (1,)
    if isinstance(spec, str):
        parts = [p for p in spec.replace(" ", "").split(",") if p]
        blocks = [int(p) for p in parts] or [1]
    elif isinstance(spec, (list, tuple)):
        blocks = [int(u) for u in spec] or [1]
    else:
        blocks = [int(spec)]
    out = tuple(sorted({max(u, 1) for u in blocks}))
    return out or (1,)


def parse_spec_tokens(spec: Union[int, str, Sequence[int], None]) -> Tuple[int, ...]:
    """The speculation ladder (draft lengths K) from its flag/env
    spelling. Same grammar as :func:`parse_decode_blocks` except that
    ``None``/``0``/``"0"``/``""`` mean *speculation off* — an empty
    ladder — and rungs < 1 are dropped rather than clamped."""
    if spec is None:
        return ()
    if isinstance(spec, str):
        parts = [p for p in spec.replace(" ", "").split(",") if p]
        ks = [int(p) for p in parts]
    elif isinstance(spec, (list, tuple)):
        ks = [int(k) for k in spec]
    else:
        ks = [int(spec)]
    return tuple(sorted({k for k in ks if k >= 1}))


SLOT_DTYPES = ("f32", "bf16")


def parse_slot_dtype(name: Union[str, None]) -> str:
    """Validate a ``--serve_slot_dtype`` spelling. ``f32`` is the
    PR-12 behavior unchanged; ``bf16`` stores slot state (GRU carries +
    captured statics) in bfloat16 while every step still accumulates in
    f32 — see graph/decode_step.plan_fused_step's mixed-precision
    plan."""
    dt = (name or "f32").strip().lower()
    if dt not in SLOT_DTYPES:
        raise ValueError(
            f"serve_slot_dtype must be one of {SLOT_DTYPES}, got {name!r}")
    return dt


@dataclasses.dataclass
class StepOut:
    """One iteration's device readback.

    ``tokens [u, B]`` int — the block's emitted tokens per slot;
    ``live [u, B]`` bool — whether the slot was still generating at that
    micro-step (False rows are frozen padding, not output);
    ``finished [B]`` bool — slot hit EOS or its token budget and is free
    for re-admission."""

    tokens: np.ndarray
    live: np.ndarray
    finished: np.ndarray


class FakeBackend:
    """Deterministic, jax-free decode backend.

    ``token_fn(rid, step_index)`` scripts the "model": it returns the
    token the request emits at its ``step_index``-th decode step
    (default: a stable hash — never EOS, so budgets do the finishing).
    ``chunk`` mirrors the jax backend's decode-block ladder (an int or
    a ladder spec). ``step_delay_s`` burns (virtual, under the race
    shim) clock per launch at dispatch — where the modeled device does
    its work. ``fail_at_launch`` makes the named dispatched launch(es)
    fault — an int or a collection of launch ordinals (consecutive
    faults are what the circuit breaker counts); like the real backend,
    the fault surfaces at ``collect()`` (the chaos seam for the
    engine's error path, pipelined included). ``fail_with`` overrides
    the injected exception — e.g. a RESOURCE_EXHAUSTED-marked error to
    drive the engine's OOM shutdown path."""

    def __init__(self, slots: int = 4, max_length: int = 8, eos: int = 1,
                 token_fn: Optional[Callable[[str, int], int]] = None,
                 chunk: Union[int, str, Sequence[int]] = 1,
                 step_delay_s: float = 0.0,
                 fail_at_launch: Union[int, Sequence[int], None] = None,
                 fail_with: Optional[Callable[[int], Exception]] = None,
                 spec_tokens: Union[int, str, Sequence[int], None] = None):
        self.slots = int(slots)
        self.max_length = int(max_length)
        self.eos = int(eos)
        self.decode_blocks = parse_decode_blocks(chunk)
        self.chunk = self.decode_blocks[-1]
        self.spec_blocks = parse_spec_tokens(spec_tokens)
        self.step_delay_s = float(step_delay_s)
        if fail_at_launch is None:
            self.fail_at_launch = frozenset()
        elif isinstance(fail_at_launch, int):
            self.fail_at_launch = frozenset((fail_at_launch,))
        else:
            self.fail_at_launch = frozenset(int(n) for n in fail_at_launch)
        self.fail_with = fail_with
        self.token_fn = token_fn or (
            lambda rid, i: 2 + (hash((rid, i)) % 97)
        )
        self.launches = 0
        self.reloads = 0                    # reload() calls, for tests
        self.admits: List[List[str]] = []   # admission waves, for tests
        self.verify_launches = 0            # draft-carrying launches
        self.spec_drafts: List[DraftBatch] = []  # verify inputs, for tests
        self._rows: List[Optional[dict]] = [None] * self.slots
        # dispatched-but-uncollected results (or faults): StepOut |
        # Exception, drained FIFO by collect()
        self._pending: collections.deque = collections.deque()

    # ------------------------------------------------------------ seam

    @property
    def inflight(self) -> int:
        return len(self._pending)

    def warmup(self) -> None:
        pass

    def reset(self) -> None:
        self._rows = [None] * self.slots
        self._pending.clear()

    def reload(self, params: Any) -> None:
        """Hot weight swap, modeled: a callable payload replaces
        ``token_fn`` (the fake's "weights" — tests observe the scripted
        output change at exactly the next launch); anything else just
        counts. Raising here must leave the old behavior serving —
        engine._apply_reload_locked's contract."""
        if callable(params):
            self.token_fn = params
        self.reloads += 1

    def admit(self, slot_ids: Sequence[int], requests: Sequence[Any],
              budgets: Sequence[int]) -> None:
        self.admits.append([r.rid for r in requests])
        for b, req, budget in zip(slot_ids, requests, budgets):
            self._rows[b] = {
                "rid": req.rid,
                "budget": min(int(budget), self.max_length),
                "emitted": 0,
                "done": int(budget) <= 0,
            }

    def dispatch(self, block: Optional[int] = None,
                 draft: Optional[DraftBatch] = None) -> None:
        """Advance the scripted rows now, surface the results (or the
        injected fault) only at collect — the jax async-dispatch
        contract the pipelined engine is written against. With
        ``draft``, the launch is a verify: each slot advances through
        its drafted tokens while the script agrees, plus the corrected
        token on the first disagreement (slots without a draft take one
        plain step)."""
        self.launches += 1
        if self.launches in self.fail_at_launch:
            if self.fail_with is not None:
                self._pending.append(self.fail_with(self.launches))
            else:
                self._pending.append(RuntimeError(
                    f"injected decode fault at launch {self.launches}"))
            return
        if self.step_delay_s:
            cc.sleep(self.step_delay_s)
        if draft:
            self.verify_launches += 1
            snap = {int(b): [int(t) for t in toks]
                    for b, toks in draft.items()}
            self.spec_drafts.append(snap)
            self._pending.append(self._verify(snap))
            return
        u = max(int(block), 1) if block else self.chunk
        B = self.slots
        tokens = np.zeros((u, B), np.int64)
        live = np.zeros((u, B), bool)
        finished = np.zeros((B,), bool)
        for b, row in enumerate(self._rows):
            if row is None:
                continue
            for i in range(u):
                if row["done"]:
                    break
                tok = int(self.token_fn(row["rid"], row["emitted"]))
                tokens[i, b] = tok
                live[i, b] = True
                row["emitted"] += 1
                if tok == self.eos or row["emitted"] >= row["budget"]:
                    row["done"] = True
            finished[b] = row["done"]
        self._pending.append(StepOut(tokens=tokens, live=live,
                                     finished=finished))

    def _verify(self, draft: DraftBatch) -> StepOut:
        """The scripted verify launch: exact greedy semantics — every
        emitted token is ``token_fn``'s own output; the draft only
        decides how many steps a slot gets this launch."""
        u = max(max((len(t) for t in draft.values()), default=0), 1)
        B = self.slots
        tokens = np.zeros((u, B), np.int64)
        live = np.zeros((u, B), bool)
        finished = np.zeros((B,), bool)
        for b, row in enumerate(self._rows):
            if row is None:
                continue
            d = draft.get(b, [])
            i = 0
            while not row["done"]:
                tok = int(self.token_fn(row["rid"], row["emitted"]))
                tokens[i, b] = tok
                live[i, b] = True
                row["emitted"] += 1
                if tok == self.eos or row["emitted"] >= row["budget"]:
                    row["done"] = True
                matched = i < len(d) and tok == d[i]
                i += 1
                if not matched or i >= max(len(d), 1):
                    break
            finished[b] = row["done"]
        return StepOut(tokens=tokens, live=live, finished=finished)

    def collect(self) -> StepOut:
        assert self._pending, "collect() with no launch in flight"
        out = self._pending.popleft()
        if isinstance(out, Exception):
            raise out
        return out

    def step(self, block: Optional[int] = None,
             draft: Optional[DraftBatch] = None) -> StepOut:
        # forward `draft` only when speculating: subclasses that
        # override dispatch(block=...) without the draft seam (every
        # pre-speculation backend shim) keep working un-speculated
        if draft is None:
            self.dispatch(block=block)
        else:
            self.dispatch(block=block, draft=draft)
        return self.collect()
