"""Host-side draft source for self-speculative decode (PR 20).

Speculative decoding (Leviathan et al. 2023) needs a cheap proposal
distribution. We do not run a second model: the serving workload itself
is the draft source. A seeded n-gram table — bigram and trigram chains
over the *generated* token streams, maintained on the host and updated
only at collect boundaries under the engine lock — proposes up to K
next tokens per slot. The device-side ``serve_verify`` launch then
scores every proposed position with the real model and commits the
longest matching prefix (plus, on a mismatch, the model's corrected
token, which rides free). Exact greedy output is preserved no matter
how bad the drafts are; draft quality only moves throughput.

Design notes:

- jax-free, like the rest of the engine seam. The table is plain dicts
  of ints; ``propose`` walks the chains greedily (most-frequent
  successor, trigram first, bigram backoff) so proposals are
  deterministic for a given observation history — golden-parity tests
  rely on runs being reproducible, and the engine serializes all
  observe/propose calls under its lock.
- ``observe(tokens, context=...)`` counts transitions *into* ``tokens``
  only; the caller passes the previously committed tail as ``context``
  so chains span collect boundaries without double-counting pairs that
  were already observed.
- Ties break toward the smallest token id (stable across dict insert
  order via explicit comparison), salted by ``seed`` only in the sense
  that the seed participates in nothing stochastic — it is kept so a
  future sampled draft policy has a home and so benches can stamp it.
- Bounded: per-context successor maps are capped (``max_successors``)
  and the table evicts the oldest contexts beyond ``max_contexts`` —
  the serving fleet runs for days; the draft table must not be a leak.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["DraftTable"]

# Longest context the chains key on (trigram = 2 tokens of context).
ORDER = 2


class DraftTable:
    """Seeded n-gram/suffix table proposing up to K next tokens.

    The table is shared across slots (requests with similar outputs feed
    each other's drafts — the high-acceptance regime for translation
    serving where decode streams repeat domain phrases) while staying
    correct for adversarial streams: a wrong proposal costs one verify
    mismatch, never a wrong token.
    """

    def __init__(self, seed: int = 0, max_contexts: int = 65536,
                 max_successors: int = 8) -> None:
        self.seed = int(seed)
        self.order = ORDER
        self._max_contexts = int(max_contexts)
        self._max_successors = int(max_successors)
        # context tuple (1 or 2 tokens) -> {next_token: count}
        self._chains: "OrderedDict[Tuple[int, ...], Dict[int, int]]" = OrderedDict()
        # () context: most common stream-opening token (decoder streams
        # all start from BOS, so first tokens correlate across requests).
        self._starts: Dict[int, int] = {}
        self.observed = 0

    # ------------------------------------------------------------- learn
    def observe(self, tokens: Sequence[int], context: Sequence[int] = ()) -> None:
        """Fold a committed token run into the chains.

        ``context`` is the tail of tokens committed *before* this run
        (the engine passes the request's last ``order`` tokens); only
        transitions whose successor lies inside ``tokens`` are counted,
        so re-passing the context never double-counts.
        """
        toks = [int(t) for t in tokens]
        if not toks:
            return
        ctx = [int(t) for t in context][-self.order:]
        if not ctx:
            self._starts[toks[0]] = self._starts.get(toks[0], 0) + 1
        stream = ctx + toks
        base = len(ctx)
        for i in range(base, len(stream)):
            nxt = stream[i]
            for n in (1, 2):
                if i - n < 0:
                    continue
                key = tuple(stream[i - n:i])
                self._bump(key, nxt)
        self.observed += len(toks)

    def _bump(self, key: Tuple[int, ...], nxt: int) -> None:
        succ = self._chains.get(key)
        if succ is None:
            while len(self._chains) >= self._max_contexts:
                self._chains.popitem(last=False)
            succ = {}
            self._chains[key] = succ
        else:
            self._chains.move_to_end(key)
        succ[nxt] = succ.get(nxt, 0) + 1
        if len(succ) > self._max_successors:
            # Drop the rarest successor (ties: largest token id goes).
            drop = min(succ.items(), key=lambda kv: (kv[1], -kv[0]))[0]
            del succ[drop]

    # ----------------------------------------------------------- propose
    def _next(self, context: Sequence[int]) -> Optional[int]:
        ctx = [int(t) for t in context]
        for n in (2, 1):
            if len(ctx) < n:
                continue
            succ = self._chains.get(tuple(ctx[-n:]))
            if succ:
                # Most frequent; ties break toward the smallest token id
                # so proposals are deterministic across dict orderings.
                return max(succ.items(), key=lambda kv: (kv[1], -kv[0]))[0]
        if not ctx and self._starts:
            return max(self._starts.items(), key=lambda kv: (kv[1], -kv[0]))[0]
        return None

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        """Greedy chain walk: up to ``k`` draft tokens following
        ``context`` (the request's committed + optimistically pending
        tail). Returns fewer than ``k`` — possibly none — when the
        chains run dry; an empty proposal means the slot rides the
        launch as a plain single-step decode."""
        out: List[int] = []
        ctx = [int(t) for t in context]
        for _ in range(max(int(k), 0)):
            nxt = self._next(ctx + out)
            if nxt is None:
                break
            out.append(nxt)
        return out

    # ------------------------------------------------------------- admin
    def __len__(self) -> int:
        return len(self._chains)
