"""Cross-host serving transport (doc/serving.md "Cross-host fleet").

The fleet router of PR 16 reached replicas only through subprocess
pipes — one host, no network to drop, stall, or tear. This module is
the socket analog of the reference's custom TCP parameter-server
transport, rebuilt on three contracts the repo already enforces:

- **Framing**: length-prefixed JSON — a 4-byte big-endian payload size
  then UTF-8 JSON. :class:`FrameReader` is torn-frame tolerant: a
  partial frame at connection close is discarded (and logged), never a
  crash — the same discipline as the metrics torn-tail readers.
- **State machine**: each :class:`SocketTransport` connection walks
  CONNECTING -> UP -> BACKOFF -> CLOSED, reconnecting on the shared
  :class:`~paddle_tpu.utils.retry.RetryPolicy` schedule (exponential
  backoff + jitter + deadline). A reconnect replays the hello
  handshake so undelivered requests are re-offered (at-least-once;
  dedupe by id on both ends absorbs the duplicates).
- **Deadlines on the wire**: :class:`SocketReplica` stamps an absolute
  wall-clock ``deadline_unix`` on each request (preserved across
  re-offers and hedges), so a remote replica sheds expired work
  *itself* through the PR-14 deadline-aware admission path.

Heartbeat ping/pong frames carry the remote ``Engine.status()`` doc
back into the router's ``replica_score`` health path; ``net.connect``
and ``net.rpc`` ``kind=span`` hops join the PR-18 trace timeline; the
``net.drop`` / ``net.stall`` / ``net.torn_frame`` / ``net.dup`` chaos
sites live on the shared frame read/write paths (`paddle faults`).

jax-free; every thread/lock/clock/sleep goes through the
``utils/concurrency`` seam so `paddle race` can explore the
reconnect-vs-send and hedge-vs-first-answer interleavings
(tests/race_specs/spec_transport.py) with fake wires.
"""

from __future__ import annotations

import errno
import json
import logging
import random
import socket
import struct
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from paddle_tpu.resilience import faultinject
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.retry import RetryPolicy

log = logging.getLogger("paddle_tpu")

# frame header: 4-byte big-endian payload length, then UTF-8 JSON
HEADER = struct.Struct("!I")
# a frame this large is a corrupt header, not a request — treat as a
# protocol error (disconnect), never an attempted 4 GiB allocation
MAX_FRAME_BYTES = 16 << 20
# client ping cadence and the bound past which a silent peer reads as
# stale (mirrors the fleet router's file-status staleness bound)
HEARTBEAT_PERIOD_S = 1.0
STALE_AFTER_S = 5.0
CONNECT_TIMEOUT_S = 5.0
# short socket timeouts keep every read/accept loop interruptible
# (close() takes effect within one tick; no unbounded blocking)
IO_TICK_S = 0.25

CONNECTING, UP, BACKOFF, CLOSED = "connecting", "up", "backoff", "closed"


def wall_time() -> float:
    """Wall-clock UNIX seconds, for ON-THE-WIRE deadlines only.

    Monotonic clocks are per-process: a deadline stamped by the router
    must be comparable on a different host, so the wire format uses
    wall time (hosts are NTP-disciplined; the skew bound is the same
    one the trace aligner already tolerates). Everything else in this
    module reads ``cc.monotonic``.
    """
    return time.time()  # lint: disable=PTL001 -- deadline_unix crosses hosts; monotonic clocks are per-process and incomparable on the wire


def parse_addr(addr: str) -> Tuple[str, int]:
    """``HOST:PORT`` -> ``(host, port)``. Bare ``:PORT`` means all
    interfaces (listen) / localhost (connect is given the full form by
    the flag author)."""
    host, sep, port = str(addr).rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"bad address {addr!r} (want HOST:PORT)")
    return host or "0.0.0.0", int(port)


class FrameError(ValueError):
    """A frame the protocol cannot have produced (oversized header) —
    the connection is poisoned and gets dropped, the process survives."""


def encode_frame(doc: Dict[str, Any]) -> bytes:
    payload = json.dumps(doc, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(f"frame of {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    return HEADER.pack(len(payload)) + payload


class FrameReader:
    """Accumulating, torn-tolerant frame decoder.

    ``feed(bytes)`` returns every complete frame decoded so far; a
    partial frame simply stays buffered until the next feed. At
    connection close the owner checks :meth:`pending_bytes` and
    discards the fragment — the torn-tail contract. A frame whose
    payload is not a JSON object is skipped (logged), not fatal.
    """

    def __init__(self) -> None:
        self._lock = cc.Lock()
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        with self._lock:
            self._buf.extend(data)
            while len(self._buf) >= HEADER.size:
                (n,) = HEADER.unpack_from(self._buf)
                if n > MAX_FRAME_BYTES:
                    raise FrameError(
                        f"frame header claims {n} bytes "
                        f"(> {MAX_FRAME_BYTES}) — corrupt stream")
                if len(self._buf) < HEADER.size + n:
                    break
                payload = bytes(self._buf[HEADER.size:HEADER.size + n])
                del self._buf[:HEADER.size + n]
                try:
                    doc = json.loads(payload)
                except ValueError as e:
                    log.warning("transport: skipping undecodable frame "
                                "(%s)", e)
                    continue
                if isinstance(doc, dict):
                    out.append(doc)
                else:
                    log.warning("transport: skipping non-object frame")
        return out

    def pending_bytes(self) -> int:
        with self._lock:
            return len(self._buf)


def _close_wire(wire) -> None:
    try:
        wire.close()
    except OSError:
        pass


def framed_send(wire, doc: Dict[str, Any]) -> None:
    """Write one frame, with the net.* wire chaos sites planted.

    ``net.torn_frame`` sends a strict prefix then resets; ``net.drop``
    resets before any byte; ``net.dup`` sends the frame twice (the
    id-dedupe on the receiving side must absorb it). All three surface
    to the caller as the OSError a real flaky network would raise.
    """
    data = encode_frame(doc)
    try:
        faultinject.fault_point("net.torn_frame")
    except faultinject.FaultInjected as e:
        try:
            wire.sendall(data[:max(1, len(data) // 2)])
        except OSError:
            pass
        _close_wire(wire)
        raise ConnectionResetError(
            errno.ECONNRESET, f"injected torn frame: {e}")
    try:
        faultinject.fault_point("net.drop")
    except faultinject.FaultInjected as e:
        _close_wire(wire)
        raise ConnectionResetError(
            errno.ECONNRESET, f"injected connection reset: {e}")
    wire.sendall(data)
    try:
        faultinject.fault_point("net.dup")
    except faultinject.FaultInjected:
        wire.sendall(data)  # duplicate delivery — dedupe-by-id absorbs


def _emit_span(name: str, t0_mono: float, dur_s: float, **fields) -> None:
    """One transport-side ``kind=span`` hop (net.connect / net.rpc)."""
    from paddle_tpu.observability import metrics as obsm

    if not obsm.enabled():
        return
    obsm.emit("span", name=name, t0=obsm.rel_time(t0_mono),
              dur_s=round(max(float(dur_s), 0.0), 6),
              **{k: v for k, v in fields.items() if v not in ("", None)})


def _count(name: str, n: float = 1.0) -> None:
    from paddle_tpu.observability import metrics as obsm

    obsm.registry().counter(name).inc(n)


def _tcp_connect(addr: str):
    host, port = parse_addr(addr)
    s = socket.create_connection((host or "127.0.0.1", port),
                                 timeout=CONNECT_TIMEOUT_S)
    s.settimeout(IO_TICK_S)
    return s


class SocketTransport:
    """One framed connection with the CONNECTING/UP/BACKOFF/CLOSED
    state machine and RetryPolicy-scheduled reconnects.

    A connector daemon thread owns the lifecycle: connect (or back
    off), then read frames inline until disconnect, then decide —
    reconnect (BACKOFF) or give up (CLOSED: the retry budget is
    exhausted, the owner called :meth:`close`, or reconnection was
    disabled for a drain). ``on_frame(doc)`` fires from that thread
    for every decoded frame; ``on_up()`` after each (re)connect —
    where :class:`SocketReplica` replays its hello handshake; and
    ``on_down()`` exactly once, on reaching CLOSED.

    ``connect_fn(addr)`` is injectable (any object with ``sendall`` /
    ``recv`` / ``close``), so the race spec drives this exact state
    machine over in-memory wires under the virtualized scheduler.
    """

    def __init__(self, name: str, addr: str, *,
                 on_frame: Callable[[Dict[str, Any]], None],
                 on_up: Optional[Callable[[], None]] = None,
                 on_down: Optional[Callable[[], None]] = None,
                 policy: Optional[RetryPolicy] = None,
                 connect_fn: Optional[Callable[[str], Any]] = None):
        self.name = name
        self.addr = addr
        self._on_frame = on_frame
        self._on_up = on_up
        self._on_down = on_down
        self._policy = policy or RetryPolicy(retry_on=(OSError,),
                                             name=f"net.{name}")
        self._connect_fn = connect_fn or _tcp_connect
        self._rng = random.Random(self._policy.seed)
        self._lock = cc.Lock()
        self._state = CONNECTING
        self._wire = None
        self._closing = False
        self._reconnect = True
        self._reconnects = 0
        self._send_lock = cc.Lock()
        self._thread = None

    # ------------------------------------------------------------ state

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def reconnects(self) -> int:
        with self._lock:
            return self._reconnects

    def closed(self) -> bool:
        return self.state == CLOSED

    # ------------------------------------------------------- lifecycle

    def start(self) -> "SocketTransport":
        t = cc.Thread(target=self._run, name=f"transport-{self.name}",
                      daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        return self

    def disable_reconnect(self) -> None:
        """The next disconnect goes straight to CLOSED — the drain
        path, where the peer closing the connection is the *success*
        signal, not a failure to retry."""
        with self._lock:
            self._reconnect = False

    def close(self) -> None:
        with self._lock:
            self._closing = True
            wire, self._wire = self._wire, None
        if wire is not None:
            _close_wire(wire)

    def join(self, timeout: float = 30.0) -> bool:
        with self._lock:
            t = self._thread
        if t is None:
            return True
        t.join(timeout=timeout)
        return not t.is_alive()

    # ------------------------------------------------------------ send

    def send(self, doc: Dict[str, Any]) -> bool:
        """Frame ``doc`` onto the live connection. False when not UP or
        the write fails (the failed wire is closed, which wakes the
        reader and triggers the reconnect machinery)."""
        with self._lock:
            wire = self._wire if self._state == UP else None
        if wire is None:
            return False
        try:
            with self._send_lock:
                framed_send(wire, doc)
            return True
        except (OSError, FrameError) as e:
            log.warning("transport %s: send failed (%s)", self.name, e)
            _close_wire(wire)
            return False

    # ------------------------------------------------------- connector

    def _run(self) -> None:
        attempt = 0
        first = True
        give_up_at = (cc.monotonic() + self._policy.deadline
                      if self._policy.deadline > 0 else None)
        while True:
            with self._lock:
                if self._closing:
                    self._state = CLOSED
                    break
                self._state = CONNECTING
            t0 = cc.monotonic()
            try:
                wire = self._connect_fn(self.addr)
            except OSError as e:
                attempt += 1
                if attempt >= self._policy.max_attempts or (
                        give_up_at is not None
                        and cc.monotonic() >= give_up_at):
                    log.warning("transport %s: giving up on %s after "
                                "%d attempt(s) (%s)", self.name,
                                self.addr, attempt, e)
                    with self._lock:
                        self._state = CLOSED
                    break
                delay = self._policy.delay_for(attempt, self._rng)
                with self._lock:
                    self._state = BACKOFF
                if not self._backoff(delay):
                    with self._lock:
                        self._state = CLOSED
                    break
                continue
            with self._lock:
                if self._closing:
                    self._state = CLOSED
                    _close_wire(wire)
                    break
                self._wire = wire
                self._state = UP
                if not first:
                    self._reconnects += 1
            if not first:
                _count("net.reconnects")
            first = False
            attempt = 0
            if give_up_at is not None:
                give_up_at = cc.monotonic() + self._policy.deadline
            _emit_span("net.connect", t0, cc.monotonic() - t0,
                       replica=self.name, addr=self.addr)
            if self._on_up is not None:
                try:
                    self._on_up()
                except Exception as e:  # a hello hiccup is a reconnect,
                    log.warning("transport %s: on_up failed (%s)",
                                self.name, e)   # not a crash
            self._read_until_disconnect(wire)
            _close_wire(wire)
            with self._lock:
                self._wire = None
                if self._closing or not self._reconnect:
                    self._state = CLOSED
                    break
                self._state = BACKOFF
        if self._on_down is not None:
            try:
                self._on_down()
            except Exception as e:
                log.warning("transport %s: on_down failed (%s)",
                            self.name, e)

    def _backoff(self, delay: float) -> bool:
        """RetryPolicy-scheduled sleep, interruptible by close().
        Returns False when the owner closed us mid-backoff."""
        deadline = cc.monotonic() + max(delay, 0.0)
        while cc.monotonic() < deadline:
            with self._lock:
                if self._closing:
                    return False
            cc.sleep(min(0.05, max(deadline - cc.monotonic(), 0.0)))
        with self._lock:
            return not self._closing

    def _read_until_disconnect(self, wire) -> None:
        reader = FrameReader()
        while True:
            with self._lock:
                if self._closing:
                    return
            try:
                # net.stall (sleep action) wedges reads right here:
                # heartbeats stop, health goes stale, the router
                # reroutes — the read-wedge drill
                faultinject.fault_point("net.stall", info=self.name)
            except faultinject.FaultInjected:
                return  # raise action: treat as a disconnect
            try:
                data = wire.recv(65536)
            except TimeoutError:
                continue
            except OSError:
                data = b""
            if not data:
                if reader.pending_bytes():
                    log.warning(
                        "transport %s: discarding %d-byte partial frame "
                        "at close (torn tail)", self.name,
                        reader.pending_bytes())
                return
            try:
                docs = reader.feed(data)
            except FrameError as e:
                log.warning("transport %s: %s — dropping connection",
                            self.name, e)
                return
            for doc in docs:
                try:
                    self._on_frame(doc)
                except Exception as e:
                    log.warning("transport %s: frame handler failed "
                                "(%s)", self.name, e)


class SocketReplica:
    """A remote `paddle serve --listen` replica behind the ProcReplica
    duck-type (``send`` / ``health`` / ``alive`` / ``poll_exit`` /
    ``pending_requests`` / ``begin_drain`` / ``kill`` / ``join`` /
    ``start``), so :class:`~paddle_tpu.serving.fleet.FleetRouter` is
    transport-agnostic.

    Transport death (retry budget exhausted) surfaces as a synthetic
    nonzero exit from :meth:`poll_exit` — the router's death path
    re-offers this replica's outstanding requests to survivors and
    charges its restart budget, exactly as for a dead pipe child; a
    restart here is a fresh transport with a fresh retry budget.

    Requests are tracked until answered; every (re)connect sends a
    ``hello`` listing them, and the server answers the already-done
    ones from its answered-map and names the ``unknown`` ones, which
    are re-sent — the at-least-once contract over the wire. The first
    send stamps ``deadline_unix`` (wall clock) into the request doc
    itself, so a re-offer or hedge carries the *shrunken* remaining
    budget, and the remote admission sheds expired work locally.
    """

    def __init__(self, name: str, addr: str, *,
                 deliver: Callable[[str, Dict[str, Any]], None],
                 timeout_s: float = 60.0,
                 policy: Optional[RetryPolicy] = None,
                 connect_fn: Optional[Callable[[str], Any]] = None):
        self.name = name
        self.addr = addr
        self._deliver = deliver
        self._timeout_s = float(timeout_s)
        self._policy = policy
        self._connect_fn = connect_fn
        self._lock = cc.Lock()
        self._transport: Optional[SocketTransport] = None
        self._incarnation = 0
        self._exit: Optional[int] = None
        self._draining = False
        self._health: Optional[Dict[str, Any]] = None
        self._health_at = 0.0
        self._ping_at = -1e18
        # rid -> (request doc, send monotonic) until answered — the
        # hello re-offer set and the net.rpc span timebase
        self._sent: Dict[str, Tuple[Dict[str, Any], float]] = {}

    # ------------------------------------------------------- lifecycle

    def start(self) -> "SocketReplica":
        with self._lock:
            self._exit = None
            self._draining = False
            self._incarnation += 1
            inc = self._incarnation
            t = SocketTransport(
                f"{self.name}#{inc}", self.addr,
                on_frame=self._on_frame,
                on_up=self._on_up,
                on_down=lambda: self._on_down(inc),
                policy=self._policy,
                connect_fn=self._connect_fn)
            self._transport = t
        t.start()
        return self

    def alive(self) -> bool:
        with self._lock:
            t, ec = self._transport, self._exit
        return t is not None and ec is None and not t.closed()

    def poll_exit(self) -> Optional[int]:
        with self._lock:
            return self._exit

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True
            t = self._transport
        if t is not None:
            # the peer closing the connection after it drains is the
            # clean-exit signal, not a failure to retry
            t.disable_reconnect()
            t.send({"op": "drain"})

    def kill(self) -> None:
        with self._lock:
            t = self._transport
        if t is not None:
            t.disable_reconnect()
            t.close()

    def join(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            t = self._transport
        if t is None:
            return True
        return t.join(timeout if timeout is not None else 30.0)

    # ---------------------------------------------------------- duties

    def send(self, doc: Dict[str, Any]) -> bool:
        rid = str(doc.get("id", ""))
        with self._lock:
            t = self._transport
            if t is None or self._exit is not None or self._draining:
                return False
            if rid and "deadline_unix" not in doc and self._timeout_s > 0:
                # stamped ONCE into the shared doc: re-offers and
                # hedges of this request carry the shrunken remainder
                doc["deadline_unix"] = round(
                    wall_time() + self._timeout_s, 3)
            if rid:
                self._sent[rid] = (doc, cc.monotonic())
        ok = t.send(doc)
        if not ok and rid:
            with self._lock:
                self._sent.pop(rid, None)
        return ok

    def pending_requests(self) -> List[str]:
        # the remote journal replays on the REMOTE side at restart; on
        # a transport death everything owed is already in the router's
        # _outstanding set, so there is no local journal to read
        return []

    def health(self, now: float) -> Dict[str, Any]:
        with self._lock:
            t = self._transport
            ping_due = now - self._ping_at >= HEARTBEAT_PERIOD_S
            if ping_due:
                self._ping_at = now
            h, h_at = self._health, self._health_at
        if ping_due and t is not None:
            t.send({"op": "ping"})
        if h is not None and now - h_at <= STALE_AFTER_S:
            out = dict(h)
            out["age_s"] = round(max(now - h_at, 0.0), 3)
            return out
        return {"stale": True,
                "age_s": round(max(now - h_at, 0.0), 3) if h else None,
                "detail": f"no pong from {self.addr}"}

    # -------------------------------------------------------- internal

    def _on_up(self) -> None:
        with self._lock:
            t = self._transport
            rids = sorted(self._sent)
        if t is not None:
            t.send({"op": "hello", "replica": self.name,
                    "outstanding": rids})

    def _on_down(self, inc: int) -> None:
        with self._lock:
            if inc != self._incarnation:
                return  # a superseded transport's last gasp
            if self._exit is None:
                self._exit = 0 if self._draining else 1

    def _on_frame(self, doc: Dict[str, Any]) -> None:
        op = doc.get("op")
        if op == "pong":
            with self._lock:
                self._health = doc.get("status") or {}
                self._health_at = cc.monotonic()
            return
        if op == "hello_ack":
            # frames that never reached the server: re-send the full
            # request docs (at-least-once; server dedupes by id)
            unknown = [str(r) for r in doc.get("unknown") or []]
            with self._lock:
                t = self._transport
                docs = [self._sent[r][0] for r in unknown
                        if r in self._sent]
            if docs:
                log.info("transport %s: re-offering %d undelivered "
                         "request(s) after reconnect", self.name,
                         len(docs))
            for d in docs:
                if t is None or not t.send(d):
                    break
            return
        if "id" in doc:
            rid = str(doc["id"])
            with self._lock:
                ent = self._sent.pop(rid, None)
            if ent is not None:
                _emit_span("net.rpc", ent[1], cc.monotonic() - ent[1],
                           trace=str(doc.get("trace_id") or ""),
                           replica=self.name)
            self._deliver(self.name, doc)


class EngineSocketServer:
    """The replica-side front door: accepts framed requests for an
    in-process :class:`~paddle_tpu.serving.engine.Engine` and answers
    them IN SUBMISSION ORDER over the live connection (the same
    ordering contract as the stdin front-end).

    One router connection is live at a time — a newer accept replaces
    the old (a reconnecting router must not split the answer stream).
    Answered results are kept by id: a ``hello`` after reconnect gets
    the already-answered subset re-sent and the never-seen subset named
    in ``hello_ack.unknown`` so the client re-offers them. With a
    journal, the done-mark lands only after a result actually went out
    on a live wire — an unsent answer is re-offered by journal replay
    on the next incarnation (at-least-once; dedupe by id).
    """

    def __init__(self, engine, listen: str, *, journal=None,
                 on_drain: Optional[Callable[[], None]] = None):
        self.engine = engine
        self.journal = journal
        self._on_drain = on_drain
        self._lock = cc.Lock()
        self._cv = cc.Condition(self._lock)
        self._pending: List[Tuple[str, Any, str]] = []  # submission order
        self._inflight: set = set()
        self._answered: Dict[str, Dict[str, Any]] = {}
        self._conn = None
        self._closing = False
        self._threads: List[Any] = []
        host, port = parse_addr(listen)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self._srv.settimeout(IO_TICK_S)
        self.host, self.port = self._srv.getsockname()[:2]

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "EngineSocketServer":
        acceptor = cc.Thread(target=self._accept, daemon=True,
                             name="transport-accept")
        pump = cc.Thread(target=self._pump, daemon=True,
                         name="transport-pump")
        with self._lock:
            self._threads = [acceptor, pump]
        acceptor.start()
        pump.start()
        return self

    def replay(self, doc: Dict[str, Any]) -> None:
        """Journal re-offer at startup: submit past queue_cap (the
        backlog was durably accepted by a previous incarnation) and
        queue its answer for whichever router connects."""
        rid = str(doc["id"])
        trace = str(doc.get("trace_id") or "")
        fut = self.engine.submit(
            doc.get("prompt") or [],
            max_new_tokens=doc.get("max_new_tokens"),
            rid=rid, replay=True, trace=trace)
        with self._lock:
            self._inflight.add(rid)
            self._pending.append((rid, fut, trace))
            self._cv.notify_all()

    def wait_idle(self, timeout: float) -> bool:
        """True when every submitted request has been answered."""
        deadline = cc.monotonic() + timeout
        with self._lock:
            while self._pending:
                remaining = deadline - cc.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=min(remaining, 0.25))
            return True

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closing = True
            conn, self._conn = self._conn, None
            threads = list(self._threads)
            self._cv.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass
        if conn is not None:
            _close_wire(conn)
        for t in threads:
            t.join(timeout=timeout)

    # -------------------------------------------------------- internal

    def _accept(self) -> None:
        while True:
            with self._lock:
                if self._closing:
                    return
            try:
                wire, _peer = self._srv.accept()
            except TimeoutError:
                continue
            except OSError:
                return
            wire.settimeout(IO_TICK_S)
            with self._lock:
                old, self._conn = self._conn, wire
            if old is not None:
                _close_wire(old)  # latest router connection wins
            cc.Thread(target=self._serve_conn, args=(wire,),
                      daemon=True, name="transport-conn").start()

    def _serve_conn(self, wire) -> None:
        reader = FrameReader()
        while True:
            with self._lock:
                if self._closing:
                    break
            try:
                faultinject.fault_point("net.stall", info="server")
            except faultinject.FaultInjected:
                break
            try:
                data = wire.recv(65536)
            except TimeoutError:
                continue
            except OSError:
                data = b""
            if not data:
                break
            try:
                docs = reader.feed(data)
            except FrameError as e:
                log.warning("transport server: %s — dropping "
                            "connection", e)
                break
            for doc in docs:
                try:
                    self._handle(doc, wire)
                except Exception as e:
                    log.warning("transport server: frame handler "
                                "failed (%s)", e)
        if reader.pending_bytes():
            log.warning("transport server: discarding %d-byte partial "
                        "frame at close (torn tail)",
                        reader.pending_bytes())
        _close_wire(wire)

    def _send(self, wire, doc: Dict[str, Any]) -> bool:
        if wire is None:
            return False
        try:
            framed_send(wire, doc)
            return True
        except (OSError, FrameError) as e:
            log.warning("transport server: send failed (%s)", e)
            _close_wire(wire)
            return False

    def _handle(self, doc: Dict[str, Any], wire) -> None:
        op = doc.get("op")
        if op == "ping":
            self._send(wire, {"op": "pong",
                              "status": self.engine.status()})
            return
        if op == "hello":
            outstanding = [str(r) for r in doc.get("outstanding") or []]
            with self._lock:
                resend = [self._answered[r] for r in outstanding
                          if r in self._answered]
                unknown = [r for r in outstanding
                           if r not in self._answered
                           and r not in self._inflight]
            for out in resend:
                if not self._send(wire, out):
                    return
            self._send(wire, {"op": "hello_ack", "unknown": unknown})
            return
        if op == "drain":
            if self._on_drain is not None:
                self._on_drain()
            return
        if op is not None:
            log.warning("transport server: unknown op %r", op)
            return
        # a request frame
        rid = str(doc.get("id", "")) or f"req-?-{id(doc)}"
        trace = str(doc.get("trace_id") or "")
        prompt = doc.get("prompt")
        if not isinstance(prompt, list) or not all(
                isinstance(t, int) for t in prompt):
            self._send(wire, {"id": rid, "outcome": "error",
                              "tokens": [],
                              "error": "prompt must be a list of "
                                       "token ids"})
            return
        with self._lock:
            done = self._answered.get(rid)
            dup = done is not None or rid in self._inflight
        if done is not None:
            self._send(wire, done)  # hedge/dup re-ask: answer again
            return
        if dup:
            return  # in flight — exactly one answer will go out
        # deadline-aware admission, now remote: the wall-clock deadline
        # the router stamped decides whether any budget remains here
        timeout_s = None
        dl = doc.get("deadline_unix")
        if dl:
            timeout_s = float(dl) - wall_time()
            if timeout_s <= 0:
                out = {"id": rid, "outcome": "timeout", "tokens": [],
                       "error": "deadline expired on arrival"}
                with self._lock:
                    self._answered[rid] = out
                self._send(wire, out)
                return
        if self.journal is not None:
            jt0 = cc.monotonic()
            accepted = self.journal.accept(doc)
            if trace:
                _emit_span("replica.journal", jt0,
                           cc.monotonic() - jt0, trace=trace)
            if not accepted:
                # journaled by a previous incarnation: replayed already
                # (its answer will flow) or done before the crash
                log.info("transport server: duplicate request id %r "
                         "skipped (journal)", rid)
                return
        fut = self.engine.submit(
            prompt, max_new_tokens=doc.get("max_new_tokens"),
            rid=rid, timeout_s=timeout_s, trace=trace)
        with self._lock:
            self._inflight.add(rid)
            self._pending.append((rid, fut, trace))
            self._cv.notify_all()

    def _pump(self) -> None:
        """Resolve futures in submission order and frame the answers
        out — the socket analog of the stdin front-end's flush loop."""
        while True:
            with self._lock:
                while not self._pending and not self._closing:
                    self._cv.wait(timeout=0.25)
                if self._closing and not self._pending:
                    return
                rid, fut, trace = self._pending[0]
            if not fut.done():
                # head-of-line blocking is the ordering contract; the
                # bounded wait keeps close() able to interrupt
                with self._lock:
                    self._cv.wait(timeout=0.05)
                continue
            res = fut.result(timeout=600.0)
            out: Dict[str, Any] = {"id": rid, "outcome": res.outcome,
                                   "tokens": res.tokens}
            if trace:
                out["trace_id"] = trace  # echoed verbatim
            if res.error:
                out["error"] = res.error
            if res.retry_after_s is not None:
                out["retry_after_s"] = res.retry_after_s
            with self._lock:
                self._pending.pop(0)
                self._inflight.discard(rid)
                self._answered[rid] = out
                conn = self._conn
                self._cv.notify_all()
            sent = self._send(conn, out)
            if self.journal is not None and sent:
                # done-mark only after the answer actually left on a
                # live wire: an unsent answer must replay next run
                self.journal.answer(rid, res.outcome)


class _WireFuture:
    """Result future for :class:`SocketEngineClient` (bench tcp
    driver) — resolves with the raw answer doc."""

    def __init__(self) -> None:
        self._ev = cc.Event()
        self.doc: Optional[Dict[str, Any]] = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout=timeout if timeout is not None
                             else 600.0):
            raise TimeoutError("no answer on the wire")
        return self.doc

    def _resolve(self, doc: Dict[str, Any]) -> None:
        self.doc = doc
        self._ev.set()


class SocketEngineClient:
    """Minimal framed request/response client for the bench harness's
    ``transport=tcp`` mode: the same engines, driven through a real
    loopback socket, so serialization + framing + syscall cost lands
    in the measured ``router_share`` instead of being assumed away."""

    def __init__(self, addr: str, *, name: str = "bench-client",
                 policy: Optional[RetryPolicy] = None,
                 connect_fn: Optional[Callable[[str], Any]] = None):
        self._lock = cc.Lock()
        self._futs: Dict[str, _WireFuture] = {}
        self._transport = SocketTransport(
            name, addr, on_frame=self._on_frame, policy=policy,
            connect_fn=connect_fn)

    def start(self) -> "SocketEngineClient":
        self._transport.start()
        return self

    def close(self) -> None:
        self._transport.close()
        self._transport.join(5.0)

    def submit(self, doc: Dict[str, Any],
               connect_timeout_s: float = 10.0) -> _WireFuture:
        rid = str(doc["id"])
        fut = _WireFuture()
        with self._lock:
            self._futs[rid] = fut
        deadline = cc.monotonic() + connect_timeout_s
        while not self._transport.send(doc):
            if (self._transport.closed()
                    or cc.monotonic() >= deadline):
                with self._lock:
                    self._futs.pop(rid, None)
                raise OSError(f"transport to {self._transport.addr} "
                              "unavailable")
            cc.sleep(0.01)
        return fut

    def _on_frame(self, doc: Dict[str, Any]) -> None:
        if "id" not in doc:
            return
        with self._lock:
            fut = self._futs.pop(str(doc["id"]), None)
        if fut is not None:
            fut._resolve(doc)
