"""``paddle serve`` — the engine's process front-end.

stdin-JSONL in, JSONL out: each input line is a request
(``{"id": ..., "prompt": [token ids...], "max_new_tokens": N}`` — or a
bare JSON list as the prompt), each output line its result
(``{"id", "outcome", "tokens"}``) in SUBMISSION order. Plain stdin EOF
is a BATCH: every accepted request completes and gets its result line
before exit (``paddle serve < requests.jsonl`` answers the whole
file). SIGTERM (and SIGINT) trigger a graceful drain instead:
in-flight sequences finish, queued and later requests are rejected,
every pending result line is still printed. Either way, when
telemetry is on (``--metrics_path``/``--save_dir``) the stream closes
with ``run_end status=completed`` as its LAST record.

The in-process Python API is :func:`build_engine` + the returned
:class:`~paddle_tpu.serving.engine.Engine`'s ``submit``/``result``
(also reachable as ``api.GradientMachine.asDecodeEngine``).
"""

from __future__ import annotations

import json
import os
import signal
import sys
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.utils import concurrency as cc


def build_engine(machine, params, *, slots: int = 8,
                 prompt_tokens: int = 32, queue_cap: int = 0,
                 request_timeout_s: float = 60.0, decode_block=1,
                 max_length: Optional[int] = None, registry=None,
                 pipeline: bool = True, fused_step: bool = False):
    """Wire a :class:`JaxDecodeBackend` + :class:`Engine` for a core
    graph machine (the in-process serving API). Caller starts it.
    ``decode_block`` takes the ladder spelling ("1,2,4,8" or an int);
    ``pipeline`` selects the overlapped dispatch/collect loop;
    ``fused_step`` the extracted attention-GRU step (doc/serving.md)."""
    from paddle_tpu.serving.engine import Engine
    from paddle_tpu.serving.jax_backend import JaxDecodeBackend

    backend = JaxDecodeBackend(
        machine, params, slots=slots, prompt_tokens=prompt_tokens,
        max_length=max_length, decode_block=decode_block, registry=registry,
        pipeline=pipeline, fused_step=fused_step,
    )
    return Engine(backend, queue_cap=queue_cap,
                  request_timeout_s=request_timeout_s, pipeline=pipeline)


def _parse_line(line: str, n: int) -> Tuple[Optional[Dict[str, Any]], str]:
    """One stdin line → (request dict, "") or (None, error)."""
    try:
        doc = json.loads(line)
    except ValueError as e:
        return None, f"bad JSON: {e}"
    if isinstance(doc, list):
        doc = {"prompt": doc}
    if not isinstance(doc, dict):
        return None, "expected a JSON object or token list"
    prompt = doc.get("prompt")
    if not isinstance(prompt, list) or not all(
        isinstance(t, int) for t in prompt
    ):
        return None, "prompt must be a list of token ids"
    doc.setdefault("id", f"req-{n}")
    return doc, ""


def main(rest: List[str]) -> int:
    from paddle_tpu.utils.flags import FLAGS

    leftover = FLAGS.parse(list(rest))
    if leftover:
        print(f"warning: unrecognized flags {leftover}", file=sys.stderr)
    if not FLAGS.use_tpu:
        # before ANYTHING imports jax (jax reads JAX_PLATFORMS once at
        # import), and therefore before the compile-cache block below
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if FLAGS.compile_cache_dir:
        # warm serve restarts skip the XLA backend compile of
        # serve_prefill/serve_decode — the compile records land with
        # cache_hit=true and Engine.start()'s warmup (time-to-first-
        # token-ready) drops to trace time (ROADMAP item 5 for serving)
        from paddle_tpu.observability.compile_log import enable_compile_cache

        enable_compile_cache(FLAGS.compile_cache_dir)
    if not FLAGS.config:
        print("error: --config is required", file=sys.stderr)
        return 2
    from paddle_tpu.config import parse_config
    from paddle_tpu.observability import metrics as obsm

    config = parse_config(FLAGS.config, FLAGS.config_args)
    obsm.configure_from_flags(FLAGS)

    import jax

    from paddle_tpu import api
    from paddle_tpu.observability.compile_log import CompileRegistry
    from paddle_tpu.serving.jax_backend import UnsupportedModelError

    am = api.GradientMachine(config.model_config, seed=FLAGS.seed)
    if FLAGS.init_model_path:
        am.loadParameters(FLAGS.init_model_path)
    else:
        print("# serving randomly initialized parameters "
              "(no --init_model_path)", file=sys.stderr)
    registry = CompileRegistry(device_kind=jax.devices()[0].device_kind)
    try:
        engine = build_engine(
            am._core, am.params,
            slots=FLAGS.serve_slots,
            prompt_tokens=FLAGS.serve_prompt_tokens,
            queue_cap=FLAGS.serve_queue_cap,
            request_timeout_s=FLAGS.serve_request_timeout,
            decode_block=FLAGS.serve_decode_block,
            registry=registry,
            pipeline=FLAGS.serve_pipeline,
            fused_step=FLAGS.serve_fused_step,
        )
    except UnsupportedModelError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    engine.start()
    print(f"# paddle serve: {engine.slots} slot(s), max_length "
          f"{engine.max_length}, decode blocks {FLAGS.serve_decode_block}, "
          f"pipeline {'on' if FLAGS.serve_pipeline else 'off'}"
          f"{', fused step' if FLAGS.serve_fused_step else ''} — "
          "reading JSONL requests from stdin", file=sys.stderr)

    drain = cc.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: drain.set())

    pending: List[Tuple[str, Any]] = []   # (id, future), submission order
    plock = cc.Lock()
    eof = cc.Event()
    n_lines = [0]   # reader progress — the drain path waits for it to
    # go quiet before the final flush (lines the client already piped
    # may still sit in the reader's buffer when SIGTERM lands; their
    # results — completed or rejected — must still be printed)

    def _reader() -> None:
        n = 0
        for line in sys.stdin:
            line = line.strip()
            if line:
                doc, err = _parse_line(line, n)
                n += 1
                if doc is None:
                    print(json.dumps({"id": f"req-{n - 1}",
                                      "outcome": "error", "tokens": [],
                                      "error": err}), flush=True)
                else:
                    fut = engine.submit(
                        doc["prompt"],
                        max_new_tokens=doc.get("max_new_tokens"),
                        rid=str(doc["id"]))
                    with plock:
                        pending.append((str(doc["id"]), fut))
            with plock:
                n_lines[0] += 1
            if drain.is_set():
                break
        eof.set()

    reader = cc.Thread(target=_reader, name="serve-stdin", daemon=True)
    reader.start()

    def _flush_pending(block: bool) -> None:
        while True:
            with plock:
                if not pending:
                    return
                rid, fut = pending[0]
                if not block and not fut.done():
                    return
                pending.pop(0)
            res = fut.result(timeout=600.0)
            out = {"id": rid, "outcome": res.outcome, "tokens": res.tokens}
            if res.error:
                out["error"] = res.error
            print(json.dumps(out), flush=True)

    while not (eof.is_set() or drain.is_set()):
        _flush_pending(block=False)
        eof.wait(timeout=0.05)
    # plain EOF is a BATCH, not an abort: the client piped its whole
    # request file (`paddle serve < requests.jsonl`) and every accepted
    # request owes a real answer — wait the pending futures out while
    # the engine works the queue down. A signal arriving mid-batch
    # falls through to the drain below (in-flight finish, queued
    # reject), so SIGTERM semantics are unchanged.
    while not drain.is_set():
        with plock:
            if not pending:
                break
            fut = pending[0][1]
        if fut.done():
            _flush_pending(block=False)
        elif engine._thread is None or not engine._thread.is_alive():
            # a dead scheduler can never resolve these futures: fall
            # through to the drain + bounded blocking flush, which
            # fails loudly instead of spinning here forever
            break
        else:
            drain.wait(timeout=0.05)
    # graceful drain: finish in-flight, reject queued + new, then print
    # every remaining result (rejections included — the client hears).
    # First give the reader a bounded window to submit lines the client
    # already piped: the whole serve cycle can fit inside one GIL switch
    # interval, so at SIGTERM the reader may not have run yet even
    # though its input buffer is full (post-drain submits come back
    # outcome=rejected, which is exactly the answer those lines get).
    deadline = cc.monotonic() + 3.0
    quiet_at = cc.monotonic()
    with plock:
        seen = n_lines[0]
    while cc.monotonic() < deadline and cc.monotonic() - quiet_at < 0.25:
        eof.wait(timeout=0.05)
        with plock:
            if n_lines[0] != seen:
                seen = n_lines[0]
                quiet_at = cc.monotonic()
        if eof.is_set():
            break
    engine.drain(timeout=600.0)
    _flush_pending(block=True)
    if obsm.enabled():
        engine.window_roll()
        obsm.emit("run_end", status="completed")
        obsm.flush()
    print("# paddle serve: drained", file=sys.stderr)
    return 0
