"""``paddle serve`` — the engine's process front-end.

stdin-JSONL in, JSONL out: each input line is a request
(``{"id": ..., "prompt": [token ids...], "max_new_tokens": N}`` — or a
bare JSON list as the prompt), each output line its result
(``{"id", "outcome", "tokens"}``) in SUBMISSION order. Plain stdin EOF
is a BATCH: every accepted request completes and gets its result line
before exit (``paddle serve < requests.jsonl`` answers the whole
file). SIGTERM (and SIGINT) trigger a graceful drain instead:
in-flight sequences finish, queued and later requests are rejected,
every pending result line is still printed. Either way, when
telemetry is on (``--metrics_path``/``--save_dir``) the stream closes
with ``run_end status=completed`` as its LAST record.

The in-process Python API is :func:`build_engine` + the returned
:class:`~paddle_tpu.serving.engine.Engine`'s ``submit``/``result``
(also reachable as ``api.GradientMachine.asDecodeEngine``).
"""

from __future__ import annotations

import json
import os
import signal
import sys
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.utils import concurrency as cc


def build_engine(machine, params, *, slots: int = 8,
                 prompt_tokens: int = 32, queue_cap: int = 0,
                 request_timeout_s: float = 60.0, decode_block=1,
                 max_length: Optional[int] = None, registry=None,
                 pipeline: bool = True, fused_step: bool = False,
                 shed_policy: str = "off", breaker_threshold: int = 0,
                 breaker_cooldown_s: float = 30.0, hangwatch=None,
                 on_oom=None, spec_tokens="0", slot_dtype: str = "f32"):
    """Wire a :class:`JaxDecodeBackend` + :class:`Engine` for a core
    graph machine (the in-process serving API). Caller starts it.
    ``decode_block`` takes the ladder spelling ("1,2,4,8" or an int);
    ``pipeline`` selects the overlapped dispatch/collect loop;
    ``fused_step`` the extracted attention-GRU step (doc/serving.md).
    The resilience plane (doc/resilience.md "Serving resilience"):
    ``shed_policy`` off|deadline|brownout, ``breaker_threshold``/
    ``breaker_cooldown_s`` the launch-failure circuit breaker (0
    disables), ``hangwatch`` a started-by-the-engine
    :class:`~paddle_tpu.serving.resilience.ServeHangWatch`, ``on_oom``
    the RESOURCE_EXHAUSTED handler (`paddle serve` installs the
    pre-mortem + exit-20 one). ``spec_tokens`` is the speculative
    draft-length ladder ("0" = off) and ``slot_dtype`` the slot-state
    storage dtype (f32|bf16) — doc/serving.md "Speculative decode" /
    "Reduced-precision slot state"."""
    from paddle_tpu.serving.engine import Engine
    from paddle_tpu.serving.jax_backend import JaxDecodeBackend
    from paddle_tpu.serving.resilience import CircuitBreaker

    backend = JaxDecodeBackend(
        machine, params, slots=slots, prompt_tokens=prompt_tokens,
        max_length=max_length, decode_block=decode_block, registry=registry,
        pipeline=pipeline, fused_step=fused_step,
        spec_tokens=spec_tokens, slot_dtype=slot_dtype,
    )
    breaker = (CircuitBreaker(breaker_threshold, breaker_cooldown_s)
               if breaker_threshold > 0 else None)
    return Engine(backend, queue_cap=queue_cap,
                  request_timeout_s=request_timeout_s, pipeline=pipeline,
                  shed_policy=shed_policy, breaker=breaker,
                  hangwatch=hangwatch, on_oom=on_oom)


def _parse_line(
    line: str, n: int
) -> Tuple[Optional[Dict[str, Any]], str, str]:
    """One stdin line → (request dict, "", id) or (None, error, id).
    The returned id is the client's own whenever one was parseable —
    an error answer under a synthetic id is uncorrelatable — falling
    back to a pid-salted auto id: the line counter restarts at 0 every
    incarnation, and a journaled ``req-0`` from a previous run must
    not make a FRESH id-less request look like a duplicate after a
    supervised restart."""
    rid = f"req-{os.getpid()}-{n}"
    try:
        doc = json.loads(line)
    except ValueError as e:
        return None, f"bad JSON: {e}", rid
    if isinstance(doc, list):
        doc = {"prompt": doc}
    if not isinstance(doc, dict):
        return None, "expected a JSON object or token list", rid
    if "id" in doc:
        rid = str(doc["id"])
    prompt = doc.get("prompt")
    if not isinstance(prompt, list) or not all(
        isinstance(t, int) for t in prompt
    ):
        return None, "prompt must be a list of token ids", rid
    doc["id"] = rid
    return doc, "", rid


def _span(name: str, t0_mono: float, dur_s: float, trace: str) -> None:
    """One replica-side ``kind=span`` hop record (doc/observability.md
    "Distributed tracing"). ``trace`` is the opaque ``trace_id`` the
    router stamped on the forwarded request — absent (direct stdin
    clients) means no span, so single-process runs keep their streams
    unchanged. ``t0_mono`` is a ``cc.monotonic`` reading, mapped into
    the stream's ``t``-offset timebase by ``rel_time``."""
    if not trace:
        return
    from paddle_tpu.observability import metrics as obsm

    if not obsm.enabled():
        return
    obsm.emit("span", name=name, t0=obsm.rel_time(t0_mono),
              dur_s=round(max(float(dur_s), 0.0), 6), trace=trace)


def _serve_listen(engine, journal, status, reloader) -> int:
    """``paddle serve --listen HOST:PORT`` (doc/serving.md "Cross-host
    fleet"): the socket front door. Framed requests in, framed answers
    out in submission order, the same journal/dedupe/drain contract as
    the stdin path — a `paddle serve-fleet --replica_addr` router on
    another host is the expected client. Runs until SIGTERM/SIGINT or
    a ``drain`` control frame, then drains exactly like stdin EOF."""
    from paddle_tpu.observability import metrics as obsm
    from paddle_tpu.serving.transport import EngineSocketServer
    from paddle_tpu.utils.flags import FLAGS

    drain = cc.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: drain.set())
    server = EngineSocketServer(engine, FLAGS.listen, journal=journal,
                                on_drain=drain.set)
    # a restarted replica re-offers its journal backlog FIRST; the
    # answers queue for whichever router connects (at-least-once)
    if journal is not None:
        replay = journal.pending()
        if replay:
            print(f"# paddle serve: re-offering {len(replay)} journaled "
                  "request(s) from a previous run", file=sys.stderr)
        for doc in replay:
            server.replay(doc)
    server.start()
    # the bound address line is the startup contract (--listen :0
    # binds an ephemeral port; launchers parse this line)
    print(f"# paddle serve: listening on {server.address}",
          file=sys.stderr, flush=True)
    while not drain.is_set():
        drain.wait(timeout=0.5)
    print("# paddle serve: drain requested", file=sys.stderr)
    if reloader is not None:
        reloader.stop()
    engine.drain(timeout=600.0)
    server.wait_idle(timeout=600.0)   # every accepted answer framed out
    server.close()
    if status is not None:
        status.stop()
    if journal is not None:
        journal.close()
    if obsm.enabled():
        engine.window_roll()
        obsm.emit("run_end", status="completed")
        obsm.flush()
    print("# paddle serve: drained", file=sys.stderr)
    return 0


def main(rest: List[str]) -> int:
    from paddle_tpu.utils.flags import FLAGS

    leftover = FLAGS.parse(list(rest))
    if leftover:
        print(f"warning: unrecognized flags {leftover}", file=sys.stderr)
    if not FLAGS.use_tpu:
        # before ANYTHING imports jax (jax reads JAX_PLATFORMS once at
        # import), and therefore before the compile-cache block below
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if FLAGS.compile_cache_dir:
        # warm serve restarts skip the XLA backend compile of
        # serve_prefill/serve_decode — the compile records land with
        # cache_hit=true and Engine.start()'s warmup (time-to-first-
        # token-ready) drops to trace time (ROADMAP item 5 for serving)
        from paddle_tpu.observability.compile_log import enable_compile_cache

        enable_compile_cache(FLAGS.compile_cache_dir)
    if not FLAGS.config:
        print("error: --config is required", file=sys.stderr)
        return 2
    from paddle_tpu.config import parse_config
    from paddle_tpu.observability import metrics as obsm

    config = parse_config(FLAGS.config, FLAGS.config_args)
    obsm.configure_from_flags(FLAGS)
    if FLAGS.fault_spec:
        # serve.* chaos sites (doc/resilience.md "Serving resilience")
        from paddle_tpu.resilience import faultinject

        faultinject.configure(FLAGS.fault_spec, FLAGS.fault_seed)

    import jax

    from paddle_tpu import api
    from paddle_tpu.observability.compile_log import CompileRegistry
    from paddle_tpu.resilience import EXIT_OOM
    from paddle_tpu.resilience.hangwatch import run_dir_of
    from paddle_tpu.serving.jax_backend import UnsupportedModelError
    from paddle_tpu.serving.resilience import (
        RequestJournal,
        ServeHangWatch,
        StatusWriter,
        WeightReloader,
    )

    am = api.GradientMachine(config.model_config, seed=FLAGS.seed)
    if FLAGS.init_model_path:
        am.loadParameters(FLAGS.init_model_path)
    else:
        print("# serving randomly initialized parameters "
              "(no --init_model_path)", file=sys.stderr)
    registry = CompileRegistry(device_kind=jax.devices()[0].device_kind)
    # forensics land next to the telemetry (or the cwd, telemetry-less):
    # serve_hang_report.json / oom_report.json — where `paddle
    # supervise` looks for them
    report_dir = run_dir_of(FLAGS.metrics_path or FLAGS.save_dir or ".")
    hangwatch = (ServeHangWatch(FLAGS.serve_hang_timeout, report_dir)
                 if FLAGS.serve_hang_timeout > 0 else None)

    def _on_oom(e: BaseException) -> None:
        # the engine already answered everything outcome=error; classify
        # the death for the supervisor: pre-mortem (ranked static plans,
        # telemetry tail, 30s backstop) + the distinct exit code. An OOM
        # loop is deterministic poison — `paddle supervise` charges it
        # to the restart budget, never restarts it for free.
        from paddle_tpu.observability.memory import trigger_oom_report

        trigger_oom_report(
            report_dir, e, groups=registry.static_memory_rows(),
            live=None, where=None,
            device_kind=registry.device_kind or "",
            exit_fn=os._exit,
        )
        obsm.flush()
        os._exit(EXIT_OOM)

    try:
        engine = build_engine(
            am._core, am.params,
            slots=FLAGS.serve_slots,
            prompt_tokens=FLAGS.serve_prompt_tokens,
            queue_cap=FLAGS.serve_queue_cap,
            request_timeout_s=FLAGS.serve_request_timeout,
            decode_block=FLAGS.serve_decode_block,
            registry=registry,
            pipeline=FLAGS.serve_pipeline,
            fused_step=FLAGS.serve_fused_step,
            shed_policy=FLAGS.serve_shed_policy,
            breaker_threshold=FLAGS.serve_breaker_threshold,
            breaker_cooldown_s=FLAGS.serve_breaker_cooldown,
            hangwatch=hangwatch,
            on_oom=_on_oom,
            spec_tokens=FLAGS.serve_spec_tokens,
            slot_dtype=FLAGS.serve_slot_dtype,
        )
    except (UnsupportedModelError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    journal = (RequestJournal(FLAGS.serve_journal_path)
               if FLAGS.serve_journal_path else None)
    engine.start()
    status = None
    if FLAGS.status_path:
        status = StatusWriter(FLAGS.status_path, engine).start()
    reloader = None
    if FLAGS.serve_reload_watch:
        # hot weight reload (doc/serving.md "Serving fleet"): when a
        # NEWER durable checkpoint lands under the watch dir, load it
        # through the same loadParameters path the startup weights took
        # and stage it for the next iteration boundary — in-flight and
        # queued requests are untouched
        def _load_ckpt(path: str):
            am.loadParameters(path)
            return am.params

        reloader = WeightReloader(FLAGS.serve_reload_watch, engine,
                                  _load_ckpt).start()
        print(f"# paddle serve: watching {FLAGS.serve_reload_watch} for "
              "durable checkpoints (hot weight reload)", file=sys.stderr)
    if FLAGS.listen:
        # the socket front door replaces the stdin reader wholesale —
        # same engine, journal, status, and reload planes
        return _serve_listen(engine, journal, status, reloader)
    print(f"# paddle serve: {engine.slots} slot(s), max_length "
          f"{engine.max_length}, decode blocks {FLAGS.serve_decode_block}, "
          f"pipeline {'on' if FLAGS.serve_pipeline else 'off'}"
          f"{', fused step' if FLAGS.serve_fused_step else ''}"
          f"{', spec ' + FLAGS.serve_spec_tokens if FLAGS.serve_spec_tokens not in ('', '0') else ''}"
          f"{', slot dtype ' + FLAGS.serve_slot_dtype if FLAGS.serve_slot_dtype != 'f32' else ''} — "
          "reading JSONL requests from stdin", file=sys.stderr)

    drain = cc.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: drain.set())

    # (id, future, trace_id), submission order — the trace_id rides to
    # the result line so the router can re-correlate the echo
    pending: List[Tuple[str, Any, str]] = []
    plock = cc.Lock()
    eof = cc.Event()
    n_lines = [0]   # reader progress — the drain path waits for it to
    # go quiet before the final flush (lines the client already piped
    # may still sit in the reader's buffer when SIGTERM lands; their
    # results — completed or rejected — must still be printed)

    # a restarted server re-offers every accepted-but-unanswered journal
    # entry FIRST (acceptance order), before reading fresh stdin: a
    # crash loses a process, not a queue (at-least-once — a request
    # whose result line printed but whose done-mark didn't land is
    # answered again; consumers dedupe by id, doc/resilience.md)
    if journal is not None:
        replay = journal.pending()
        if replay:
            print(f"# paddle serve: re-offering {len(replay)} journaled "
                  "request(s) from a previous run", file=sys.stderr)
        for doc in replay:
            # replay=True: this backlog was durably accepted by a
            # previous incarnation — queue_cap governs NEW arrivals;
            # capping the re-offer would reject-and-done-mark the
            # tail, permanently truncating the very queue the journal
            # exists to preserve
            trace = str(doc.get("trace_id") or "")
            fut = engine.submit(
                doc.get("prompt") or [],
                max_new_tokens=doc.get("max_new_tokens"),
                rid=str(doc["id"]), replay=True, trace=trace)
            with plock:
                pending.append((str(doc["id"]), fut, trace))

    def _reader() -> None:
        n = 0
        for line in sys.stdin:
            line = line.strip()
            if line:
                doc, err, rid = _parse_line(line, n)
                n += 1
                if doc is None:
                    print(json.dumps({"id": rid,
                                      "outcome": "error", "tokens": [],
                                      "error": err}), flush=True)
                else:
                    trace = str(doc.get("trace_id") or "")
                    accepted = True
                    if journal is not None:
                        jt0 = cc.monotonic()
                        accepted = journal.accept(doc)
                        # the durable append (flush + fsync) is a real
                        # hop on the request's critical path
                        _span("replica.journal", jt0,
                              cc.monotonic() - jt0, trace)
                    if not accepted:
                        # this id is already journaled: answered in a
                        # previous incarnation, or re-offered above — a
                        # replayed stdin after a supervised restart must
                        # not double-submit (dedupe by request id)
                        print(f"# paddle serve: duplicate request id "
                              f"{doc['id']!r} skipped (journal)",
                              file=sys.stderr)
                    else:
                        # the journal accept above was flushed+fsynced
                        # BEFORE this submit — crash-ordered ahead of
                        # any accept effect
                        _span("replica.accept", cc.monotonic(), 0.0,
                              trace)
                        fut = engine.submit(
                            doc["prompt"],
                            max_new_tokens=doc.get("max_new_tokens"),
                            rid=str(doc["id"]), trace=trace)
                        with plock:
                            pending.append((str(doc["id"]), fut, trace))
            with plock:
                n_lines[0] += 1
            if drain.is_set():
                break
        eof.set()

    reader = cc.Thread(target=_reader, name="serve-stdin", daemon=True)
    reader.start()

    def _flush_pending(block: bool) -> None:
        while True:
            with plock:
                if not pending:
                    return
                rid, fut, trace = pending[0]
                if not block and not fut.done():
                    return
                pending.pop(0)
            res = fut.result(timeout=600.0)
            out = {"id": rid, "outcome": res.outcome, "tokens": res.tokens}
            if trace:
                # echoed verbatim — the propagation contract
                out["trace_id"] = trace
            if res.error:
                out["error"] = res.error
            if res.retry_after_s is not None:
                # shed answers hint when capacity is expected back
                out["retry_after_s"] = res.retry_after_s
            print(json.dumps(out), flush=True)
            if journal is not None:
                # done-mark AFTER the print: a crash in between re-
                # answers this request on restart (at-least-once)
                journal.answer(rid, res.outcome)

    if hangwatch is not None:
        # the hang exit (monitor thread) resolves every future
        # outcome=error and then os._exit(19)s — without this hook the
        # main-thread printer never wakes, the error lines never reach
        # stdout, and journal-less clients hear NOTHING. hang_fail_all
        # resolved (or draining-rejects) every future first, so the
        # blocking flush cannot wedge on an unresolved one; a wedged
        # stdout is capped by the hangwatch's forensics backstop.
        def _hang_answer_flush() -> None:
            _flush_pending(block=True)
            sys.stdout.flush()

        hangwatch.answer_flush = _hang_answer_flush

    while not (eof.is_set() or drain.is_set()):
        _flush_pending(block=False)
        eof.wait(timeout=0.05)
    # plain EOF is a BATCH, not an abort: the client piped its whole
    # request file (`paddle serve < requests.jsonl`) and every accepted
    # request owes a real answer — wait the pending futures out while
    # the engine works the queue down. A signal arriving mid-batch
    # falls through to the drain below (in-flight finish, queued
    # reject), so SIGTERM semantics are unchanged.
    while not drain.is_set():
        with plock:
            if not pending:
                break
            fut = pending[0][1]
        if fut.done():
            _flush_pending(block=False)
        elif engine._thread is None or not engine._thread.is_alive():
            # a dead scheduler can never resolve these futures: fall
            # through to the drain + bounded blocking flush, which
            # fails loudly instead of spinning here forever
            break
        else:
            drain.wait(timeout=0.05)
    # graceful drain: finish in-flight, reject queued + new, then print
    # every remaining result (rejections included — the client hears).
    # First give the reader a bounded window to submit lines the client
    # already piped: the whole serve cycle can fit inside one GIL switch
    # interval, so at SIGTERM the reader may not have run yet even
    # though its input buffer is full (post-drain submits come back
    # outcome=rejected, which is exactly the answer those lines get).
    deadline = cc.monotonic() + 3.0
    quiet_at = cc.monotonic()
    with plock:
        seen = n_lines[0]
    while cc.monotonic() < deadline and cc.monotonic() - quiet_at < 0.25:
        eof.wait(timeout=0.05)
        with plock:
            if n_lines[0] != seen:
                seen = n_lines[0]
                quiet_at = cc.monotonic()
        if eof.is_set():
            break
    if reloader is not None:
        reloader.stop()  # no swap may race the drain's final windows
    engine.drain(timeout=600.0)
    _flush_pending(block=True)
    if status is not None:
        status.stop()  # final snapshot carries draining=True
    if journal is not None:
        journal.close()
    if obsm.enabled():
        engine.window_roll()
        obsm.emit("run_end", status="completed")
        obsm.flush()
    print("# paddle serve: drained", file=sys.stderr)
    return 0
