"""``paddle serve-fleet`` — the multi-replica serving router.

One engine process per accelerator was PR 14's hard-to-kill unit; this
module is the tier above it (ROADMAP item 1's data-parallel serving
fan-out): a jax-free router process that supervises N ``paddle serve``
replica children, admits requests over the SAME stdin-JSONL front-end
contract as a single server, and routes each request to the least-
loaded live replica. The client cannot tell a fleet from one server —
results come back as JSONL lines in submission order, SIGTERM drains,
stdin EOF is a batch, and ``run_end`` closes the router's telemetry
stream last.

Supervision reuses the training stack's discipline rather than
reinventing it (``resilience/supervisor.py``):

- exit-code classes: ``EXIT_PREEMPTED`` (18) restarts budget-free up
  to ``FREE_RESTART_LIMIT``; everything else — including the serving
  deaths ``EXIT_CRASH_LOOP``/``EXIT_HANG``/``EXIT_OOM`` (17/19/20) —
  consumes the shared restart budget with exponential backoff;
- liveness via each replica's ``--status_path`` health JSON (the
  ``resilience/heartbeat.py`` idiom): a missing, torn, doc-level
  ``stale``, or not-renewed status file makes the replica UNHEALTHY
  (never crashes the router); persistently stale past
  ``--heartbeat_stale_after``-style bounds it is killed and treated as
  a death;
- failover via the PR-14 request journal: each replica journals its
  accepted requests (``--serve_journal_path``); on a death the router
  re-offers that replica's accepted-but-unanswered entries
  (:func:`RequestJournal.pending` semantics via the shared read-only
  parser) to the survivors. Semantics stay **at-least-once, dedupe by
  id**: a restarted replica replays its own journal, so the same id
  can be answered by two processes — the router emits the FIRST answer
  and counts the duplicate (``fleet.duplicate_answers``), so the
  client hears exactly once.

Routing is health-scored least-loaded: the router's own outstanding
count per replica plus the health JSON's queue depth and slot
occupancy; replicas whose breaker is open, which are draining, or
whose status is stale are skipped. A replica with no health document
yet (still warming up) is routable on its outstanding count alone —
child stdin buffers until its engine is ready, exactly like piping
requests to a cold single server.

The scheduling loop (:meth:`FleetRouter.run`) is a registered hot loop
(PTL002) and runs strictly through the ``utils/concurrency`` seam, so
``paddle race`` explores its interleavings against the submit/deliver
threads (tests/race_specs/spec_serve_fleet.py). Chaos sites
``fleet.replica_crash`` and ``fleet.status_stale`` fire inside the
supervision poll (doc/resilience.md).

Replica handles are duck-typed so the race spec and unit tests drive
the REAL router over in-process fakes; :class:`ProcReplica` is the
subprocess implementation ``main`` uses. The handle protocol::

    name                 str, stable replica id ("replica-0")
    start()              spawn (or revive) the child
    alive()              child process currently running
    poll_exit()          -> Optional[int] exit code once dead
    send(doc)            -> bool, forward one request JSON line
    health(now)          -> dict health doc; {"stale": True, ...} when
                            unknown/unreadable/wedged
    pending_requests()   -> journaled accepted-but-unanswered docs
    begin_drain()        SIGTERM-equivalent graceful drain
    kill()               hard kill
    join(timeout)        -> bool, wait for exit

Results flow back through a ``deliver(name, doc)`` callback the router
owns — :class:`ProcReplica` calls it from its stdout reader thread.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import subprocess
import sys
from typing import Any, Callable, Dict, List, Optional

from paddle_tpu.resilience import (
    EXIT_PREEMPTED,
    faultinject,
)
from paddle_tpu.resilience.supervisor import FREE_RESTART_LIMIT
from paddle_tpu.serving.resilience import _read_journal, read_status
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

#: seconds without a status-file change (StatusWriter renews ~1/s)
#: before a replica's health is considered stale; persistent staleness
#: past the same bound AFTER the startup grace is treated as a death
STALE_AFTER_S = 5.0

#: a freshly (re)started replica gets this long to import jax, warm its
#: compiles and write its first status snapshot before staleness can
#: kill it — requests routed meanwhile buffer in its stdin pipe
STARTUP_GRACE_S = 300.0

#: how often the supervision poll re-reads replica health files
HEALTH_PERIOD_S = 0.25

#: exponential-backoff cap between restarts of the same replica
RESTART_DELAY_CAP_S = 60.0

#: per-replica child fault env (chaos drills): the router strips
#: PADDLE_TPU_FAULTS from every child's environment — a fleet-level
#: spec must not fire identically in N children — and re-injects
#: PADDLE_TPU_FLEET_CHILD_FAULTS_<i> (with the shared fault seed) into
#: child i only, so "kill exactly one replica" is expressible
CHILD_FAULTS_ENV = "PADDLE_TPU_FLEET_CHILD_FAULTS_"


def replica_score(outstanding: int, health: Optional[Dict[str, Any]]) -> float:
    """Least-loaded routing score — shared by the router and the
    in-process bench fleet (:func:`drive_fleet_rung`): the caller's own
    unanswered count plus the replica's self-reported queue depth and
    slot occupancy. A stale (or absent) health doc contributes nothing:
    the outstanding count is then the only honest signal."""
    score = float(outstanding)
    if health and not health.get("stale"):
        try:
            score += float(health.get("queue_depth") or 0)
            score += float(health.get("occupancy") or 0)
        except (TypeError, ValueError):
            pass
    return score


class ProcReplica:
    """One supervised ``paddle serve`` child process.

    Owns the child's argv (status/journal/metrics paths are per-replica
    under the fleet status dir), its stdin pipe (requests in), and a
    daemon reader thread that parses result JSONL lines off its stdout
    into the router's ``deliver`` callback. Stderr is inherited — the
    child's banners and diagnostics interleave with the router's, all
    off the result stream."""

    def __init__(self, name: str, argv: List[str], *, status_path: str,
                 journal_path: str, deliver: Callable[[str, Dict], None],
                 env: Optional[Dict[str, str]] = None):
        self.name = name
        self.argv = list(argv)
        self.status_path = status_path
        self.journal_path = journal_path
        self._deliver = deliver
        self._env = env
        self._lock = cc.Lock()
        self._proc: Optional[subprocess.Popen] = None
        # (mtime_ns, size) signature of the status file and the
        # monotonic instant it last CHANGED — staleness is judged from
        # change age, never from file timestamps (PTL001: the router is
        # a hot path; wall-clock mtimes also skew across filesystems)
        self._sig: Optional[tuple] = None
        self._sig_at = cc.monotonic()

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        # a stale status file from a previous incarnation must not make
        # the fresh child look live (or wedged) before it writes one
        try:
            os.remove(self.status_path)
        except OSError:
            pass
        proc = subprocess.Popen(
            self.argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1, env=self._env,
        )
        with self._lock:
            self._proc = proc
            self._sig = None
            self._sig_at = cc.monotonic()
        reader = cc.Thread(target=self._read_stdout, args=(proc,),
                           name=f"fleet-{self.name}-out", daemon=True)
        reader.start()

    def _read_stdout(self, proc: subprocess.Popen) -> None:
        # one reader per incarnation: it dies with its process's stdout
        # EOF, so a restart never leaves two readers on one callback
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue  # never let child noise kill the router
                if isinstance(doc, dict) and "id" in doc:
                    self._deliver(self.name, doc)
        except (OSError, ValueError):
            pass

    def alive(self) -> bool:
        with self._lock:
            proc = self._proc
        return proc is not None and proc.poll() is None

    def poll_exit(self) -> Optional[int]:
        with self._lock:
            proc = self._proc
        return None if proc is None else proc.poll()

    def send(self, doc: Dict[str, Any]) -> bool:
        with self._lock:
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return False
        try:
            proc.stdin.write(json.dumps(doc) + "\n")
            proc.stdin.flush()
        except (OSError, ValueError):
            return False  # a dying child's broken pipe = routing miss,
            # caught here; the death itself is reaped by the next poll
        return True

    # --------------------------------------------------------- health

    def health(self, now: float) -> Dict[str, Any]:
        """The replica's status document, or ``{"stale": True, ...}``
        when it is missing, torn, self-declared stale (the engine's
        bounded-lock timeout) or not renewed for :data:`STALE_AFTER_S`.
        Never raises — an unreadable probe is a health verdict, not a
        router crash."""
        try:
            st = os.stat(self.status_path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            sig = None
        with self._lock:
            if sig != self._sig:
                self._sig = sig
                self._sig_at = now
            age = now - self._sig_at
        doc = read_status(self.status_path) if sig is not None else None
        if doc is None:
            return {"stale": True, "age_s": age,
                    "detail": "status file missing or torn"}
        if doc.get("stale"):
            doc.setdefault("age_s", age)
            return doc
        if age > STALE_AFTER_S:
            return {"stale": True, "age_s": age,
                    "detail": f"status not renewed for {age:.1f}s"}
        return doc

    def pending_requests(self) -> List[Dict[str, Any]]:
        """Accepted-but-unanswered journal entries, acceptance order —
        what failover re-offers to the survivors."""
        accepted, done = _read_journal(self.journal_path)
        return [dict(doc) for rid, doc in accepted.items()
                if rid not in done]

    # ------------------------------------------------------- shutdown

    def begin_drain(self) -> None:
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()
            except OSError:
                pass

    def kill(self) -> None:
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass

    def join(self, timeout: float) -> bool:
        with self._lock:
            proc = self._proc
        if proc is None:
            return True
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return False
        return True


class FleetRouter:
    """Route requests across replica handles; supervise their lives.

    Thread contract: ``submit``/``deliver``/``note_eof``/
    ``request_drain`` are the cross-thread entry points (stdin reader,
    per-replica stdout readers, signal path); :meth:`run` is the ONE
    scheduling/supervision loop and the only emitter — results print
    in submission order exactly once, whatever the interleaving
    (tests/race_specs/spec_serve_fleet.py)."""

    def __init__(self, replicas: List[Any], *,
                 emit: Callable[[Dict[str, Any]], None],
                 poll_s: float = 0.02,
                 stale_after_s: float = STALE_AFTER_S,
                 startup_grace_s: float = STARTUP_GRACE_S,
                 health_period_s: float = HEALTH_PERIOD_S,
                 restart_budget: int = 5,
                 restart_base_delay: float = 1.0,
                 hedge_after: float = 0.0,
                 clock: Optional[Callable[[], float]] = None):
        self._emit = emit
        self.poll_s = float(poll_s)
        self.stale_after_s = float(stale_after_s)
        self.startup_grace_s = float(startup_grace_s)
        self.health_period_s = float(health_period_s)
        self.restart_budget = max(0, int(restart_budget))
        self.restart_base_delay = float(restart_base_delay)
        # hedged retries (doc/serving.md "Cross-host fleet"): a request
        # outstanding past max(hedge_after, streaming p99 of answer
        # latency) is re-sent to the next-healthiest replica; first
        # answer wins, the loser is absorbed by the dedupe. 0 disables.
        self.hedge_after = float(hedge_after)
        self._clock = clock or cc.monotonic
        self._lock = cc.Lock()
        self._wake = cc.Condition(self._lock)
        now = self._clock()
        # request state — all under self._lock
        self._order: List[str] = []            # submission order
        self._docs: Dict[str, Dict] = {}       # rid -> request doc
        self._results: Dict[str, Dict] = {}    # rid -> result doc
        self._emit_idx = 0
        self._unsent: collections.deque = collections.deque()
        self._owner: Dict[str, str] = {}       # rid -> replica name
        # distributed tracing (doc/observability.md "Distributed
        # tracing") — all under self._lock: per-rid wait-start / route
        # instant / routing-attempt counter, driving the
        # router.{enqueue,wait,reoffer,answer} spans and the per-hop
        # ids stamped on forwarded request lines
        self._t_wait0: Dict[str, float] = {}
        self._t_route: Dict[str, float] = {}
        self._attempt: Dict[str, int] = {}
        self._outstanding: Dict[str, set] = {r.name: set() for r in replicas}
        # replica supervision state
        self._rep: Dict[str, Dict[str, Any]] = {
            r.name: {
                "handle": r,
                "up": False,        # process believed running
                "down": False,      # permanently out (budget exhausted)
                "stopping": False,  # drain-initiated, exit expected
                "restarts": 0,
                "free_restarts": 0,
                "next_restart_at": None,   # monotonic due time, or None
                "started_at": now,
                "stale_since": None,
                "health": None,
                "health_at": 0.0,
            }
            for r in replicas
        }
        self._eof = False
        self._draining = False
        self._drain_req = cc.Event()
        self._failed = False
        self._done_running = False  # run() exited: late submits self-emit
        # hedging state — all under self._lock: rid -> hedge-target
        # replica (at most one live hedge per request) and the
        # streaming answer-latency quantile the adaptive delay tracks
        self._hedged: Dict[str, str] = {}
        self._lat_p99 = 0.0
        self._lat_scale = 0.0
        # counters mirrored into telemetry + `paddle serve-status`
        self.routed = 0
        self.reoffers = 0
        self.duplicate_answers = 0
        self.deaths = 0
        self.hedges = 0
        self.hedge_wins = 0

    # ---------------------------------------------------- client side

    def start(self) -> "FleetRouter":
        for st in self._rep.values():
            st["handle"].start()
            with self._lock:
                st["up"] = True
                st["started_at"] = self._clock()
        return self

    def submit(self, doc: Dict[str, Any]) -> bool:
        """Admit one request. False = duplicate id (the fleet front
        door dedupes, mirroring the journal-backed single server)."""
        rid = str(doc.get("id"))
        with self._lock:
            if rid in self._docs:
                return False
            # the router is the trace origin: stamp (or echo verbatim)
            # the opaque join key BEFORE the doc is stored, so routing,
            # failover re-offers and the replicas' journals all carry
            # the same trace_id (doc/observability.md)
            doc["trace_id"] = str(doc.get("trace_id") or rid)
            self._docs[rid] = doc
            self._order.append(rid)
            if self._draining or self._done_running:
                # drain semantics fleet-wide: in-flight finish, NEW
                # arrivals reject — same answer a draining engine gives
                self._results[rid] = {"id": rid, "outcome": "rejected",
                                      "tokens": []}
                if self._done_running:
                    # the run loop (the one ordered emitter) already
                    # exited — a late arrival off the stdin reader must
                    # still hear its rejection; emitting here is safe
                    # because the loop can never emit again and the
                    # lock serializes order
                    self._emit_ready_locked()
            else:
                now = self._clock()
                self._t_wait0[rid] = now
                self._span("router.enqueue", now, 0.0,
                           trace=doc["trace_id"], rid=rid)
                self._unsent.append(rid)
            self._wake.notify_all()
        return True

    def deliver(self, name: str, doc: Dict[str, Any]) -> None:
        """One replica answered. First answer wins; replays of the same
        id (at-least-once journal semantics) are counted, not emitted."""
        rid = str(doc.get("id"))
        with self._lock:
            out = self._outstanding.get(name)
            if out is not None:
                out.discard(rid)
            if rid not in self._docs:
                return  # not ours (child noise) — never crash the router
            if rid in self._results:
                self.duplicate_answers += 1
                return
            t_route = self._t_route.get(rid)
            if t_route is not None:
                # feed the adaptive hedge delay with the winning
                # answer's route->answer latency
                self._note_latency_locked(self._clock() - t_route)
            if self._hedged.get(rid) == name:
                self.hedge_wins += 1
            self._hedged.pop(rid, None)
            self._results[rid] = doc
            self._span("router.answer", self._clock(), 0.0,
                       trace=str(self._docs[rid].get("trace_id") or rid),
                       rid=rid, replica=name)
            self._t_wait0.pop(rid, None)
            self._t_route.pop(rid, None)
            self._wake.notify_all()

    def note_eof(self) -> None:
        with self._lock:
            self._eof = True
            self._wake.notify_all()

    def request_drain(self) -> None:
        """Signal-safe drain request: just set the event — the run loop
        executes the drain (taking locks from a signal handler that
        interrupted the loop mid-critical-section would deadlock)."""
        self._drain_req.set()

    def status(self) -> Dict[str, Any]:
        """Router-level counters + per-replica supervision view (the
        fleet analog of Engine.status())."""
        with self._lock:
            return {
                "replicas": {
                    name: {
                        "up": st["up"], "down": st["down"],
                        "stopping": st["stopping"],
                        "restarts": st["restarts"],
                        "outstanding": len(self._outstanding.get(name, ())),
                    }
                    for name, st in self._rep.items()
                },
                "draining": self._draining,
                "queue_depth": len(self._unsent),
                "submitted": len(self._order),
                "emitted": self._emit_idx,
                "routed": self.routed,
                "reoffers": self.reoffers,
                "duplicate_answers": self.duplicate_answers,
                "deaths": self.deaths,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
            }

    def _span(self, name: str, t0_mono: float, dur_s: float,
              **fields: Any) -> None:
        """One router-side ``kind=span`` hop record (doc/observability.
        md "Distributed tracing"). ``t0_mono`` is a ``self._clock``
        reading, mapped into the router stream's ``t``-offset timebase;
        a no-op when telemetry is off, so library/race harness use
        emits nothing."""
        from paddle_tpu.observability import metrics as obsm

        if not obsm.enabled():
            return
        obsm.emit("span", name=name, t0=obsm.rel_time(t0_mono),
                  dur_s=round(max(float(dur_s), 0.0), 6), **fields)

    # ------------------------------------------------------ scheduling

    def run(self) -> int:
        """The router loop (PTL002 hot loop): supervise, route, emit —
        until the batch (EOF) or drain completes. Returns the process
        exit code (1 = the fleet failed its requests: every replica
        permanently down with work unanswered)."""
        while True:
            self._route_once()
            with self._lock:
                if self._finished_locked():
                    # flag flips in the SAME critical section as the
                    # exit decision: a concurrent submit either lands
                    # before (the loop still emits it) or after (it
                    # self-emits) — never in a gap
                    self._done_running = True
                    break
                self._wake.wait(timeout=self.poll_s)
        with self._lock:
            return 1 if self._failed else 0

    def _route_once(self) -> None:
        now = self._clock()
        if self._drain_req.is_set():
            self._begin_drain()
        self._chaos_poll()
        self._reap(now)
        self._refresh_health(now)
        self._due_restarts(now)
        self._route_pending(now)
        self._hedge_pending(now)
        with self._lock:
            self._fail_if_abandoned_locked()
            self._emit_ready_locked()

    def _chaos_poll(self) -> None:
        # chaos: hard-kill replica K mid-fleet (raise:K) — the journal
        # re-offer / failover drill (doc/resilience.md)
        try:
            faultinject.fault_point("fleet.replica_crash")
        except faultinject.FaultInjected as e:
            names = sorted(self._rep)
            try:
                idx = int(e.arg or 0)
            except ValueError:
                idx = 0
            name = names[idx % len(names)]
            logger.warning("fleet chaos: hard-killing %s (%s)", name, e)
            self._rep[name]["handle"].kill()

    def _reap(self, now: float) -> None:
        for name, st in self._rep.items():
            with self._lock:
                up = st["up"]
            if not up:
                continue
            rc = st["handle"].poll_exit()
            if rc is not None:
                self._on_death(name, rc, now)

    def _refresh_health(self, now: float) -> None:
        for name, st in self._rep.items():
            with self._lock:
                if not st["up"] or now - st["health_at"] < self.health_period_s:
                    continue
            try:
                # chaos: this replica's status probe reads as stale —
                # the router must route around it, and kill it only
                # past the persistence bound
                faultinject.fault_point("fleet.status_stale", info=name)
                h = st["handle"].health(now)
            except faultinject.FaultInjected as e:
                h = {"stale": True, "detail": f"injected: {e}"}
            except Exception as e:  # a broken probe is a health verdict
                h = {"stale": True, "detail": f"probe failed: {e}"}
            kill = False
            with self._lock:
                st["health"] = h
                st["health_at"] = now
                if not h.get("stale"):
                    st["stale_since"] = None
                elif now - st["started_at"] > self.startup_grace_s:
                    if st["stale_since"] is None:
                        st["stale_since"] = now
                    elif now - st["stale_since"] > self.stale_after_s:
                        kill = True
            if kill:
                logger.warning(
                    "fleet: %s health stale beyond %.1fs (%s) — killing "
                    "and treating as a death", name, self.stale_after_s,
                    h.get("detail", ""))
                st["handle"].kill()
                if st["handle"].join(timeout=5.0):
                    self._on_death(name, st["handle"].poll_exit() or 1, now)

    def _on_death(self, name: str, rc: int, now: float) -> None:
        st = self._rep[name]
        handle = st["handle"]
        with self._lock:
            st["up"] = False
            st["stale_since"] = None
            st["health"] = None
            stopping = st["stopping"] or self._draining
            self.deaths += 1
        # the journal is the durable truth of what the dead replica
        # still owed; the router's outstanding set covers requests the
        # child may not have journaled yet (accepted at the router,
        # lost in its stdin pipe)
        try:
            journal_pending = handle.pending_requests()
        except Exception:
            journal_pending = []
        with self._lock:
            owed = {str(d.get("id")) for d in journal_pending}
            owed |= self._outstanding.get(name, set())
            orphans = [rid for rid in self._order
                       if rid in owed and rid not in self._results]
            self._outstanding[name] = set()
            if stopping:
                # drain path: the child answered what it could before
                # exiting; whatever is left gets an honest error — the
                # survivors are draining too, a re-offer would only be
                # rejected later
                for rid in orphans:
                    self._results[rid] = {
                        "id": rid, "outcome": "error", "tokens": [],
                        "error": f"replica {name} exited {rc} during drain",
                    }
                st["next_restart_at"] = None
                self._wake.notify_all()
                return
            for rid in reversed(orphans):
                self._owner.pop(rid, None)
                if self._hedged.get(rid) == name:
                    # the hedge target died: the request may hedge again
                    self._hedged.pop(rid, None)
                self._unsent.appendleft(rid)
            for rid in orphans:
                # routed-but-lost: [route → death detected] — failover's
                # DISTINCT share in the tail-latency attribution table
                t_route = self._t_route.pop(rid, now)
                self._span(
                    "router.reoffer", t_route, now - t_route,
                    trace=str(self._docs[rid].get("trace_id") or rid),
                    rid=rid, replica=name,
                    attempt=self._attempt.get(rid, 1))
                # the next router.wait measures from the re-offer, not
                # the original front-door enqueue (no double-count)
                self._t_wait0[rid] = now
            self.reoffers += len(orphans)
            # exit-code discipline (resilience/supervisor.py): 18 =
            # preemption, budget-free up to the storm limit; everything
            # else (17/19/20 and plain crashes) consumes the budget
            if rc == EXIT_PREEMPTED and st["free_restarts"] < FREE_RESTART_LIMIT:
                st["free_restarts"] += 1
                delay = 0.0
            elif st["restarts"] < self.restart_budget:
                st["restarts"] += 1
                delay = min(
                    self.restart_base_delay * (2 ** (st["restarts"] - 1)),
                    RESTART_DELAY_CAP_S,
                )
            else:
                st["down"] = True
                st["next_restart_at"] = None
                logger.error(
                    "fleet: %s exit %d — restart budget (%d) exhausted, "
                    "replica permanently down", name, rc,
                    self.restart_budget)
                self._wake.notify_all()
                return
            st["next_restart_at"] = now + delay
            self._wake.notify_all()
        logger.warning(
            "fleet: %s exit %d — re-offering %d unanswered request(s) to "
            "survivors, restart in %.1fs", name, rc, len(orphans), delay)

    def _due_restarts(self, now: float) -> None:
        for name, st in self._rep.items():
            with self._lock:
                due = (not st["up"] and not st["down"] and not st["stopping"]
                       and not self._draining
                       and st["next_restart_at"] is not None
                       and now >= st["next_restart_at"])
                if due:
                    st["next_restart_at"] = None
            if due:
                st["handle"].start()
                with self._lock:
                    st["up"] = True
                    st["started_at"] = self._clock()
                    st["health"] = None
                    st["stale_since"] = None
                logger.info("fleet: %s restarted (budgeted %d/%d, free %d) "
                            "— rejoining rotation", name, st["restarts"],
                            self.restart_budget, st["free_restarts"])

    def _candidates(self) -> List[tuple]:
        """(score, name, handle) for every routable replica — caller
        holds the lock."""
        out = []
        for name, st in sorted(self._rep.items()):
            if not st["up"] or st["down"] or st["stopping"]:
                continue
            h = st["health"]
            if h is not None and not h.get("stale"):
                if h.get("draining") or h.get("breaker") == "open":
                    continue
            elif h is not None and h.get("stale"):
                # stale health: routable only during the startup grace
                # (no snapshot exists yet); a formerly-healthy replica
                # gone stale is suspect — route around it
                if st["stale_since"] is not None:
                    continue
            out.append((replica_score(len(self._outstanding[name]), h),
                        name, st["handle"]))
        return sorted(out, key=lambda t: (t[0], t[1]))

    def _route_pending(self, now: float) -> None:
        while True:
            with self._lock:
                if not self._unsent:
                    return
                rid = self._unsent[0]
                if rid in self._results:      # answered while queued
                    self._unsent.popleft()    # (re-offer raced a replay)
                    continue
                cands = self._candidates()
                if not cands:
                    return  # nobody routable — requests wait; a restart
                    # or health recovery re-enters here next poll
                _score, name, handle = cands[0]
                self._unsent.popleft()
                doc = self._docs[rid]
                attempt = self._attempt.get(rid, 0) + 1
                self._attempt[rid] = attempt
                trace = str(doc.get("trace_id") or rid)
                # per-hop ids ride the forwarded line (opaque to the
                # replica, echoed onto its journal): the parent is the
                # front-door enqueue, one child hop per routing attempt
                doc["trace_id"] = trace
                doc["span_id"] = f"{trace}:send:{attempt}"
                doc["parent_id"] = f"{trace}:enqueue"
            # the pipe write runs OUTSIDE the lock: a full pipe to a
            # busy child must not block submit/deliver
            if handle.send(doc):
                with self._lock:
                    self._owner[rid] = name
                    self._outstanding[name].add(rid)
                    self.routed += 1
                    t_route = self._clock()
                    t0 = self._t_wait0.get(rid, t_route)
                    self._t_route[rid] = t_route
                    self._span("router.wait", t0, t_route - t0,
                               trace=trace, rid=rid, replica=name,
                               attempt=attempt)
            else:
                # send failed: the child is dying — requeue and let the
                # reaper classify the death (its journal never saw this
                # request, so the requeue IS its re-offer)
                with self._lock:
                    self._unsent.appendleft(rid)
                return

    def _note_latency_locked(self, lat: float) -> None:
        """Streaming p99 of route->answer latency (stochastic quantile
        estimate — no sample buffer): each observation nudges the
        estimate up by 0.99 steps or down by 0.01 steps, with the step
        scaled to the latency magnitude EMA. Caller holds the lock."""
        lat = max(float(lat), 0.0)
        self._lat_scale += (lat - self._lat_scale) * 0.05
        step = max(self._lat_scale, 1e-4) * 0.05
        if lat > self._lat_p99:
            self._lat_p99 += step * 0.99
        else:
            self._lat_p99 = max(self._lat_p99 - step * 0.01, 0.0)

    def _hedge_pending(self, now: float) -> None:
        """Hedged retries: a request outstanding on its owner past the
        adaptive hedge delay is re-sent (once) to the next-healthiest
        replica. First answer wins in :meth:`deliver`; the loser lands
        in ``duplicate_answers``. The hedge send rides the SAME doc, so
        a wire-stamped ``deadline_unix`` carries the shrunken budget."""
        if self.hedge_after <= 0:
            return
        sends = []
        with self._lock:
            if self._draining:
                return
            delay = max(self.hedge_after, self._lat_p99)
            for rid, t_route in list(self._t_route.items()):
                if now - t_route < delay:
                    continue
                if rid in self._results or rid in self._hedged:
                    continue
                owner = self._owner.get(rid)
                cands = [c for c in self._candidates() if c[1] != owner]
                if not cands:
                    continue
                _score, name, handle = cands[0]
                doc = self._docs[rid]
                attempt = self._attempt.get(rid, 1) + 1
                self._attempt[rid] = attempt
                trace = str(doc.get("trace_id") or rid)
                doc["span_id"] = f"{trace}:send:{attempt}"
                self._hedged[rid] = name
                sends.append((rid, name, handle, doc, t_route, trace,
                              attempt))
        # sends run OUTSIDE the lock, like _route_pending's
        for rid, name, handle, doc, t_route, trace, attempt in sends:
            if handle.send(doc):
                with self._lock:
                    self._outstanding[name].add(rid)
                    self.hedges += 1
                    # [route -> hedge fired]: the tail the hedge is
                    # trying to cut, attributed as its own bucket in
                    # the trace tail table (doc/observability.md)
                    self._span("net.hedge", t_route, now - t_route,
                               trace=trace, rid=rid, replica=name,
                               attempt=attempt)
            else:
                with self._lock:
                    if self._hedged.get(rid) == name:
                        del self._hedged[rid]

    def _fail_if_abandoned_locked(self) -> None:
        if self._draining or self._failed:
            return
        if any(not st["down"] for st in self._rep.values()):
            return
        # every replica permanently down: no capacity will ever return.
        # Answer everything unanswered honestly instead of hanging the
        # client forever.
        unanswered = [rid for rid in self._order if rid not in self._results]
        if not unanswered:
            return
        self._failed = True
        for rid in unanswered:
            self._results[rid] = {
                "id": rid, "outcome": "error", "tokens": [],
                "error": "fleet failed: every replica is permanently down",
            }
        self._unsent.clear()
        logger.error("fleet: all replicas permanently down — answering %d "
                     "request(s) outcome=error", len(unanswered))

    def _emit_ready_locked(self) -> None:
        while self._emit_idx < len(self._order):
            res = self._results.get(self._order[self._emit_idx])
            if res is None:
                break
            self._emit_idx += 1
            self._emit(res)

    # --------------------------------------------------------- drain

    def _begin_drain(self) -> None:
        with self._lock:
            if self._draining:
                return
            self._draining = True
            # structural rejection for everything not yet routed — the
            # same answer a draining engine's queue gets
            while self._unsent:
                rid = self._unsent.popleft()
                if rid not in self._results:
                    self._results[rid] = {"id": rid, "outcome": "rejected",
                                          "tokens": []}
            for st in self._rep.values():
                if st["up"]:
                    st["stopping"] = True
                st["next_restart_at"] = None
            self._wake.notify_all()
        logger.info("fleet: draining — in-flight work completes, queued "
                    "and new requests reject")
        for st in self._rep.values():
            if st["stopping"]:
                st["handle"].begin_drain()

    def _finished_locked(self) -> bool:
        if self._emit_idx < len(self._order):
            return False
        if self._draining:
            # every answer emitted; done once every child exited
            return all(not st["up"] for st in self._rep.values())
        # plain EOF is a batch: everything submitted must be answered
        # (failover and restarts run for as long as that takes)
        return self._eof and not self._unsent

    def shutdown(self, timeout: float = 30.0) -> None:
        """Post-run cleanup: drain and reap any children still up (the
        EOF-batch path gets here with all requests answered)."""
        for st in self._rep.values():
            if st["handle"].alive():
                st["handle"].begin_drain()
        deadline = self._clock() + timeout
        for st in self._rep.values():
            left = max(deadline - self._clock(), 0.1)
            if not st["handle"].join(timeout=left):
                st["handle"].kill()
                st["handle"].join(timeout=5.0)
            with self._lock:
                st["up"] = False


# ------------------------------------------------- in-process fleet

def drive_fleet_rung(engines, requests, *, rate_rps: float, rung: int = 0,
                     result_timeout_s: float = 300.0,
                     clients=None) -> Dict[str, Any]:
    """Open-loop driver for one offered-load rung across N in-process
    engines (``bench.py serve --replicas=N``): each arrival routes to
    the least-loaded replica under the SAME :func:`replica_score`
    policy the subprocess router uses, so the bench measures the real
    routing discipline. Emits each engine's per-replica window (its
    RequestLog carries ``replica=i``), then a MERGED ``serve_window``
    stamped ``replicas=N`` — the record `paddle compare` joins the
    scaling curve on. The merge is conservative: counts/goodput sum,
    p99s take the worst replica, p50s/means average weighted by
    completions.

    ``clients`` (optional) is a parallel list of
    :class:`~paddle_tpu.serving.transport.SocketEngineClient` — the
    bench's ``transport=tcp`` mode: scoring still reads each engine's
    in-process status, but the submit and the answer take the real
    framed-socket path, so serialization + syscall cost lands in the
    measured ``router_share`` and the merged window is stamped
    ``transport=tcp`` instead of ``pipe``."""
    for e in engines:
        e.begin_window()
    t0 = cc.monotonic()
    futures = []
    outstanding = [0] * len(engines)
    router_s = 0.0
    for req in requests:
        delay = req.t_enqueue - (cc.monotonic() - t0)
        if delay > 0:
            cc.sleep(delay)
        r0 = cc.perf_counter()
        scores = []
        for i, e in enumerate(engines):
            # status() is bounded-lock: a busy scheduler yields a stale
            # doc, and the outstanding count carries the decision
            scores.append((replica_score(outstanding[i], e.status()), i))
        i = min(scores)[1]
        if clients is not None:
            # the framed-socket hop is part of the routing cost being
            # measured — keep it inside the router_s stopwatch
            fut = clients[i].submit({"id": req.rid,
                                     "prompt": req.prompt or [],
                                     "max_new_tokens": req.max_new})
        else:
            fut = engines[i].submit(req.prompt or [],
                                    max_new_tokens=req.max_new,
                                    rid=req.rid)
        router_s += cc.perf_counter() - r0
        outstanding[i] += 1

        def _dec(i=i):
            outstanding[i] -= 1

        futures.append((fut, _dec))
    for fut, dec in futures:
        fut.result(timeout=result_timeout_s)
        dec()
    elapsed = cc.monotonic() - t0
    window_s = max(elapsed, requests[-1].t_enqueue if requests else 0.0)
    per = [e.window_roll(offered_rps=rate_rps, rung=rung, window_s=window_s)
           for e in engines]
    return merge_windows(per, rate_rps=rate_rps, rung=rung,
                         window_s=window_s, router_s=router_s,
                         transport="tcp" if clients is not None else "pipe")


def merge_windows(per: List[Dict[str, Any]], *, rate_rps: float, rung: int,
                  window_s: float, router_s: float = 0.0,
                  transport: str = "") -> Dict[str, Any]:
    """Fold N per-replica ``serve_window`` records into one fleet
    window (``replicas=N``). Sums for counts and token totals; for the
    latency histograms the merged p99 is the WORST replica's (tail
    honesty) and the p50/mean are completion-weighted averages — a
    cross-replica histogram merge without the samples is necessarily
    approximate, and this direction never understates the tail.
    ``transport`` (pipe|tcp) is stamped when known, so `paddle
    compare` can qualify and judge the A/B."""
    from paddle_tpu.observability import metrics as obs

    n = len(per)
    rec: Dict[str, Any] = {
        "rung": int(rung), "engine": per[0].get("engine", "continuous"),
        "offered_rps": round(float(rate_rps), 6),
        "window_s": round(float(window_s), 6),
        "replicas": n,
    }
    if isinstance(per[0].get("pipeline"), str):
        rec["pipeline"] = per[0]["pipeline"]
    if transport:
        # pipe|tcp — `paddle compare` joins scaling curves on it so
        # transport overhead is measured, not assumed
        rec["transport"] = transport
    for key in ("arrived", "admitted", "completed", "rejected", "timeouts",
                "cancelled", "errors", "shed", "breaker_open", "launches",
                "gen_tokens"):
        rec[key] = sum(int(w.get(key) or 0) for w in per)
    rec["exec_s"] = round(sum(float(w.get("exec_s") or 0.0) for w in per), 6)
    rec["goodput_tok_s"] = round(rec["gen_tokens"] / max(window_s, 1e-9), 3)
    rec["completed_rps"] = round(rec["completed"] / max(window_s, 1e-9), 6)
    if router_s:
        rec["router_share"] = round(router_s / max(window_s, 1e-9), 4)
    weights = [max(int(w.get("completed") or 0), 0) for w in per]
    wsum = sum(weights) or 1

    def _merged_snap(key: str) -> Dict[str, float]:
        snaps = [w.get(key) or {} for w in per]
        count = sum(int(s.get("count") or 0) for s in snaps)
        if key in ("queue_depth", "occupancy"):
            # gauges are TIME-sampled, not per-completion: weighting by
            # completions silently drops a zero-completion replica from
            # the mean even though it held slots/queue all window, so
            # occupancy reads high under imbalance. Weight by each
            # snap's sample count instead (1 when unknown) — an idle
            # replica contributes its honest zero.
            wts = [max(int(s.get("count") or 0), 1) for s in snaps]
        else:
            wts = weights
        wts_sum = sum(wts) or 1
        return {
            "count": count,
            "mean": round(sum(float(s.get("mean") or 0.0) * wt
                              for s, wt in zip(snaps, wts)) / wts_sum, 6),
            "p50": round(sum(float(s.get("p50") or 0.0) * wt
                             for s, wt in zip(snaps, wts)) / wts_sum, 6),
            "p99": round(max((float(s.get("p99") or 0.0) for s in snaps),
                             default=0.0), 6),
            "max": round(max((float(s.get("max") or 0.0) for s in snaps),
                             default=0.0), 6),
        }

    for key in ("latency", "ttft", "queue_wait", "queue_depth", "occupancy"):
        rec[key] = _merged_snap(key)
    shares = [w.get("queue_wait_share") for w in per]
    if any(isinstance(s, (int, float)) for s in shares):
        rec["queue_wait_share"] = round(
            sum(float(s or 0.0) * wt for s, wt in zip(shares, weights))
            / wsum, 4)
    obs.emit("serve_window", **rec)
    return rec


# ------------------------------------------------------------ process

def _child_argv(rest: List[str], status_dir: str, i: int) -> List[str]:
    """Replica i's ``paddle serve`` argv: the router's args minus the
    fleet/router-owned flags, plus per-replica status/journal/metrics
    paths under the fleet status dir."""
    from paddle_tpu.utils.flags import strip_flag

    args = list(rest)
    for name in ("fleet_replicas", "fleet_status_dir", "status_path",
                 "serve_journal_path", "metrics_path", "fault_spec",
                 "fault_seed"):
        args = strip_flag(args, name)
    args += [
        f"--status_path={os.path.join(status_dir, f'replica-{i}.json')}",
        f"--serve_journal_path="
        f"{os.path.join(status_dir, f'replica-{i}.journal.jsonl')}",
        f"--metrics_path={os.path.join(status_dir, f'replica-{i}')}",
    ]
    return [sys.executable, "-m", "paddle_tpu.cli", "serve"] + args


def _child_env(i: int) -> Dict[str, str]:
    """Replica i's environment: the fleet-level fault plan must not
    fire identically in every child, so PADDLE_TPU_FAULTS is stripped
    and the per-replica CHILD_FAULTS env re-injects a child-scoped spec
    (chaos drills that kill exactly one replica)."""
    env = dict(os.environ)
    env.pop("PADDLE_TPU_FAULTS", None)
    child_spec = os.environ.get(f"{CHILD_FAULTS_ENV}{i}", "")
    if child_spec:
        env["PADDLE_TPU_FAULTS"] = child_spec
    return env


def main(rest: List[str]) -> int:
    """``paddle serve-fleet`` — jax-free, like the supervisor: the
    router process never imports jax; the replicas own the device."""
    from paddle_tpu.observability import metrics as obsm
    from paddle_tpu.serving.frontend import _parse_line
    from paddle_tpu.utils.flags import FLAGS, flag_values

    # repeatable: --replica_addr h1:9000 --replica_addr h2:9000 (or one
    # comma list) — collected BEFORE FLAGS.parse keeps only the last
    addrs = flag_values(list(rest), "replica_addr")
    leftover = FLAGS.parse(list(rest))
    if leftover:
        print(f"warning: unrecognized flags {leftover}", file=sys.stderr)
    if not addrs and not FLAGS.config:
        print("error: --config is required", file=sys.stderr)
        return 2
    n = len(addrs) if addrs else max(1, FLAGS.fleet_replicas)
    status_dir = FLAGS.fleet_status_dir or os.path.join(
        FLAGS.save_dir or "output", "fleet_status")
    os.makedirs(status_dir, exist_ok=True)
    obsm.configure_from_flags(FLAGS)
    if FLAGS.fault_spec:
        # the fleet.* (and, socket mode, net.*) chaos sites fire in
        # THIS process; serve.* specs for pipe children ride the
        # per-replica CHILD_FAULTS env
        faultinject.configure(FLAGS.fault_spec, FLAGS.fault_seed)

    def emit(doc: Dict[str, Any]) -> None:
        print(json.dumps(doc), flush=True)

    if addrs:
        # cross-host fleet (doc/serving.md "Cross-host fleet"): each
        # address is a running `paddle serve --listen` replica; the
        # shared --io_retry_* policy drives reconnect backoff, and a
        # retry-budget-exhausted transport surfaces as a death (the
        # restart budget then governs fresh connection attempts)
        from paddle_tpu.serving.transport import SocketReplica
        from paddle_tpu.utils.retry import RetryPolicy

        replicas = [
            SocketReplica(
                f"replica-{i}", addr,
                deliver=lambda name, doc: router.deliver(name, doc),
                timeout_s=FLAGS.serve_request_timeout,
                policy=RetryPolicy.from_flags(
                    FLAGS, retry_on=(OSError,), name="net.connect"),
            )
            for i, addr in enumerate(addrs)
        ]
    else:
        replicas = [
            ProcReplica(
                f"replica-{i}", _child_argv(rest, status_dir, i),
                status_path=os.path.join(status_dir, f"replica-{i}.json"),
                journal_path=os.path.join(
                    status_dir, f"replica-{i}.journal.jsonl"),
                deliver=lambda name, doc: router.deliver(name, doc),
                env=_child_env(i),
            )
            for i in range(n)
        ]
    router = FleetRouter(
        replicas,
        emit=emit,
        restart_budget=FLAGS.restart_budget,
        restart_base_delay=FLAGS.restart_base_delay,
        hedge_after=FLAGS.hedge_after,
    )
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: router.request_drain())
    router.start()
    where = (f"replica addr(s) {', '.join(addrs)}" if addrs
             else f"status dir {status_dir}")
    print(f"# paddle serve-fleet: {n} replica(s), {where} "
          "— reading JSONL requests from stdin", file=sys.stderr)

    def _reader() -> None:
        ln = 0
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            doc, err, rid = _parse_line(line, ln)
            ln += 1
            if doc is None:
                emit({"id": rid, "outcome": "error", "tokens": [],
                      "error": err})
            elif not router.submit(doc):
                print(f"# paddle serve-fleet: duplicate request id "
                      f"{doc['id']!r} skipped", file=sys.stderr)
        router.note_eof()

    reader = cc.Thread(target=_reader, name="fleet-stdin", daemon=True)
    reader.start()

    rc = router.run()
    router.shutdown()
    if obsm.enabled():
        st = router.status()
        reg = obsm.registry()
        reg.counter("fleet.routed").inc(st["routed"])
        reg.counter("fleet.reoffers").inc(st["reoffers"])
        reg.counter("fleet.duplicate_answers").inc(st["duplicate_answers"])
        reg.counter("fleet.deaths").inc(st["deaths"])
        # net.reconnects is already live in the registry (the transport
        # counts each re-established connection as it happens)
        reg.counter("net.hedges").inc(st["hedges"])
        reg.counter("net.hedge_wins").inc(st["hedge_wins"])
        # run_end is the router stream's LAST record, mirroring the
        # single-process serve contract; it carries the fleet counters
        # snapshot (the trainer's pass_end idiom)
        obsm.emit("run_end", status="completed", counters=reg.snapshot())
        obsm.flush()
    print("# paddle serve-fleet: drained", file=sys.stderr)
    return rc
