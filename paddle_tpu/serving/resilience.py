"""Serving resilience — the engine-side half of the PR 1–11 hardening
stack, applied to the continuous-batching server (doc/resilience.md
"Serving resilience").

The training tier survives hangs (hangwatch → ``hang_report.json`` +
exit 19), OOMs (pre-mortem → exit 20), crashes (`paddle supervise`),
and overload-shaped data stalls. The serving tier — which iteration-
level scheduling deliberately runs at the device's limit — previously
survived exactly one failure (a single faulted decode launch). This
module supplies the rest, REUSING the existing mechanisms instead of
reinventing them:

- :class:`ServeHangWatch` — ``resilience/hangwatch.py`` subclassed for
  the serve loop: the engine pings it at every collect boundary; a
  wedged ``serve_decode`` launch dumps ``serve_hang_report.json``
  (thread stacks + metrics tail + the in-flight cohort snapshot),
  answers every in-flight request ``outcome=error`` (the
  ``_pre_exit`` hook), and exits ``EXIT_HANG`` (19) — so `paddle
  supervise` sees a *diagnosed* death and clients hear "the server
  hung" instead of waiting out their own timeouts.
- :class:`CircuitBreaker` — N consecutive launch faults open the
  breaker: submits are answered ``outcome=shed`` with a retry-after
  hint for a cooldown instead of burning fresh cohorts against a
  faulting device; a half-open probe cohort closes it again.
- :class:`RequestJournal` — the `paddle serve` front-end's durable
  request log: every accepted request is appended (flush + fsync)
  BEFORE it is submitted to the engine, and marked done after its
  result line is printed. A crash therefore loses a process, not a
  queue: the restarted server re-offers every accepted-but-unanswered
  request. Semantics are **at-least-once** — a crash between printing
  a result and journaling it re-answers that request on restart;
  consumers dedupe by request id (doc/resilience.md).
- :class:`StatusWriter` / :func:`status_main` — ``--status_path``:
  periodic atomic status JSON (queue depth, slot occupancy,
  last-collect age, shed/error totals, draining flag) — the health/
  readiness probe a load balancer needs — and the jax-free
  ``paddle serve-status`` renderer.
- :func:`journal_progress` — the supervisor's jax-free progress probe
  for serve children (the serving analog of ``probe_restorable``):
  answered-request count between deaths distinguishes a crash loop
  from a run that is working its queue down.
- :class:`WeightReloader` — hot weight reload (doc/serving.md "Serving
  fleet"): a daemon thread polls a checkpoint dir with the
  supervisor's durability probe (``probe_restorable`` — manifests
  gate, torn saves never load) and, when a NEWER durable checkpoint
  lands, loads it and stages it via ``Engine.request_reload`` for the
  next iteration boundary. Requests admitted before the swap finish on
  the old weights; nothing is dropped or stranded. The
  ``fleet.reload_torn`` chaos site fires between the durability probe
  and the load — the checkpoint-becomes-durable-mid-swap drill.

Everything here is jax-free and, like the engine, reads clocks only
through the ``utils/concurrency`` seam (PTL001: the one wall-clock
stamp in a hang report comes from the base class in ``resilience/``,
outside the hot path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Callable, Dict, List, Optional

from paddle_tpu.resilience import faultinject
from paddle_tpu.resilience.hangwatch import HangWatch
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

SERVE_HANG_REPORT = "serve_hang_report.json"

__all__ = [
    "SERVE_HANG_REPORT", "ServeHangWatch", "CircuitBreaker",
    "RequestJournal", "StatusWriter", "WeightReloader", "read_status",
    "journal_progress", "status_main",
]


# ------------------------------------------------------------ hangwatch


class ServeHangWatch(HangWatch):
    """The serve loop's hangwatch: same monitor thread, same backstop
    timer, same exit 19 — the deltas are the report name (a serve hang
    and a train hang in one save_dir must not overwrite each other's
    forensics), the in-flight cohort snapshot in the report, and the
    ``_pre_exit`` answer pass.

    ``attach(engine)`` is called once by ``Engine.start()`` before the
    monitor starts; the engine pings at every collect boundary (and on
    idle polls — an idle server is alive, not hung)."""

    REPORT_NAME = SERVE_HANG_REPORT
    REASON = "serve_hang"

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._engine = None  # set by attach() before start()
        # frontend-registered best-effort hook: after the in-flight
        # cohort is failed, PRINT the resolved answers before the
        # process exits — resolving a future the exiting process never
        # flushes to stdout answers nobody. Runs inside the forensics
        # backstop window, so a wedged stdout still exits 19 in time.
        self.answer_flush: Optional[Callable[[], None]] = None

    def attach(self, engine) -> "ServeHangWatch":
        self._engine = engine
        return self

    def build_report(self, age: float, where) -> Dict[str, Any]:
        report = super().build_report(age, where)
        eng = self._engine
        if eng is not None:
            # captured BEFORE _pre_exit fails the cohort: the report
            # must show what was in flight when the launch wedged
            try:
                report["inflight"] = eng.hang_snapshot()
            except Exception as e:  # forensics never mask the hang
                report["inflight_error"] = str(e)
        return report

    def _pre_exit(self) -> None:
        eng = self._engine
        if eng is None:
            return
        n = eng.hang_fail_all(
            f"serve decode hang: no collect progress for >{self.timeout_s:g}s"
            f" (forensics: {self.REPORT_NAME})"
        )
        logger.error(
            "serve hangwatch: answered %d in-flight/queued request(s) "
            "with outcome=error before exit", n,
        )
        flush = self.answer_flush
        if flush is not None:
            try:  # best-effort: the hang must exit regardless
                flush()
            except Exception:
                pass


# ------------------------------------------------------ circuit breaker


class CircuitBreaker:
    """Launch-failure circuit breaker (closed → open → half-open).

    ``record_fault()`` after every failed launch; ``threshold``
    consecutive faults open the breaker for ``cooldown_s``: submits are
    shed fast (``allow_submit`` False) and no cohorts are launched
    (``allow_launch`` False) — a faulting device burns no more
    requests. Once the cooldown elapses the state reads ``half_open``:
    launches are allowed again, the first success closes the breaker,
    the first fault reopens it (a fresh cooldown). ``clock`` is
    injectable for tests and virtualized under `paddle race`.

    Thread-safety: all methods are called with the engine's lock held
    (submit() and the scheduler loop both serialize on it), so the
    breaker itself carries no lock — documented, and explored by
    tests/race_specs/spec_serve_engine.py."""

    def __init__(self, threshold: int, cooldown_s: float,
                 clock: Optional[Callable[[], float]] = None):
        assert threshold > 0, threshold
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock if clock is not None else cc.monotonic
        self._consecutive = 0
        self._open = False
        self._opened_at = 0.0
        self._probing = False  # half-open probe cohort in flight
        self.opened_total = 0  # lifetime opens (status / telemetry)

    @property
    def state(self) -> str:
        if not self._open:
            return "closed"
        if self._clock() - self._opened_at >= self.cooldown_s:
            return "half_open"
        return "open"

    def allow_launch(self) -> bool:
        """May the scheduler admit + launch a cohort right now? False
        while open-and-cooling; half-open lets ONE cohort probe through
        (its collect resolves the state either way) — the engine marks
        it with :meth:`note_probe`, and further boundaries wait out the
        verdict instead of burning fresh cohorts against a device the
        probe may be about to prove still bad (the pipelined loop runs
        boundaries faster than collects resolve)."""
        st = self.state
        return st != "open" and not (st == "half_open" and self._probing)

    def note_probe(self) -> None:
        """The engine launched a cohort while half-open: latch until
        its collect resolves the state (record_success/record_fault)."""
        if self.state == "half_open":
            self._probing = True

    def allow_submit(self) -> bool:
        """May a new request enter the queue? Open = shed fast; the
        half-open probe window accepts again (those requests wait out
        the probe in the queue like any others)."""
        return self.state != "open"

    def retry_after_s(self) -> float:
        """Cooldown remaining — the shed answer's retry-after hint."""
        if not self._open:
            return 0.0
        return max(self.cooldown_s - (self._clock() - self._opened_at), 0.0)

    def record_fault(self) -> bool:
        """One launch fault. Returns True exactly when this fault
        OPENED (or re-opened) the breaker — the window's
        ``breaker_open`` count."""
        self._consecutive += 1
        was_open = self._open and self.state != "half_open"
        self._probing = False
        if self._consecutive >= self.threshold or self._open:
            self._open = True
            self._opened_at = self._clock()
            if not was_open:
                self.opened_total += 1
                return True
        return False

    def record_success(self) -> None:
        self._consecutive = 0
        self._open = False
        self._probing = False


# -------------------------------------------------------------- journal


def _read_journal(path: str):
    """Parse a journal file read-only → (accepted, done) maps. Shared
    by :class:`RequestJournal` and the supervisor's jax-free
    :func:`journal_progress` probe (which must not open-for-append).
    Tolerates a missing file and a torn tail line (the crash the
    journal exists for tears mid-append)."""
    accepted: Dict[str, Dict[str, Any]] = {}
    done: Dict[str, str] = {}
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return accepted, done
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue  # torn tail (or garbage): skip, never raise
        if not isinstance(doc, dict) or "id" not in doc:
            continue
        rid = str(doc["id"])
        if doc.get("op") == "accept":
            accepted.setdefault(rid, doc)
        elif doc.get("op") == "done":
            done[rid] = str(doc.get("outcome", ""))
    return accepted, done


class RequestJournal:
    """Durable at-least-once request journal (``--serve_journal_path``).

    Append-only JSONL, one op per line::

        {"op": "accept", "id": ..., "prompt": [...], "max_new_tokens": N}
        {"op": "done",   "id": ..., "outcome": "ok"}

    The ``accept`` append is flushed AND fsynced before the request is
    submitted to the engine (crash-ordered before any accept effect),
    so a crash at any later point re-offers the request on restart.
    ``done`` is appended after the result line is printed — a crash in
    between re-answers that request (at-least-once; dedupe by id is
    the consumer's contract, doc/resilience.md "Serving resilience").
    The loader tolerates a torn tail line (the crash the journal
    exists for tears mid-append)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = cc.Lock()
        self.accepted, self.done = _read_journal(path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        # seal a torn tail (a crash mid-append leaves no trailing
        # newline): appending onto the fragment would corrupt the FIRST
        # record this incarnation writes — losing an accept on the next
        # restart, which is the one loss the journal must never allow
        try:
            size = os.path.getsize(path)
            if size:
                with open(path, "rb") as rf:
                    rf.seek(size - 1)
                    if rf.read(1) != b"\n":
                        self._f.write("\n")
                        self._f.flush()
        except OSError:
            pass

    def pending(self) -> List[Dict[str, Any]]:
        """Accepted-but-unanswered requests, in acceptance order —
        what a restarted server re-offers."""
        with self._lock:
            return [dict(doc) for rid, doc in self.accepted.items()
                    if rid not in self.done]

    def is_done(self, rid: str) -> bool:
        with self._lock:
            return str(rid) in self.done

    def is_accepted(self, rid: str) -> bool:
        with self._lock:
            return str(rid) in self.accepted

    def accept(self, doc: Dict[str, Any]) -> bool:
        """Journal one accepted request DURABLY (flush + fsync) before
        the caller submits it. False = this id was already accepted (a
        replayed stdin line after a restart) — the caller must not
        double-submit."""
        rid = str(doc.get("id"))
        with self._lock:
            if rid in self.accepted:
                return False
            rec = {"op": "accept", "id": rid,
                   "prompt": doc.get("prompt"),
                   "max_new_tokens": doc.get("max_new_tokens")}
            if doc.get("trace_id"):
                # a replayed backlog keeps its distributed-tracing join
                # key — the restarted incarnation's spans still stitch
                # into the same cross-process timeline
                rec["trace_id"] = str(doc["trace_id"])
            self.accepted[rid] = rec
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
        return True

    def answer(self, rid: str, outcome: str) -> None:
        """Mark one request answered (its result line was printed)."""
        with self._lock:
            self.done[str(rid)] = str(outcome)
            self._f.write(json.dumps(
                {"op": "done", "id": str(rid), "outcome": str(outcome)}
            ) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


def journal_progress(journal_path: str) -> Optional[str]:
    """The supervisor's progress probe for serve children: a compact
    fingerprint of the journal's ANSWERED set. Two consecutive deaths
    with the SAME fingerprint made no serving progress — the crash-loop
    signal, exactly like ``probe_restorable``'s restored-pass equality
    for trainers. Deliberately blind to the accepted count: a child
    that keeps accepting traffic but answers nothing is the crash loop,
    and a growing accept count must not disguise it as progress. None
    when there is no journal (progress unknowable — every death then
    looks loop-like, which errs toward stopping)."""
    if not journal_path or not os.path.exists(journal_path):
        return None
    try:
        _accepted, done = _read_journal(journal_path)
    except Exception:
        return None
    return f"answered:{len(done)}"


# ------------------------------------------------------------- status


class StatusWriter:
    """``--status_path``: a daemon thread renews an atomic status JSON
    every ``interval_s`` — the liveness/readiness file a load balancer
    (or `paddle serve-status`) polls. The engine's ``status()`` is
    bounded-lock (a wedged scheduler yields a stale-but-honest
    snapshot), and the write is tmp→replace so readers never see a torn
    document. ``stop()`` writes one final snapshot with the draining
    flag set."""

    def __init__(self, path: str, engine, interval_s: float = 1.0):
        self.path = path
        self.interval_s = float(interval_s)
        self._engine = engine
        self._stop = cc.Event()
        self._thread = None

    def write_now(self) -> None:
        try:
            doc = self._engine.status()
        except Exception as e:  # the probe must never kill the server
            doc = {"error": str(e)}
        d = os.path.dirname(self.path)
        try:
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2, default=str)
            os.replace(tmp, self.path)
        except OSError as e:
            logger.warning("serve status write failed (%s): %s",
                           self.path, e)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.write_now()

    def start(self) -> "StatusWriter":
        if self._thread is None:
            self._stop.clear()
            t = cc.Thread(target=self._run, name="serve-status", daemon=True)
            self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(self.interval_s * 2, 1.0))
        self.write_now()  # final snapshot carries the draining flag


def read_status(path: str) -> Optional[Dict[str, Any]]:
    """Tolerant ``--status_path`` reader: the document, or ``None`` on
    any missing/torn/non-object file. The fleet router and the fleet
    status view call this per poll — an unreadable probe is a health
    verdict over there, never an exception here."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


# ------------------------------------------------------------- reload


class WeightReloader:
    """Hot weight reload: watch a checkpoint dir, stage newer durable
    checkpoints into a live engine (doc/serving.md "Serving fleet").

    The durability probe is the supervisor's ``probe_restorable`` —
    only manifest-verified saves qualify, so a checkpoint still being
    written (or torn by a trainer crash) never loads. The baseline is
    whatever is newest at START: serving begins on the weights it was
    launched with, and only checkpoints landing AFTER that trigger a
    swap. ``loader(path)`` turns a checkpoint path into backend params
    (the front-end passes ``GradientMachine.loadParameters`` + a device
    re-shard); it runs on the watcher thread, off the scheduler — the
    engine only sees the O(1) ``request_reload`` staging.

    Failure posture: a probe or load error logs and keeps the current
    weights serving (a poison checkpoint is skipped permanently, not
    retried in a hot loop); the ``fleet.reload_torn`` chaos site aborts
    the attempt and retries next poll."""

    def __init__(self, watch_dir: str, engine, loader, *,
                 interval_s: float = 2.0, probe=None):
        if probe is None:
            from paddle_tpu.resilience.supervisor import probe_restorable
            probe = probe_restorable
        self.watch_dir = watch_dir
        self.interval_s = float(interval_s)
        self._engine = engine
        self._loader = loader
        self._probe = probe
        self._lock = cc.Lock()
        self._stop = cc.Event()
        self._thread = None
        self.reloads = 0
        try:
            baseline = probe(watch_dir)
        except Exception:
            baseline = None
        self._last = baseline

    def check_once(self) -> bool:
        """One poll: True iff a new checkpoint was staged."""
        try:
            path = self._probe(self.watch_dir)
        except Exception as e:  # probe trouble = no news, not a crash
            logger.warning("weight reload probe failed (%s): %s",
                           self.watch_dir, e)
            return False
        with self._lock:
            if not path or path == self._last:
                return False
        try:
            # chaos: the checkpoint became durable mid-swap — abort this
            # attempt, retry next poll (doc/resilience.md)
            faultinject.fault_point("fleet.reload_torn", info=path)
            params = self._loader(path)
        except faultinject.FaultInjected as e:
            logger.warning("weight reload of %s aborted (%s) — retrying "
                           "next poll", path, e)
            return False
        except Exception as e:  # noqa: BLE001 — poison ckpt: skip, serve on
            logger.error("weight reload of %s failed (%s) — keeping "
                         "current weights, will not retry this one",
                         path, e)
            with self._lock:
                self._last = path
            return False
        self._engine.request_reload(params, tag=path)
        with self._lock:
            self._last = path
            self.reloads += 1
        logger.info("weight reload staged: %s (swap lands at the next "
                    "iteration boundary)", path)
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_once()

    def start(self) -> "WeightReloader":
        if self._thread is None:
            self._stop.clear()
            t = cc.Thread(target=self._run, name="serve-reload",
                          daemon=True)
            self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(self.interval_s * 2, 1.0))


def _fleet_status(dirpath: str, as_json: bool) -> int:
    """``paddle serve-status <fleet_status_dir>`` — the aggregate view
    over every replica's status JSON in one directory (the layout
    ``paddle serve-fleet`` maintains). Missing or torn documents render
    as a STALE row, never an error — mid-rewrite snapshots are normal
    under load."""
    try:
        names = sorted(n for n in os.listdir(dirpath)
                       if n.endswith(".json") and not n.endswith(".tmp"))
    except OSError as e:
        print(f"error: cannot list {dirpath!r}: {e}", file=sys.stderr)
        return 1
    docs = {n[:-len(".json")]: read_status(os.path.join(dirpath, n))
            for n in names}
    if as_json:
        print(json.dumps(
            {name: (doc if doc is not None else {"stale": True})
             for name, doc in docs.items()}, indent=2))
        return 0
    if not docs:
        print(f"(no replica status files in {dirpath})")
        return 0
    header = ("replica", "state", "queue", "slots", "breaker",
              "collect age", "ok", "err")
    rows = [header]
    tot_queue = tot_ok = tot_err = tot_occ = tot_slots = up = 0
    for name, doc in docs.items():
        if doc is None or doc.get("stale") or doc.get("error"):
            detail = ("torn/missing" if doc is None
                      else doc.get("detail") or doc.get("error") or "stale")
            rows.append((name, f"STALE ({detail})", "-", "-", "-", "-",
                         "-", "-"))
            continue
        totals = doc.get("totals") or {}
        state = ("draining" if doc.get("draining")
                 else ("up" if doc.get("started") else "starting"))
        if state == "up":
            up += 1
        occ, slots = int(doc.get("occupancy") or 0), int(doc.get("slots") or 0)
        q = int(doc.get("queue_depth") or 0)
        ok, err = int(totals.get("ok") or 0), int(totals.get("error") or 0)
        rows.append((name, state, str(q), f"{occ}/{slots}",
                     str(doc.get("breaker", "disabled")),
                     f"{float(doc.get('last_collect_age_s') or 0.0):.3f}s",
                     str(ok), str(err)))
        tot_queue += q
        tot_ok += ok
        tot_err += err
        tot_occ += occ
        tot_slots += slots
    rows.append(("fleet", f"{up}/{len(docs)} up", str(tot_queue),
                 f"{tot_occ}/{tot_slots}", "-", "-", str(tot_ok),
                 str(tot_err)))
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    for i, r in enumerate(rows):
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            print("  ".join("-" * w for w in widths))
    return 0


def status_main(argv=None) -> int:
    """``paddle serve-status <path>`` — render a ``--status_path``
    snapshot. jax-free: the probe side runs anywhere."""
    p = argparse.ArgumentParser(
        prog="paddle serve-status",
        description="render a `paddle serve --status_path` health "
                    "snapshot (doc/serving.md \"Serving resilience\")",
    )
    p.add_argument("path", help="a --status_path JSON file, or a fleet "
                                "status DIRECTORY (--fleet_status_dir) "
                                "for the aggregate per-replica view")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the raw document(s)")
    args = p.parse_args(argv)
    if os.path.isdir(args.path):
        return _fleet_status(args.path, args.as_json)
    try:
        with open(args.path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read status file {args.path!r}: {e}",
              file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(doc, indent=2))
        return 0
    if doc.get("error"):
        # StatusWriter's probe-failed document: the snapshot itself
        # could not be taken — surface it, don't render a blank table
        print(f"! status probe error: {doc['error']}")
        return 1
    if doc.get("stale"):
        # the engine's bounded-lock timeout: the scheduler was busy or
        # wedged when this snapshot was taken — say so LOUDLY; the
        # normal keys are absent and 'not started' would be a lie
        print("! STALE snapshot: "
              f"{doc.get('detail', 'engine lock unavailable')}")
        return 0
    totals = doc.get("totals") or {}
    rows = [
        ("serving", "draining" if doc.get("draining")
         else ("up" if doc.get("started") else "not started")),
        ("queue depth", doc.get("queue_depth")),
        ("slots", f"{doc.get('occupancy')}/{doc.get('slots')} occupied"),
        ("in-flight launches", doc.get("inflight")),
        ("last collect age", f"{doc.get('last_collect_age_s', 0.0):.3f}s"),
        ("loop age", f"{doc.get('loop_age_s', 0.0):.3f}s"),
        ("breaker", doc.get("breaker", "disabled")),
        ("brownout", "engaged" if doc.get("brownout") else "off"),
        ("shed policy", doc.get("shed_policy", "off")),
        ("pipeline", doc.get("pipeline")),
        ("completed", totals.get("ok", 0)),
        ("shed", totals.get("shed", 0)),
        ("errors", totals.get("error", 0)),
        ("rejected", totals.get("rejected", 0)),
        ("timeouts", totals.get("timeout", 0)),
        ("cancelled", totals.get("cancelled", 0)),
    ]
    width = max(len(k) for k, _v in rows)
    for k, v in rows:
        print(f"{k:<{width}}  {v}")
    return 0


if __name__ == "__main__":
    sys.exit(status_main())
