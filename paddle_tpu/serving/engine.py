"""The continuous-batching scheduler — slot admission/eviction at every
iteration boundary, with the device/host pipeline kept full.

Run-to-completion batching (the PR-8 static driver, and every
``SequenceGenerator.generate`` call) holds a whole cohort until its
LONGEST sequence finishes: with mixed output lengths most slots spend
most steps finished-but-occupied. Orca (OSDI '22) showed iteration-level
scheduling — re-batching between decode steps — recovers that capacity.
This engine is that loop:

    while serving:
        evict   — finished (EOS / budget / max_length), cancelled, or
                  wall-deadline-expired slots free at the boundary
        admit   — queued requests (strict FIFO) prefill into free slots
        step    — ONE jitted launch advances every slot

The PR-12 loop ran those phases strictly serially: every decode launch
was followed by a blocking readback, and every admission blocked on the
prefill — so the device idled during host scheduling and the host idled
during every launch. The **pipelined** loop (default) splits the
backend step into ``dispatch()`` (enqueue launch N+1; the backend
starts ``copy_to_host_async`` on launch N's outputs before the first
collect — the PR-5 snapshot discipline) and ``collect()`` (gather N's
results), and schedules/evicts/admits on iteration N's results WHILE
the device runs N+1. Consequences, all deliberate:

- admissions decided from launch N's results prefill between launches
  N+1 and N+2 — a one-iteration admission lag (doc/serving.md
  "Pipelined decode");
- each in-flight launch carries a SNAPSHOT of its slot cohort; collect
  applies tokens to that snapshot (a slot evicted mid-flight is simply
  skipped — its device row self-terminates at its bounded budget);
- deadlines, TTFT, and exec attribution are all judged at collect
  boundaries — the only place results exist under overlap;
- a faulted in-flight launch surfaces at collect: it errors its cohort
  (and every other in-flight request), the device state resets, and the
  engine keeps serving — exactly the blocking loop's fault contract.

``pipeline=False`` keeps the PR-12 serial loop (the A/B baseline:
``PADDLE_TPU_BENCH_SERVE_PIPELINE=off``). Both loops share the
boundary/admission/apply code and the adaptive decode-block ladder
(:func:`pick_block`), so the pipeline is the ONLY delta in that A/B.

Everything here is jax-free and thread-safe strictly through the
``utils/concurrency`` seam (``cc``): the scheduler runs on one
``cc.Thread``; ``submit``/``cancel``/``drain`` are the only cross-
thread entry points and every shared field is guarded by ``self._lock``
— the ``paddle race`` spec (tests/race_specs/spec_serve_engine.py)
explores exactly these interleavings, pipelined and blocking. Device
work hides behind the backend seam (backend.py): ``FakeBackend`` for
tests, ``JaxDecodeBackend`` for TPUs.

Telemetry is the PR-8 contract unchanged — per-request ``kind=request``
records (REAL wall-clock TTFT: the first token's readback timestamp,
mid-sequence) and ``kind=serve_window`` rollups with
``engine="continuous"`` — plus the overlap plane: ``serve.
dispatch_depth`` (gauge), ``serve.overlap_s`` (counter), and a window
``host_share`` whose exec side is the UNION of dispatch→collect spans,
so overlap shows up as host_share going to ~0 instead of exec_s
double-counting past the wall clock.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.observability import serving as slog
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

ENGINE_NAME = "continuous"

# terminal request outcomes (race-spec invariant: every submitted
# request's future resolves exactly once with one of these)
OUTCOMES = ("ok", "rejected", "timeout", "cancelled", "error")

# a launch whose measured host-side cost exceeds this share of its
# device time is dispatch-dominated — the ladder steps up a rung
BLOCK_OVERHEAD_SHARE = 0.5

# EMA smoothing for the ladder's host/device time estimates
_EMA = 0.3


def pick_block(ladder: Sequence[int], cap: int, pressed: bool,
               host_s: float, step_s: float) -> int:
    """The adaptive decode-block policy: how many greedy micro-steps the
    next launch should run (doc/serving.md "The decode-block ladder").

    ``ladder`` — the pre-warmed rungs, ascending; ``cap`` — the smallest
    remaining token budget among live slots (running past it buys only
    frozen micro-steps); ``pressed`` — queue/TTFT pressure: requests are
    waiting to be admitted, or a live slot has not yet delivered its
    first token (both only resolve at a collect boundary, so boundaries
    should come sooner); ``host_s`` — measured host+dispatch seconds per
    iteration (EMA); ``step_s`` — measured device seconds per micro-step
    (EMA).

    Under pressure: the SMALLEST rung whose device time still keeps the
    measured launch overhead under :data:`BLOCK_OVERHEAD_SHARE` — pay
    for boundaries only what they cost. No pressure: the largest rung
    the budget cap allows — boundaries buy nothing, overhead
    amortization is free. With no measurements yet (warmup), pressure
    picks the bottom rung and quiet picks the top."""
    if not ladder:
        return 1
    if len(ladder) == 1:
        return int(ladder[0])
    cands = [u for u in ladder if u <= max(int(cap), int(ladder[0]))]
    if not cands:
        cands = [int(ladder[0])]
    if not pressed:
        return int(cands[-1])
    if host_s > 0 and step_s > 0:
        for u in cands:
            if host_s <= BLOCK_OVERHEAD_SHARE * u * step_s:
                return int(u)
        return int(cands[-1])
    return int(cands[0])


@dataclasses.dataclass
class ServeResult:
    """What a resolved :class:`ResultFuture` carries."""

    rid: str
    outcome: str
    tokens: List[int]
    error: Optional[str] = None


class ResultFuture:
    """A one-shot, condition-backed result future (``cc`` seam)."""

    def __init__(self) -> None:
        self._cv = cc.Condition()
        self._done = False
        self._result: Optional[ServeResult] = None

    def done(self) -> bool:
        return self._done

    def _resolve(self, result: ServeResult) -> bool:
        """Exactly-once; a second resolution is dropped and reported
        False (the race spec asserts it never happens)."""
        with self._cv:
            if self._done:
                return False
            self._result = result
            self._done = True
            self._cv.notify_all()
            return True

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        deadline = None if timeout is None else cc.monotonic() + float(timeout)
        with self._cv:
            while not self._done:
                if deadline is None:
                    self._cv.wait(timeout=60.0)
                    continue
                remaining = deadline - cc.monotonic()
                if remaining <= 0:
                    raise TimeoutError("request result not ready")
                self._cv.wait(timeout=remaining)
        return self._result


@dataclasses.dataclass
class EngineRequest(slog.Request):
    """A :class:`~paddle_tpu.observability.serving.Request` plus the
    engine-side lifecycle: future, wall deadline, accumulated tokens,
    the slot it occupies, cancellation and exactly-once bookkeeping."""

    future: Optional[ResultFuture] = None
    deadline: float = math.inf
    cancelled: bool = False
    queued: bool = False      # passed admission control (arrival counted)
    done: bool = False
    slot: int = -1
    budget: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None


class Engine:
    """Slot-based continuous-batching decode engine.

    ``backend`` supplies capacity (``backend.slots``) and the device
    work; ``queue_cap`` rejects submits past the bound (0 = unbounded);
    ``request_timeout_s`` is the default wall-clock deadline from submit
    — expiry frees the queue entry OR the decode slot at the next
    iteration boundary with ``outcome=timeout``. ``pipeline`` selects
    the overlapped dispatch/collect loop (default) vs the PR-12 serial
    loop — identical request semantics, pinned by the golden
    pipelined==blocking test. ``clock`` is injectable for tests
    (defaults to the ``cc`` seam's monotonic, so ``paddle race``
    virtualizes it automatically)."""

    def __init__(self, backend, queue_cap: int = 0,
                 request_timeout_s: float = 60.0,
                 clock: Optional[Callable[[], float]] = None,
                 idle_poll_s: float = 0.02,
                 pipeline: bool = True):
        self._backend = backend
        self.queue_cap = int(queue_cap)
        self.request_timeout_s = float(request_timeout_s)
        self.idle_poll_s = float(idle_poll_s)
        self.pipeline = bool(pipeline)
        self._clock = clock or cc.monotonic
        self._lock = cc.Lock()
        self._wake = cc.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._slots: List[Optional[EngineRequest]] = [None] * backend.slots
        # requests between queue-pop and slot placement (the prefill
        # launch runs outside the lock): cancel() must still find them
        self._admitting: List[EngineRequest] = []
        self._ladder = tuple(sorted(set(
            int(u) for u in (getattr(backend, "decode_blocks", None)
                             or (getattr(backend, "chunk", 1),))
        ))) or (1,)
        self._log = self._fresh_log()
        self._t0 = self._clock()
        self._thread = None
        self._started = False
        self._draining = False
        self._n_submitted = 0
        self._pid = os.getpid()
        self.warmup_s: Optional[float] = None

    # ----------------------------------------------------------- client

    @property
    def slots(self) -> int:
        return self._backend.slots

    @property
    def max_length(self) -> int:
        return self._backend.max_length

    def _fresh_log(self) -> slog.RequestLog:
        return slog.RequestLog(engine=ENGINE_NAME,
                               pipeline="on" if self.pipeline else "off")

    def start(self) -> "Engine":
        """Warm the backend (all compiles land BEFORE serving — the
        recompiles=0 acceptance; every ladder rung is exercised) and
        spawn the scheduler thread. ``warmup_s`` records the wall cost —
        with ``--compile_cache_dir`` a warm restart's figure drops to
        trace time (the time-to-first-token-ready satellite)."""
        with self._lock:
            if self._started:
                return self
            self._started = True
        t0 = cc.perf_counter()
        self._backend.warmup()
        self.warmup_s = cc.perf_counter() - t0
        from paddle_tpu.observability import metrics as obs

        obs.registry().gauge("serve.warmup_s").set(round(self.warmup_s, 6))
        th = cc.Thread(target=self._loop, name="serve-engine", daemon=True)
        with self._lock:
            self._thread = th
        th.start()
        return self

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               rid: Optional[str] = None,
               timeout_s: Optional[float] = None) -> ResultFuture:
        """Enqueue one request; returns its future. Rejected immediately
        (``outcome=rejected``) when draining, stopped, or past
        ``queue_cap`` — a rejection is an answer, never an exception."""
        fut = ResultFuture()
        with self._lock:
            now = self._now()
            if rid is None:
                rid = f"c{self._pid}-{self._n_submitted}"
            self._n_submitted += 1
            limit = timeout_s if timeout_s is not None else self.request_timeout_s
            req = EngineRequest(
                rid=rid, t_enqueue=now, prompt=list(prompt),
                prompt_tokens=len(prompt), max_new=max_new_tokens,
                future=fut, deadline=now + float(limit),
            )
            if self._draining or not self._started or self._thread is None:
                self._finish_locked(req, "rejected", now)
            elif self.queue_cap and len(self._queue) >= self.queue_cap:
                self._finish_locked(req, "rejected", now)
            elif max_new_tokens is not None and int(max_new_tokens) <= 0:
                # 0 is a LEGAL budget, not an unset sentinel: the answer
                # is the empty generation, no slot needed
                req.queued = True
                req.t_admit = now
                self._log.enqueued(req)
                self._log.admit(req)
                self._finish_locked(req, "ok", now)
            else:
                req.queued = True
                self._queue.append(req)
                self._log.enqueued(req)
                self._wake.notify_all()
        return fut

    def cancel(self, rid: str) -> bool:
        """Request cancellation; applied at the next iteration boundary
        (frees the queue entry or the slot, ``outcome=cancelled``).
        False when the id is unknown or already finished."""
        with self._lock:
            for req in self._queue:
                if req.rid == rid and not req.done:
                    req.cancelled = True
                    self._wake.notify_all()
                    return True
            for req in self._slots:
                if req is not None and req.rid == rid and not req.done:
                    req.cancelled = True
                    return True
            for req in self._admitting:
                if req.rid == rid and not req.done:
                    req.cancelled = True
                    return True
        return False

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: finish in-flight slots, reject the queue
        and every later submit, stop the loop. True when the scheduler
        thread exited within ``timeout``."""
        with self._lock:
            self._draining = True
            self._wake.notify_all()
            th = self._thread
        if th is None:
            return True
        th.join(timeout if timeout is not None else 600.0)
        return not th.is_alive()

    close = drain

    # -------------------------------------------------------- telemetry

    def begin_window(self) -> None:
        """Re-anchor the telemetry window (rung start). Caller must be
        quiescent — in-flight requests would straddle the anchor."""
        with self._lock:
            self._log = self._fresh_log()
            self._t0 = self._clock()

    def window_roll(self, offered_rps: float = 0.0, rung: int = 0,
                    window_s: Optional[float] = None) -> Dict[str, Any]:
        """Emit the current window's ``kind=serve_window`` rollup and
        start a fresh one; returns the record (sans envelope)."""
        with self._lock:
            now = self._now()
            log = self._log
            log.rung = int(rung)
            log.offered_rps = float(offered_rps)
            wall = max(now, 1e-9)
            host_share = max(1.0 - log.exec_s / wall, 0.0)
            rec = log.window_record(
                max(window_s if window_s is not None else now, 1e-9),
                host_share=host_share,
            )
            self._log = self._fresh_log()
            self._t0 = self._clock()
            return rec

    # -------------------------------------------------------- scheduler

    def _now(self) -> float:
        return self._clock() - self._t0

    def _finish_locked(self, req: EngineRequest, outcome: str,
                       now: float, error: Optional[str] = None) -> None:
        """Resolve one request exactly once: telemetry record + future."""
        if req.done:
            return
        req.done = True
        req.error = error
        if outcome == "ok":
            req.t_finish = now
            req.gen_tokens = len(req.tokens)
            self._log.complete(req)
        elif outcome == "rejected":
            # a drain-path rejection already counted its arrival at
            # enqueue; a submit-time one never arrived in the window
            self._log.reject(req, arrived=req.queued)
        elif outcome == "timeout":
            self._log.timeout(req, now)
        elif outcome == "cancelled":
            self._log.cancel(req, now)
        else:
            self._log.error(req, error=error or "decode failed")
        req.future._resolve(ServeResult(
            rid=req.rid, outcome=req.outcome,
            tokens=list(req.tokens), error=error,
        ))

    def _sweep_locked(self, now: float) -> None:
        """Iteration boundary policy: cancellations and wall deadlines,
        queue entries first (FIFO — the oldest expire first), then
        in-flight slots (the device row keeps decoding to its bounded
        budget and is simply overwritten by the next admission)."""
        for _ in range(len(self._queue)):
            req = self._queue.popleft()
            if req.cancelled:
                self._finish_locked(req, "cancelled", now)
            elif now > req.deadline:
                self._finish_locked(req, "timeout", now)
            else:
                self._queue.append(req)  # full rotation keeps FIFO order
        for b, req in enumerate(self._slots):
            if req is None:
                continue
            if req.cancelled:
                self._slots[b] = None
                self._finish_locked(req, "cancelled", now)
            elif now > req.deadline:
                self._slots[b] = None
                self._finish_locked(req, "timeout", now)

    def _fail_inflight_locked(self, now: float, error: str) -> None:
        for b, req in enumerate(self._slots):
            if req is not None:
                self._slots[b] = None
                self._finish_locked(req, "error", now, error=error)

    # --------------------------------------------- shared loop phases

    def _boundary(self) -> Tuple[List[int], List[EngineRequest]]:
        """One iteration boundary under the lock: sweep cancellations
        and deadlines, reject the queue when draining, pick the FIFO
        admissions for the free slots."""
        admit_slots: List[int] = []
        admit_reqs: List[EngineRequest] = []
        with self._lock:
            now = self._now()
            self._sweep_locked(now)
            if self._draining:
                while self._queue:
                    self._finish_locked(self._queue.popleft(),
                                        "rejected", now)
            free = [b for b, r in enumerate(self._slots) if r is None]
            take = min(len(free), len(self._queue))
            for j in range(take):
                admit_slots.append(free[j])
                admit_reqs.append(self._queue.popleft())
            self._admitting = admit_reqs
        return admit_slots, admit_reqs

    def _do_admit(self, admit_slots: List[int],
                  admit_reqs: List[EngineRequest]) -> bool:
        """Prefill launch outside the lock (submit() must never block
        behind device work); place the cohort on success. In pipelined
        mode the backend dispatches without syncing, so the measured
        time is enqueue cost — the prefill's device time surfaces at
        the next collect boundary (doc/serving.md). False = the cohort
        (and everything in flight) was errored; caller resets."""
        backend = self._backend
        budgets = [
            max(1, min(backend.max_length if r.max_new is None
                       else r.max_new, backend.max_length))
            for r in admit_reqs
        ]
        t0 = self._clock()
        try:
            backend.admit(admit_slots, admit_reqs, budgets)
        except Exception as e:  # noqa: BLE001 — cohort gets the evidence
            err = f"{type(e).__name__}: {e}"
            logger.error("serve admit failed: %s", err)
            with self._lock:
                now = self._now()
                for req in admit_reqs:
                    self._finish_locked(req, "error", now, error=err)
                self._admitting = []
                self._fail_inflight_locked(now, err)
            return False
        dt = self._clock() - t0
        with self._lock:
            now = self._now()
            for b, req, budget in zip(admit_slots, admit_reqs, budgets):
                req.slot = b
                req.budget = budget
                req.t_admit = now
                self._slots[b] = req
                self._log.admit(req)
            self._admitting = []
            self._log.note_exec(dt)
        return True

    def _loop(self) -> None:
        if self.pipeline:
            self._loop_pipelined()
        else:
            self._loop_blocking()

    def _safe_reset(self) -> None:
        try:
            self._backend.reset()
        except Exception as e:  # noqa: BLE001
            logger.error("serve backend reset failed: %s", e)

    def _block_inputs_locked(self) -> Tuple[int, bool]:
        """(budget cap, pressure) for :func:`pick_block`, from the
        engine's view of the slots — under pipelining this lags the
        device by the in-flight launches, which only over-runs into
        frozen micro-steps (bounded, harmless)."""
        cap = 0
        pressed = bool(self._queue)
        for req in self._slots:
            if req is None:
                continue
            left = max(req.budget - len(req.tokens), 1)
            cap = left if cap == 0 else min(cap, left)
            if req.t_first_token < 0:
                pressed = True  # a slot still owes its first token
        return (cap or self._ladder[-1]), pressed

    # ------------------------------------------------- the PR-12 loop

    def _loop_blocking(self) -> None:
        """The serial loop: boundary → admit (sync) → one blocking
        step() → apply. Kept verbatim as the pipeline A/B baseline
        (``pipeline=False`` / PADDLE_TPU_BENCH_SERVE_PIPELINE=off)."""
        backend = self._backend
        host_ema = 0.0
        step_ema = 0.0
        t_host0 = self._clock()
        while True:
            admit_slots, admit_reqs = self._boundary()
            if admit_reqs and not self._do_admit(admit_slots, admit_reqs):
                self._safe_reset()
                t_host0 = self._clock()
                continue
            with self._lock:
                occupancy = sum(1 for r in self._slots if r is not None)
                if occupancy == 0:
                    if self._draining and not self._queue:
                        break
                    if not self._queue:
                        self._wake.wait(timeout=self.idle_poll_s)
                    # idle time is not host overhead: a stale anchor
                    # here would dump the whole idle stretch into
                    # host_ema and push pick_block to the top rung
                    # exactly when a fresh request wants a fast first
                    # boundary
                    t_host0 = self._clock()
                    continue
                cap, pressed = self._block_inputs_locked()
            u = pick_block(self._ladder, cap, pressed, host_ema, step_ema)
            t0 = self._clock()
            host_ema = (1 - _EMA) * host_ema + _EMA * (t0 - t_host0)
            try:
                out = backend.step(block=u)
            except Exception as e:  # noqa: BLE001 — engine survives a bad launch
                err = f"{type(e).__name__}: {e}"
                logger.error("serve decode launch failed: %s", err)
                with self._lock:
                    self._fail_inflight_locked(self._now(), err)
                self._safe_reset()
                t_host0 = self._clock()
                continue
            dt = self._clock() - t0
            t_host0 = self._clock()
            step_ema = (1 - _EMA) * step_ema + _EMA * (dt / max(u, 1))
            with self._lock:
                self._apply_step_locked(out, dt, occupancy)

    # ----------------------------------------------- the pipelined loop

    def _loop_pipelined(self) -> None:
        """Boundary and apply work overlap the in-flight launch: each
        iteration dispatches launch N+1 BEFORE collecting launch N, so
        the device never waits for host scheduling and the host never
        waits for a launch it has nothing to say about. ``inflight``
        holds (cohort snapshot, dispatch time) per launch — loop-local:
        the only cross-thread state stays the lock-guarded slots/queue."""
        backend = self._backend
        inflight: collections.deque = collections.deque()
        host_ema = 0.0
        step_ema = 0.0
        union_end = self._clock()   # union of dispatch->collect spans
        t_host0 = self._clock()
        while True:
            admit_slots, admit_reqs = self._boundary()
            if admit_reqs and not self._do_admit(admit_slots, admit_reqs):
                inflight = self._abort_inflight(inflight)
                # failure handling (logging, reset, device realloc) is
                # not host overhead — same stale-anchor rule as idle
                t_host0 = self._clock()
                continue
            # --- dispatch launch N+1 (device-ordered after the prefill)
            with self._lock:
                occupancy = sum(1 for r in self._slots if r is not None)
                cohort = [(b, r) for b, r in enumerate(self._slots)
                          if r is not None]
                cap, pressed = self._block_inputs_locked()
                # speculate only when it can pay: if every live slot's
                # remaining budget is already covered by in-flight
                # micro-steps, launch N+1 would run all-frozen rows —
                # pure waste (the short-budget regime) — so collect
                # first and let the boundary see the finishes. EOS
                # finishes stay unknowable ahead of time; budgets are
                # the bound we do know.
                pending_steps = sum(u for _c, u, _t, _lg in inflight)
                live_next = any(
                    r.budget - len(r.tokens) - pending_steps > 0
                    for _b, r in cohort
                )
            dispatched = False
            if occupancy and (live_next or not inflight):
                dispatched = True
                u = pick_block(self._ladder, cap, pressed, host_ema, step_ema)
                t_disp = self._clock()
                host_ema = (1 - _EMA) * host_ema + _EMA * (t_disp - t_host0)
                try:
                    backend.dispatch(block=u)
                except Exception as e:  # noqa: BLE001
                    err = f"{type(e).__name__}: {e}"
                    logger.error("serve decode dispatch failed: %s", err)
                    with self._lock:
                        self._fail_inflight_locked(self._now(), err)
                    inflight = self._abort_inflight(inflight, err)
                    t_host0 = self._clock()
                    continue
                with self._lock:
                    # the launch belongs to the CURRENT telemetry
                    # window: a window_roll between this dispatch and
                    # its collect closes that window, and the stray
                    # launch must not leak its exec/overlap into the
                    # next one (begin_window's quiescence note)
                    inflight.append((cohort, u, t_disp, self._log))
                    self._log.note_dispatch(len(inflight))
            # --- collect launch N while N+1 runs; collect immediately
            # when nothing was dispatched ahead (tail / no-speculation)
            if inflight and (len(inflight) > 1 or not dispatched):
                cohort, u, t_disp, disp_log = inflight[0]
                t_wait = self._clock()
                try:
                    out = backend.collect()
                except Exception as e:  # noqa: BLE001 — fault surfaces HERE
                    err = f"{type(e).__name__}: {e}"
                    logger.error("serve decode launch failed: %s", err)
                    with self._lock:
                        self._fail_inflight_locked(self._now(), err)
                    inflight = self._abort_inflight(inflight, err)
                    t_host0 = self._clock()
                    continue
                inflight.popleft()
                t_done = self._clock()
                # exec side of host_share: the UNION of dispatch->done
                # spans — overlapping spans must not double-count
                service = max(t_done - max(t_disp, union_end), 0.0)
                union_end = max(union_end, t_done)
                # the ladder's device estimate uses the DE-OVERLAPPED
                # span: the raw dispatch->done time of launch N+1 also
                # contains its wait behind launch N, which would read
                # as ~2x the true per-micro-step cost under steady
                # pipelining and skew pick_block a rung low
                step_ema = (1 - _EMA) * step_ema + _EMA * (service / max(u, 1))
                with self._lock:
                    stale = disp_log is not self._log
                    if not stale:
                        self._log.note_overlap(max(t_wait - t_disp, 0.0))
                    self._log.note_dispatch(len(inflight))
                    # tokens/finishes always apply (requests legally
                    # span windows); the launch/overlap/exec accounting
                    # is skipped when the dispatching window has rolled
                    # closed — its record is already emitted
                    self._apply_step_locked(out, service, len(cohort),
                                            cohort=cohort,
                                            count_launch=not stale)
                t_host0 = self._clock()
                continue
            # --- idle / exit
            with self._lock:
                if not inflight and not any(
                    r is not None for r in self._slots
                ):
                    if self._draining and not self._queue:
                        break
                    if not self._queue:
                        self._wake.wait(timeout=self.idle_poll_s)
            # anchored AFTER any idle wait: idle seconds are not host
            # overhead and must not inflate the ladder's host_ema
            t_host0 = self._clock()

    def _abort_inflight(self, inflight: collections.deque,
                        error: str = "decode failed") -> collections.deque:
        """A faulted launch takes every in-flight cohort with it: their
        results are unrecoverable once the device state resets. Each
        snapshot request resolves exactly once (`done` guards repeats
        across overlapping snapshots and the slot sweep)."""
        with self._lock:
            now = self._now()
            for cohort, _u, _t, _lg in inflight:
                for _b, req in cohort:
                    self._finish_locked(req, "error", now, error=error)
            self._log.note_dispatch(0)
        self._safe_reset()
        return collections.deque()

    def _apply_step_locked(self, out, service_s: float, occupancy: int,
                           cohort=None, count_launch: bool = True) -> None:
        """Fold one launch's readback into the request lifecycles.
        ``cohort`` (pipelined) is the slot snapshot taken at dispatch:
        tokens belong to THOSE requests — a slot re-assigned between
        dispatch and collect must not leak a previous occupant's tokens
        to the new one (the snapshot discipline); evicted (done)
        requests just skip."""
        now = self._now()
        tokens, live, finished = out.tokens, out.live, out.finished
        u = tokens.shape[0]
        rows = (cohort if cohort is not None
                else enumerate(self._slots))
        for b, req in rows:
            if req is None or req.done:
                continue
            emitted = [int(tokens[i, b]) for i in range(u) if bool(live[i, b])]
            if emitted:
                if req.t_first_token < 0:
                    # REAL wall-clock TTFT: this readback is the moment
                    # the first token left the device — mid-sequence,
                    # not at finish (and, pipelined, at the COLLECT
                    # boundary: the earliest the host can know)
                    req.t_first_token = now
                req.tokens.extend(emitted)
            if bool(finished[b]) and self._slots[b] is req:
                self._slots[b] = None
                self._finish_locked(req, "ok", now)
        if count_launch:
            self._log.launch(len(self._queue), occupancy, service_s)


# ------------------------------------------------------------- driver


def drive_rung(engine: Engine, requests: Sequence[slog.Request], *,
               rate_rps: float, rung: int = 0,
               result_timeout_s: float = 300.0) -> Dict[str, Any]:
    """Open-loop wall-clock driver for one offered-load rung against a
    live engine — the continuous counterpart of the PR-8 virtual-clock
    ``run_rung``, fed the SAME :func:`~paddle_tpu.observability.serving.
    schedule_requests` workload. Submits each request at its scheduled
    arrival offset (sleeping the gaps; a late submit stays late — open
    loop never hides coordinated omission), waits for every future, and
    rolls the window."""
    engine.begin_window()
    t0 = cc.monotonic()
    futures = []
    for req in requests:
        delay = req.t_enqueue - (cc.monotonic() - t0)
        if delay > 0:
            cc.sleep(delay)
        futures.append(engine.submit(
            req.prompt or [], max_new_tokens=req.max_new, rid=req.rid,
        ))
    for fut in futures:
        fut.result(timeout=result_timeout_s)
    elapsed = cc.monotonic() - t0
    window_s = max(elapsed, requests[-1].t_enqueue if requests else 0.0)
    return engine.window_roll(offered_rps=rate_rps, rung=rung,
                              window_s=window_s)
