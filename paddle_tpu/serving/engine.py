"""The continuous-batching scheduler — slot admission/eviction at every
iteration boundary, with the device/host pipeline kept full.

Run-to-completion batching (the PR-8 static driver, and every
``SequenceGenerator.generate`` call) holds a whole cohort until its
LONGEST sequence finishes: with mixed output lengths most slots spend
most steps finished-but-occupied. Orca (OSDI '22) showed iteration-level
scheduling — re-batching between decode steps — recovers that capacity.
This engine is that loop:

    while serving:
        evict   — finished (EOS / budget / max_length), cancelled, or
                  wall-deadline-expired slots free at the boundary
        admit   — queued requests (strict FIFO) prefill into free slots
        step    — ONE jitted launch advances every slot

The PR-12 loop ran those phases strictly serially: every decode launch
was followed by a blocking readback, and every admission blocked on the
prefill — so the device idled during host scheduling and the host idled
during every launch. The **pipelined** loop (default) splits the
backend step into ``dispatch()`` (enqueue launch N+1; the backend
starts ``copy_to_host_async`` on launch N's outputs before the first
collect — the PR-5 snapshot discipline) and ``collect()`` (gather N's
results), and schedules/evicts/admits on iteration N's results WHILE
the device runs N+1. Consequences, all deliberate:

- admissions decided from launch N's results prefill between launches
  N+1 and N+2 — a one-iteration admission lag (doc/serving.md
  "Pipelined decode");
- each in-flight launch carries a SNAPSHOT of its slot cohort; collect
  applies tokens to that snapshot (a slot evicted mid-flight is simply
  skipped — its device row self-terminates at its bounded budget);
- deadlines, TTFT, and exec attribution are all judged at collect
  boundaries — the only place results exist under overlap;
- a faulted in-flight launch surfaces at collect: it errors its cohort
  (and every other in-flight request), the device state resets, and the
  engine keeps serving — exactly the blocking loop's fault contract.

``pipeline=False`` keeps the PR-12 serial loop (the A/B baseline:
``PADDLE_TPU_BENCH_SERVE_PIPELINE=off``). Both loops share the
boundary/admission/apply code and the adaptive decode-block ladder
(:func:`pick_block`), so the pipeline is the ONLY delta in that A/B.

Everything here is jax-free and thread-safe strictly through the
``utils/concurrency`` seam (``cc``): the scheduler runs on one
``cc.Thread``; ``submit``/``cancel``/``drain`` are the only cross-
thread entry points and every shared field is guarded by ``self._lock``
— the ``paddle race`` spec (tests/race_specs/spec_serve_engine.py)
explores exactly these interleavings, pipelined and blocking. Device
work hides behind the backend seam (backend.py): ``FakeBackend`` for
tests, ``JaxDecodeBackend`` for TPUs.

Telemetry is the PR-8 contract unchanged — per-request ``kind=request``
records (REAL wall-clock TTFT: the first token's readback timestamp,
mid-sequence) and ``kind=serve_window`` rollups with
``engine="continuous"`` — plus the overlap plane: ``serve.
dispatch_depth`` (gauge), ``serve.overlap_s`` (counter), and a window
``host_share`` whose exec side is the UNION of dispatch→collect spans,
so overlap shows up as host_share going to ~0 instead of exec_s
double-counting past the wall clock.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.observability import serving as slog
from paddle_tpu.resilience import faultinject
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

ENGINE_NAME = "continuous"

# terminal request outcomes (race-spec invariant: every submitted
# request's future resolves exactly once with one of these). `shed` is
# the overload-defense answer (doc/resilience.md "Serving resilience"):
# a POLICY refusal — brownout pressure, open breaker, or an admission
# estimate proving the deadline unmeetable — delivered within one
# collect boundary, with a retry-after hint where one exists; distinct
# from `rejected` (structural: queue cap, draining, not started)
OUTCOMES = ("ok", "rejected", "timeout", "cancelled", "error", "shed")

# valid --serve_shed_policy values: "off" = the PR-13 behavior
# (overload resolves through queue caps and timeouts only); "deadline"
# adds deadline-aware admission shedding; "brownout" additionally caps
# output budgets and sheds new arrivals under sustained queue pressure
SHED_POLICIES = ("off", "deadline", "brownout")

# queue-pressure EMA (queue depth / slots) thresholds for entering and
# leaving brownout — hysteresis, so one bursty boundary can't flap the
# degraded mode on and off
BROWNOUT_ON = 1.0
BROWNOUT_OFF = 0.5

# engaged brownout caps every admission's token budget to this share of
# max_length (floor 1): shorter answers for everyone beats no answers
# for the tail — the "degrade, don't die" half of the shed policy
BROWNOUT_BUDGET_SHARE = 0.25

# the shed retry-after hint while the prefill/step EMAs are still
# unmeasured (a burst before the first collect boundary): a fixed
# conservative backoff, never the idle-poll interval
UNMEASURED_RETRY_S = 1.0

# a launch whose measured host-side cost exceeds this share of its
# device time is dispatch-dominated — the ladder steps up a rung
BLOCK_OVERHEAD_SHARE = 0.5

# EMA smoothing for the ladder's host/device time estimates
_EMA = 0.3

# --- self-speculative decode policy (doc/serving.md "Speculative
# decode"). Acceptance-rate EMAs pick the draft length (the spec
# ladder's rung) the way pick_block picks decode blocks; collapse falls
# back to plain decode with ZERO recompiles (the verify launch's traced
# K bound never changes signature).
SPEC_MIN_SAMPLES = 4    # verify collects before the EMA is trusted
SPEC_EMA_OFF = 0.2      # global EMA below this: plain decode (fallback)
SPEC_EMA_FULL = 0.75    # global EMA at/above this: the top spec rung
SPEC_REQ_OFF = 0.15     # per-request EMA below this: stop proposing
                        # for that slot (it rides launches plain)
SPEC_REPROBE = 64       # plain launches in fallback before the EMAs
                        # reset and the bottom rung probes again — a
                        # workload shift can re-earn its drafts


def pick_spec_k(ladder: Sequence[int], ema: float, samples: int) -> int:
    """The adaptive speculation policy: how many draft tokens to propose
    per slot for the next verify launch — 0 means plain decode. Mirrors
    :func:`pick_block`'s shape: unmeasured probes the bottom rung,
    a collapsed acceptance EMA turns speculation off, and in between the
    EMA interpolates across the pre-warmed ladder."""
    if not ladder:
        return 0
    if samples < SPEC_MIN_SAMPLES:
        return int(ladder[0])
    if ema < SPEC_EMA_OFF:
        return 0
    if ema >= SPEC_EMA_FULL or len(ladder) == 1:
        return int(ladder[-1])
    frac = (ema - SPEC_EMA_OFF) / (SPEC_EMA_FULL - SPEC_EMA_OFF)
    return int(ladder[min(int(frac * len(ladder)), len(ladder) - 1)])


def pick_block(ladder: Sequence[int], cap: int, pressed: bool,
               host_s: float, step_s: float) -> int:
    """The adaptive decode-block policy: how many greedy micro-steps the
    next launch should run (doc/serving.md "The decode-block ladder").

    ``ladder`` — the pre-warmed rungs, ascending; ``cap`` — the smallest
    remaining token budget among live slots (running past it buys only
    frozen micro-steps); ``pressed`` — queue/TTFT pressure: requests are
    waiting to be admitted, or a live slot has not yet delivered its
    first token (both only resolve at a collect boundary, so boundaries
    should come sooner); ``host_s`` — measured host+dispatch seconds per
    iteration (EMA); ``step_s`` — measured device seconds per micro-step
    (EMA).

    Under pressure: the SMALLEST rung whose device time still keeps the
    measured launch overhead under :data:`BLOCK_OVERHEAD_SHARE` — pay
    for boundaries only what they cost. No pressure: the largest rung
    the budget cap allows — boundaries buy nothing, overhead
    amortization is free. With no measurements yet (warmup), pressure
    picks the bottom rung and quiet picks the top."""
    if not ladder:
        return 1
    if len(ladder) == 1:
        return int(ladder[0])
    cands = [u for u in ladder if u <= max(int(cap), int(ladder[0]))]
    if not cands:
        cands = [int(ladder[0])]
    if not pressed:
        return int(cands[-1])
    if host_s > 0 and step_s > 0:
        for u in cands:
            if host_s <= BLOCK_OVERHEAD_SHARE * u * step_s:
                return int(u)
        return int(cands[-1])
    return int(cands[0])


@dataclasses.dataclass
class ServeResult:
    """What a resolved :class:`ResultFuture` carries.

    ``retry_after_s`` rides ``outcome=shed`` answers when the engine
    can estimate when capacity returns (breaker cooldown remaining,
    queue-drain ETA); None means "don't bother retrying" (a deadline
    the admission estimate proved unmeetable)."""

    rid: str
    outcome: str
    tokens: List[int]
    error: Optional[str] = None
    retry_after_s: Optional[float] = None


class ResultFuture:
    """A one-shot, condition-backed result future (``cc`` seam)."""

    def __init__(self) -> None:
        self._cv = cc.Condition()
        self._done = False
        self._result: Optional[ServeResult] = None

    def done(self) -> bool:
        return self._done

    def _resolve(self, result: ServeResult) -> bool:
        """Exactly-once; a second resolution is dropped and reported
        False (the race spec asserts it never happens)."""
        with self._cv:
            if self._done:
                return False
            self._result = result
            self._done = True
            self._cv.notify_all()
            return True

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        deadline = None if timeout is None else cc.monotonic() + float(timeout)
        with self._cv:
            while not self._done:
                if deadline is None:
                    self._cv.wait(timeout=60.0)
                    continue
                remaining = deadline - cc.monotonic()
                if remaining <= 0:
                    raise TimeoutError("request result not ready")
                self._cv.wait(timeout=remaining)
        return self._result


@dataclasses.dataclass
class EngineRequest(slog.Request):
    """A :class:`~paddle_tpu.observability.serving.Request` plus the
    engine-side lifecycle: future, wall deadline, accumulated tokens,
    the slot it occupies, cancellation and exactly-once bookkeeping."""

    future: Optional[ResultFuture] = None
    deadline: float = math.inf
    cancelled: bool = False
    queued: bool = False      # passed admission control (arrival counted)
    done: bool = False
    slot: int = -1
    budget: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    # absolute monotonic enqueue stamp for span emission: the inherited
    # t_enqueue is WINDOW-relative (re-anchored at every window_roll),
    # but a span's t0 must live in the stream timebase, which
    # obs.rel_time derives from absolute monotonic readings
    t_enqueue_abs: float = 0.0
    # --- self-speculation bookkeeping (collect-boundary only, under
    # the engine lock): per-request acceptance EMA and the per-slot
    # fallback latch — a request whose drafts keep missing stops
    # proposing (it still rides verify launches as a plain step)
    spec_ema: float = 0.0
    spec_samples: int = 0
    spec_off: bool = False


class Engine:
    """Slot-based continuous-batching decode engine.

    ``backend`` supplies capacity (``backend.slots``) and the device
    work; ``queue_cap`` rejects submits past the bound (0 = unbounded);
    ``request_timeout_s`` is the default wall-clock deadline from submit
    — expiry frees the queue entry OR the decode slot at the next
    iteration boundary with ``outcome=timeout``. ``pipeline`` selects
    the overlapped dispatch/collect loop (default) vs the PR-12 serial
    loop — identical request semantics, pinned by the golden
    pipelined==blocking test. ``clock`` is injectable for tests
    (defaults to the ``cc`` seam's monotonic, so ``paddle race``
    virtualizes it automatically)."""

    def __init__(self, backend, queue_cap: int = 0,
                 request_timeout_s: float = 60.0,
                 clock: Optional[Callable[[], float]] = None,
                 idle_poll_s: float = 0.02,
                 pipeline: bool = True,
                 shed_policy: str = "off",
                 breaker=None,
                 hangwatch=None,
                 on_oom: Optional[Callable[[BaseException], None]] = None,
                 replica: str = ""):
        if shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"unknown shed policy {shed_policy!r}: expected one of "
                f"{SHED_POLICIES}"
            )
        self._backend = backend
        self.queue_cap = int(queue_cap)
        self.request_timeout_s = float(request_timeout_s)
        self.idle_poll_s = float(idle_poll_s)
        self.pipeline = bool(pipeline)
        # fleet identity: stamped on every request/serve_window record
        # this engine emits, so N in-process replicas (bench --replicas)
        # stay distinguishable in one telemetry stream
        self.replica = str(replica)
        self._clock = clock or cc.monotonic
        self._lock = cc.Lock()
        self._wake = cc.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._slots: List[Optional[EngineRequest]] = [None] * backend.slots
        # requests between queue-pop and slot placement (the prefill
        # launch runs outside the lock): cancel() must still find them
        self._admitting: List[EngineRequest] = []
        self._ladder = tuple(sorted(set(
            int(u) for u in (getattr(backend, "decode_blocks", None)
                             or (getattr(backend, "chunk", 1),))
        ))) or (1,)
        # --- self-speculative decode (doc/serving.md "Speculative
        # decode"): the backend advertises its pre-warmed draft-length
        # ladder; empty/absent = speculation off and every spec field
        # below stays inert. The draft table and its EMAs are touched
        # ONLY under self._lock (the race spec's draft-table phases).
        self._spec_ladder = tuple(
            int(k) for k in (getattr(backend, "spec_blocks", ()) or ()))
        self._draft = None
        if self._spec_ladder:
            from paddle_tpu.serving.draft import DraftTable

            self._draft = DraftTable()
        self._spec_ema = 0.0
        self._spec_samples = 0
        self._spec_cooloff = 0
        self.slot_dtype = str(getattr(backend, "slot_dtype", "") or "")
        self._log = self._fresh_log()
        self._t0 = self._clock()
        self._thread = None
        self._started = False
        self._draining = False
        self._n_submitted = 0
        # distributed tracing (doc/observability.md "Distributed
        # tracing"): latched under the lock at the first traced submit;
        # until then the engine emits zero kind=span records, so
        # untraced single-process runs keep their telemetry unchanged
        self._tracing = False
        self._pid = os.getpid()
        self.warmup_s: Optional[float] = None
        # --- resilience plane (doc/resilience.md "Serving resilience")
        self.shed_policy = str(shed_policy)
        # the launch-failure CircuitBreaker (serving/resilience.py) —
        # consulted and mutated ONLY with self._lock held, so it needs
        # no lock of its own
        self._breaker = breaker
        self._hangwatch = hangwatch     # ServeHangWatch or None
        self._on_oom = on_oom           # `paddle serve`: pre-mortem + exit 20
        # measured EMAs the shed policy estimates from — mirrored from
        # the scheduler's loop-locals under the lock at every collect
        # boundary (pick_block keeps reading the hot locals): device
        # seconds per decode micro-step, host+dispatch seconds per
        # iteration, prefill seconds per admission cohort
        self._step_ema = 0.0
        self._host_ema = 0.0
        self._prefill_ema = 0.0
        # queue-pressure EMA (depth / slots) + brownout engagement
        self._pressure_ema = 0.0
        self._brownout = False
        # lifetime outcome totals + liveness timestamps for status()
        self._totals: Dict[str, int] = {o: 0 for o in OUTCOMES}
        self._last_collect = self._clock()   # last collect/step result
        self._last_loop = self._clock()      # last scheduler-loop beat
        # --- hot weight reload (doc/serving.md "Serving fleet"): a
        # pending (params, tag) pair set by request_reload() and
        # applied by the scheduler at the NEXT iteration boundary —
        # dispatched launches snapshot their arguments, so swapping
        # between boundaries never tears an in-flight decode
        self._pending_reload: Optional[Tuple[Any, str]] = None
        self._reloads = 0
        self._reload_tag = ""

    # ----------------------------------------------------------- client

    @property
    def slots(self) -> int:
        return self._backend.slots

    @property
    def max_length(self) -> int:
        return self._backend.max_length

    def _fresh_log(self) -> slog.RequestLog:
        return slog.RequestLog(engine=ENGINE_NAME,
                               pipeline="on" if self.pipeline else "off",
                               replica=self.replica,
                               spec=(",".join(str(k) for k in
                                              self._spec_ladder)
                                     if self._spec_ladder else
                                     ("off" if hasattr(self._backend,
                                                       "spec_blocks")
                                      else None)),
                               slot_dtype=self.slot_dtype or None)

    def seed_draft(self, seqs: Sequence[Sequence[int]]) -> int:
        """Warm the speculation draft table from committed token
        sequences — ``bench.py serve`` reuses the calibration's warmup
        launches' outputs here, so spec-on first-rung goodput isn't
        penalized by draft-table cold start (those launches already ran
        with ``backend.serving`` off and stay out of the rung
        telemetry). Returns how many sequences were folded in; a no-op
        (0) when speculation is off."""
        if self._draft is None:
            return 0
        n = 0
        with self._lock:
            for toks in seqs:
                toks = list(toks or ())
                if toks:
                    self._draft.observe(toks)
                    n += 1
        return n

    def start(self) -> "Engine":
        """Warm the backend (all compiles land BEFORE serving — the
        recompiles=0 acceptance; every ladder rung is exercised) and
        spawn the scheduler thread. ``warmup_s`` records the wall cost —
        with ``--compile_cache_dir`` a warm restart's figure drops to
        trace time (the time-to-first-token-ready satellite)."""
        with self._lock:
            if self._started:
                return self
            self._started = True
        t0 = cc.perf_counter()
        self._backend.warmup()
        self.warmup_s = cc.perf_counter() - t0
        from paddle_tpu.observability import metrics as obs

        obs.registry().gauge("serve.warmup_s").set(round(self.warmup_s, 6))
        hw = self._hangwatch
        if hw is not None:
            # started AFTER warmup: compile time is startup, not a hang
            hw.attach(self)
            hw.start()
        th = cc.Thread(target=self._loop, name="serve-engine", daemon=True)
        with self._lock:
            self._thread = th
        th.start()
        return self

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               rid: Optional[str] = None,
               timeout_s: Optional[float] = None,
               replay: bool = False,
               trace: str = "") -> ResultFuture:
        """Enqueue one request; returns its future. Rejected immediately
        (``outcome=rejected``) when draining, stopped, or past
        ``queue_cap`` — a rejection is an answer, never an exception.
        ``replay=True`` re-offers a durably journaled backlog after a
        restart: arrival control (``queue_cap``, brownout arrival shed)
        governs new arrivals, not the already-accepted queue.
        ``trace`` is the opaque distributed-tracing join key: when set,
        the request's record carries it as ``trace_id`` and the engine
        emits ``kind=span`` hop records for it."""
        fut = ResultFuture()
        with self._lock:
            now = self._now()
            if rid is None:
                rid = f"c{self._pid}-{self._n_submitted}"
            self._n_submitted += 1
            limit = timeout_s if timeout_s is not None else self.request_timeout_s
            req = EngineRequest(
                rid=rid, t_enqueue=now, prompt=list(prompt),
                prompt_tokens=len(prompt), max_new=max_new_tokens,
                future=fut, deadline=now + float(limit),
                trace=str(trace or ""), t_enqueue_abs=now + self._t0,
            )
            if req.trace:
                self._tracing = True
            if self._draining or not self._started or self._thread is None:
                self._finish_locked(req, "rejected", now)
            elif max_new_tokens is not None and int(max_new_tokens) <= 0:
                # 0 is a LEGAL budget, not an unset sentinel: the answer
                # is the empty generation, no slot needed (and no device
                # — an open breaker doesn't stop it either)
                req.queued = True
                req.t_admit = now
                self._log.enqueued(req)
                self._log.admit(req)
                self._finish_locked(req, "ok", now)
            elif self._breaker is not None and not self._breaker.allow_submit():
                # reject-fast while the launch-failure breaker cools:
                # queueing behind a faulting device only converts this
                # request into a slower error/timeout
                if req.trace:
                    self._span_locked("engine.breaker_reject",
                                      now + self._t0, 0.0,
                                      trace=req.trace, rid=req.rid)
                self._finish_locked(
                    req, "shed", now,
                    retry_after=self._breaker.retry_after_s(),
                )
            elif (not replay and self.queue_cap
                  and len(self._queue) >= self.queue_cap):
                self._finish_locked(req, "rejected", now)
            elif (not replay and self._brownout
                  and len(self._queue) >= max(self.slots, 1)):
                # engaged brownout sheds arrivals past one full slot
                # wave: the queue stays bounded by policy, and the
                # client gets a drain-ETA hint instead of a timeout
                self._finish_locked(
                    req, "shed", now, retry_after=self._drain_eta_locked(),
                )
            else:
                req.queued = True
                self._queue.append(req)
                self._log.enqueued(req)
                self._wake.notify_all()
        return fut

    def cancel(self, rid: str) -> bool:
        """Request cancellation; applied at the next iteration boundary
        (frees the queue entry or the slot, ``outcome=cancelled``).
        False when the id is unknown or already finished."""
        with self._lock:
            for req in self._queue:
                if req.rid == rid and not req.done:
                    req.cancelled = True
                    self._wake.notify_all()
                    return True
            for req in self._slots:
                if req is not None and req.rid == rid and not req.done:
                    req.cancelled = True
                    return True
            for req in self._admitting:
                if req.rid == rid and not req.done:
                    req.cancelled = True
                    return True
        return False

    def request_reload(self, params, tag: str = "") -> None:
        """Stage a hot weight swap: the scheduler applies ``params`` via
        ``backend.reload`` at the next iteration boundary, so requests
        admitted before the swap finish on the OLD weights (their
        dispatched launches already snapshotted them) and everything
        after decodes on the new ones — nothing is dropped, nothing is
        stranded. A second call before the boundary supersedes the
        first (only the newest checkpoint matters)."""
        with self._lock:
            self._pending_reload = (params, str(tag))
            self._wake.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: finish in-flight slots, reject the queue
        and every later submit, stop the loop. True when the scheduler
        thread exited within ``timeout``."""
        with self._lock:
            self._draining = True
            self._wake.notify_all()
            th = self._thread
        if th is None:
            return True
        th.join(timeout if timeout is not None else 600.0)
        return not th.is_alive()

    close = drain

    # -------------------------------------------------------- telemetry

    def begin_window(self) -> None:
        """Re-anchor the telemetry window (rung start). Caller must be
        quiescent — in-flight requests would straddle the anchor."""
        with self._lock:
            self._log = self._fresh_log()
            self._t0 = self._clock()

    def window_roll(self, offered_rps: float = 0.0, rung: int = 0,
                    window_s: Optional[float] = None) -> Dict[str, Any]:
        """Emit the current window's ``kind=serve_window`` rollup and
        start a fresh one; returns the record (sans envelope)."""
        with self._lock:
            now = self._now()
            log = self._log
            log.rung = int(rung)
            log.offered_rps = float(offered_rps)
            wall = max(now, 1e-9)
            host_share = max(1.0 - log.exec_s / wall, 0.0)
            rec = log.window_record(
                max(window_s if window_s is not None else now, 1e-9),
                host_share=host_share,
            )
            self._log = self._fresh_log()
            self._t0 = self._clock()
            return rec

    # -------------------------------------------------------- scheduler

    def _now(self) -> float:
        return self._clock() - self._t0

    def _span_locked(self, name: str, t0_abs: float, dur_s: float,
                     **fields: Any) -> None:
        """One ``kind=span`` hop record (doc/observability.md
        "Distributed tracing"). ``t0_abs`` is an absolute monotonic
        reading from ``self._clock`` — mapped into the stream timebase
        here, because request stamps like ``t_enqueue`` are
        window-relative and re-anchor at every roll. Caller holds
        ``self._lock`` (telemetry under the engine lock follows the
        ``_note_reload`` precedent); a no-op until the first traced
        submit, so untraced runs emit nothing."""
        if not self._tracing:
            return
        from paddle_tpu.observability import metrics as obs

        obs.emit("span", name=name, t0=obs.rel_time(t0_abs),
                 dur_s=round(max(float(dur_s), 0.0), 6),
                 engine=ENGINE_NAME,
                 **({"replica": self.replica} if self.replica else {}),
                 **fields)

    def _finish_locked(self, req: EngineRequest, outcome: str,
                       now: float, error: Optional[str] = None,
                       retry_after: Optional[float] = None) -> None:
        """Resolve one request exactly once: telemetry record + future."""
        if req.done:
            return
        req.done = True
        req.error = error
        if outcome == "ok":
            req.t_finish = now
            req.gen_tokens = len(req.tokens)
            self._log.complete(req)
        elif outcome == "rejected":
            # a drain-path rejection already counted its arrival at
            # enqueue; a submit-time one never arrived in the window
            self._log.reject(req, arrived=req.queued)
        elif outcome == "timeout":
            self._log.timeout(req, now)
        elif outcome == "cancelled":
            self._log.cancel(req, now)
        elif outcome == "shed":
            if req.trace:
                # interference instant: the deadline/brownout/breaker
                # shed that ended this trace early shows up in its
                # timeline, not just in the aggregate counters
                self._span_locked("engine.shed", now + self._t0, 0.0,
                                  trace=req.trace, rid=req.rid)
            self._log.shed(req, now, arrived=req.queued,
                           retry_after_s=retry_after)
        else:
            self._log.error(req, error=error or "decode failed")
        self._totals[req.outcome] = self._totals.get(req.outcome, 0) + 1
        req.future._resolve(ServeResult(
            rid=req.rid, outcome=req.outcome,
            tokens=list(req.tokens), error=error,
            retry_after_s=retry_after,
        ))

    def _sweep_locked(self, now: float) -> None:
        """Iteration boundary policy: cancellations and wall deadlines,
        queue entries first (FIFO — the oldest expire first), then
        in-flight slots (the device row keeps decoding to its bounded
        budget and is simply overwritten by the next admission)."""
        for _ in range(len(self._queue)):
            req = self._queue.popleft()
            if req.cancelled:
                self._finish_locked(req, "cancelled", now)
            elif now > req.deadline:
                self._finish_locked(req, "timeout", now)
            else:
                self._queue.append(req)  # full rotation keeps FIFO order
        for b, req in enumerate(self._slots):
            if req is None:
                continue
            if req.cancelled:
                self._slots[b] = None
                self._finish_locked(req, "cancelled", now)
            elif now > req.deadline:
                self._slots[b] = None
                self._finish_locked(req, "timeout", now)

    def _fail_inflight_locked(self, now: float, error: str) -> None:
        for b, req in enumerate(self._slots):
            if req is not None:
                self._slots[b] = None
                self._finish_locked(req, "error", now, error=error)

    # ------------------------------------------------ resilience plane

    def _note_launch_fault_locked(self) -> None:
        """One failed launch toward the circuit breaker; counts the
        window's breaker_open when this fault tripped it."""
        if self._breaker is not None and self._breaker.record_fault():
            self._log.note_breaker_open()
            logger.error(
                "serve launch-failure breaker OPEN after %d consecutive "
                "fault(s): shedding submits for %.1fs, no cohorts "
                "launched until the half-open probe",
                self._breaker.threshold, self._breaker.cooldown_s,
            )

    def _chaos_boundary(self) -> None:
        """The serve-tier chaos sites, one hit per collect boundary —
        the serving twins of trainer.{crash,stall,oom} (`paddle faults`;
        doc/resilience.md "Serving resilience"). Raise-action faults
        deliberately fire INSIDE the loops' launch try-blocks, so they
        travel the same error/breaker/OOM paths a real device fault
        would."""
        faultinject.fault_point("serve.crash")
        faultinject.fault_point("serve.stall")
        try:
            faultinject.fault_point("serve.oom")
        except faultinject.FaultInjected as e:
            from paddle_tpu.observability.memory import SyntheticOomError

            # the canonical RESOURCE_EXHAUSTED marker, so is_oom_error
            # (and the pre-mortem path) classify it like the real thing
            raise SyntheticOomError("serve decode launch") from e
        faultinject.fault_point("serve.launch_fault")

    def _oom_check(self, e: BaseException) -> bool:
        """RESOURCE_EXHAUSTED escaping a serve launch is deterministic
        poison — the same slots at the same signature OOM again, so
        "error the cohort and keep serving" would burn every future
        cohort. With an ``on_oom`` handler installed (`paddle serve`:
        trigger_oom_report → exit EXIT_OOM=20) the engine answers
        everything it holds with outcome=error, stops, and hands the
        error over; without one (library embeddings, unit tests) the
        generic fault path stands. True = OOM handled, loop must exit."""
        if self._on_oom is None:
            return False
        from paddle_tpu.observability.memory import is_oom_error

        if not is_oom_error(e):
            return False
        err = f"oom: {type(e).__name__}: {e}"
        logger.error("serve launch OOM — answering %s and stopping: %s",
                     "everything queued/in-flight", err)
        with self._lock:
            now = self._now()
            self._fail_inflight_locked(now, err)
            for req in self._admitting:
                self._finish_locked(req, "error", now, error=err)
            self._admitting = []
            while self._queue:
                self._finish_locked(self._queue.popleft(), "error", now,
                                    error=err)
            self._draining = True
            self._wake.notify_all()
        handler = self._on_oom
        try:
            handler(e)  # `paddle serve`: oom_report.json + os._exit(20)
        except Exception as he:  # noqa: BLE001 — never mask the OOM
            logger.error("serve on_oom handler failed: %s", he)
        return True

    def _ping(self) -> None:
        """Scheduler-loop liveness beat for the hangwatch, called once
        per loop iteration (idle polls included — an idle server is
        alive, not hung). The status file's loop-age stamp rides in
        :meth:`_boundary`'s existing critical section instead of taking
        the engine lock here a second time per iteration."""
        hw = self._hangwatch
        if hw is not None:
            hw.ping()

    def _note_collect_locked(self) -> None:
        """Caller holds self._lock (the collect-boundary beat is
        written there, lexically under the lock, for PTL005)."""
        if self._breaker is not None:
            self._breaker.record_success()

    # hang_snapshot/hang_fail_all run on the HANGWATCH MONITOR thread
    # while the scheduler is wedged mid-collect: bounded lock acquires —
    # if the scheduler wedged while holding the lock, a degraded answer
    # beats joining the hang (the backstop timer caps everything anyway)

    def hang_snapshot(self) -> Dict[str, Any]:
        """The in-flight cohort snapshot for serve_hang_report.json."""
        if not self._lock.acquire(timeout=2.0):
            return {"lock": "unavailable — scheduler may hold it"}
        try:
            return {
                "queue_depth": len(self._queue),
                "queued": [r.rid for r in list(self._queue)[:32]],
                "slots": [
                    None if r is None else {
                        "rid": r.rid, "tokens": len(r.tokens),
                        "budget": r.budget,
                        "deadline_in_s": round(r.deadline - self._now(), 3),
                    }
                    for r in self._slots
                ],
                "admitting": [r.rid for r in self._admitting],
                "inflight_launches": getattr(self._backend, "inflight",
                                             None),
                "draining": self._draining,
                "breaker": (self._breaker.state
                            if self._breaker is not None else None),
                "brownout": self._brownout,
                "totals": dict(self._totals),
            }
        finally:
            self._lock.release()

    def hang_fail_all(self, error: str) -> int:
        """Answer what we can before the hang exit: every queued,
        admitting, and in-flight request resolves outcome=error (the
        client hears "the server hung" now instead of timing out
        later). Returns how many were answered. Also sets draining so
        any submit racing this exit resolves outcome=rejected
        immediately — the frontend's pre-exit answer flush must never
        find an unresolvable future."""
        if not self._lock.acquire(timeout=2.0):
            return 0
        try:
            now = self._now()
            self._draining = True
            n = 0
            while self._queue:
                self._finish_locked(self._queue.popleft(), "error", now,
                                    error=error)
                n += 1
            for req in self._admitting:
                if not req.done:
                    self._finish_locked(req, "error", now, error=error)
                    n += 1
            self._admitting = []
            for b, req in enumerate(self._slots):
                if req is not None:
                    self._slots[b] = None
                    if not req.done:
                        self._finish_locked(req, "error", now, error=error)
                        n += 1
            return n
        finally:
            self._lock.release()

    def status(self) -> Dict[str, Any]:
        """The ``--status_path`` health document (serving/resilience.
        StatusWriter): queue depth, slot occupancy, last-collect age,
        outcome totals, draining flag, breaker/brownout state. Bounded
        lock — a wedged scheduler yields a stale-but-honest snapshot."""
        now = self._clock()
        if not self._lock.acquire(timeout=0.5):
            return {"stale": True,
                    "detail": "engine lock unavailable (scheduler busy "
                              "or wedged — see the hangwatch)"}
        try:
            return {
                "started": self._started,
                "draining": self._draining,
                "queue_depth": len(self._queue),
                "slots": self.slots,
                "occupancy": sum(1 for r in self._slots if r is not None),
                "inflight": getattr(self._backend, "inflight", None),
                "last_collect_age_s": round(now - self._last_collect, 3),
                "loop_age_s": round(now - self._last_loop, 3),
                "breaker": (self._breaker.state
                            if self._breaker is not None else "disabled"),
                "breaker_opens": (self._breaker.opened_total
                                  if self._breaker is not None else 0),
                "brownout": self._brownout,
                "shed_policy": self.shed_policy,
                "pipeline": "on" if self.pipeline else "off",
                "warmup_s": self.warmup_s,
                "reloads": self._reloads,
                "reload_tag": self._reload_tag,
                "totals": dict(self._totals),
            }
        finally:
            self._lock.release()

    # --------------------------------------------- shared loop phases

    def _effective_budget_locked(self, req: EngineRequest) -> int:
        budget = max(1, min(
            self._backend.max_length if req.max_new is None else req.max_new,
            self._backend.max_length,
        ))
        if self._brownout:
            # degraded mode: everyone gets a shorter answer rather than
            # the tail getting none (doc/resilience.md)
            budget = min(budget, max(
                1, int(self._backend.max_length * BROWNOUT_BUDGET_SHARE)))
        return budget

    def _eta_s_locked(self, budget: int) -> Optional[float]:
        """Estimated seconds to serve a request admitted NOW: measured
        prefill + budget decode micro-steps. None while unmeasured
        (warmup) — the shed policy never guesses."""
        if self._step_ema <= 0.0:
            return None
        return self._prefill_ema + float(budget) * self._step_ema

    def _drain_eta_locked(self) -> float:
        """Rough queue-drain ETA for shed retry-after hints: how long
        the queued waves ahead take at the measured per-step rate.
        While unmeasured (a burst before the first collect boundary)
        the hint is a fixed conservative backoff — echoing the 20 ms
        idle poll would invite near-immediate retries into the same
        overloaded queue."""
        if self._step_ema <= 0.0:
            return UNMEASURED_RETRY_S
        waves = (len(self._queue) / max(self.slots, 1)) + 1.0
        per_wave = self._prefill_ema + self._step_ema * max(
            1, int(self._backend.max_length * BROWNOUT_BUDGET_SHARE))
        return max(waves * per_wave, self.idle_poll_s)

    def _boundary(self) -> Tuple[List[int], List[EngineRequest], List[int]]:
        """One iteration boundary under the lock: sweep cancellations
        and deadlines, reject the queue when draining, update the
        queue-pressure EMA (brownout engage/disengage), gate on the
        launch-failure breaker, then pick the FIFO admissions for the
        free slots — shedding, under a deadline-aware policy, any
        candidate whose remaining deadline the measured prefill+decode
        estimate already proves unmeetable (shed at admission, not
        after wasting a slot on a request that times out mid-decode)."""
        admit_slots: List[int] = []
        admit_reqs: List[EngineRequest] = []
        budgets: List[int] = []
        with self._lock:
            now = self._now()
            self._last_loop = self._clock()  # status loop-age beat
            if self._pending_reload is not None:
                params, tag = self._pending_reload
                self._pending_reload = None
                if self._swap_weights(params, tag):
                    self._reloads += 1
                    self._reload_tag = tag
                    self._note_reload(tag)
            self._sweep_locked(now)
            if self._draining:
                while self._queue:
                    self._finish_locked(self._queue.popleft(),
                                        "rejected", now)
            if self.shed_policy == "brownout":
                pressure = len(self._queue) / max(self.slots, 1)
                self._pressure_ema = (
                    (1 - _EMA) * self._pressure_ema + _EMA * pressure
                )
                if not self._brownout and self._pressure_ema >= BROWNOUT_ON:
                    self._brownout = True
                    self._set_brownout_gauge(1)
                    logger.warning(
                        "serve brownout ENGAGED (queue-pressure EMA %.2f "
                        ">= %g): output budgets capped to %d%% of "
                        "max_length, excess arrivals shed",
                        self._pressure_ema, BROWNOUT_ON,
                        int(BROWNOUT_BUDGET_SHARE * 100),
                    )
                elif self._brownout and self._pressure_ema <= BROWNOUT_OFF:
                    self._brownout = False
                    self._set_brownout_gauge(0)
                    logger.info(
                        "serve brownout released (queue-pressure EMA %.2f)",
                        self._pressure_ema,
                    )
            if self._breaker is not None and not self._breaker.allow_launch():
                # open breaker: no admissions, no launches — queued
                # requests wait out the cooldown (their deadlines still
                # sweep above); the half-open probe re-enters here
                self._admitting = []
                return [], [], []
            free = [b for b, r in enumerate(self._slots) if r is None]
            fi = 0
            while fi < len(free) and self._queue:
                req = self._queue.popleft()
                budget = self._effective_budget_locked(req)
                if self.shed_policy != "off":
                    eta = self._eta_s_locked(budget)
                    if eta is not None and now + eta > req.deadline:
                        # unmeetable deadline: answer now (no retry
                        # hint — more time won't fit this budget either)
                        self._finish_locked(req, "shed", now)
                        continue
                admit_slots.append(free[fi])
                admit_reqs.append(req)
                budgets.append(budget)
                fi += 1
            if admit_reqs and self._breaker is not None:
                # a half-open breaker lets exactly ONE cohort probe:
                # latch it so later boundaries wait out its collect
                # verdict instead of launching more (no-op when closed)
                self._breaker.note_probe()
            self._admitting = admit_reqs
        return admit_slots, admit_reqs, budgets

    def _set_brownout_gauge(self, v: int) -> None:
        from paddle_tpu.observability import metrics as obs

        obs.registry().gauge("serve.brownout").set(v)

    def _swap_weights(self, params, tag: str) -> bool:
        """The iteration-boundary weight swap. The backend assignment
        is an O(1) reference swap (same shapes → no recompile; the
        NON-donated params argument means no dispatched launch can be
        torn by it). A failing swap keeps the old weights serving —
        reload is an upgrade, never an outage. Caller (the boundary,
        under the engine lock) bumps the reload bookkeeping on True."""
        try:
            self._backend.reload(params)
        except Exception as e:  # noqa: BLE001 — old weights keep serving
            logger.error("serve weight reload %r failed: %s — keeping "
                         "current weights", tag, e)
            return False
        return True

    def _note_reload(self, tag: str) -> None:
        from paddle_tpu.observability import metrics as obs

        obs.registry().counter("serve.reloads").inc()
        obs.emit("reload", path=tag, engine=ENGINE_NAME,
                 **({"replica": self.replica} if self.replica else {}))
        # reload-boundary interference marker for traced timelines
        self._span_locked("engine.reload_boundary", self._clock(), 0.0,
                          tag=tag)
        logger.info("serve weights hot-reloaded at iteration boundary "
                    "(%s, reload #%d)", tag or "<untagged>", self._reloads)

    def _do_admit(self, admit_slots: List[int],
                  admit_reqs: List[EngineRequest],
                  budgets: List[int]) -> bool:
        """Prefill launch outside the lock (submit() must never block
        behind device work); place the cohort on success. In pipelined
        mode the backend dispatches without syncing, so the measured
        time is enqueue cost — the prefill's device time surfaces at
        the next collect boundary (doc/serving.md). False = the cohort
        (and everything in flight) was errored; caller resets. Budgets
        come from the boundary (brownout caps applied there)."""
        backend = self._backend
        t0 = self._clock()
        try:
            backend.admit(admit_slots, admit_reqs, budgets)
        except Exception as e:  # noqa: BLE001 — cohort gets the evidence
            err = f"{type(e).__name__}: {e}"
            logger.error("serve admit failed: %s", err)
            with self._lock:
                now = self._now()
                for req in admit_reqs:
                    self._finish_locked(req, "error", now, error=err)
                self._admitting = []
                self._fail_inflight_locked(now, err)
                self._note_launch_fault_locked()
            self._oom_check(e)
            return False
        dt = self._clock() - t0
        with self._lock:
            now = self._now()
            for b, req, budget in zip(admit_slots, admit_reqs, budgets):
                req.slot = b
                req.budget = budget
                req.t_admit = now
                self._slots[b] = req
                self._log.admit(req)
                if req.trace:
                    # request-perspective hops: time queued behind the
                    # cohort wave, then the (shared) prefill launch
                    self._span_locked(
                        "engine.queue_wait", req.t_enqueue_abs,
                        (now + self._t0) - req.t_enqueue_abs,
                        trace=req.trace, rid=req.rid)
                    self._span_locked("engine.prefill", t0, dt,
                                      trace=req.trace, rid=req.rid)
            self._admitting = []
            self._log.note_exec(dt)
            self._prefill_ema = (1 - _EMA) * self._prefill_ema + _EMA * dt
        return True

    def _loop(self) -> None:
        try:
            if self.pipeline:
                self._loop_pipelined()
            else:
                self._loop_blocking()
        finally:
            hw = self._hangwatch
            if hw is not None:
                hw.stop()  # a drained engine stops pinging — not a hang

    def _safe_reset(self) -> None:
        try:
            self._backend.reset()
        except Exception as e:  # noqa: BLE001
            logger.error("serve backend reset failed: %s", e)

    def _block_inputs_locked(self) -> Tuple[int, bool]:
        """(budget cap, pressure) for :func:`pick_block`, from the
        engine's view of the slots — under pipelining this lags the
        device by the in-flight launches, which only over-runs into
        frozen micro-steps (bounded, harmless)."""
        cap = 0
        pressed = bool(self._queue)
        for req in self._slots:
            if req is None:
                continue
            left = max(req.budget - len(req.tokens), 1)
            cap = left if cap == 0 else min(cap, left)
            if req.t_first_token < 0:
                pressed = True  # a slot still owes its first token
        return (cap or self._ladder[-1]), pressed

    # ------------------------------------------ self-speculation phases

    def _spec_k_locked(self) -> int:
        """The speculation rung for the NEXT launch (0 = plain decode),
        from the lock-guarded acceptance EMA. In the fallback regime
        this also runs the re-probe clock: after SPEC_REPROBE plain
        launches the EMAs reset so the bottom rung probes again."""
        if not self._spec_ladder:
            return 0
        k = pick_spec_k(self._spec_ladder, self._spec_ema,
                        self._spec_samples)
        if k <= 0:
            # the _locked suffix contract: every caller holds self._lock
            # (a non-reentrant cc.Lock — re-wrapping would deadlock)
            self._spec_cooloff += 1  # lint: disable=PTL005 -- caller holds self._lock (_locked contract; non-reentrant Lock)
            if self._spec_cooloff >= SPEC_REPROBE:
                self._spec_cooloff = 0  # lint: disable=PTL005 -- caller holds self._lock (_locked contract)
                self._spec_samples = 0  # lint: disable=PTL005 -- caller holds self._lock (_locked contract)
                self._spec_ema = 0.0  # lint: disable=PTL005 -- caller holds self._lock (_locked contract)
        return k

    def _gather_spec_locked(self, cohort, k: int):
        """The draft batch for one verify launch: up to ``k`` proposed
        tokens per live slot from the n-gram table, capped by the slot's
        remaining budget. Slots that opted out (per-request fallback),
        finished, or for which the chains run dry simply get no entry —
        they ride the launch as one plain greedy step. Returns None when
        NO slot proposes (caller dispatches a plain decode block
        instead; zero recompiles either way)."""
        if self._draft is None or k <= 0:
            return None
        drafts: Dict[int, List[int]] = {}
        for b, req in cohort:
            if req is None or req.done or req.spec_off:
                continue
            room = req.budget - len(req.tokens)
            kk = min(int(k), room)
            if kk <= 0:
                continue
            d = self._draft.propose(req.tokens, kk)
            if d:
                drafts[b] = d
        return drafts or None

    # ------------------------------------------------- the PR-12 loop

    def _loop_blocking(self) -> None:
        """The serial loop: boundary → admit (sync) → one blocking
        step() → apply. Kept verbatim as the pipeline A/B baseline
        (``pipeline=False`` / PADDLE_TPU_BENCH_SERVE_PIPELINE=off)."""
        backend = self._backend
        host_ema = self._host_ema
        step_ema = self._step_ema
        t_host0 = self._clock()
        while True:
            self._ping()
            admit_slots, admit_reqs, budgets = self._boundary()
            if admit_reqs and not self._do_admit(admit_slots, admit_reqs,
                                                 budgets):
                # an OOM admit additionally emptied the queue and set
                # draining inside _oom_check — the idle branch below
                # then exits the loop
                self._safe_reset()
                t_host0 = self._clock()
                continue
            with self._lock:
                occupancy = sum(1 for r in self._slots if r is not None)
                if occupancy == 0:
                    if self._draining and not self._queue:
                        break
                    if not self._queue or (
                        self._breaker is not None
                        and not self._breaker.allow_launch()
                    ):
                        # nothing admittable: empty queue, or the open
                        # breaker refused admissions at the boundary —
                        # poll instead of spinning the cooldown down
                        self._wake.wait(timeout=self.idle_poll_s)
                    # idle time is not host overhead: a stale anchor
                    # here would dump the whole idle stretch into
                    # host_ema and push pick_block to the top rung
                    # exactly when a fresh request wants a fast first
                    # boundary
                    t_host0 = self._clock()
                    continue
                cap, pressed = self._block_inputs_locked()
                spec = self._gather_spec_locked(
                    [(b, r) for b, r in enumerate(self._slots)
                     if r is not None],
                    self._spec_k_locked(),
                )
            if spec is not None:
                u = max(len(d) for d in spec.values())
            else:
                u = pick_block(self._ladder, cap, pressed, host_ema,
                               step_ema)
            t0 = self._clock()
            host_ema = (1 - _EMA) * host_ema + _EMA * (t0 - t_host0)
            try:
                self._chaos_boundary()
                out = (backend.step(draft=spec) if spec is not None
                       else backend.step(block=u))
            except Exception as e:  # noqa: BLE001 — engine survives a bad launch
                err = f"{type(e).__name__}: {e}"
                logger.error("serve decode launch failed: %s", err)
                with self._lock:
                    self._fail_inflight_locked(self._now(), err)
                    self._note_launch_fault_locked()
                self._safe_reset()
                if self._oom_check(e):
                    continue  # queue emptied + draining: exit via idle
                t_host0 = self._clock()
                continue
            dt = self._clock() - t0
            t_host0 = self._clock()
            step_ema = (1 - _EMA) * step_ema + _EMA * (dt / max(u, 1))
            with self._lock:
                # mirror the hot-loop EMAs for the shed policy + status
                self._host_ema = host_ema
                self._step_ema = step_ema
                self._last_collect = self._clock()
                self._note_collect_locked()
                traces = [r.trace for r in self._slots
                          if r is not None and r.trace]
                if traces:
                    self._span_locked("engine.decode_window", t0, dt,
                                      traces=traces, block=int(u))
                    rb = float(getattr(backend, "last_readback_s", 0.0)
                               or 0.0)
                    if rb > 0.0:
                        self._span_locked("engine.readback",
                                          t0 + dt - rb, rb,
                                          traces=traces)
                self._apply_step_locked(out, dt, occupancy, spec=spec)

    # ----------------------------------------------- the pipelined loop

    def _loop_pipelined(self) -> None:
        """Boundary and apply work overlap the in-flight launch: each
        iteration dispatches launch N+1 BEFORE collecting launch N, so
        the device never waits for host scheduling and the host never
        waits for a launch it has nothing to say about. ``inflight``
        holds (cohort snapshot, dispatch time) per launch — loop-local:
        the only cross-thread state stays the lock-guarded slots/queue."""
        backend = self._backend
        inflight: collections.deque = collections.deque()
        host_ema = self._host_ema
        step_ema = self._step_ema
        union_end = self._clock()   # union of dispatch->collect spans
        t_host0 = self._clock()
        while True:
            self._ping()
            admit_slots, admit_reqs, budgets = self._boundary()
            if admit_reqs and not self._do_admit(admit_slots, admit_reqs,
                                                 budgets):
                inflight = self._abort_inflight(inflight)
                # failure handling (logging, reset, device realloc) is
                # not host overhead — same stale-anchor rule as idle
                t_host0 = self._clock()
                continue
            # --- dispatch launch N+1 (device-ordered after the prefill)
            with self._lock:
                occupancy = sum(1 for r in self._slots if r is not None)
                cohort = [(b, r) for b, r in enumerate(self._slots)
                          if r is not None]
                cap, pressed = self._block_inputs_locked()
                # dispatch ahead only when it can pay: if every live
                # slot's remaining budget is already covered by
                # in-flight micro-steps, launch N+1 would run
                # all-frozen rows — pure waste (the short-budget
                # regime) — so collect first and let the boundary see
                # the finishes. EOS finishes stay unknowable ahead of
                # time; budgets are the bound we do know.
                pending_steps = sum(u for _c, u, _t, _lg, _sp in inflight)
                live_next = any(
                    r.budget - len(r.tokens) - pending_steps > 0
                    for _b, r in cohort
                )
                # self-speculation runs the launch pipeline at depth 1:
                # drafts must be proposed from fully-committed context
                # (a draft chained over an uncollected launch's unknown
                # tokens would miss by construction), so while a launch
                # is in flight the engine neither proposes nor
                # interleaves a plain launch — it collects first. The
                # in-flight launch still overlaps all host scheduling,
                # and each launch commits up to K+1 tokens instead of
                # the plain block's pipelined depth. With the EMA in
                # the fallback regime (k=0) the plain depth-2 pipeline
                # is back unchanged.
                spec = None
                spec_hold = False
                spec_k = self._spec_k_locked()
                if spec_k > 0:
                    if inflight:
                        spec_hold = True
                    else:
                        spec = self._gather_spec_locked(cohort, spec_k)
            dispatched = False
            if occupancy and not spec_hold and (live_next or not inflight):
                dispatched = True
                if spec is not None:
                    u = max(len(d) for d in spec.values())
                else:
                    u = pick_block(self._ladder, cap, pressed, host_ema,
                                   step_ema)
                t_disp = self._clock()
                host_ema = (1 - _EMA) * host_ema + _EMA * (t_disp - t_host0)
                try:
                    if spec is not None:
                        backend.dispatch(draft=spec)
                    else:
                        backend.dispatch(block=u)
                except Exception as e:  # noqa: BLE001
                    err = f"{type(e).__name__}: {e}"
                    logger.error("serve decode dispatch failed: %s", err)
                    with self._lock:
                        self._fail_inflight_locked(self._now(), err)
                        self._note_launch_fault_locked()
                    inflight = self._abort_inflight(inflight, err)
                    self._oom_check(e)
                    t_host0 = self._clock()
                    continue
                with self._lock:
                    # the launch belongs to the CURRENT telemetry
                    # window: a window_roll between this dispatch and
                    # its collect closes that window, and the stray
                    # launch must not leak its exec/overlap into the
                    # next one (begin_window's quiescence note)
                    inflight.append((cohort, u, t_disp, self._log, spec))
                    self._log.note_dispatch(len(inflight))
            # --- collect launch N while N+1 runs; collect immediately
            # when nothing was dispatched ahead (tail / draft cadence /
            # nothing worth dispatching)
            if inflight and (len(inflight) > 1 or not dispatched):
                cohort, u, t_disp, disp_log, spec_snap = inflight[0]
                t_wait = self._clock()
                try:
                    self._chaos_boundary()
                    out = backend.collect()
                except Exception as e:  # noqa: BLE001 — fault surfaces HERE
                    err = f"{type(e).__name__}: {e}"
                    logger.error("serve decode launch failed: %s", err)
                    with self._lock:
                        self._fail_inflight_locked(self._now(), err)
                        self._note_launch_fault_locked()
                    inflight = self._abort_inflight(inflight, err)
                    self._oom_check(e)
                    t_host0 = self._clock()
                    continue
                inflight.popleft()
                t_done = self._clock()
                # exec side of host_share: the UNION of dispatch->done
                # spans — overlapping spans must not double-count
                service = max(t_done - max(t_disp, union_end), 0.0)
                union_end = max(union_end, t_done)
                # the ladder's device estimate uses the DE-OVERLAPPED
                # span: the raw dispatch->done time of launch N+1 also
                # contains its wait behind launch N, which would read
                # as ~2x the true per-micro-step cost under steady
                # pipelining and skew pick_block a rung low
                step_ema = (1 - _EMA) * step_ema + _EMA * (service / max(u, 1))
                with self._lock:
                    # mirror the hot-loop EMAs for the shed policy +
                    # status, and beat the collect-liveness clock
                    self._host_ema = host_ema
                    self._step_ema = step_ema
                    self._last_collect = self._clock()
                    self._note_collect_locked()
                    traces = [r.trace for _b, r in cohort if r.trace]
                    if traces:
                        # decode-iteration window from the COHORT
                        # snapshot: the requests whose tokens this
                        # collect actually carries, even if their
                        # slots were since reassigned
                        self._span_locked("engine.decode_window",
                                          t_disp, t_done - t_disp,
                                          traces=traces, block=int(u))
                        rb = float(getattr(backend, "last_readback_s",
                                           0.0) or 0.0)
                        if rb > 0.0:
                            self._span_locked("engine.readback",
                                              t_done - rb, rb,
                                              traces=traces)
                    stale = disp_log is not self._log
                    if not stale:
                        self._log.note_overlap(max(t_wait - t_disp, 0.0))
                    self._log.note_dispatch(len(inflight))
                    # tokens/finishes always apply (requests legally
                    # span windows); the launch/overlap/exec accounting
                    # is skipped when the dispatching window has rolled
                    # closed — its record is already emitted
                    self._apply_step_locked(out, service, len(cohort),
                                            cohort=cohort,
                                            count_launch=not stale,
                                            spec=spec_snap)
                t_host0 = self._clock()
                continue
            # --- idle / exit
            with self._lock:
                if not inflight and not any(
                    r is not None for r in self._slots
                ):
                    if self._draining and not self._queue:
                        break
                    if not self._queue or (
                        self._breaker is not None
                        and not self._breaker.allow_launch()
                    ):
                        # empty queue, or the open breaker refused
                        # admissions — poll, don't spin the cooldown
                        self._wake.wait(timeout=self.idle_poll_s)
            # anchored AFTER any idle wait: idle seconds are not host
            # overhead and must not inflate the ladder's host_ema
            t_host0 = self._clock()

    def _abort_inflight(self, inflight: collections.deque,
                        error: str = "decode failed") -> collections.deque:
        """A faulted launch takes every in-flight cohort with it: their
        results are unrecoverable once the device state resets. Each
        snapshot request resolves exactly once (`done` guards repeats
        across overlapping snapshots and the slot sweep)."""
        with self._lock:
            now = self._now()
            for cohort, _u, _t, _lg, _sp in inflight:
                for _b, req in cohort:
                    self._finish_locked(req, "error", now, error=error)
            self._log.note_dispatch(0)
        self._safe_reset()
        return collections.deque()

    def _apply_step_locked(self, out, service_s: float, occupancy: int,
                           cohort=None, count_launch: bool = True,
                           spec=None) -> None:
        """Fold one launch's readback into the request lifecycles.
        ``cohort`` (pipelined) is the slot snapshot taken at dispatch:
        tokens belong to THOSE requests — a slot re-assigned between
        dispatch and collect must not leak a previous occupant's tokens
        to the new one (the snapshot discipline); evicted (done)
        requests just skip. ``spec`` is the launch's draft snapshot
        (slot -> proposed tokens, carried like the cohort snapshot):
        acceptance is judged HERE, against the committed tokens, and the
        draft table learns from them — the collect boundary is the only
        place the table is ever written (under this lock)."""
        now = self._now()
        tokens, live, finished = out.tokens, out.live, out.finished
        u = tokens.shape[0]
        rows = (cohort if cohort is not None
                else enumerate(self._slots))
        for b, req in rows:
            if req is None or req.done:
                continue
            emitted = [int(tokens[i, b]) for i in range(u) if bool(live[i, b])]
            d = spec.get(b) if spec else None
            if d:
                # accepted = the emitted prefix that matched the draft
                # (the verify launch emits accepted + the one corrected
                # token, so this is exact, not inferred from counts)
                acc = 0
                for t, want in zip(emitted, d):
                    if t != want:
                        break
                    acc += 1
                rate = acc / len(d)
                self._log.note_spec(len(d), acc)
                self._spec_samples += 1  # lint: disable=PTL005 -- caller holds self._lock (_locked contract; non-reentrant Lock)
                self._spec_cooloff = 0  # lint: disable=PTL005 -- caller holds self._lock (_locked contract)
                self._spec_ema = (rate if self._spec_samples == 1 else
                                  (1 - _EMA) * self._spec_ema + _EMA * rate)  # lint: disable=PTL005 -- caller holds self._lock (_locked contract)
                req.spec_samples += 1
                req.spec_ema = (rate if req.spec_samples == 1 else
                                (1 - _EMA) * req.spec_ema + _EMA * rate)
                if (req.spec_samples >= SPEC_MIN_SAMPLES
                        and req.spec_ema < SPEC_REQ_OFF):
                    # per-slot fallback: this request's drafts keep
                    # missing — stop proposing for it (zero recompiles:
                    # it rides verify launches as a plain step)
                    req.spec_off = True
            if emitted:
                if self._draft is not None:
                    # collect-boundary table update: context is the
                    # previously committed tail, so chains span launch
                    # boundaries without double-counting
                    self._draft.observe(
                        emitted, context=req.tokens[-self._draft.order:])
                if req.t_first_token < 0:
                    # REAL wall-clock TTFT: this readback is the moment
                    # the first token left the device — mid-sequence,
                    # not at finish (and, pipelined, at the COLLECT
                    # boundary: the earliest the host can know)
                    req.t_first_token = now
                req.tokens.extend(emitted)
            if bool(finished[b]) and self._slots[b] is req:
                self._slots[b] = None
                self._finish_locked(req, "ok", now)
        if count_launch:
            self._log.launch(len(self._queue), occupancy, service_s)


# ------------------------------------------------------------- driver


def drive_rung(engine: Engine, requests: Sequence[slog.Request], *,
               rate_rps: float, rung: int = 0,
               result_timeout_s: float = 300.0) -> Dict[str, Any]:
    """Open-loop wall-clock driver for one offered-load rung against a
    live engine — the continuous counterpart of the PR-8 virtual-clock
    ``run_rung``, fed the SAME :func:`~paddle_tpu.observability.serving.
    schedule_requests` workload. Submits each request at its scheduled
    arrival offset (sleeping the gaps; a late submit stays late — open
    loop never hides coordinated omission), waits for every future, and
    rolls the window."""
    engine.begin_window()
    t0 = cc.monotonic()
    futures = []
    for req in requests:
        delay = req.t_enqueue - (cc.monotonic() - t0)
        if delay > 0:
            cc.sleep(delay)
        futures.append(engine.submit(
            req.prompt or [], max_new_tokens=req.max_new, rid=req.rid,
        ))
    for fut in futures:
        fut.result(timeout=result_timeout_s)
    elapsed = cc.monotonic() - t0
    window_s = max(elapsed, requests[-1].t_enqueue if requests else 0.0)
    return engine.window_roll(offered_rps=rate_rps, rung=rung,
                              window_s=window_s)
