"""The continuous-batching scheduler — slot admission/eviction at every
iteration boundary.

Run-to-completion batching (the PR-8 static driver, and every
``SequenceGenerator.generate`` call) holds a whole cohort until its
LONGEST sequence finishes: with mixed output lengths most slots spend
most steps finished-but-occupied. Orca (OSDI '22) showed iteration-level
scheduling — re-batching between decode steps — recovers that capacity.
This engine is that loop:

    while serving:
        evict   — finished (EOS / budget / max_length), cancelled, or
                  wall-deadline-expired slots free at the boundary
        admit   — queued requests (strict FIFO) prefill into free slots
        step    — ONE jitted launch advances every slot

Everything here is jax-free and thread-safe strictly through the
``utils/concurrency`` seam (``cc``): the scheduler runs on one
``cc.Thread``; ``submit``/``cancel``/``drain`` are the only cross-
thread entry points and every shared field is guarded by ``self._lock``
— the ``paddle race`` spec (tests/race_specs/spec_serve_engine.py)
explores exactly these interleavings. Device work hides behind the
backend seam (backend.py): ``FakeBackend`` for tests,
``JaxDecodeBackend`` for TPUs.

Telemetry is the PR-8 contract unchanged — per-request ``kind=request``
records (now with REAL wall-clock TTFT: the first token's readback
timestamp, mid-sequence) and ``kind=serve_window`` rollups with
``engine="continuous"`` — so ``paddle serve-report`` renders an engine
run with zero new code.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from paddle_tpu.observability import serving as slog
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

ENGINE_NAME = "continuous"

# terminal request outcomes (race-spec invariant: every submitted
# request's future resolves exactly once with one of these)
OUTCOMES = ("ok", "rejected", "timeout", "cancelled", "error")


@dataclasses.dataclass
class ServeResult:
    """What a resolved :class:`ResultFuture` carries."""

    rid: str
    outcome: str
    tokens: List[int]
    error: Optional[str] = None


class ResultFuture:
    """A one-shot, condition-backed result future (``cc`` seam)."""

    def __init__(self) -> None:
        self._cv = cc.Condition()
        self._done = False
        self._result: Optional[ServeResult] = None

    def done(self) -> bool:
        return self._done

    def _resolve(self, result: ServeResult) -> bool:
        """Exactly-once; a second resolution is dropped and reported
        False (the race spec asserts it never happens)."""
        with self._cv:
            if self._done:
                return False
            self._result = result
            self._done = True
            self._cv.notify_all()
            return True

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        deadline = None if timeout is None else cc.monotonic() + float(timeout)
        with self._cv:
            while not self._done:
                if deadline is None:
                    self._cv.wait(timeout=60.0)
                    continue
                remaining = deadline - cc.monotonic()
                if remaining <= 0:
                    raise TimeoutError("request result not ready")
                self._cv.wait(timeout=remaining)
        return self._result


@dataclasses.dataclass
class EngineRequest(slog.Request):
    """A :class:`~paddle_tpu.observability.serving.Request` plus the
    engine-side lifecycle: future, wall deadline, accumulated tokens,
    the slot it occupies, cancellation and exactly-once bookkeeping."""

    future: Optional[ResultFuture] = None
    deadline: float = math.inf
    cancelled: bool = False
    queued: bool = False      # passed admission control (arrival counted)
    done: bool = False
    slot: int = -1
    budget: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None


class Engine:
    """Slot-based continuous-batching decode engine.

    ``backend`` supplies capacity (``backend.slots``) and the device
    work; ``queue_cap`` rejects submits past the bound (0 = unbounded);
    ``request_timeout_s`` is the default wall-clock deadline from submit
    — expiry frees the queue entry OR the decode slot at the next
    iteration boundary with ``outcome=timeout``. ``clock`` is
    injectable for tests (defaults to the ``cc`` seam's monotonic, so
    ``paddle race`` virtualizes it automatically)."""

    def __init__(self, backend, queue_cap: int = 0,
                 request_timeout_s: float = 60.0,
                 clock: Optional[Callable[[], float]] = None,
                 idle_poll_s: float = 0.02):
        self._backend = backend
        self.queue_cap = int(queue_cap)
        self.request_timeout_s = float(request_timeout_s)
        self.idle_poll_s = float(idle_poll_s)
        self._clock = clock or cc.monotonic
        self._lock = cc.Lock()
        self._wake = cc.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._slots: List[Optional[EngineRequest]] = [None] * backend.slots
        # requests between queue-pop and slot placement (the prefill
        # launch runs outside the lock): cancel() must still find them
        self._admitting: List[EngineRequest] = []
        self._log = slog.RequestLog(engine=ENGINE_NAME)
        self._t0 = self._clock()
        self._thread = None
        self._started = False
        self._draining = False
        self._n_submitted = 0
        self._pid = os.getpid()

    # ----------------------------------------------------------- client

    @property
    def slots(self) -> int:
        return self._backend.slots

    @property
    def max_length(self) -> int:
        return self._backend.max_length

    def start(self) -> "Engine":
        """Warm the backend (all compiles land BEFORE serving — the
        recompiles=0 acceptance) and spawn the scheduler thread."""
        with self._lock:
            if self._started:
                return self
            self._started = True
        self._backend.warmup()
        th = cc.Thread(target=self._loop, name="serve-engine", daemon=True)
        with self._lock:
            self._thread = th
        th.start()
        return self

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               rid: Optional[str] = None,
               timeout_s: Optional[float] = None) -> ResultFuture:
        """Enqueue one request; returns its future. Rejected immediately
        (``outcome=rejected``) when draining, stopped, or past
        ``queue_cap`` — a rejection is an answer, never an exception."""
        fut = ResultFuture()
        with self._lock:
            now = self._now()
            if rid is None:
                rid = f"c{self._pid}-{self._n_submitted}"
            self._n_submitted += 1
            limit = timeout_s if timeout_s is not None else self.request_timeout_s
            req = EngineRequest(
                rid=rid, t_enqueue=now, prompt=list(prompt),
                prompt_tokens=len(prompt), max_new=max_new_tokens,
                future=fut, deadline=now + float(limit),
            )
            if self._draining or not self._started or self._thread is None:
                self._finish_locked(req, "rejected", now)
            elif self.queue_cap and len(self._queue) >= self.queue_cap:
                self._finish_locked(req, "rejected", now)
            elif max_new_tokens is not None and int(max_new_tokens) <= 0:
                # 0 is a LEGAL budget, not an unset sentinel: the answer
                # is the empty generation, no slot needed
                req.queued = True
                req.t_admit = now
                self._log.enqueued(req)
                self._log.admit(req)
                self._finish_locked(req, "ok", now)
            else:
                req.queued = True
                self._queue.append(req)
                self._log.enqueued(req)
                self._wake.notify_all()
        return fut

    def cancel(self, rid: str) -> bool:
        """Request cancellation; applied at the next iteration boundary
        (frees the queue entry or the slot, ``outcome=cancelled``).
        False when the id is unknown or already finished."""
        with self._lock:
            for req in self._queue:
                if req.rid == rid and not req.done:
                    req.cancelled = True
                    self._wake.notify_all()
                    return True
            for req in self._slots:
                if req is not None and req.rid == rid and not req.done:
                    req.cancelled = True
                    return True
            for req in self._admitting:
                if req.rid == rid and not req.done:
                    req.cancelled = True
                    return True
        return False

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: finish in-flight slots, reject the queue
        and every later submit, stop the loop. True when the scheduler
        thread exited within ``timeout``."""
        with self._lock:
            self._draining = True
            self._wake.notify_all()
            th = self._thread
        if th is None:
            return True
        th.join(timeout if timeout is not None else 600.0)
        return not th.is_alive()

    close = drain

    # -------------------------------------------------------- telemetry

    def begin_window(self) -> None:
        """Re-anchor the telemetry window (rung start). Caller must be
        quiescent — in-flight requests would straddle the anchor."""
        with self._lock:
            self._log = slog.RequestLog(engine=ENGINE_NAME)
            self._t0 = self._clock()

    def window_roll(self, offered_rps: float = 0.0, rung: int = 0,
                    window_s: Optional[float] = None) -> Dict[str, Any]:
        """Emit the current window's ``kind=serve_window`` rollup and
        start a fresh one; returns the record (sans envelope)."""
        with self._lock:
            now = self._now()
            log = self._log
            log.rung = int(rung)
            log.offered_rps = float(offered_rps)
            wall = max(now, 1e-9)
            host_share = max(1.0 - log.exec_s / wall, 0.0)
            rec = log.window_record(
                max(window_s if window_s is not None else now, 1e-9),
                host_share=host_share,
            )
            self._log = slog.RequestLog(engine=ENGINE_NAME)
            self._t0 = self._clock()
            return rec

    # -------------------------------------------------------- scheduler

    def _now(self) -> float:
        return self._clock() - self._t0

    def _finish_locked(self, req: EngineRequest, outcome: str,
                       now: float, error: Optional[str] = None) -> None:
        """Resolve one request exactly once: telemetry record + future."""
        if req.done:
            return
        req.done = True
        req.error = error
        if outcome == "ok":
            req.t_finish = now
            req.gen_tokens = len(req.tokens)
            self._log.complete(req)
        elif outcome == "rejected":
            # a drain-path rejection already counted its arrival at
            # enqueue; a submit-time one never arrived in the window
            self._log.reject(req, arrived=req.queued)
        elif outcome == "timeout":
            self._log.timeout(req, now)
        elif outcome == "cancelled":
            self._log.cancel(req, now)
        else:
            self._log.error(req, error=error or "decode failed")
        req.future._resolve(ServeResult(
            rid=req.rid, outcome=req.outcome,
            tokens=list(req.tokens), error=error,
        ))

    def _sweep_locked(self, now: float) -> None:
        """Iteration boundary policy: cancellations and wall deadlines,
        queue entries first (FIFO — the oldest expire first), then
        in-flight slots (the device row keeps decoding to its bounded
        budget and is simply overwritten by the next admission)."""
        for _ in range(len(self._queue)):
            req = self._queue.popleft()
            if req.cancelled:
                self._finish_locked(req, "cancelled", now)
            elif now > req.deadline:
                self._finish_locked(req, "timeout", now)
            else:
                self._queue.append(req)  # full rotation keeps FIFO order
        for b, req in enumerate(self._slots):
            if req is None:
                continue
            if req.cancelled:
                self._slots[b] = None
                self._finish_locked(req, "cancelled", now)
            elif now > req.deadline:
                self._slots[b] = None
                self._finish_locked(req, "timeout", now)

    def _fail_inflight_locked(self, now: float, error: str) -> None:
        for b, req in enumerate(self._slots):
            if req is not None:
                self._slots[b] = None
                self._finish_locked(req, "error", now, error=error)

    def _loop(self) -> None:
        backend = self._backend
        while True:
            # --- boundary: evict, reject-on-drain, pick admissions
            admit_slots: List[int] = []
            admit_reqs: List[EngineRequest] = []
            with self._lock:
                now = self._now()
                self._sweep_locked(now)
                if self._draining:
                    while self._queue:
                        self._finish_locked(self._queue.popleft(),
                                            "rejected", now)
                free = [b for b, r in enumerate(self._slots) if r is None]
                take = min(len(free), len(self._queue))
                for j in range(take):
                    admit_slots.append(free[j])
                    admit_reqs.append(self._queue.popleft())
                self._admitting = admit_reqs
            # --- admit (prefill launch outside the lock: submit() must
            # never block behind device work)
            if admit_reqs:
                budgets = [
                    max(1, min(backend.max_length if r.max_new is None
                               else r.max_new, backend.max_length))
                    for r in admit_reqs
                ]
                t0 = self._clock()
                try:
                    backend.admit(admit_slots, admit_reqs, budgets)
                except Exception as e:  # noqa: BLE001 — cohort gets the evidence
                    err = f"{type(e).__name__}: {e}"
                    logger.error("serve admit failed: %s", err)
                    with self._lock:
                        now = self._now()
                        for req in admit_reqs:
                            self._finish_locked(req, "error", now, error=err)
                        self._admitting = []
                        self._fail_inflight_locked(now, err)
                    self._safe_reset()
                    continue
                dt = self._clock() - t0
                with self._lock:
                    now = self._now()
                    for b, req, budget in zip(admit_slots, admit_reqs, budgets):
                        req.slot = b
                        req.budget = budget
                        req.t_admit = now
                        self._slots[b] = req
                        self._log.admit(req)
                    self._admitting = []
                    self._log.note_exec(dt)
            # --- step or idle
            with self._lock:
                occupancy = sum(1 for r in self._slots if r is not None)
                if occupancy == 0:
                    if self._draining and not self._queue:
                        break
                    if not self._queue:
                        self._wake.wait(timeout=self.idle_poll_s)
                    continue
            t0 = self._clock()
            try:
                out = backend.step()
            except Exception as e:  # noqa: BLE001 — engine survives a bad launch
                err = f"{type(e).__name__}: {e}"
                logger.error("serve decode launch failed: %s", err)
                with self._lock:
                    self._fail_inflight_locked(self._now(), err)
                self._safe_reset()
                continue
            dt = self._clock() - t0
            with self._lock:
                self._apply_step_locked(out, dt, occupancy)

    def _safe_reset(self) -> None:
        try:
            self._backend.reset()
        except Exception as e:  # noqa: BLE001
            logger.error("serve backend reset failed: %s", e)

    def _apply_step_locked(self, out, service_s: float,
                           occupancy: int) -> None:
        """Fold one launch's readback into the request lifecycles."""
        now = self._now()
        tokens, live, finished = out.tokens, out.live, out.finished
        u = tokens.shape[0]
        for b, req in enumerate(self._slots):
            if req is None:
                continue
            emitted = [int(tokens[i, b]) for i in range(u) if bool(live[i, b])]
            if emitted:
                if req.t_first_token < 0:
                    # REAL wall-clock TTFT: this readback is the moment
                    # the first token left the device — mid-sequence,
                    # not at finish
                    req.t_first_token = now
                req.tokens.extend(emitted)
            if bool(finished[b]):
                self._slots[b] = None
                self._finish_locked(req, "ok", now)
        self._log.launch(len(self._queue), occupancy, service_s)


# ------------------------------------------------------------- driver


def drive_rung(engine: Engine, requests: Sequence[slog.Request], *,
               rate_rps: float, rung: int = 0,
               result_timeout_s: float = 300.0) -> Dict[str, Any]:
    """Open-loop wall-clock driver for one offered-load rung against a
    live engine — the continuous counterpart of the PR-8 virtual-clock
    ``run_rung``, fed the SAME :func:`~paddle_tpu.observability.serving.
    schedule_requests` workload. Submits each request at its scheduled
    arrival offset (sleeping the gaps; a late submit stays late — open
    loop never hides coordinated omission), waits for every future, and
    rolls the window."""
    engine.begin_window()
    t0 = cc.monotonic()
    futures = []
    for req in requests:
        delay = req.t_enqueue - (cc.monotonic() - t0)
        if delay > 0:
            cc.sleep(delay)
        futures.append(engine.submit(
            req.prompt or [], max_new_tokens=req.max_new, rid=req.rid,
        ))
    for fut in futures:
        fut.result(timeout=result_timeout_s)
    elapsed = cc.monotonic() - t0
    window_s = max(elapsed, requests[-1].t_enqueue if requests else 0.0)
    return engine.window_roll(offered_rps=rate_rps, rung=rung,
                              window_s=window_s)
