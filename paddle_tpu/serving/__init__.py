"""paddle_tpu.serving — the continuous-batching generation engine.

The first subsystem that *serves* rather than trains (ROADMAP item 1):
an :class:`~paddle_tpu.serving.engine.Engine` holds fixed-shape donated
device state for ``--serve_slots`` concurrent sequences and runs one
jitted ``serve_decode`` launch over all slots per iteration; a
scheduler loop evicts finished slots and admits queued requests at
every iteration boundary, so a long sequence never holds short ones
hostage (Orca-style iteration-level scheduling — see doc/serving.md).

Layering (mirrors the analysis/resilience discipline):

- ``engine.py`` — the jax-free core: thread-safe front-end queue on the
  ``utils/concurrency`` seam, slot scheduler, request lifecycle
  telemetry (PR-8 contract: ``kind=request``/``kind=serve_window``).
- ``backend.py`` — the decode-seam protocol + a deterministic
  :class:`FakeBackend` (tests and ``tests/race_specs/``).
- ``draft.py`` — the host-side n-gram draft table behind speculative
  decode (doc/serving.md "Speculative decode"): jax-free, fed by
  committed tokens at collect boundaries, proposals verified by one
  fused ``serve_verify`` launch.
- ``jax_backend.py`` — the real thing: donated slot state, jitted
  ``serve_prefill``/``serve_decode`` launch groups through the PR-7
  CompileRegistry (one signature each — zero recompiles after warmup).
- ``frontend.py`` — ``paddle serve``: stdin-JSONL with SIGTERM
  graceful drain, and the in-process Python API.
- ``resilience.py`` — the serving resilience plane (doc/resilience.md
  "Serving resilience"): engine hangwatch (serve_hang_report.json +
  exit 19), launch-failure circuit breaker, durable request journal
  (at-least-once restart recovery), the ``--status_path`` health
  probe + `paddle serve-status`, and the hot weight-reload watcher.
- ``fleet.py`` — ``paddle serve-fleet``: the multi-replica router
  (health-based least-loaded balancing, journal-replay failover under
  at-least-once dedupe, fleet-wide graceful drain — doc/serving.md
  "Serving fleet").
"""

from paddle_tpu.serving.backend import (
    FakeBackend,
    StepOut,
    parse_decode_blocks,
    parse_slot_dtype,
    parse_spec_tokens,
)
from paddle_tpu.serving.draft import DraftTable
from paddle_tpu.serving.engine import (
    Engine,
    EngineRequest,
    ResultFuture,
    ServeResult,
    drive_rung,
    pick_block,
    pick_spec_k,
)
from paddle_tpu.serving.fleet import (
    FleetRouter,
    drive_fleet_rung,
    replica_score,
)
from paddle_tpu.serving.resilience import (
    SERVE_HANG_REPORT,
    CircuitBreaker,
    RequestJournal,
    ServeHangWatch,
    StatusWriter,
    WeightReloader,
    read_status,
)

__all__ = [
    "Engine", "EngineRequest", "ResultFuture", "ServeResult",
    "FakeBackend", "StepOut", "drive_rung", "pick_block",
    "pick_spec_k", "DraftTable", "parse_spec_tokens", "parse_slot_dtype",
    "parse_decode_blocks", "CircuitBreaker", "RequestJournal",
    "ServeHangWatch", "StatusWriter", "SERVE_HANG_REPORT",
    "FleetRouter", "drive_fleet_rung", "replica_score",
    "WeightReloader", "read_status",
]
