"""Structured-prediction layers: linear-chain CRF, CTC, LambdaRank,
selective FC.

Reference counterparts (/root/reference/paddle/gserver/layers/):
- CRFLayer.cpp + LinearChainCRF.cpp (param layout [C+2, C]: row 0 = start
  weights a, row 1 = end weights b, rows 2.. = transitions w;
  P(s) ∝ exp(a_{s1} + b_{sL} + Σ x_{t,s_t} + Σ w_{s_{t-1}, s_t})).
- CRFDecodingLayer.cpp (Viterbi decode; with a label input, emits per-token
  0/1 mismatch).
- CTCLayer.cpp + LinearChainCTC.cpp (blank = num_classes - 1,
  ``norm_by_times`` divides the per-sequence cost by its length).
- CostLayer.cpp LambdaCost (NDCG_num truncation; gradient = LambdaRank
  lambdas). Here the forward value is -NDCG@K and the gradient comes from
  the standard LambdaRank pairwise surrogate via a stop-gradient splice.
- SelectiveFullyConnectedLayer.cpp (fc restricted to selected columns).

All recursions are ``lax.scan`` over the padded time axis with per-batch
length masks — the XLA-native replacement for the reference's per-sequence
CPU loops. Gradients (the reference's hand-written backward()s) come from
jax.grad of these forwards.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, register_layer
from paddle_tpu.layers.cost import _finish_cost, _hp
from paddle_tpu.ops.activations import apply_activation
from paddle_tpu.proto import LayerConfig

Array = jax.Array
NEG = -1e30


def _crf_weights(w: Array) -> Tuple[Array, Array, Array]:
    """Split the [C+2, C] CRF parameter into (start a, end b, transitions w)."""
    return w[0], w[1], w[2:]


def crf_log_likelihood(x: Array, labels: Array, lengths: Array, param: Array) -> Array:
    """Per-sequence negative log likelihood. x [B,T,C], labels [B,T] int,
    lengths [B] int, param [C+2, C]. Returns [B]."""
    a, b, w = _crf_weights(param)
    B, T, C = x.shape
    t_iota = jnp.arange(T, dtype=jnp.int32)
    mask = (t_iota[None, :] < lengths[:, None])  # [B, T]

    # --- numerator: score of the gold path
    emit = jnp.take_along_axis(x, labels[..., None], axis=-1)[..., 0]  # [B,T]
    emit_score = jnp.sum(jnp.where(mask, emit, 0.0), axis=1)
    trans = w[labels[:, :-1], labels[:, 1:]]  # [B, T-1]
    trans_score = jnp.sum(jnp.where(mask[:, 1:], trans, 0.0), axis=1)
    last_idx = jnp.clip(lengths - 1, 0, T - 1)
    last_lab = jnp.take_along_axis(labels, last_idx[:, None], axis=1)[:, 0]
    gold = emit_score + trans_score + a[labels[:, 0]] + b[last_lab]

    # --- denominator: log Z by forward recursion (frozen past each length)
    def step(alpha, inp):
        x_t, m_t = inp  # [B,C], [B]
        new = jax.nn.logsumexp(alpha[:, :, None] + w[None], axis=1) + x_t
        alpha = jnp.where(m_t[:, None], new, alpha)
        return alpha, None

    alpha0 = a[None] + x[:, 0]
    xs = (jnp.swapaxes(x[:, 1:], 0, 1), jnp.swapaxes(mask[:, 1:], 0, 1))
    alpha, _ = lax.scan(step, alpha0, xs)
    log_z = jax.nn.logsumexp(alpha + b[None], axis=1)
    return log_z - gold


def crf_decode(x: Array, lengths: Array, param: Array) -> Array:
    """Viterbi decode. x [B,T,C], lengths [B]. Returns int32 [B,T] (padding
    positions are 0)."""
    a, b, w = _crf_weights(param)
    B, T, C = x.shape
    t_iota = jnp.arange(T, dtype=jnp.int32)
    mask = (t_iota[None, :] < lengths[:, None])

    def fwd(delta, inp):
        x_t, m_t = inp
        scores = delta[:, :, None] + w[None]  # [B, C_prev, C]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [B, C]
        new = jnp.max(scores, axis=1) + x_t
        delta_next = jnp.where(m_t[:, None], new, delta)
        return delta_next, (delta_next, best_prev)

    delta0 = a[None] + x[:, 0]
    xs = (jnp.swapaxes(x[:, 1:], 0, 1), jnp.swapaxes(mask[:, 1:], 0, 1))
    _, (deltas, tracks) = lax.scan(fwd, delta0, xs)
    # deltas: [T-1, B, C] (delta at t=1..T-1); tracks[t] maps state at t+1 -> best state at t
    all_delta = jnp.concatenate([delta0[None], deltas], axis=0)  # [T, B, C]
    end_choice = jnp.argmax(all_delta + b[None, None], axis=2).astype(jnp.int32)  # [T, B]
    # pad tracks with a dummy row so tracks_full[t] maps state at t+1 (t = T-1 unused)
    tracks_full = jnp.concatenate(
        [tracks, jnp.zeros((1, B, C), dtype=jnp.int32)], axis=0
    )  # [T, B, C]; tracks_full[t][b, s_{t+1}] = s_t for t in [0, T-2]

    def bwd(carry, inp):
        nxt = carry  # chosen state at t+1 [B]
        t, end_t, track_t = inp
        from_next = jnp.take_along_axis(track_t, nxt[:, None], axis=1)[:, 0]
        is_end = (t == lengths - 1)
        in_seq = (t < lengths - 1)
        cur = jnp.where(is_end, end_t, jnp.where(in_seq, from_next, 0))
        return cur, cur

    ts = jnp.arange(T - 1, -1, -1, dtype=jnp.int32)
    init = jnp.zeros((B,), dtype=jnp.int32)
    _, path_rev = lax.scan(bwd, init, (ts, end_choice[::-1], tracks_full[::-1]))
    path = jnp.swapaxes(path_rev[::-1], 0, 1)  # [B, T]
    return jnp.where(mask, path, 0).astype(jnp.int32)


@register_layer("crf")
def crf_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    feats, label = inputs[0], inputs[1]
    weight = inputs[2] if len(inputs) > 2 else None
    # CRF recursions are logsumexp chains — run them f32 even when the
    # features arrive bf16 (param stays master dtype)
    param = ctx.param(cfg.inputs[0].input_parameter_name, cast=False)
    nll = crf_log_likelihood(_hp(feats.value), label.ids, feats.seq_lengths, param)
    # per-sequence cost (already reduced over time) — feed _finish_cost a
    # non-sequence view so it only applies coeff/weight.
    return _finish_cost(cfg, nll, Argument(value=nll), weight)


@register_layer("crf_decoding")
def crf_decoding_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    feats = inputs[0]
    param = ctx.param(cfg.inputs[0].input_parameter_name, cast=False)
    path = crf_decode(_hp(feats.value), feats.seq_lengths, param)
    out = Argument(ids=path, seq_lengths=feats.seq_lengths)
    if len(inputs) > 1:  # label given: per-token 0/1 mismatch (ref: CRFDecodingLayer.cpp:52-62)
        label = inputs[1]
        err = (path != label.ids).astype(ctx.dtype) * feats.seq_mask()
        out = Argument(ids=path, value=err[..., None], seq_lengths=feats.seq_lengths)
    return out


# --------------------------------------------------------------------- CTC


def ctc_loss(log_probs: Array, in_lengths: Array, labels: Array, label_lengths: Array,
             blank: int) -> Array:
    """Per-sequence CTC negative log likelihood.

    log_probs [B,T,C], in_lengths [B], labels [B,S] (no blanks), label_lengths
    [B]. Standard alpha recursion (Graves 2006) over the extended sequence
    blank,l1,blank,l2,...,blank of length 2S+1, log-space, lax.scan over T.
    """
    B, T, C = log_probs.shape
    S = labels.shape[1]
    U = 2 * S + 1
    u_iota = jnp.arange(U, dtype=jnp.int32)
    # extended label sequence: even u -> blank, odd u -> labels[(u-1)/2]
    lab_idx = jnp.clip((u_iota - 1) // 2, 0, S - 1)
    ext = jnp.where(u_iota % 2 == 1, labels[:, lab_idx], blank)  # [B, U]
    u_valid = u_iota[None, :] < (2 * label_lengths[:, None] + 1)

    # skip connection u-2 allowed when ext[u] != blank and ext[u] != ext[u-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, dtype=ext.dtype), ext[:, :-2]], axis=1)
    can_skip = (u_iota[None, :] % 2 == 1) & (ext != ext_m2)

    def emit(t_slice, ext_):
        return jnp.take_along_axis(t_slice, ext_, axis=1)  # [B, U]

    alpha0 = jnp.where((u_iota[None, :] <= 1) & u_valid, emit(log_probs[:, 0], ext), NEG)

    def step(alpha, inp):
        lp_t, m_t = inp  # [B,C], [B]
        a_m1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        a_m2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        a_m2 = jnp.where(can_skip, a_m2, NEG)
        stacked = jnp.stack([alpha, a_m1, a_m2], axis=0)
        merged = jax.nn.logsumexp(stacked, axis=0) + emit(lp_t, ext)
        merged = jnp.where(u_valid, merged, NEG)
        return jnp.where(m_t[:, None], merged, alpha), None

    t_iota = jnp.arange(T, dtype=jnp.int32)
    mask = (t_iota[None, :] < in_lengths[:, None])
    xs = (jnp.swapaxes(log_probs[:, 1:], 0, 1), jnp.swapaxes(mask[:, 1:], 0, 1))
    alpha, _ = lax.scan(step, alpha0, xs)

    u_last = 2 * label_lengths  # index of final blank
    a_last = jnp.take_along_axis(alpha, u_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, jnp.clip(u_last - 1, 0, U - 1)[:, None], axis=1)[:, 0]
    a_prev = jnp.where(label_lengths > 0, a_prev, NEG)
    ll = jnp.logaddexp(a_last, a_prev)
    return -ll


@register_layer("ctc")
def ctc_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # input 0: softmax probabilities [B,T,C] (ref CTCLayer feeds softmax
    # output to LinearChainCTC, which takes log internally); input 1: label
    # id sequence. blank = size - 1 (LinearChainCTC.cpp:88).
    probs, label = inputs[0], inputs[1]
    log_p = jnp.log(jnp.clip(_hp(probs.value), 1e-10, None))
    cost = ctc_loss(log_p, probs.seq_lengths, label.ids, label.seq_lengths,
                    blank=cfg.size - 1)
    if cfg.norm_by_times:
        cost = cost / jnp.maximum(probs.seq_lengths.astype(cost.dtype), 1.0)
    return _finish_cost(cfg, cost, Argument(value=cost), None)


# --------------------------------------------------------------- LambdaRank


def _ndcg_at_k(scores: Array, rels: Array, mask: Array, k: int):
    """NDCG@k per list. scores/rels/mask: [B, T].

    Returns (ndcg [B], rank_discount [B, T], idcg [B]) so lambda_cost can
    reuse the per-item discounts for the pairwise |ΔNDCG| weights."""
    neg = jnp.where(mask, scores, NEG)
    order = jnp.argsort(-neg, axis=1)  # indices of items by model score desc
    rel_sorted = jnp.take_along_axis(jnp.where(mask, rels, 0.0), order, axis=1)
    pos = jnp.arange(scores.shape[1], dtype=scores.dtype)
    disc = jnp.where(pos < k, 1.0 / jnp.log2(pos + 2.0), 0.0)[None, :]
    dcg = jnp.sum((2.0 ** rel_sorted - 1.0) * disc, axis=1)
    ideal_sorted = -jnp.sort(-jnp.where(mask, rels, 0.0), axis=1)
    idcg = jnp.sum((2.0 ** ideal_sorted - 1.0) * disc, axis=1)
    ndcg = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-10), 0.0)
    # discount of each item at its current model-score rank
    rank = jnp.argsort(jnp.argsort(-neg, axis=1), axis=1).astype(scores.dtype)
    rank_disc = jnp.where(rank < k, 1.0 / jnp.log2(rank + 2.0), 0.0)
    return ndcg, rank_disc, idcg


@register_layer("lambda_cost")
def lambda_cost_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # inputs: [model scores (seq, dim 1), relevance scores (seq, dim 1)]
    out, score = inputs[0], inputs[1]
    s = _hp(out.value)[..., 0]       # [B, T]
    r = _hp(score.value)[..., 0]
    mask = out.seq_mask()
    k = cfg.NDCG_num or 5
    ndcg, disc, idcg = _ndcg_at_k(s, lax.stop_gradient(r), mask, k)

    # LambdaRank pairwise surrogate: grad matches the reference's calcGrad
    # lambdas; spliced in via stop_gradient so forward value stays -NDCG@k.
    pair_mask = (mask[:, :, None] * mask[:, None, :])
    rel_diff = r[:, :, None] - r[:, None, :]
    better = (rel_diff > 0).astype(s.dtype) * pair_mask
    # |ΔNDCG| from swapping i,j at their current ranks
    gain = 2.0 ** r - 1.0
    dg = jnp.abs(
        (gain[:, :, None] - gain[:, None, :]) * (disc[:, :, None] - disc[:, None, :])
    ) / jnp.maximum(idcg, 1e-10)[:, None, None]
    s_diff = s[:, :, None] - s[:, None, :]
    surrogate = jnp.sum(
        lax.stop_gradient(better * dg) * jnp.logaddexp(0.0, -s_diff), axis=(1, 2)
    )
    cost = -ndcg + (surrogate - lax.stop_gradient(surrogate))
    return _finish_cost(cfg, cost, Argument(value=cost), None)


# ------------------------------------------------------------ selective fc


@register_layer("selective_fc")
def selective_fc_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # Data inputs carry parameters; a trailing parameter-less input is the
    # column-selection id set (ref: SelectiveFullyConnectedLayer.cpp — when
    # no selection, behaves exactly like fc).
    n_data = sum(1 for ic in cfg.inputs if ic.input_parameter_name)
    acc: Optional[Array] = None
    for ic, arg in zip(cfg.inputs[:n_data], inputs[:n_data]):
        w = ctx.param(ic.input_parameter_name)
        y = jnp.dot(arg.value, w)
        acc = y if acc is None else acc + y
    sel = inputs[n_data] if len(inputs) > n_data else None
    if cfg.bias_parameter_name:
        acc = acc + ctx.param(cfg.bias_parameter_name)
    meta = inputs[0]
    if sel is not None and sel.ids is not None:
        # mask of selected columns per row: scatter ones at selected ids;
        # variable-size selection sets arrive zero-padded, so drop padded
        # entries via the selection's lengths (else column 0 leaks in)
        onehot = jax.nn.one_hot(sel.ids, cfg.size, dtype=acc.dtype)  # [..., K, size]
        if sel.seq_lengths is not None:
            k_iota = jnp.arange(sel.ids.shape[-1], dtype=jnp.int32)
            valid = (k_iota[None, :] < sel.seq_lengths[:, None]).astype(acc.dtype)
            onehot = onehot * valid[..., None]
        m = jnp.clip(jnp.sum(onehot, axis=-2), 0.0, 1.0)
        if cfg.active_type in ("softmax", "sequence_softmax"):
            logits = jnp.where(m > 0, acc, NEG)
            value = jax.nn.softmax(logits, axis=-1) * m
        else:
            value = apply_activation(cfg.active_type, acc, None) * m
    else:  # no selection: behaves exactly like fc (bias applied above)
        value = apply_activation(cfg.active_type, acc, None)
    return Argument(value=value, seq_lengths=meta.seq_lengths,
                    sub_seq_lengths=meta.sub_seq_lengths)
