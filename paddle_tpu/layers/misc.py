"""Elementwise / miscellaneous layers.

Reference counterparts in /root/reference/paddle/gserver/layers/:
InterpolationLayer, PowerLayer, ScalingLayer, SlopeInterceptLayer,
SumToOneNormLayer, ConvexCombinationLayer, CosSimLayer, CosSimVecMatLayer,
OuterProdLayer, ConvShiftLayer, MultiplexLayer, DataNormLayer,
HierarchicalSigmoidLayer, NCELayer.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import (
    LayerContext,
    finalize_output,
    first_seq_meta,
    input_mask,
    register_layer,
    with_seq_meta,
)
from paddle_tpu.proto import LayerConfig

Array = jax.Array
_EPS = 1e-10


@register_layer("interpolation")
def interpolation_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # out = w * x + (1 - w) * y ; w is [B, 1]
    w, x, y = inputs[0].value, inputs[1].value, inputs[2].value
    out = w * x + (1.0 - w) * y
    meta = first_seq_meta(inputs[1:])
    return with_seq_meta(meta, out)


@register_layer("power")
def power_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # out = x ^ w ; w is [B, 1] scalar exponent per sample
    w, x = inputs[0].value, inputs[1].value
    out = jnp.power(jnp.clip(x, _EPS, None), w)
    return with_seq_meta(inputs[1], out)


@register_layer("scaling")
def scaling_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # out = w * x ; w is [B, 1] per-sample scale
    w, x = inputs[0].value, inputs[1].value
    return with_seq_meta(inputs[1], w * x)


@register_layer("slope_intercept")
def slope_intercept_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    out = cfg.slope * inputs[0].value + cfg.intercept
    return with_seq_meta(inputs[0], out)


@register_layer("sum_to_one_norm")
def sum_to_one_norm_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    x = inputs[0].value
    s = jnp.sum(x, axis=-1, keepdims=True)
    return with_seq_meta(inputs[0], x / jnp.where(jnp.abs(s) < _EPS, 1.0, s))


@register_layer("convex_comb")
def convex_comb_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ConvexCombinationLayer — inputs: (weights [B, M], vectors
    # [B, M*size]); out[b] = sum_m w[b,m] * v[b,m,:].
    w, v = inputs[0].value, inputs[1].value
    M = w.shape[-1]
    vv = v.reshape(v.shape[0], M, cfg.size)
    out = jnp.einsum("bm,bmd->bd", w, vv)
    return Argument(value=out)


@register_layer("cos")
def cos_sim_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    x, y = inputs[0].value, inputs[1].value
    dot = jnp.sum(x * y, axis=-1, keepdims=True)
    nx = jnp.linalg.norm(x, axis=-1, keepdims=True)
    ny = jnp.linalg.norm(y, axis=-1, keepdims=True)
    out = cfg.cos_scale * dot / jnp.clip(nx * ny, _EPS, None)
    meta = first_seq_meta(inputs)
    return with_seq_meta(meta, out)


@register_layer("cos_vm")
def cos_vec_mat_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: CosSimVecMatLayer — cosine of a vector against each row of a
    # matrix input: x [B, D], m [B, K*D] → out [B, K].
    x, m = inputs[0].value, inputs[1].value
    K = cfg.size
    D = x.shape[-1]
    mm = m.reshape(m.shape[0], K, D)
    dot = jnp.einsum("bd,bkd->bk", x, mm)
    nx = jnp.linalg.norm(x, axis=-1, keepdims=True)
    nm = jnp.linalg.norm(mm, axis=-1)
    out = cfg.cos_scale * dot / jnp.clip(nx * nm, _EPS, None)
    return Argument(value=out)


@register_layer("out_prod")
def outer_prod_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    x, y = inputs[0].value, inputs[1].value
    out = jnp.einsum("bi,bj->bij", x, y).reshape(x.shape[0], -1)
    return Argument(value=out)


@register_layer("conv_shift")
def conv_shift_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ConvShiftLayer — circular convolution (NTM-style shift):
    # out[i] = sum_j a[(i + j - (K-1)/2) mod D] * b[j], b of odd width K.
    a, b = inputs[0].value, inputs[1].value
    D, K = a.shape[-1], b.shape[-1]
    half = (K - 1) // 2
    idx = (jnp.arange(D)[:, None] + jnp.arange(K)[None, :] - half) % D  # [D, K]
    gathered = a[:, idx]  # [B, D, K]
    out = jnp.einsum("bdk,bk->bd", gathered, b)
    return Argument(value=out)


@register_layer("multiplex")
def multiplex_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: MultiplexLayer — first input: index ids choosing which of the
    # remaining inputs supplies each row.
    sel = inputs[0].ids
    stacked = jnp.stack([a.value for a in inputs[1:]], axis=0)  # [N, B, D]
    out = jnp.take_along_axis(stacked, sel[None, :, None], axis=0)[0]
    return Argument(value=out)


@register_layer("data_norm")
def data_norm_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: DataNormLayer — normalize features with precomputed stats held
    # in the (static) input parameter, strategies z-score/min-max/decimal.
    x = inputs[0].value
    stats = ctx.param(cfg.inputs[0].input_parameter_name).reshape(5, cfg.size)
    # rows: min, max, sum, sum_of_squares, count (reference layout)
    mn, mx, sm, ssq, cnt = stats
    cnt = jnp.clip(cnt, 1.0, None)
    mean = sm / cnt
    std = jnp.sqrt(jnp.clip(ssq / cnt - mean * mean, _EPS, None))
    strat = cfg.data_norm_strategy
    if strat == "z-score":
        out = (x - mean) / std
    elif strat == "min-max":
        out = (x - mn) / jnp.clip(mx - mn, _EPS, None)
    else:  # decimal-scaling
        out = x / jnp.clip(jnp.power(10.0, jnp.ceil(jnp.log10(jnp.clip(jnp.abs(mx), 1.0, None)))), 1.0, None)
    return with_seq_meta(inputs[0], out)


@register_layer("hsigmoid")
def hsigmoid_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    """Hierarchical sigmoid cost (ref: HierarchicalSigmoidLayer.cpp +
    math/MatrixBitCode.cpp): binary-code decomposition of a num_classes
    softmax; cost only (generation path not reproduced).

    Code of class c: bits of (c + num_classes) below the MSB, walked from
    the bit under the MSB downward; node index for bit j is
    (c + num_classes) >> (j+1) minus 1... following the reference's
    simplified arithmetic: idx_j = ((c + num_classes) >> (j + 1)) - 1.
    """
    label = inputs[-1]
    feats = inputs[:-1]
    num_classes = cfg.num_classes
    code_len = max(1, (num_classes - 1).bit_length())
    c = (label.ids if label.ids is not None else jnp.argmax(label.value, -1)).astype(jnp.int32)
    code = c + num_classes
    js = jnp.arange(code_len, dtype=jnp.int32)
    node = (code[:, None] >> (js[None, :] + 1)) - 1        # [B, L]
    bit = ((code[:, None] >> js[None, :]) & 1).astype(jnp.float32)
    valid = (node >= 0).astype(jnp.float32)
    node_c = jnp.clip(node, 0, num_classes - 2)
    acc = jnp.zeros(bit.shape, jnp.float32)
    for in_cfg, f in zip(cfg.inputs[:-1], feats):
        # gather the path rows from the master-dtype table (casting the
        # whole [num_classes-1, D] table per step would be an HBM-bound
        # full pass); the cost is an f32 island anyway
        w = ctx.param(in_cfg.input_parameter_name, cast=False)
        acc = acc + jnp.einsum("bd,bld->bl", f.value, w[node_c])
    if cfg.bias_parameter_name:
        b = ctx.param(cfg.bias_parameter_name, cast=False).reshape(-1)
        acc = acc + b[node_c]
    # per-node binary CE: bit=1 ⇒ -log sigmoid(acc) ... reference sums
    # -log(sigmoid) over the path with sign from the bit.
    per_node = jnp.logaddexp(0.0, acc) - bit * acc
    cost = jnp.sum(per_node * valid, axis=1)
    return Argument(value=cost[:, None])


@register_layer("nce")
def nce_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    """Noise-contrastive estimation cost (ref: NCELayer.cpp).

    inputs: feature(s) + label (+ optional per-sample weight). Samples
    num_neg_samples negatives from neg_sampling_dist (or uniform).
    """
    # feature inputs are exactly those with a parameter attached
    # (reference NCELayer.cpp:80-84: label then optional weight follow)
    n_feat = sum(1 for ic in cfg.inputs if ic.input_parameter_name)
    feats = inputs[:n_feat]
    label = inputs[n_feat]
    weight = inputs[n_feat + 1] if len(inputs) > n_feat + 1 else None
    num_classes = cfg.num_classes
    k = cfg.num_neg_samples
    pos = (label.ids if label.ids is not None else jnp.argmax(label.value, -1)).astype(jnp.int32)
    B = pos.shape[0]
    rng = ctx.layer_rng(cfg.name, "nce")
    if cfg.neg_sampling_dist:
        dist = jnp.asarray(cfg.neg_sampling_dist)
        logits = jnp.log(jnp.clip(dist, _EPS, None))
        neg = jax.random.categorical(rng, logits, shape=(B, k)).astype(jnp.int32)
        p_noise = dist
    else:
        neg = jax.random.randint(rng, (B, k), 0, num_classes, jnp.int32)
        p_noise = jnp.full((num_classes,), 1.0 / num_classes)
    samples = jnp.concatenate([pos[:, None], neg], axis=1)  # [B, 1+k]
    acc = jnp.zeros((B, 1 + k), jnp.float32)
    for in_cfg, f in zip(cfg.inputs[: len(feats)], feats):
        # gather sampled rows from the master-dtype table — NCE's whole
        # point is avoiding O(vocab) work, so never cast the full table
        w = ctx.param(in_cfg.input_parameter_name, cast=False)
        acc = acc + jnp.einsum("bd,bkd->bk", f.value, w[samples])
    if cfg.bias_parameter_name:
        b = ctx.param(cfg.bias_parameter_name, cast=False).reshape(-1)
        acc = acc + b[samples]
    log_kp = jnp.log(k * jnp.clip(p_noise[samples], _EPS, None))
    delta = acc - log_kp  # logit of P(data | sample)
    labels01 = jnp.concatenate([jnp.ones((B, 1)), jnp.zeros((B, k))], axis=1)
    per = jnp.logaddexp(0.0, delta) - labels01 * delta
    cost = jnp.sum(per, axis=1)
    if weight is not None and weight.value is not None:
        cost = cost * weight.value.reshape(cost.shape)
    return Argument(value=cost[:, None])
