"""Multi-head self-attention layer with optional context parallelism.

A TPU extension beyond the 2016 reference (whose only attention is the
additive simple_attention inside recurrent groups,
/root/reference/python/paddle/trainer_config_helpers/networks.py:943):
transformer-style scaled-dot-product attention over a padded sequence
[B, T, D], with the context dimension shardable across chips — the layer
dispatches to ring / all-to-all attention (paddle_tpu.parallel.
sequence_parallel) when the active mesh has a "seq" axis.

Parameters: ``_<name>.wqkv`` [D, 3·H·Dh] fused projection, ``_<name>.wo``
[H·Dh, D] output projection.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, register_layer, finalize_output, with_seq_meta
from paddle_tpu.proto import LayerConfig

Array = jax.Array


@register_layer("multi_head_attention")
def multi_head_attention(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    from paddle_tpu.parallel.sequence_parallel import (
        alltoall_attention,
        full_attention,
        ring_attention,
    )

    arg = inputs[0]
    assert arg.is_seq and arg.value is not None, (
        f"{cfg.name}: multi_head_attention needs a dense sequence input"
    )
    x = arg.value                                   # [B, T, D]
    B, T, D = x.shape
    H = max(cfg.num_heads, 1)
    model_dim = cfg.size
    Dh = model_dim // H
    assert H * Dh == model_dim, f"{cfg.name}: size {model_dim} not divisible by heads {H}"

    wqkv = ctx.param(f"_{cfg.name}.wqkv")           # [D, 3·H·Dh]
    wo = ctx.param(f"_{cfg.name}.wo")               # [H·Dh, size_out]
    qkv = jnp.einsum("btd,de->bte", x, wqkv).reshape(B, T, 3, H, Dh)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    lengths = arg.seq_lengths
    causal = cfg.causal_attention
    mesh = ctx.mesh
    mode = cfg.seq_parallel_mode
    if mesh is not None and "seq" in getattr(mesh, "axis_names", ()) and mode != "":
        attn = ring_attention if mode == "ring" else alltoall_attention
        out = attn(q, k, v, mesh, lengths=lengths, causal=causal)
    else:
        out = full_attention(q, k, v, lengths=lengths, causal=causal)
    out = out.reshape(B, T, H * Dh)
    value = jnp.einsum("bte,ed->btd", out, wo)
    value = finalize_output(cfg, value, ctx, mask=arg.seq_mask())
    # zero padded positions so downstream pooling/costs see clean zeros
    # (mask cast keeps bf16 activations bf16)
    value = value * arg.seq_mask(dtype=value.dtype)[..., None]
    return with_seq_meta(arg, value)
