"""Layer forward framework.

TPU-native replacement for the reference's ``Layer`` base
(/root/reference/paddle/gserver/layers/Layer.h:58): instead of stateful
objects with hand-written forward/backward over Matrix, a layer is a pure
function ``(LayerConfig, [Argument], LayerContext) -> Argument``. Backward
comes from jax.grad of the whole graph; bias/activation/dropout
post-processing is shared here (mirroring Layer::forwardActivation /
backwardActivation semantics, including dropout after activation).
"""

from __future__ import annotations

import functools
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.ops.activations import apply_activation
from paddle_tpu.proto import LayerConfig, ModelConfig
from paddle_tpu.utils.error import layer_scope
from paddle_tpu.utils.registry import Registry

Array = jax.Array

LayerFn = Callable[[LayerConfig, List[Argument], "LayerContext"], Argument]
layer_registry: Registry[LayerFn] = Registry("layer type")


class TimeMajorLogits(NamedTuple):
    """Pre-softmax logits of a HOISTED recurrent out-link, kept in the
    vocab projection's native flat [T*B, V] form instead of the
    transposed [B, T, V] view. The fused-CE consumer reduces over V
    directly on this layout and transposes only the tiny [T, B]
    per-step costs — transposing the V-sized tensor itself forced a
    full-tensor relayout copy inside the train step (~0.5 GB/step on
    the NMT flagship, 3.6% of device time in the 2026-08-01 TPU trace:
    %copy.167 bf16[3750,8,32,B] between the projection's {0,1} layout
    and the [B,T,V] consumers)."""

    flat: jax.Array   # [T*B, V]
    T: int
    B: int


def register_layer(*type_names: str):
    return layer_registry.register(*type_names)


@dataclass
class LayerContext:
    """Mutable context threaded through one network forward pass.

    Carries everything the reference's Layer pulled from its members:
    parameter store, pass type, rng, sibling outputs, and (for batch-norm
    style layers) read/write running state.
    """

    params: Dict[str, Array]
    model: ModelConfig
    pass_type: str = "train"                    # train | test | gen
    rng: Optional[Array] = None
    states: Dict[str, Any] = field(default_factory=dict)
    state_updates: Dict[str, Any] = field(default_factory=dict)
    outputs: Dict[str, Argument] = field(default_factory=dict)
    dtype: Any = jnp.float32
    # mixed precision (OptimizationConfig.dtype="bfloat16"): master params
    # and optimizer state stay `dtype` (f32); activations and matmul
    # operands are cast to `compute_dtype` so the MXU runs bf16. Softmax,
    # losses, batch-norm statistics and CRF/CTC recursions stay f32
    # (upcast at their entry points). jax.grad of the cast yields f32
    # parameter gradients automatically (convert_element_type transpose).
    compute_dtype: Any = None
    # data layers feeding ONLY cost layers (regression targets, soft
    # labels, per-sample weights) — their dense values must NOT be
    # narrowed, or the f32 loss island would see pre-rounded targets
    # (GradientMachine computes this set from the graph)
    no_cast_inputs: frozenset = frozenset()
    # device mesh for layers that issue explicit collectives (ring
    # attention); None outside meshed execution
    mesh: Any = None
    # lax.scan unroll factor for recurrent layers/groups
    # (OptimizationConfig.scan_unroll; 1 = no unrolling)
    scan_unroll: int = 1
    # OptimizationConfig.pallas_rnn: lstmemory/gated_recurrent layers use
    # the fused Pallas sequence kernels when shapes/activations allow
    pallas_rnn: bool = False
    # OptimizationConfig.pallas_flat: the kernels take the transpose-free
    # batch-major interface (PADDLE_TPU_PALLAS_FLAT=1 still forces it)
    pallas_flat: bool = False
    # OptimizationConfig.conv_s2d: few-channel 7x7/s2 stem convs rewrite
    # to a space-to-depth 4x4/s1 conv (layers/vision.py _stem_s2d_conv)
    conv_s2d: bool = False
    # OptimizationConfig.conv_stats_mode: 1x1/s1 convs publish their
    # output's per-channel (sum, sumsq, rows) into `conv_stats` — via
    # input-side Gram algebra ("gram", pure XLA) or the fused Pallas
    # matmul kernel ("pallas", ops/pallas_conv1x1_bn); "" = off
    conv_stats_mode: str = ""
    # OptimizationConfig.pallas_decoder: attention-GRU decoder groups
    # run as one fused Pallas launch (graph/fused_decoder.py)
    pallas_decoder: bool = False
    # recurrent-group prologue hoisting (graph/recurrent_group.py
    # _plan_prologue): mixed layer name -> (skip_input_indices,
    # precomputed [B, out] slice) for scan-input projections computed
    # outside the scan; set only on per-step contexts
    mixed_prologue: Optional[Dict[str, Any]] = None
    # NHWC layout side-table (layer name -> [B, H, W, C] array): the conv
    # family publishes its pre-flatten output here and prefers consuming
    # it, so chains of conv/pool/bn/norm skip the per-layer
    # flat->NCHW->NHWC round-trip (XLA does not reliably cancel it; the
    # flat Argument.value stays authoritative and is DCE'd when every
    # consumer took the NHWC view). Recurrent groups build their own
    # context, so entries never cross a scan boundary.
    nhwc: Dict[str, Array] = field(default_factory=dict)
    # pre-softmax logits side-table (layer name -> pre-activation array,
    # OR a TimeMajorLogits wrapper for hoisted recurrent out-links —
    # check isinstance before assuming an array): finalize_output
    # publishes here when the activation is a plain feature-axis softmax,
    # so a downstream multi-class cross-entropy can compute fused
    # log-softmax CE from the logits instead of re-upcasting the
    # materialized probabilities ([B*T, V] f32 traffic at NMT vocab
    # sizes). The softmax output stays authoritative for every other
    # consumer and is DCE'd when only the loss reads it.
    logits: Dict[str, Any] = field(default_factory=dict)
    # fused conv+BN statistics side-table (producer layer name ->
    # (sum [C] f32, sumsq [C] f32, rows)): a 1x1 conv that ran the
    # pallas_conv_stats kernel publishes its output's per-channel
    # statistics here; a downstream batch_norm consuming that layer in
    # training mode uses them instead of re-reading the activation from
    # HBM. The conv output Argument stays authoritative for every other
    # consumer; both come from one custom_vjp call, so gradients through
    # output and statistics compose in its backward.
    conv_stats: Dict[str, Any] = field(default_factory=dict)
    # sparse-embedding prefetch (GradientMachine::prefetch analog): rows
    # pre-gathered outside autodiff, keyed by (param_name, input_layer);
    # the table projection returns these instead of gathering, so
    # jax.grad yields row gradients, never a dense [V, D] scatter
    table_overrides: Optional[Dict[Any, Array]] = None
    # enclosing scope for recurrent-group steps: group-ENTRY resolution
    # (static links, memory boot layers, nested-group in-links) may walk
    # up this chain; ordinary layer-input lookup deliberately cannot, so
    # referencing an outer sequence without StaticInput stays an error
    parent: Optional["LayerContext"] = None
    # generation-capture sink (graph/decode_step.py): when a dict is
    # supplied, a generator recurrent group stores its prepared decode
    # inputs (static-link Arguments, unexpanded memory boots) here and
    # SKIPS the beam-search loop — the serving engine's prefill path,
    # which scatters the captured state into slot buffers and then
    # drives per-step decode launches itself
    gen_capture: Optional[Dict[str, Any]] = None

    @property
    def is_training(self) -> bool:
        return self.pass_type == "train"

    def param(self, name: str, cast: bool = True) -> Array:
        try:
            v = self.params[name]
        except KeyError:
            known = ", ".join(sorted(self.params))
            raise KeyError(f"parameter {name!r} not found (have: {known})") from None
        if cast and self.compute_dtype is not None and jnp.issubdtype(v.dtype, jnp.floating):
            v = v.astype(self.compute_dtype)
        return v

    def cast_compute(self, x: Optional[Array]) -> Optional[Array]:
        """Cast a float activation to the compute dtype (no-op otherwise)."""
        if (
            x is not None
            and self.compute_dtype is not None
            and jnp.issubdtype(x.dtype, jnp.floating)
            and x.dtype != self.compute_dtype
        ):
            return x.astype(self.compute_dtype)
        return x

    def layer_rng(self, layer_name: str, salt: str = "") -> Array:
        assert self.rng is not None, "LayerContext.rng not set but layer needs randomness"
        return jax.random.fold_in(self.rng, zlib.crc32(f"{layer_name}/{salt}".encode()))


def input_mask(arg: Argument) -> Optional[Array]:
    """[B, T] float validity mask if arg is a sequence, else None."""
    if arg.is_nested_seq:
        return arg.sub_seq_mask()
    if arg.is_seq:
        return arg.seq_mask()
    return None


def finalize_output(
    cfg: LayerConfig,
    value: Array,
    ctx: LayerContext,
    mask: Optional[Array] = None,
) -> Array:
    """Shared bias + activation + dropout tail of a layer forward."""
    if cfg.bias_parameter_name:
        value = value + ctx.param(cfg.bias_parameter_name)
    # dropout after softmax would make the probabilities the only honest
    # source, so the logits view is published only for dropout-free layers
    if cfg.active_type == "softmax" and not cfg.drop_rate:
        ctx.logits[cfg.name] = value
    value = apply_activation(cfg.active_type, value, mask)
    if cfg.drop_rate > 0.0 and ctx.is_training:
        keep = 1.0 - cfg.drop_rate
        rng = ctx.layer_rng(cfg.name, "dropout")
        m = jax.random.bernoulli(rng, keep, value.shape)
        # inverted dropout (scale at train time) — reference scales at train
        # time too (Layer.cpp forwardDropOut divides by (1 - drop_rate)).
        value = jnp.where(m, value / keep, 0.0)
    return value


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _clip_error(x, t):
    """Identity forward; backward clips the cotangent to [-t, t] — the
    reference's per-layer error clipping (Layer.cpp backwardActivation
    errorClip on the output gradient, configured by
    ExtraAttr(error_clipping_threshold))."""
    return x


def _clip_error_fwd(x, t):
    return x, None


def _clip_error_bwd(t, _, g):
    return (jnp.clip(g, -t, t),)


_clip_error.defvjp(_clip_error_fwd, _clip_error_bwd)


def forward_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    fn = layer_registry.get(cfg.type)
    with layer_scope(f"{cfg.name}({cfg.type})"):
        out = fn(cfg, inputs, ctx)
    if cfg.error_clipping_threshold > 0 and out.value is not None:
        out = out.replace(
            value=_clip_error(out.value, float(cfg.error_clipping_threshold))
        )
        # a published NHWC or logits view would bypass the clip wrapper —
        # drop them so every consumer goes through the clipped value
        ctx.nhwc.pop(cfg.name, None)
        ctx.logits.pop(cfg.name, None)
        ctx.conv_stats.pop(cfg.name, None)
    ctx.outputs[cfg.name] = out
    return out


def first_seq_meta(inputs: List[Argument]) -> Argument:
    """Propagate sequence metadata from the first sequence input."""
    for a in inputs:
        if a.is_seq or a.is_nested_seq:
            return a
    return inputs[0] if inputs else Argument()


def with_seq_meta(template: Argument, value: Array) -> Argument:
    return Argument(
        value=value,
        seq_lengths=template.seq_lengths,
        sub_seq_lengths=template.sub_seq_lengths,
    )
