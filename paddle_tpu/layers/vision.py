"""Vision layers: conv, pool, batch-norm, response norm, block expand.

Reference counterparts: ExpandConvLayer.cpp (im2col conv), CudnnConvLayer,
PoolLayer/CudnnPoolLayer, BatchNormalizationLayer/CudnnBatchNormLayer,
NormProjectionLayer (cross-map LRN), BlockExpandLayer, ResizeLayer,
FeatureMapExpandLayer in /root/reference/paddle/gserver/layers/.

Data contract matches the reference: images flow between layers as
flattened NCHW rows [B, C*H*W]. Internally we reshape to NHWC and use
``lax.conv_general_dilated`` / ``lax.reduce_window`` so XLA tiles the MXU
directly — no im2col materialization.

Weight layout (set by our config_parser): conv filters are stored flat as
[num_filters, filter_channels * fh * fw], reshaped here to HWIO.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, register_layer
from paddle_tpu.ops.activations import apply_activation
from paddle_tpu.ops.precision import hp
from paddle_tpu.proto import ConvConfig, LayerConfig, OperatorConfig

Array = jax.Array


def conv_output_size(img: int, filter_size: int, padding: int, stride: int, caffe_mode: bool) -> int:
    # ref: paddle/math/MathUtils.cpp outputSize
    if caffe_mode:
        return (img - filter_size + 2 * padding) // stride + 1
    return (img - filter_size + 2 * padding + stride - 1) // stride + 1


def _nchw_to_nhwc(x: Array, channels: int, h: int, w: int) -> Array:
    return x.reshape(x.shape[0], channels, h, w).transpose(0, 2, 3, 1)


def _nhwc_to_flat(x: Array) -> Array:
    return x.transpose(0, 3, 1, 2).reshape(x.shape[0], -1)


from paddle_tpu.ops.activations import is_elementwise


def _take_nhwc(ctx: LayerContext, input_layer_name: str, arg, channels: int,
               h: int, w: int) -> Array:
    """The producer's published NHWC view when shapes agree, else convert
    from the flat NCHW value (see LayerContext.nhwc)."""
    x = ctx.nhwc.get(input_layer_name)
    if x is not None and x.shape[1:] == (h, w, channels):
        return x
    return _nchw_to_nhwc(arg.value, channels, h, w)


def _dropout(ctx: LayerContext, cfg: LayerConfig, x: Array) -> Array:
    if cfg.drop_rate > 0.0 and ctx.is_training:
        keep = 1.0 - cfg.drop_rate
        m = jax.random.bernoulli(ctx.layer_rng(cfg.name, "dropout"), keep, x.shape)
        x = jnp.where(m, x / keep, 0.0)
    return x


def _publish_nhwc(ctx: LayerContext, cfg: LayerConfig, y_nhwc: Array) -> Argument:
    """Publish the NHWC view for downstream conv-family layers and return
    the flat Argument (DCE'd by XLA if every consumer took the view)."""
    ctx.nhwc[cfg.name] = y_nhwc
    return Argument(value=_nhwc_to_flat(y_nhwc))


def _conv2d(x_nhwc: Array, w_hwio: Array, stride: Tuple[int, int], padding, groups: int) -> Array:
    # bf16 in/out is safe on TPU: the MXU accumulates partial products in
    # f32 internally regardless of the result dtype, so no explicit
    # preferred_element_type (which this JAX's conv transpose rejects for
    # mixed bf16-operand/f32-cotangent pairs).
    return lax.conv_general_dilated(
        x_nhwc,
        w_hwio,
        window_strides=stride,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )


def _stem_s2d_conv(x: Array, w_hwio: Array) -> Array:
    """Space-to-depth rewrite of the 7x7/stride-2/pad-3 few-channel stem
    conv (the ResNet conv1): C=3 wastes the MXU's 128-deep contraction,
    so re-express the conv EXACTLY as a 4x4/stride-1 VALID conv over a
    2x2 space-to-depth view with 4C input channels (the MLPerf trick).

    Derivation: with x padded (4, 2) per spatial dim and the kernel
    zero-padded 7→8 at the FRONT, y[i,j] = Σ_{u',v'<8} w'[u',v']
    xp[2i+u', 2j+v']; substituting u' = 2α+a turns the sum into a 4x4
    conv over X[i,j,(a,b,c)] = xp[2i+a, 2j+b, c]. Summation order aside,
    this is the same arithmetic (parity pinned in tests/test_s2d.py)."""
    B, H, W, C = x.shape
    O = w_hwio.shape[-1]
    xp = jnp.pad(x, ((0, 0), (4, 2), (4, 2), (0, 0)))
    Hp, Wp = H + 6, W + 6
    X = (
        xp.reshape(B, Hp // 2, 2, Wp // 2, 2, C)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(B, Hp // 2, Wp // 2, 4 * C)
    )
    w8 = jnp.pad(w_hwio, ((1, 0), (1, 0), (0, 0), (0, 0)))  # 7→8, zero row/col FIRST
    w4 = (
        w8.reshape(4, 2, 4, 2, C, O)
        .transpose(0, 2, 1, 3, 4, 5)
        .reshape(4, 4, 4 * C, O)
    )
    return _conv2d(X, w4, (1, 1), "VALID", 1)


def _stem_s2d_applies(ctx, cc, fy, sy, py, h, w) -> bool:
    return (
        ctx.conv_s2d
        and cc.channels <= 4
        and fy == cc.filter_size == 7
        and sy == cc.stride == 2
        and py == cc.padding == 3
        and cc.groups == 1
        and h % 2 == 0
        and w % 2 == 0
    )


def _fused_stats_gates(cfg: LayerConfig, ctx: LayerContext,
                       allow_stride: bool = False):
    """Shared eligibility gate for BOTH fused conv+BN-statistics modes:
    single-input 1x1/p0 ungrouped conv whose output is exactly what a
    downstream batch_norm would reduce — identity activation, no
    dropout, shared (or no) bias — in a training pass. Returns the conv
    input config, or None. ``allow_stride`` admits strided 1x1 convs
    (resnet downsample projections): a stride-s 1x1/p0 conv is a matmul
    over the ::s-sliced input, so input-side statistics stay exact —
    but only under caffe-mode output sizing, where the conv output rows
    are exactly the ceil(img/s) slice positions."""
    if not ctx.is_training or len(cfg.inputs) != 1:
        return None
    in_cfg = cfg.inputs[0]
    cc = in_cfg.conv_conf
    fy = cc.filter_size_y or cc.filter_size
    sy = cc.stride_y or cc.stride
    py = cc.padding_y if cc.padding_y >= 0 else cc.padding
    stride_ok = (
        sy == 1 and cc.stride == 1
        or (allow_stride and cc.caffe_mode and sy >= 1 and cc.stride >= 1)
    )
    if not (fy == 1 and cc.filter_size == 1 and stride_ok
            and py == 0 and cc.padding == 0 and cc.groups == 1):
        return None
    if cfg.active_type not in ("", "linear") or cfg.drop_rate > 0.0:
        return None
    if cfg.bias_parameter_name and not cfg.shared_biases:
        return None
    return in_cfg


def _conv1x1_stats_forward(cfg: LayerConfig, inputs: List[Argument],
                           ctx: LayerContext):
    """1x1/s1 conv through the fused matmul + BN-statistics Pallas kernel
    (ops/pallas_conv1x1_bn): publishes per-channel (sum, sumsq, rows) into
    ctx.conv_stats so a downstream batch_norm skips its statistics pass's
    full HBM re-read of this output. Returns None whenever any gate fails
    — the caller falls through to the XLA conv, identical semantics.

    Measured end-to-end LOSER on v5e (doc/performance.md round-5
    conv-stats A/B: layout-boundary copies); kept as the
    conv_stats_mode="pallas" A/B knob. Gates beyond the shared ones
    mirror the fused-RNN path (layers/recurrent.py): single-device only
    (no GSPMD partitioning rule for the custom call), TPU backend or
    forced interpret mode, and kernel shape/VMEM support.
    """
    import os

    if ctx.mesh is not None:
        return None
    in_cfg = _fused_stats_gates(cfg, ctx)
    if in_cfg is None:
        return None
    cc = in_cfg.conv_conf
    on_tpu = jax.default_backend() == "tpu"
    force_interpret = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET") == "1"
    if not (on_tpu or force_interpret):
        return None
    from paddle_tpu.ops import pallas_conv1x1_bn as pcb

    h = w = cc.img_size
    x = _take_nhwc(ctx, in_cfg.input_layer_name, inputs[0], cc.channels, h, w)
    B = x.shape[0]
    M, K, N = B * h * w, cc.channels, cfg.num_filters
    if not pcb.supported(M, K, N, x.dtype.itemsize):
        return None
    wf = ctx.param(in_cfg.input_parameter_name).reshape(N, K)
    if cfg.bias_parameter_name:
        b = ctx.param(cfg.bias_parameter_name).reshape(N).astype(x.dtype)
    else:
        b = jnp.zeros((N,), x.dtype)
    y2, s, q = pcb.conv1x1_stats(x.reshape(M, K), wf.T, b, force_interpret)
    ctx.conv_stats[cfg.name] = (s, q, M)
    return _publish_nhwc(ctx, cfg, y2.reshape(B, h, w, N))


def _gram_stats_gates(cfg: LayerConfig, ctx: LayerContext):
    """Gate for input-side Gram statistics: the shared fused-stats gate
    plus a stride-dependent width ratio (N >= 2K at stride 1, N >= 4K
    strided — derivation at the check below). Unlike the pallas path
    this is pure XLA (any backend, works under a mesh — the reduces
    shard like BN's own), and only worthwhile when the output is
    sufficiently wider than the input (resnet expand convs are N = 4K;
    its stride-2 downsample projections are N = 2K and stay on the
    direct path)."""
    in_cfg = _fused_stats_gates(cfg, ctx, allow_stride=True)
    if in_cfg is None:
        return None
    cc = in_cfg.conv_conf
    strided = cc.stride_y > 1 or cc.stride > 1
    # break-even math: the stats-side reads x (or its ::s slice) TWICE
    # vs the saved single read of y. Stride 1: 2*M*K vs M*N -> N >= 2K.
    # Stride s: the slice has the SAME row count as y, so 2*M_out*K vs
    # M_out*N breaks even at N = 2K exactly (resnet downsample
    # projections are all N = 2K, plus strided reads waste cache lines)
    # -> require N >= 4K so strided convs only engage at a clear win.
    need = 4 if strided else 2
    if cfg.num_filters < need * cc.channels:
        return None
    return in_cfg


def _publish_gram_stats(cfg: LayerConfig, ctx: LayerContext, x_nhwc: Array,
                        w2: Array, bias) -> None:
    """Per-channel sum/sumsq of y = x@w + b computed from the INPUT side:

        sum_m(y)   = colsum(x) @ w + M*b
        sum_m(y^2) = diag(w^T (x^T x) w) + 2*b*(colsum(x) @ w) + M*b^2

    exact algebra (associativity aside), so the BN stats pass never has
    to re-read y from HBM — it reads x twice (colsum + Gram) instead,
    a win at the _gram_stats_gates width ratios and FREE when no
    batch_norm consumes the entry
    (XLA dead-code-eliminates the unused reduces). All plain jnp ops:
    autodiff composes the stats' gradient with the conv's naturally, and
    XLA keeps its own conv layouts — the measured failure mode of the
    pallas variant (doc/performance.md round-5 conv-stats A/B).

    Semantics note: these are statistics of the UNROUNDED x@w (the
    activation-dtype path reduces the bf16-rounded y) — a ~1e-3-relative
    difference on the mean, inside BN's own eps regime; the parity test
    pins it (tests/test_conv_stats.py).
    """
    f32 = jnp.float32
    M = x_nhwc.shape[0] * x_nhwc.shape[1] * x_nhwc.shape[2]
    cs = jnp.sum(x_nhwc, axis=(0, 1, 2), dtype=f32)          # [K]
    gram = jnp.einsum("bhwk,bhwl->kl", x_nhwc, x_nhwc,
                      preferred_element_type=f32)            # [K, K]
    w32 = w2.astype(f32)
    csw = cs @ w32                                           # [N]
    s = csw
    q = jnp.einsum("kn,kl,ln->n", w32, gram, w32)
    if bias is not None:
        b32 = bias.astype(f32)
        s = s + M * b32
        q = q + 2.0 * b32 * csw + M * jnp.square(b32)
    ctx.conv_stats[cfg.name] = (s, q, M)


def _conv_forward(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    if ctx.conv_stats_mode == "pallas":
        out = _conv1x1_stats_forward(cfg, inputs, ctx)
        if out is not None:
            return out
    gram_in = (
        _gram_stats_gates(cfg, ctx) if ctx.conv_stats_mode == "gram" else None
    )
    acc = None
    for in_cfg, arg in zip(cfg.inputs, inputs):
        cc = in_cfg.conv_conf
        h = w = cc.img_size
        fy = cc.filter_size_y or cc.filter_size
        sy = cc.stride_y or cc.stride
        py = cc.padding_y if cc.padding_y >= 0 else cc.padding
        x = _take_nhwc(ctx, in_cfg.input_layer_name, arg, cc.channels, h, w)
        wf = ctx.param(in_cfg.input_parameter_name)
        wf = wf.reshape(cfg.num_filters, cc.filter_channels, fy, cc.filter_size)
        w_hwio = wf.transpose(2, 3, 1, 0)  # OIHW → HWIO
        if gram_in is not None:
            # a strided 1x1/p0 conv only ever reads the ::s positions —
            # statistics of the sliced view are exact (the slice fuses
            # into the stats reduces; nothing materializes)
            x_stats = x[:, ::sy, ::cc.stride, :] if (sy > 1 or cc.stride > 1) else x
            gram_operands = (x_stats, w_hwio.reshape(cc.channels, cfg.num_filters))
        if _stem_s2d_applies(ctx, cc, fy, sy, py, h, w):
            y = _stem_s2d_conv(x, w_hwio)
        else:
            y = _conv2d(x, w_hwio, (sy, cc.stride), [(py, py), (cc.padding, cc.padding)], cc.groups)
        acc = y if acc is None else acc + y
    if gram_in is not None:
        bias = (
            ctx.param(cfg.bias_parameter_name)
            if cfg.bias_parameter_name
            else None
        )
        _publish_gram_stats(cfg, ctx, *gram_operands,
                            bias.reshape(cfg.num_filters) if bias is not None else None)
    if cfg.bias_parameter_name:
        b = ctx.param(cfg.bias_parameter_name)
        if cfg.shared_biases:
            acc = acc + b.reshape(1, 1, 1, cfg.num_filters)
        else:
            # flat layout is filter-major [F, H, W] (reference
            # addUnsharedBias over NCHW rows) — transpose into NHWC
            b_hwf = b.reshape(cfg.num_filters, acc.shape[1], acc.shape[2]).transpose(1, 2, 0)
            acc = acc + b_hwf[None]
    if not is_elementwise(cfg.active_type):
        out = apply_activation(cfg.active_type, _nhwc_to_flat(acc))
        out = _dropout(ctx, cfg, out)
        return Argument(value=out)
    acc = _dropout(ctx, cfg, apply_activation(cfg.active_type, acc))
    return _publish_nhwc(ctx, cfg, acc)


register_layer("conv", "exconv", "cudnn_conv")(_conv_forward)


def conv_operator_forward(op: OperatorConfig, inputs: List[Argument]) -> Array:
    """ConvOperator in a mixed layer: conv(image_input, filter_input).

    ref: ConvOperator.cpp — the second input *is* the filter values
    (dynamic filters), used e.g. for spatial attention.
    """
    cc = op.conv_conf
    x = _nchw_to_nhwc(inputs[0].value, cc.channels, cc.img_size, cc.img_size)
    B = x.shape[0]
    wf = inputs[1].value.reshape(B, op.num_filters, cc.filter_channels, cc.filter_size, cc.filter_size)

    def one(xi, wi):
        return _conv2d(
            xi[None],
            wi.transpose(2, 3, 1, 0),
            (cc.stride, cc.stride),
            [(cc.padding, cc.padding), (cc.padding, cc.padding)],
            cc.groups,
        )[0]

    y = jax.vmap(one)(x, wf)
    return _nhwc_to_flat(y)


@register_layer("pool")
def pool_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    pc = cfg.inputs[0].pool_conf
    h = pc.img_size_y or pc.img_size
    w = pc.img_size
    ky = pc.size_y or pc.size_x
    sy = pc.stride_y or pc.stride
    py = pc.padding_y or pc.padding
    x = _take_nhwc(ctx, cfg.inputs[0].input_layer_name, inputs[0], pc.channels, h, w)
    window = (1, ky, pc.size_x, 1)
    strides = (1, sy, pc.stride, 1)
    # the config declares ceil-mode output sizes (reference outputSize with
    # caffeMode=false); extend the high-edge padding so the last window fits
    oy = pc.output_y or pc.output_x
    ox = pc.output_x
    hi_y = max(0, (oy - 1) * sy + ky - h - py)
    hi_x = max(0, (ox - 1) * pc.stride + pc.size_x - w - pc.padding)
    pads = ((0, 0), (py, hi_y), (pc.padding, hi_x), (0, 0))
    kind = pc.pool_type
    # in-image element count per window, computed in numpy at trace time
    # (all static): a full-shape reduce_window over ones compiles to an
    # O(B*C*H*W*window) constant-fold inside XLA — minutes at B=256 — for
    # what is really an [out_y] x [out_x] outer product. A ceil-mode
    # window can land entirely in padding — guard those outputs to 0.
    def _axis_counts(n_out, stride, pad, k, img):
        starts = np.arange(n_out) * stride - pad
        return np.clip(np.minimum(starts + k, img) - np.maximum(starts, 0), 0, None)

    counts = jnp.asarray(
        np.outer(_axis_counts(oy, sy, py, ky, h),
                 _axis_counts(ox, pc.stride, pc.padding, pc.size_x, w))
        [None, :, :, None],
        dtype=x.dtype,
    )
    if "max" in kind:
        y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        y = jnp.where(counts > 0, y, 0.0)
    else:
        # avg pooling divides each window by its *in-image* area (reference
        # avgPoolForward clips hstart/hend to the image before dividing)
        y = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        y = y / jnp.maximum(counts, 1.0)
    if not is_elementwise(cfg.active_type):
        return Argument(value=apply_activation(cfg.active_type, _nhwc_to_flat(y)))
    return _publish_nhwc(ctx, cfg, apply_activation(cfg.active_type, y))


@register_layer("batch_norm", "cudnn_batch_norm")
def batch_norm_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    """ref: BatchNormalizationLayer.cpp.

    inputs[0] carries the data plus an ImageConfig; per-channel gamma is the
    input parameter, beta the bias parameter; moving mean/var live in params
    as the 2nd/3rd input parameters (is_static) and are updated through
    ``ctx.state_updates`` with moving_average_fraction.
    """
    ic = cfg.inputs[0].image_conf
    a = inputs[0]
    x = a.value
    seq_meta = {}
    if a.is_seq:
        seq_meta = dict(seq_lengths=a.seq_lengths)
        B, T, D = x.shape
        x = x.reshape(B * T, D)
    x_nhwc = None
    if ic is not None and ic.img_size > 0:
        C, hw = ic.channels, ic.img_size * ic.img_size
        if not a.is_seq:
            # NHWC flattens to per-pixel rows of C directly — same row
            # set as the NCHW transpose dance, so identical statistics
            x_nhwc = _take_nhwc(ctx, cfg.inputs[0].input_layer_name, a,
                                C, ic.img_size, ic.img_size)
            xr = x_nhwc.reshape(-1, C)
        else:
            xr = x.reshape(x.shape[0], C, hw).transpose(0, 2, 1).reshape(-1, C)
    else:
        C = cfg.size
        xr = x
    # STATISTICS run in f32 even when activations are bf16 (bf16 mean/var
    # over big batches is too lossy), but the full-size activation is never
    # upcast: the reductions accumulate in f32 directly over the bf16 rows
    # (XLA fuses the widening convert into the reduce) and the NORMALIZATION
    # applies as a per-channel scale/offset in the activation dtype. The
    # previous hp(xr)-then-normalize-in-f32 formulation materialized f32
    # copies/reshapes of every BN input — ~60% of the ResNet-50 bf16 step's
    # device time on TPU (see benchmarks/RESULTS.md round-4 trace analysis).
    # gamma/beta/running stats are master-dtype params (cast=False).
    gamma = ctx.param(cfg.inputs[0].input_parameter_name, cast=False).reshape(C)
    beta = (
        ctx.param(cfg.bias_parameter_name, cast=False).reshape(C)
        if cfg.bias_parameter_name
        else None
    )
    mean_name = cfg.inputs[1].input_parameter_name
    var_name = cfg.inputs[2].input_parameter_name
    eps = 1e-5
    use_global = cfg.use_global_stats or not ctx.is_training
    if use_global:
        mean = ctx.params[mean_name].reshape(C)
        var = ctx.params[var_name].reshape(C)
        centered = (hp(xr) - mean).astype(xr.dtype)
    else:
        # at-least-f32 accumulation (f64 under the x64 gradient check)
        acc_dt = jnp.promote_types(xr.dtype, jnp.float32)
        # fused statistics (conv_stats_mode): a 1x1 conv feeding this BN
        # already published sum/sumsq — from its matmul epilogue
        # ("pallas", ops/pallas_conv1x1_bn) or from input-side Gram
        # algebra ("gram", _publish_gram_stats) — and consuming them
        # skips this pass's full HBM re-read of the activation. Gated on
        # exact row-count match and f32 accumulation (the x64 gradient
        # check wants f64 stats, which the producers do not make).
        pub = (
            ctx.conv_stats.get(cfg.inputs[0].input_layer_name)
            if (x_nhwc is not None and not a.is_seq and acc_dt == jnp.float32)
            else None
        )
        if pub is not None and pub[2] == xr.shape[0] and pub[0].shape == (C,):
            s_pub, q_pub, rows = pub
            mean = s_pub / rows
            msq = q_pub / rows
        else:
            # one-pass statistics: mean and E[x^2] are independent
            # reductions over the same input, so XLA fuses them into a
            # single traversal (a two-pass centered variance would read
            # the activation twice — the var reduce depends on the mean).
            # The squares are exact (bf16->f32 widening then f32 multiply
            # inside the fusion); the E[x^2]-mean^2 cancellation at f32
            # only bites for channels with |mean|/std >~ 1e3, far beyond
            # post-conv activations.
            mean = jnp.mean(xr, axis=0, dtype=acc_dt)
            msq = jnp.mean(jnp.square(hp(xr)), axis=0, dtype=acc_dt)
        var = jnp.maximum(msq - jnp.square(mean), 0.0)
        # center against the EXACT f32 mean (a bf16-rounded mean would
        # bias every centered value); the convert-sub-convert chain
        # fuses, so no f32 tensor reaches HBM
        centered = (hp(xr) - mean).astype(xr.dtype)
        f = cfg.moving_average_fraction
        ctx.state_updates[mean_name] = (
            f * ctx.params[mean_name].reshape(C) + (1.0 - f) * mean
        ).reshape(ctx.params[mean_name].shape)
        ctx.state_updates[var_name] = (
            f * ctx.params[var_name].reshape(C) + (1.0 - f) * var
        ).reshape(ctx.params[var_name].shape)
    scale = hp(gamma) * lax.rsqrt(hp(var) + eps)  # f32 [C]
    # center-then-scale in the activation dtype (both branches): folding
    # the mean into a bf16 offset would cancel catastrophically for
    # channels whose mean is large relative to their std
    yn = centered * scale.astype(xr.dtype)
    if beta is not None:
        yn = yn + beta.astype(xr.dtype)
    if x_nhwc is not None and is_elementwise(cfg.active_type):
        y_img = apply_activation(cfg.active_type, yn.reshape(x_nhwc.shape))
        return _publish_nhwc(ctx, cfg, y_img)
    if x_nhwc is not None:
        y = _nhwc_to_flat(yn.reshape(x_nhwc.shape))
    elif ic is not None and ic.img_size > 0:
        y = yn.reshape(x.shape[0], hw, C).transpose(0, 2, 1).reshape(x.shape[0], -1)
    else:
        y = yn
    if seq_meta:
        y = y.reshape(a.value.shape)
    y = apply_activation(cfg.active_type, y)
    return Argument(value=y, **seq_meta)


@register_layer("norm", "norm-projection")
def norm_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: NormProjectionLayer (cmrnorm-projection): cross-map local
    # response normalization: y = x / (1 + scale/size * sum_window x^2)^pow
    nc = cfg.inputs[0].norm_conf
    x = _take_nhwc(ctx, cfg.inputs[0].input_layer_name, inputs[0],
                   nc.channels, nc.img_size, nc.img_size)
    half = nc.size // 2
    sq = jnp.square(x)
    acc = lax.reduce_window(
        sq, 0.0, lax.add, (1, 1, 1, nc.size), (1, 1, 1, 1), ((0, 0), (0, 0), (0, 0), (half, nc.size - 1 - half))
    )
    # NormConfig.scale already carries scale/size (the reference's
    # config_parser divides before storing; our DSL does the same)
    denom = jnp.power(1.0 + nc.scale * acc, nc.pow)
    return _publish_nhwc(ctx, cfg, x / denom)


@register_layer("blockexpand")
def block_expand_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: BlockExpandLayer.cpp — extract sliding blocks as a sequence of
    # flattened patches (OCR-style); output is a sequence of length
    # output_x * output_y per image.
    bc = cfg.inputs[0].block_expand_conf
    x = _take_nhwc(ctx, cfg.inputs[0].input_layer_name, inputs[0],
                   bc.channels, bc.img_size_y, bc.img_size_x)
    patches = lax.conv_general_dilated_patches(
        x.transpose(0, 3, 1, 2),  # NCHW
        filter_shape=(bc.block_y, bc.block_x),
        window_strides=(bc.stride_y, bc.stride_x),
        padding=[(bc.padding_y, bc.padding_y), (bc.padding_x, bc.padding_x)],
    )  # [B, C*by*bx, oy, ox]
    B, D, oy, ox = patches.shape
    seq = patches.transpose(0, 2, 3, 1).reshape(B, oy * ox, D)
    lengths = jnp.full((B,), oy * ox, jnp.int32)
    return Argument(value=seq, seq_lengths=lengths)


@register_layer("featmap_expand")
def featmap_expand_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: FeatureMapExpandLayer — tile a sequence input num_filters times.
    a = inputs[0]
    out = jnp.tile(a.value, (1,) * (a.value.ndim - 1) + (cfg.num_filters,))
    return Argument(value=out, seq_lengths=a.seq_lengths)


@register_layer("resize")
def resize_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ResizeLayer — reinterpret rows with a new feature width.
    x = inputs[0].value
    return Argument(value=x.reshape(-1, cfg.size))
