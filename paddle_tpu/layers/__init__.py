"""Layer library.

Each reference C++ Layer subclass (/root/reference/paddle/gserver/layers/,
64 REGISTER_LAYER types) becomes a pure function
``(cfg, inputs, ctx) -> Argument`` registered by the same type string the
config_parser emits. Importing this package registers everything.
"""

from paddle_tpu.layers.base import LayerContext, layer_registry, register_layer, forward_layer
import paddle_tpu.layers.core  # noqa: F401
import paddle_tpu.layers.cost  # noqa: F401
import paddle_tpu.layers.sequence  # noqa: F401
import paddle_tpu.layers.recurrent  # noqa: F401
import paddle_tpu.layers.vision  # noqa: F401
import paddle_tpu.layers.misc  # noqa: F401
import paddle_tpu.layers.structured  # noqa: F401
import paddle_tpu.layers.attention  # noqa: F401

__all__ = ["LayerContext", "layer_registry", "register_layer", "forward_layer"]
