"""Sequence manipulation layers.

Reference counterparts: MaxLayer/AverageLayer (SequencePool subtypes),
SequenceLastInstanceLayer, ExpandLayer, SequenceConcatLayer,
SequenceReshapeLayer, SubSequenceLayer
(/root/reference/paddle/gserver/layers/). The reference walks ragged rows
via sequenceStartPositions; here everything is masked reductions/gathers on
padded [B, T, D] — XLA turns these into fused reduce/gather kernels.

``trans_type`` ("non-seq" | "seq") mirrors the reference's pooling levels
(AggregateLevel): "non-seq" (default) aggregates the WHOLE outer
sequence — a nested input flattens to one row per sample; "seq"
aggregates each SUBSEQUENCE (nested input required) → output is a plain
sequence over subsequences.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, finalize_output, register_layer
from paddle_tpu.proto import LayerConfig

Array = jax.Array


def _pool(cfg: LayerConfig, a: Argument, mode: str) -> Argument:
    """Masked pooling at the configured trans_type level (see the
    module docstring for the AggregateLevel semantics)."""
    per_subseq = cfg.trans_type == "seq"
    if per_subseq:
        assert a.is_nested_seq, (
            f"{cfg.name}: trans_type='seq' needs a nested (sub-sequence) "
            "input (reference: 'input must hasSubseq')"
        )
    if a.is_nested_seq and per_subseq:
        mask = a.sub_seq_mask()  # [B, S, T]
        x = a.value  # [B, S, T, D]
        axis = 2
        lengths = a.sub_seq_lengths
        out_meta = dict(seq_lengths=a.seq_lengths)
    elif a.is_nested_seq:
        # "non-seq" over a nested input: one row per SAMPLE, all valid
        # tokens of all subsequences participate
        mask = a.sub_seq_mask()  # [B, S, T]
        x = a.value  # [B, S, T, D]
        axis = (1, 2)
        lengths = jnp.sum(a.sub_seq_lengths, axis=1)  # total tokens [B]
        out_meta = {}
    else:
        assert a.is_seq, f"{cfg.name}: pooling a non-sequence input"
        mask = a.seq_mask()  # [B, T]
        x = a.value  # [B, T, D]
        axis = 1
        lengths = a.seq_lengths
        out_meta = {}
    m = mask[..., None].astype(x.dtype)  # keep bf16 activations bf16
    if mode == "max":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(m > 0, x, neg), axis=axis)
        out = jnp.where(lengths[..., None] > 0, out, 0.0)
    else:
        s = jnp.sum(x * m, axis=axis)
        n = jnp.clip(lengths[..., None].astype(x.dtype), 1.0, None)
        if mode == "sum":
            out = s
        elif mode == "squarerootn":
            out = s / jnp.sqrt(n)
        else:  # average
            out = s / n
    return Argument(value=out, **out_meta)


@register_layer("max")
def max_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    out = _pool(cfg, inputs[0], "max")
    if cfg.output_max_index:
        # ref: MaxLayer with output_max_index — emit argmax positions.
        a = inputs[0]
        if a.is_nested_seq and cfg.trans_type != "seq":
            raise NotImplementedError(
                f"{cfg.name}: output_max_index over a whole nested sequence "
                "(trans_type='non-seq') is unsupported — use trans_type='seq' "
                "for per-subsequence indices"
            )
        mask = a.sub_seq_mask() if a.is_nested_seq else a.seq_mask()
        neg = jnp.finfo(a.value.dtype).min
        axis = 2 if a.is_nested_seq else 1
        idx = jnp.argmax(jnp.where(mask[..., None] > 0, a.value, neg), axis=axis)
        return Argument(ids=idx.astype(jnp.int32), seq_lengths=out.seq_lengths)
    v = finalize_output(cfg, out.value, ctx)
    return Argument(value=v, seq_lengths=out.seq_lengths)


@register_layer("average")
def average_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    mode = cfg.average_strategy or "average"
    out = _pool(cfg, inputs[0], mode)
    v = finalize_output(cfg, out.value, ctx)
    return Argument(value=v, seq_lengths=out.seq_lengths)


def _select_instance(cfg: LayerConfig, a: Argument, first: bool) -> Argument:
    """First/last instance at the configured trans_type level (see the
    module docstring for the AggregateLevel semantics)."""
    per_subseq = cfg.trans_type == "seq"
    if per_subseq:
        assert a.is_nested_seq, (
            f"{cfg.name}: trans_type='seq' needs a nested (sub-sequence) "
            "input (reference: 'input must hasSubseq')"
        )
        x, lengths = a.value, a.sub_seq_lengths  # [B,S,T,D], [B,S]
        idx = jnp.zeros_like(lengths) if first else jnp.clip(lengths - 1, 0, None)
        out = jnp.take_along_axis(x, idx[..., None, None], axis=2)[:, :, 0]
        return Argument(value=out, seq_lengths=a.seq_lengths)
    if a.is_nested_seq:
        # whole-sequence instance over a nested input: first token of the
        # first NON-EMPTY subsequence / last token of the last non-empty
        # one (empty subsequences hold only padding)
        B, S = a.value.shape[:2]
        n_subs = (
            a.seq_lengths
            if a.seq_lengths is not None
            else jnp.full((B,), S, jnp.int32)
        )
        s_iota = jnp.arange(S, dtype=jnp.int32)[None, :]
        valid = (s_iota < n_subs[:, None]) & (a.sub_seq_lengths > 0)
        if first:
            s_idx = jnp.min(jnp.where(valid, s_iota, S), axis=1)
        else:
            s_idx = jnp.max(jnp.where(valid, s_iota, -1), axis=1)
        s_idx = jnp.clip(s_idx, 0, S - 1)
        sub = jnp.take_along_axis(a.value, s_idx[:, None, None, None], axis=1)[:, 0]
        sub_len = jnp.take_along_axis(a.sub_seq_lengths, s_idx[:, None], axis=1)[:, 0]
        t_idx = jnp.zeros_like(sub_len) if first else jnp.clip(sub_len - 1, 0, None)
        out = jnp.take_along_axis(sub, t_idx[:, None, None], axis=1)[:, 0]
        return Argument(value=out)
    assert a.is_seq
    x, lengths = a.value, a.seq_lengths
    idx = jnp.zeros_like(lengths) if first else jnp.clip(lengths - 1, 0, None)
    out = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]
    return Argument(value=out)


@register_layer("seqlastins")
def seq_last_ins_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    out = _select_instance(cfg, inputs[0], first=cfg.select_first)
    return Argument(value=finalize_output(cfg, out.value, ctx), seq_lengths=out.seq_lengths)


@register_layer("seqfirstins")
def seq_first_ins_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    out = _select_instance(cfg, inputs[0], first=True)
    return Argument(value=finalize_output(cfg, out.value, ctx), seq_lengths=out.seq_lengths)


@register_layer("expand")
def expand_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ExpandLayer — broadcast a dense (or seq-level) input along the
    # sequence layout of the second input.
    src, layout = inputs[0], inputs[1]
    if layout.is_nested_seq and src.is_seq:
        # seq over subseqs → nested: broadcast each subsequence value over T
        out = jnp.broadcast_to(
            src.value[:, :, None, :], layout.value.shape[:3] + (src.value.shape[-1],)
        )
        return Argument(value=out, seq_lengths=layout.seq_lengths, sub_seq_lengths=layout.sub_seq_lengths)
    T = layout.max_len
    out = jnp.broadcast_to(src.value[:, None, :], (src.value.shape[0], T, src.value.shape[-1]))
    return Argument(value=out, seq_lengths=layout.seq_lengths)


@register_layer("seqconcat")
def seq_concat_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: SequenceConcatLayer — concatenate two sequences in time per
    # sample. Padded impl: place b after a's valid region via gather.
    a, b = inputs[0], inputs[1]
    Ta, Tb = a.max_len, b.max_len
    T = Ta + Tb
    la, lb = a.seq_lengths, b.seq_lengths
    pos = jnp.arange(T, dtype=jnp.int32)[None, :]  # [1, T]
    from_a = pos < la[:, None]
    idx_a = jnp.clip(pos, 0, Ta - 1)
    idx_b = jnp.clip(pos - la[:, None], 0, Tb - 1)
    ga = jnp.take_along_axis(a.value, idx_a[..., None], axis=1)
    gb = jnp.take_along_axis(b.value, idx_b[..., None], axis=1)
    out = jnp.where(from_a[..., None], ga, gb)
    lengths = la + lb
    valid = pos < lengths[:, None]
    out = jnp.where(valid[..., None], out, 0.0)
    return Argument(value=finalize_output(cfg, out, ctx), seq_lengths=lengths)


@register_layer("seqreshape")
def seq_reshape_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: SequenceReshapeLayer — reinterpret [B, T, D] as [B, T*D/size,
    # size]; only exact multiples are meaningful with padding.
    a = inputs[0]
    B, T, D = a.value.shape
    new_T = T * D // cfg.size
    out = a.value.reshape(B, new_T, cfg.size)
    lengths = (a.seq_lengths * D) // cfg.size
    return Argument(value=finalize_output(cfg, out, ctx), seq_lengths=lengths)


@register_layer("subseq")
def sub_seq_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: SubSequenceLayer — inputs: (sequence, offsets, sizes); output is
    # the slice [offset, offset+size) of each sequence.
    a, offs, sizes = inputs[0], inputs[1], inputs[2]
    o = (offs.ids if offs.ids is not None else offs.value[..., 0].astype(jnp.int32)).reshape(-1)
    s = (sizes.ids if sizes.ids is not None else sizes.value[..., 0].astype(jnp.int32)).reshape(-1)
    T = a.max_len
    pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    idx = jnp.clip(pos + o[:, None], 0, T - 1)
    out = jnp.take_along_axis(a.value, idx[..., None], axis=1)
    valid = pos < s[:, None]
    out = jnp.where(valid[..., None], out, 0.0)
    return Argument(value=finalize_output(cfg, out, ctx), seq_lengths=s)
