"""Cost layers.

Reference: /root/reference/paddle/gserver/layers/CostLayer.cpp (square
error, multi-class CE, binary/soft CE, self-norm CE, rank cost, huber) and
config_parser's define_cost type strings (config_parser.py:1700-1708).

Each cost layer outputs per-sample cost [B, 1] (sequences: summed over
valid timesteps — the padded-batch equivalent of the reference's ragged
per-row costs), already scaled by ``coeff`` and the optional per-sample
weight. The gradient machine averages over the batch to form the scalar
loss that jax.grad differentiates.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, TimeMajorLogits, register_layer
from paddle_tpu.proto import LayerConfig

from paddle_tpu.ops.precision import hp as _hp

Array = jax.Array
_EPS = 1e-10
# test hook: force the probability-path cross-entropy (parity tests
# compare the fused-logits formulation against it on the same graph)
_USE_FUSED_CE = True


def _finish_cost(cfg: LayerConfig, per_step: Array, arg: Argument, weight_arg: Optional[Argument]) -> Argument:
    """Reduce per-step cost over time (masked) and apply coeff/weight."""
    if arg.is_nested_seq:
        cost = jnp.sum(per_step * arg.sub_seq_mask(), axis=(1, 2))
    elif arg.is_seq:
        cost = jnp.sum(per_step * arg.seq_mask(), axis=1)
    else:
        cost = per_step
    if weight_arg is not None and weight_arg.value is not None:
        cost = cost * weight_arg.value.reshape(cost.shape)
    return Argument(value=(cfg.coeff * cost)[:, None])


def _label_ids(label: Argument) -> Array:
    if label.ids is not None:
        return label.ids
    return jnp.argmax(label.value, axis=-1).astype(jnp.int32)


def _fused_softmax_ce(z: Array, ids: Array) -> Array:
    """-log softmax(z)[ids] from logits, never materializing the
    full-width probabilities in f32: the max is exact in any float dtype,
    exp runs in the logits dtype, and only the reduction accumulates in
    (at least) f32 — XLA fuses the widening convert into the reduce. The
    gradient autodiff derives is softmax(z) - onehot in the logits dtype,
    the standard mixed-precision formulation."""
    m = jax.lax.stop_gradient(jnp.max(z, axis=-1, keepdims=True))
    acc = jnp.promote_types(z.dtype, jnp.float32)
    se = jnp.sum(jnp.exp(z - m), axis=-1, dtype=acc)
    lse = _hp(jnp.squeeze(m, -1)) + jnp.log(se)
    picked = _hp(jnp.take_along_axis(z, ids[..., None], axis=-1)[..., 0])
    return lse - picked


@register_layer("multi-class-cross-entropy")
def multi_class_cross_entropy(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # inputs: [probabilities (post-softmax), label(, weight)]
    out, label = inputs[0], inputs[1]
    weight = inputs[2] if len(inputs) > 2 else None
    ids = _label_ids(label)
    z = (
        ctx.logits.get(cfg.inputs[0].input_layer_name)
        if _USE_FUSED_CE and not cfg.inputs[0].input_layer_argument
        else None
    )
    per_step = _fused_or_plain_ce(z, out, ids)
    return _finish_cost(cfg, per_step, out, weight)


def _fused_or_plain_ce(z, out: Argument, ids: Array) -> Array:
    """Per-step CE: fused from logits when the published view matches,
    else -log(p) from the probabilities. A hoisted recurrent out-link
    publishes TimeMajorLogits (flat [T*B, V]); the CE then runs in that
    native layout and only the [T, B] per-step costs transpose — never
    the V-sized tensor (see layers/base.py TimeMajorLogits)."""
    if isinstance(z, TimeMajorLogits):
        B, T = out.value.shape[0], out.value.shape[1]
        if (
            out.value.ndim == 3
            and (z.T, z.B) == (T, B)
            and z.flat.shape == (T * B, out.value.shape[2])
        ):
            ids_flat = jnp.swapaxes(ids, 0, 1).reshape(-1)      # [T*B], tiny
            per_flat = _fused_softmax_ce(z.flat, ids_flat)
            return jnp.swapaxes(per_flat.reshape(T, B), 0, 1)   # [B, T], tiny
        z = None
    if z is not None and z.shape == out.value.shape:
        return _fused_softmax_ce(z, ids)
    p = jnp.take_along_axis(_hp(out.value), ids[..., None], axis=-1)[..., 0]
    return -jnp.log(jnp.clip(p, _EPS, None))


@register_layer("multi_class_cross_entropy_with_selfnorm")
def selfnorm_cross_entropy(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: CostLayer.cpp MultiClassCrossEntropyWithSelfNorm — CE on
    # unnormalized softmax plus alpha * log(Z)^2 keeping Z near 1.
    out, label = inputs[0], inputs[1]
    ids = _label_ids(label)
    v = _hp(out.value)
    z = jnp.sum(v, axis=-1)
    p = jnp.take_along_axis(v, ids[..., None], axis=-1)[..., 0]
    per_step = -jnp.log(jnp.clip(p / jnp.clip(z, _EPS, None), _EPS, None))
    per_step = per_step + cfg.softmax_selfnorm_alpha * jnp.square(jnp.log(jnp.clip(z, _EPS, None)))
    return _finish_cost(cfg, per_step, out, None)


@register_layer("square_error")
def square_error(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    out, label = inputs[0], inputs[1]
    weight = inputs[2] if len(inputs) > 2 else None
    v = _hp(out.value)
    target = _hp(label.value) if label.value is not None else label.ids.astype(v.dtype)
    if target.ndim < out.value.ndim:
        target = target[..., None]
    per_step = jnp.sum(jnp.square(v - target), axis=-1)
    return _finish_cost(cfg, per_step, out, weight)


@register_layer("multi_binary_label_cross_entropy")
def multi_binary_label_cross_entropy(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    out, label = inputs[0], inputs[1]
    p = jnp.clip(_hp(out.value), _EPS, 1.0 - _EPS)
    y = _hp(label.value)
    per_step = -jnp.sum(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p), axis=-1)
    return _finish_cost(cfg, per_step, out, None)


@register_layer("soft_binary_class_cross_entropy")
def soft_binary_class_cross_entropy(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    return multi_binary_label_cross_entropy(cfg, inputs, ctx)


@register_layer("rank-cost")
def rank_cost(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: RankingCost — inputs: left score, right score, label (1 if left
    # should rank higher, 0.5 for ties), optional weight.
    left, right, label = inputs[0], inputs[1], inputs[2]
    weight = inputs[3] if len(inputs) > 3 else None
    o = (_hp(left.value) - _hp(right.value))[..., 0]
    t = label.value[..., 0] if label.value is not None else label.ids.astype(o.dtype)
    per_step = jnp.logaddexp(0.0, o) - t * o
    return _finish_cost(cfg, per_step, left, weight)


@register_layer("huber")
def huber_two_class(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: HuberTwoClass — labels {0,1} → y in {-1,+1}; quadratic in
    # (-1, 1), linear outside, zero when y*f >= 1.
    out, label = inputs[0], inputs[1]
    f = _hp(out.value)[..., 0]
    y = 2.0 * _label_ids(label).astype(f.dtype) - 1.0
    a = y * f
    per_step = jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))
    return _finish_cost(cfg, per_step, out, None)


@register_layer("auc-validation", "pnpair-validation")
def validation_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ValidationLayer family (paddle/gserver/layers/ValidationLayer.h:
    # 52 AucValidation, :84 PnpairValidation; registered cost types in
    # config_parser.py:1703-1704) — metric-only nodes: forward contributes
    # ZERO cost and no gradient; the metric itself accumulates in the
    # evaluator the DSL registers alongside (trainer/evaluators.py
    # AucEvaluator / PnpairEvaluator), reported per log period / pass end.
    out = inputs[0]
    ref = out.value if out.value is not None else out.ids
    per_step = jnp.zeros(ref.shape[:-1] if out.value is not None else ref.shape,
                         jnp.float32)
    return _finish_cost(cfg, per_step, out, None)


@register_layer("classification_error")
def classification_error_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ClassificationErrorLayer — 1.0 where argmax(output) != label.
    out, label = inputs[0], inputs[1]
    pred = jnp.argmax(out.value, axis=-1)
    err = (pred != _label_ids(label)).astype(jnp.promote_types(out.value.dtype, jnp.float32))
    return _finish_cost(cfg, err, out, inputs[2] if len(inputs) > 2 else None)
