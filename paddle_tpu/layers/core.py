"""Core layers: data, fc, mixed (projections/operators), concat, addto...

Reference counterparts live in /root/reference/paddle/gserver/layers/
(DataLayer.cpp, FullyConnectedLayer.cpp, MixedLayer.cpp, Projection.h
subtypes, ConcatenateLayer.cpp, AddtoLayer.cpp, MaxIdLayer.cpp,
TransLayer.cpp, TensorLayer.cpp, ParameterReluLayer.cpp). All matmuls hit
the MXU via jnp.dot/einsum; sequence inputs are padded [B, T, D] and the
matmul batches over B*T.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import (
    LayerContext,
    finalize_output,
    first_seq_meta,
    input_mask,
    register_layer,
    with_seq_meta,
)
from paddle_tpu.proto import LayerConfig, LayerInputConfig, ProjectionConfig

Array = jax.Array


@register_layer("data")
def data_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # DataLayer (ref: DataLayer.cpp): passes through the fed Argument.
    # Under mixed precision, float FEATURES enter the graph in the compute
    # dtype so every downstream matmul is narrow from the first layer;
    # pure cost inputs (targets/labels/weights) stay full precision.
    assert len(inputs) == 1, f"data layer {cfg.name} not fed"
    a = inputs[0]
    if a.value is not None and cfg.size > 0 and a.value.shape[-1] != cfg.size:
        raise ValueError(
            f"data layer {cfg.name!r} declares size={cfg.size} but was fed "
            f"width {a.value.shape[-1]} (shape {a.value.shape}) — check the "
            "provider's input_types against the config's data_layer sizes"
        )
    if a.value is not None and cfg.name not in ctx.no_cast_inputs:
        cast = ctx.cast_compute(a.value)
        if cast is not a.value:
            a = a.replace(value=cast)
    return a


@register_layer("fc")
def fc_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: FullyConnectedLayer.cpp — sum_i x_i @ W_i (+ bias, act).
    acc: Optional[Array] = None
    for in_cfg, arg in zip(cfg.inputs, inputs):
        w = ctx.param(in_cfg.input_parameter_name)
        y = jnp.dot(arg.value, w)
        acc = y if acc is None else acc + y
    meta = first_seq_meta(inputs)
    out = finalize_output(cfg, acc, ctx, input_mask(meta))
    return with_seq_meta(meta, out)


# ----------------------------------------------------------- projections


def _context_projection(p: ProjectionConfig, arg: Argument, w: Optional[Array]) -> Array:
    """Sliding-window concat of neighboring timesteps.

    ref: ContextProjection.cpp + hl_sequence context ops. For offset o in
    [context_start, context_start + context_length), timestep t contributes
    input[t + o]; out-of-sequence offsets read zeros or trainable padding
    rows (w: [|start| + max(0, start+len-1), input_size]).
    """
    x = arg.value  # [B, T, D]
    B, T, D = x.shape
    cols = []
    begin_pad = max(0, -p.context_start)
    for k in range(p.context_length):
        off = p.context_start + k
        shifted = jnp.roll(x, -off, axis=1)
        pos = jnp.arange(T)[None, :] + off
        if arg.seq_lengths is not None:
            valid = (pos >= 0) & (pos < arg.seq_lengths[:, None])
        else:
            valid = (pos >= 0) & (pos < T)
        col = jnp.where(valid[:, :, None], shifted, 0.0)
        if w is not None:
            # trainable padding: before-sequence offsets use pad rows
            # [0, begin_pad); after-sequence use rows [begin_pad, ...).
            if off < 0:
                # row index = begin_pad + pos for pos in [-begin_pad, 0)
                # (reference ContextProjection keys the pad row off the
                # out-of-range position, not the offset)
                row_idx = jnp.clip(begin_pad + pos[0], 0, begin_pad - 1)  # [T]
                pad_rows = w[row_idx]  # [T, D]
                col = jnp.where((pos < 0)[:, :, None], pad_rows[None, :, :], col)
            elif off > 0:
                lengths = (
                    arg.seq_lengths[:, None]
                    if arg.seq_lengths is not None
                    else jnp.full((B, 1), T)
                )
                over = pos - lengths  # 0-based index past the end
                over_c = jnp.clip(over, 0, w.shape[0] - begin_pad - 1)
                pad_rows = w[begin_pad + over_c]  # [B, T, D]
                col = jnp.where((over >= 0)[:, :, None], pad_rows, col)
        cols.append(col)
    return jnp.concatenate(cols, axis=-1)


def apply_projection(
    p: ProjectionConfig, in_cfg: LayerInputConfig, arg: Argument, ctx: LayerContext
) -> Array:
    t = p.type
    pname = in_cfg.input_parameter_name
    if t == "identity":
        return arg.value
    if t == "identity_offset":
        return jax.lax.slice_in_dim(arg.value, p.offset, p.offset + p.output_size, axis=-1)
    if t == "dot_mul":
        return arg.value * ctx.param(pname)
    if t == "table":
        if ctx.table_overrides is not None:
            ov = ctx.table_overrides.get((pname, in_cfg.input_layer_name))
            if ov is not None:  # prefetched rows, already [batch..., dim]
                return ctx.cast_compute(ov)
        # gather from the master-dtype table, THEN cast: converting the
        # whole [V, D] table to bf16 each step would be an HBM-bound pass
        # over the full vocabulary
        table = ctx.param(pname, cast=False)  # [vocab, dim]
        return ctx.cast_compute(jnp.take(table, arg.ids, axis=0))
    if t == "fc":  # FullMatrixProjection
        return jnp.dot(arg.value, ctx.param(pname))
    if t == "trans_fc":  # TransposedFullMatrixProjection
        return jnp.dot(arg.value, ctx.param(pname).T)
    if t == "context":
        w = ctx.param(pname) if pname else None
        return _context_projection(p, arg, w)
    raise NotImplementedError(f"projection type {t!r}")


@register_layer("mixed")
def mixed_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: MixedLayer.cpp — sum of per-input projections plus operators.
    # Inside a recurrent-group scan, projections of plain scan inputs may
    # have been hoisted before the scan (prologue hoisting): the sum then
    # starts from the precomputed slice and skips those inputs.
    pro = ctx.mixed_prologue.get(cfg.name) if ctx.mixed_prologue else None
    skip_idx = frozenset(pro[0]) if pro else frozenset()
    acc: Optional[Array] = pro[1] if pro else None
    for i, (in_cfg, arg) in enumerate(zip(cfg.inputs, inputs)):
        if in_cfg.proj_conf is None or i in skip_idx:
            continue  # operator-only input / prologue-hoisted projection
        y = apply_projection(in_cfg.proj_conf, in_cfg, arg, ctx)
        acc = y if acc is None else acc + y
    for op in cfg.operator_confs:
        op_ins = [inputs[i] for i in op.input_indices]
        if op.type == "dot_mul":
            y = op.dotmul_scale * op_ins[0].value * op_ins[1].value
        elif op.type == "conv":
            from paddle_tpu.layers.vision import conv_operator_forward

            y = conv_operator_forward(op, op_ins)
        else:
            raise NotImplementedError(f"operator type {op.type!r}")
        acc = y if acc is None else acc + y
    meta = first_seq_meta(inputs)
    out = finalize_output(cfg, acc, ctx, input_mask(meta))
    return with_seq_meta(meta, out)


@register_layer("concat2")
def concat2_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ConcatenateLayer2 (ConcatenateLayer.cpp:95-115) — like concat,
    # but each input first passes through its OWN projection; the
    # projection outputs are concatenated (mixed sums them instead).
    parts = []
    for in_cfg, arg in zip(cfg.inputs, inputs):
        assert in_cfg.proj_conf is not None, f"concat2 {cfg.name}: input needs a projection"
        parts.append(apply_projection(in_cfg.proj_conf, in_cfg, arg, ctx))
    out = jnp.concatenate(parts, axis=-1)
    meta = first_seq_meta(inputs)
    return with_seq_meta(meta, finalize_output(cfg, out, ctx, input_mask(meta)))


@register_layer("addto")
def addto_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # NHWC fast path: residual junctions between conv-family layers (the
    # ResNet shortcut) stay in the published layout so the chain never
    # round-trips through flat NCHW (see LayerContext.nhwc)
    from paddle_tpu.layers.vision import _publish_nhwc
    from paddle_tpu.ops.activations import apply_activation, is_elementwise

    nh = [ctx.nhwc.get(ic.input_layer_name) for ic in cfg.inputs]
    if (
        all(x is not None for x in nh)
        and len({x.shape for x in nh}) == 1
        and not cfg.bias_parameter_name
        and cfg.drop_rate == 0.0
        and is_elementwise(cfg.active_type)
    ):
        acc = nh[0]
        for x in nh[1:]:
            acc = acc + x
        return _publish_nhwc(ctx, cfg, apply_activation(cfg.active_type, acc))
    acc = inputs[0].value
    for a in inputs[1:]:
        acc = acc + a.value
    meta = first_seq_meta(inputs)
    return with_seq_meta(meta, finalize_output(cfg, acc, ctx, input_mask(meta)))


@register_layer("concat")
def concat_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    out = jnp.concatenate([a.value for a in inputs], axis=-1)
    meta = first_seq_meta(inputs)
    return with_seq_meta(meta, finalize_output(cfg, out, ctx, input_mask(meta)))


@register_layer("tensor")
def tensor_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: TensorLayer.cpp — out_k = x @ W_k @ y^T diag; out[:, k] = sum_ij
    # x_i W^k_ij y_j. Parameter per slice: [in1, in2] stacked as
    # [in1, size*in2] in the reference; we store [size, in1, in2].
    x, y = inputs[0].value, inputs[1].value
    w = ctx.param(cfg.inputs[0].input_parameter_name)
    if w.ndim == 2:  # stored flat [in1, size*in2]
        w = w.reshape(x.shape[-1], cfg.size, y.shape[-1]).transpose(1, 0, 2)
    out = jnp.einsum("...i,kij,...j->...k", x, w, y)
    meta = first_seq_meta(inputs)
    return with_seq_meta(meta, finalize_output(cfg, out, ctx, input_mask(meta)))


@register_layer("prelu")
def prelu_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: ParameterReluLayer.cpp — per-partition leaky slope.
    x = inputs[0].value
    w = ctx.param(cfg.inputs[0].input_parameter_name)  # [size / partial_sum]
    slope = jnp.repeat(w, cfg.partial_sum)
    out = jnp.where(x > 0, x, x * slope)
    meta = first_seq_meta(inputs)
    return with_seq_meta(meta, out)


@register_layer("maxid")
def maxid_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: MaxIdLayer.cpp — argmax over features → ids.
    a = inputs[0]
    ids = jnp.argmax(a.value, axis=-1).astype(jnp.int32)
    return Argument(
        ids=ids,
        value=jnp.max(a.value, axis=-1, keepdims=True),
        seq_lengths=a.seq_lengths,
        sub_seq_lengths=a.sub_seq_lengths,
    )


@register_layer("eos_id")
def eos_id_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: EosIdCheckLayer.cpp — 1.0 where input id == eos_id.
    a = inputs[0]
    out = (a.ids == cfg.eos_id).astype(ctx.dtype)[..., None]
    return Argument(value=out, seq_lengths=a.seq_lengths, sub_seq_lengths=a.sub_seq_lengths)


@register_layer("trans")
def trans_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: TransLayer.cpp — transpose the (batch, feature) matrix; only
    # meaningful for non-sequence 2-D use (weight visualization etc.).
    return Argument(value=inputs[0].value.T)


@register_layer("get_output")
def get_output_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: GetOutputLayer.cpp — selects a named output of the input layer;
    # our layers have a single output so this is identity.
    return inputs[0]


@register_layer("sampling_id")
def sampling_id_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: SamplingIdLayer.cpp — sample an id from each row's distribution.
    a = inputs[0]
    rng = ctx.layer_rng(cfg.name, "sample")
    logits = jnp.log(jnp.clip(a.value, 1e-20, None))
    ids = jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
    return Argument(ids=ids, seq_lengths=a.seq_lengths, sub_seq_lengths=a.sub_seq_lengths)
