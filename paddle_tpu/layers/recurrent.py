"""Recurrent layers: simple RNN, LSTM, GRU (full-sequence and step forms).

Reference counterparts: RecurrentLayer.cpp, LstmLayer.cpp (+LstmCompute),
GatedRecurrentLayer.cpp (+GruCompute), LstmStepLayer.cpp, GruStepLayer.cpp
in /root/reference/paddle/gserver/layers/. The reference fuses per-frame
cell math in CUDA and schedules variable-length sequences densely via
SequenceToBatch (SequenceToBatch.h:41); the TPU-native formulation is a
``lax.scan`` over padded [T, B, D] with a carry mask — XLA fuses the cell,
and the MXU sees one [B, D]x[D, kD] matmul per step.

Layout contracts (from config_parser.py LstmLayer/GatedRecurrentLayer):
- lstmemory: input is the 4*size x-projection, recurrent weight
  [size, 4*size], bias 7*size = 4 gate biases + 3 peephole vectors,
  gate order [candidate, input, forget, output].
- gated_recurrent: input is the 3*size x-projection, weight [size, 3*size]
  split [update, reset | candidate], bias 3*size.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, register_layer
from paddle_tpu.ops.activations import apply_activation
from paddle_tpu.proto import LayerConfig

Array = jax.Array


def _scan_time(cell, x_tbd: Array, mask_tb: Array, init_carry, reverse: bool,
               unroll: int = 1):
    """Scan ``cell`` over the time-major sequence with carry masking.

    Padded steps pass the carry through unchanged so that (a) forward scans
    keep the final state at the last valid step and (b) reversed scans stay
    at the init state until the sequence actually starts.
    """

    def step(carry, inp):
        x_t, m_t = inp
        new_carry, y = cell(carry, x_t)
        m = m_t[:, None]
        merged = jax.tree_util.tree_map(lambda n, o: m * n + (1.0 - m) * o, new_carry, carry)
        return merged, y * m

    carry, ys = jax.lax.scan(
        step, init_carry, (x_tbd, mask_tb), reverse=reverse, unroll=unroll
    )
    return carry, ys


def _prep(a: Argument) -> Tuple[Array, Array]:
    x = jnp.swapaxes(a.value, 0, 1)  # [T, B, D]
    mask = jnp.swapaxes(a.seq_mask(dtype=x.dtype), 0, 1)  # [T, B]
    return x, mask


@register_layer("recurrent")
def recurrent_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    a = inputs[0]
    x, mask = _prep(a)
    w = ctx.param(cfg.inputs[0].input_parameter_name).reshape(cfg.size, cfg.size)
    b = ctx.param(cfg.bias_parameter_name).reshape(-1) if cfg.bias_parameter_name else 0.0

    def cell(h, x_t):
        h_new = apply_activation(cfg.active_type, x_t + jnp.dot(h, w) + b)
        return h_new, h_new

    B = x.shape[1]
    h0 = jnp.zeros((B, cfg.size), x.dtype)
    _, ys = _scan_time(cell, x, mask, h0, cfg.reversed, unroll=ctx.scan_unroll)
    return Argument(value=jnp.swapaxes(ys, 0, 1), seq_lengths=a.seq_lengths)


def lstm_cell_step(
    cfg: LayerConfig,
    x4: Array,            # [B, 4*size] x-projection (candidate,i,f,o)
    h_prev: Array,
    c_prev: Array,
    w: Array,             # [size, 4*size]
    bias: Optional[Array],  # [7*size] or None
) -> Tuple[Array, Array]:
    size = h_prev.shape[-1]
    gates = x4 + jnp.dot(h_prev, w)
    if bias is not None:
        gates = gates + bias[: 4 * size]
        peep_i = bias[4 * size : 5 * size]
        peep_f = bias[5 * size : 6 * size]
        peep_o = bias[6 * size : 7 * size]
    else:
        peep_i = peep_f = peep_o = None
    a, gi, gf, go = jnp.split(gates, 4, axis=-1)
    act_gate = lambda v: apply_activation(cfg.active_gate_type or "sigmoid", v)
    act_in = lambda v: apply_activation(cfg.active_type or "tanh", v)
    act_state = lambda v: apply_activation(cfg.active_state_type or "sigmoid", v)
    i = act_gate(gi + (peep_i * c_prev if peep_i is not None else 0.0))
    f = act_gate(gf + (peep_f * c_prev if peep_f is not None else 0.0))
    c = f * c_prev + i * act_in(a)
    o = act_gate(go + (peep_o * c if peep_o is not None else 0.0))
    h = o * act_state(c)
    return h, c


def _pallas_rnn_path(ctx, cfg, a, x, mask, w, bias, usable_fn, fwd_fn):
    """The fused Pallas kernel path shared by lstmemory/gated_recurrent,
    or None to take the scan. Gating: TPU backend (non-TPU would run the
    Python interpreter — tests force it via PADDLE_TPU_PALLAS_INTERPRET=1,
    production falls back to the scan); shapes/activations/VMEM checked
    by the kernel's usable(). Meshes: single-device, or a purely
    data-parallel mesh — there the kernel runs per-shard under shard_map
    (each shard's batch rows are independent sequences); any non-trivial
    model/seq axis falls back to the scan, whose ops GSPMD can partition.
    Callers guard on ctx.pallas_rnn BEFORE importing the kernel module,
    keeping the ops import lazy on the default path."""
    import os

    data_extent = None
    T, B = mask.shape
    if ctx.mesh is not None:
        from paddle_tpu.parallel.mesh import data_only_extent

        data_extent = data_only_extent(ctx.mesh)
        if data_extent is None or B % data_extent:
            return None
    on_tpu = jax.default_backend() == "tpu"
    force_interpret = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET") == "1"
    if not (on_tpu or force_interpret):
        return None
    if data_extent is not None:
        # gate on the PER-SHARD batch the kernel will actually see
        local = jax.ShapeDtypeStruct((T, B // data_extent, x.shape[2]), x.dtype)
        if not usable_fn(cfg, local):
            return None
    elif not usable_fn(cfg, x):
        return None
    # transpose-free interface — the kernel reads the projection
    # output's batch-major value through a free [B, T*width] reshape
    # instead of a materialized time-major swap (A/B knob; flip the
    # default only on a measured win). settings(pallas_flat=True) is
    # the config-level switch; the PADDLE_TPU_PALLAS_FLAT=1 env var
    # still forces it for configs that can't be edited.
    flat = ctx.pallas_flat or os.environ.get("PADDLE_TPU_PALLAS_FLAT") == "1"
    x_bt = a.value if flat else None
    # the env flag wins even on TPU so a compiled-kernel discrepancy can
    # be A/B'd in interpret mode on the device where it manifests (off
    # TPU the guard above already required the flag)
    if data_extent is None:
        ys = fwd_fn(cfg, x, mask, w, bias, interpret=force_interpret, x_bt=x_bt)
    else:
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.parallel.mesh import replicated_specs, shard_map_compat

        def shard_fn(xin, mask_l, *wb):
            w_l = wb[0]
            bias_l = wb[1] if len(wb) > 1 else None
            return fwd_fn(cfg, xin, mask_l, w_l, bias_l,
                          interpret=force_interpret,
                          x_bt=xin if flat else None)

        x_spec = P("data") if flat else P(None, "data")
        wb_args = (w,) if bias is None else (w, bias)
        ys = shard_map_compat(
            shard_fn, ctx.mesh,
            in_specs=(x_spec, P(None, "data")) + replicated_specs(*wb_args),
            out_specs=x_spec,  # ys shards on batch exactly like x
        )(x_bt if flat else x, mask, *wb_args)
    value = ys if flat else jnp.swapaxes(ys, 0, 1)
    return Argument(value=value, seq_lengths=a.seq_lengths)


@register_layer("lstmemory")
def lstmemory_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    a = inputs[0]
    x, mask = _prep(a)  # [T, B, 4*size]
    size = cfg.size
    w = ctx.param(cfg.inputs[0].input_parameter_name).reshape(size, 4 * size)
    bias = ctx.param(cfg.bias_parameter_name).reshape(-1) if cfg.bias_parameter_name else None

    if ctx.pallas_rnn:
        from paddle_tpu.ops import pallas_lstm as pk

        out = _pallas_rnn_path(
            ctx, cfg, a, x, mask, w, bias, pk.usable, pk.lstm_layer_forward
        )
        if out is not None:
            return out

    def cell(carry, x_t):
        h, c = carry
        h2, c2 = lstm_cell_step(cfg, x_t, h, c, w, bias)
        return (h2, c2), h2

    B = x.shape[1]
    init = (jnp.zeros((B, size), x.dtype), jnp.zeros((B, size), x.dtype))
    _, ys = _scan_time(cell, x, mask, init, cfg.reversed, unroll=ctx.scan_unroll)
    return Argument(value=jnp.swapaxes(ys, 0, 1), seq_lengths=a.seq_lengths)


def gru_cell_step(
    cfg: LayerConfig,
    x3: Array,        # [B, 3*size] x-projection (update,reset,candidate)
    h_prev: Array,
    w: Array,         # [size, 3*size]: [:, :2s]=gates, [:, 2s:]=candidate
    bias: Optional[Array],
) -> Array:
    size = h_prev.shape[-1]
    xg, xc = x3[..., : 2 * size], x3[..., 2 * size :]
    wg, wc = w[:, : 2 * size], w[:, 2 * size :]
    g = xg + jnp.dot(h_prev, wg)
    if bias is not None:
        g = g + bias[: 2 * size]
    act_gate = lambda v: apply_activation(cfg.active_gate_type or "sigmoid", v)
    u, r = jnp.split(act_gate(g), 2, axis=-1)
    cand = xc + jnp.dot(r * h_prev, wc)
    if bias is not None:
        cand = cand + bias[2 * size :]
    c = apply_activation(cfg.active_type or "tanh", cand)
    # ref GruCompute: output = update * prev + (1 - update) * candidate
    return u * h_prev + (1.0 - u) * c


@register_layer("gated_recurrent")
def gated_recurrent_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    a = inputs[0]
    x, mask = _prep(a)
    size = cfg.size
    w = ctx.param(cfg.inputs[0].input_parameter_name).reshape(size, 3 * size)
    bias = ctx.param(cfg.bias_parameter_name).reshape(-1) if cfg.bias_parameter_name else None

    if ctx.pallas_rnn:
        from paddle_tpu.ops import pallas_gru as pg

        out = _pallas_rnn_path(
            ctx, cfg, a, x, mask, w, bias, pg.usable, pg.gru_layer_forward
        )
        if out is not None:
            return out

    def cell(h, x_t):
        h2 = gru_cell_step(cfg, x_t, h, w, bias)
        return h2, h2

    B = x.shape[1]
    h0 = jnp.zeros((B, size), x.dtype)
    _, ys = _scan_time(cell, x, mask, h0, cfg.reversed, unroll=ctx.scan_unroll)
    return Argument(value=jnp.swapaxes(ys, 0, 1), seq_lengths=a.seq_lengths)


@register_layer("lstm_step")
def lstm_step_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: LstmStepLayer.cpp — one LSTM step inside a recurrent_group.
    # inputs: [x-projection 4*size, prev cell state]; primary output is the
    # hidden state; the new cell state is published as "<name>@state" for
    # get_output(..., arg_name='state').
    x4, c_prev = inputs[0].value, inputs[1].value
    size = cfg.size
    w = jnp.zeros((size, 4 * size), x4.dtype)  # step layers have no recurrent weight
    bias = ctx.param(cfg.bias_parameter_name).reshape(-1) if cfg.bias_parameter_name else None
    h_prev = jnp.zeros((x4.shape[0], size), x4.dtype)
    h, c = lstm_cell_step(cfg, x4, h_prev, c_prev, w, bias)
    ctx.outputs[f"{cfg.name}@state"] = Argument(value=c, seq_lengths=inputs[0].seq_lengths)
    return Argument(value=h, seq_lengths=inputs[0].seq_lengths)


@register_layer("gru_step")
def gru_step_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    # ref: GruStepLayer.cpp — inputs: [x-projection 3*size, prev output].
    x3, h_prev = inputs[0].value, inputs[1].value
    size = cfg.size
    w = ctx.param(cfg.inputs[0].input_parameter_name).reshape(size, 3 * size)
    bias = ctx.param(cfg.bias_parameter_name).reshape(-1) if cfg.bias_parameter_name else None
    h = gru_cell_step(cfg, x3, h_prev, w, bias)
    return Argument(value=h, seq_lengths=inputs[0].seq_lengths)


@register_layer("mdlstmemory")
def mdlstm_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    """Multi-dimensional LSTM over a 2-D grid (ref: MDLstmLayer.cpp:81-473,
    Graves-style MDLSTM). Input is a NESTED argument [B, H, W, (3+D)*size]
    holding the precomputed x-projections for blocks
    [inputNode, inputGate, forgetGate×D, outputGate]; the recurrent weight
    [size, (3+D)*size] is SHARED across the D predecessor directions and
    the bias packs (3+D) gate biases + checkIg + checkFg×D + checkOg
    (config_parser.py:2608 MDLstmLayer). directions[d]=False scans dim d
    backwards. Per position: each predecessor (top/left) contributes its
    output through W, its state through the peepholes and through an
    independent forget gate — out-of-grid predecessors contribute zeros,
    which reproduces the reference's skip semantics exactly.

    TPU formulation: lax.scan over rows carrying the previous row's
    (out, state) [W, B, size], with an inner lax.scan over columns — the
    cell math vectorizes over the batch. Ragged grids (per-sample
    sub_seq_lengths) are handled by zeroing out-of-grid cells' out/state,
    which makes them behave exactly like the reference's out-of-grid
    skip.
    """
    a = inputs[0]
    x = a.value
    assert x is not None and x.ndim == 4, (
        "mdlstmemory expects a nested [B, H, W, (3+D)*size] input "
        "(dense_vector_sub_sequence grid)"
    )
    dirs = list(cfg.directions) or [True, True]
    D = len(dirs)
    assert D == 2, "mdlstmemory: 2-D grids supported (directions must have 2 entries)"
    nb = cfg.size
    w = ctx.param(cfg.inputs[0].input_parameter_name).reshape(nb, (3 + D) * nb)
    bias = ctx.param(cfg.bias_parameter_name).reshape(-1)
    gate_bias = bias[: (3 + D) * nb]
    check_ig = bias[(3 + D) * nb : (4 + D) * nb]
    check_fg = bias[(4 + D) * nb : (4 + 2 * D) * nb].reshape(D, nb)
    check_og = bias[(4 + 2 * D) * nb : (5 + 2 * D) * nb]

    if a.sub_seq_lengths is not None:
        grid_mask = a.sub_seq_mask(dtype=x.dtype)[..., None]  # [B, H, W, 1]
    else:
        grid_mask = jnp.ones(x.shape[:3] + (1,), x.dtype)
    if not dirs[0]:
        x = jnp.flip(x, 1)
        grid_mask = jnp.flip(grid_mask, 1)
    if not dirs[1]:
        x = jnp.flip(x, 2)
        grid_mask = jnp.flip(grid_mask, 2)
    B, H, W, _ = x.shape
    g_all = jnp.transpose(x + gate_bias, (1, 2, 0, 3))  # [H, W, B, (3+D)nb]
    m_all = jnp.transpose(grid_mask, (1, 2, 0, 3))      # [H, W, B, 1]

    act_gate = lambda v: apply_activation(cfg.active_gate_type or "sigmoid", v)
    act_in = lambda v: apply_activation(cfg.active_type or "tanh", v)
    act_state = lambda v: apply_activation(cfg.active_state_type or "sigmoid", v)

    def col_cell(carry, inp):
        out_l, st_l = carry                        # left neighbor [B, nb]
        g, out_t, st_t, m = inp                    # this col + top neighbor
        g = g + jnp.dot(out_t + out_l, w)          # shared recurrent weight
        in_pre = g[:, :nb]
        ig_pre = g[:, nb : 2 * nb]
        fg_pre = g[:, 2 * nb : (2 + D) * nb]
        og_pre = g[:, (2 + D) * nb : (3 + D) * nb]
        ig = act_gate(ig_pre + (st_t + st_l) * check_ig)
        fg = act_gate(
            fg_pre + jnp.concatenate([st_t * check_fg[0], st_l * check_fg[1]], -1)
        )
        state = fg[:, :nb] * st_t + fg[:, nb:] * st_l + act_in(in_pre) * ig
        og = act_gate(og_pre + state * check_og)
        out = og * act_state(state)
        # out-of-grid cells emit zeros so neighbors treat them as absent
        out = out * m
        state = state * m
        return (out, state), (out, state)

    def row_step(carry, inp):
        g_row, m_row = inp
        out_top, st_top = carry                    # previous row [W, B, nb]
        z = jnp.zeros((B, nb), x.dtype)
        (_, _), (outs, sts) = jax.lax.scan(
            col_cell, (z, z), (g_row, out_top, st_top, m_row)
        )
        return (outs, sts), outs

    zrow = jnp.zeros((W, B, nb), x.dtype)
    _, ys = jax.lax.scan(row_step, (zrow, zrow), (g_all, m_all))  # [H, W, B, nb]
    out = jnp.transpose(ys, (2, 0, 1, 3))                # [B, H, W, nb]
    if not dirs[1]:
        out = jnp.flip(out, 2)
    if not dirs[0]:
        out = jnp.flip(out, 1)
    return Argument(
        value=out, seq_lengths=a.seq_lengths, sub_seq_lengths=a.sub_seq_lengths
    )
