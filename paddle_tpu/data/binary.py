"""Binary dataset format — the ProtoDataProvider role.

The reference's ProtoDataProvider (/root/reference/paddle/gserver/
dataproviders/ProtoDataProvider.h:49) reads pre-serialized protobuf
`DataFormat` files so training needn't re-run Python preprocessing. The
TPU-era analog: one `.pdz` (npz) shard per file holding column-packed
slots — ragged sequences stored flat + offsets — loaded with zero Python
per-sample work and streamed through the normal feeder/scanner path.

Write shards with ``write_shard``; configure with
``define_bin_data_sources(train_list, test_list, input_types=...)`` or
DataConfig(type="bin"). Each line of the file list names one shard.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.data.provider import DataType, InputType, SequenceType

MAGIC = "paddle_tpu.bin.v1"


def _type_dict(tp: InputType) -> Dict[str, int]:
    return {"dim": tp.dim, "seq_type": tp.seq_type, "type": tp.type}


def write_shard(path: str, samples: List[Sequence[Any]], input_types: Sequence[InputType]) -> None:
    """Column-pack ``samples`` (lists of per-slot values, @provider yield
    format) into one npz shard."""
    arrays: Dict[str, np.ndarray] = {}
    n = len(samples)
    for i, tp in enumerate(input_types):
        col = [s[i] for s in samples]
        if tp.seq_type == SequenceType.NO_SEQUENCE:
            if tp.type == DataType.Index:
                arrays[f"s{i}_data"] = np.asarray(col, dtype=np.int32)
            elif tp.type == DataType.Dense:
                arrays[f"s{i}_data"] = np.asarray(col, dtype=np.float32)
            else:  # sparse rows: flat ids (+values) with offsets
                offs = np.zeros(n + 1, np.int64)
                flat_i: List[int] = []
                flat_v: List[float] = []
                for j, row in enumerate(col):
                    if tp.type == DataType.SparseValue:
                        flat_i.extend(int(p[0]) for p in row)
                        flat_v.extend(float(p[1]) for p in row)
                    else:
                        flat_i.extend(int(x) for x in row)
                    offs[j + 1] = len(flat_i)
                arrays[f"s{i}_ids"] = np.asarray(flat_i, dtype=np.int64)
                arrays[f"s{i}_offs"] = offs
                if tp.type == DataType.SparseValue:
                    arrays[f"s{i}_vals"] = np.asarray(flat_v, dtype=np.float32)
        elif tp.seq_type == SequenceType.SEQUENCE:
            offs = np.zeros(n + 1, np.int64)
            flat: List[Any] = []
            for j, seq in enumerate(col):
                flat.extend(seq)
                offs[j + 1] = len(flat)
            dtype = np.int32 if tp.type == DataType.Index else np.float32
            arrays[f"s{i}_data"] = np.asarray(flat, dtype=dtype)
            arrays[f"s{i}_offs"] = offs
        else:
            # nested sequences (ref ProtoDataProvider subseq handling,
            # ProtoDataProvider.h:49): two offset levels — sub_offs maps
            # each SUBSEQUENCE to its flat token range, offs maps each
            # sample to its subsequence range
            offs = np.zeros(n + 1, np.int64)
            sub_offs: List[int] = [0]
            flat = []
            for j, subseqs in enumerate(col):
                for seq in subseqs:
                    flat.extend(seq)
                    sub_offs.append(len(flat))
                offs[j + 1] = len(sub_offs) - 1
            dtype = np.int32 if tp.type == DataType.Index else np.float32
            arrays[f"s{i}_data"] = np.asarray(flat, dtype=dtype)
            arrays[f"s{i}_offs"] = offs
            arrays[f"s{i}_sub_offs"] = np.asarray(sub_offs, dtype=np.int64)
    meta = {"magic": MAGIC, "n": n, "types": [_type_dict(t) for t in input_types]}
    arrays["__meta__"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    # np.savez appends .npz to a bare path; write through a file object so
    # a '.pdz' shard lands at exactly the path the file list names
    with open(path, "wb") as f:
        np.savez(f, **arrays)


def read_shard(path: str):
    """Yield samples from a shard in @provider format."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        assert meta["magic"] == MAGIC, f"{path}: not a paddle_tpu binary shard"
        types = [InputType(t["dim"], t["seq_type"], t["type"]) for t in meta["types"]]
        arrays = {k: z[k] for k in z.files}
    n = meta["n"]
    for j in range(n):
        sample = []
        for i, tp in enumerate(types):
            if tp.seq_type == SequenceType.NO_SEQUENCE:
                if tp.type in (DataType.Index, DataType.Dense):
                    sample.append(arrays[f"s{i}_data"][j])
                else:
                    lo, hi = arrays[f"s{i}_offs"][j], arrays[f"s{i}_offs"][j + 1]
                    ids = arrays[f"s{i}_ids"][lo:hi]
                    if tp.type == DataType.SparseValue:
                        vals = arrays[f"s{i}_vals"][lo:hi]
                        sample.append(list(zip(ids.tolist(), vals.tolist())))
                    else:
                        sample.append(ids.tolist())
            elif tp.seq_type == SequenceType.SEQUENCE:
                lo, hi = arrays[f"s{i}_offs"][j], arrays[f"s{i}_offs"][j + 1]
                sample.append(arrays[f"s{i}_data"][lo:hi].tolist())
            else:  # nested: sample -> subseq range -> token ranges
                lo, hi = arrays[f"s{i}_offs"][j], arrays[f"s{i}_offs"][j + 1]
                so = arrays[f"s{i}_sub_offs"]
                sample.append(
                    [
                        arrays[f"s{i}_data"][so[s] : so[s + 1]].tolist()
                        for s in range(int(lo), int(hi))
                    ]
                )
        yield sample


def shard_input_types(path: str) -> List[InputType]:
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
    return [InputType(t["dim"], t["seq_type"], t["type"]) for t in meta["types"]]


class BinaryProvider:
    """@provider-shaped adapter over binary shards (duck-types the object
    the feeder consumes: .init()/.generator_fn/flags)."""

    should_shuffle = None
    pool_size = -1
    min_pool_size = -1
    can_over_batch_size = True
    calc_batch_size = None
    cache = 0
    name = "binary"

    def __init__(self, first_shard: str):
        self.input_types = shard_input_types(first_shard)

    def init(self, **kwargs):
        from paddle_tpu.data.provider import _ProviderSettings

        settings = _ProviderSettings()
        settings.input_types = self.input_types
        settings.should_shuffle = None
        return settings

    @staticmethod
    def generator_fn(settings, file_name):
        yield from read_shard(file_name)
