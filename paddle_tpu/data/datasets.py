"""Shared text-dataset utilities for the demo data converters.

Role analog of the reference's per-demo preprocess scripts
(demo/quick_start/preprocess.py create_dict/tokenize, demo/seqToseq's
dict+sbeos corpus layout): tokenization, frequency-ordered dictionaries,
and the two line formats every text demo uses —

  labeled lines:   "<label>\t<text>"   (reference sentiment used
                   "<label>\t\t<text>"; both are accepted on read)
  parallel lines:  "<source sentence>\t<target sentence>"

Dictionaries are one word per line, id = line number; sequence dicts
reserve <s>/<e>/<unk> as ids 0/1/2 (the reference seqToseq convention).
"""

from __future__ import annotations

import gzip
import os
import re
from functools import lru_cache as _functools_lru_cache
from typing import Dict, Iterable, Iterator, List, Sequence, Tuple

__all__ = [
    "tokenize",
    "build_dict",
    "save_dict",
    "load_dict",
    "read_labeled_lines",
    "write_labeled_lines",
    "read_parallel_lines",
    "open_maybe_gz",
    "labeled_samples_or_synth",
    "resolve_word_dict",
    "SEQ_RESERVED",
]

_TOKEN_RE = re.compile(r"[a-z0-9']+")
SEQ_RESERVED = ("<s>", "<e>", "<unk>")


def tokenize(text: str) -> List[str]:
    """Lowercase word tokens (alphanumerics + apostrophes). A deliberate
    simplification of the reference's mosesdecoder tokenizer — documented
    in doc/divergences.md; the corpus format is tokenizer-agnostic."""
    return _TOKEN_RE.findall(text.lower())


def open_maybe_gz(path: str, mode: str = "rt"):
    return gzip.open(path, mode) if str(path).endswith(".gz") else open(path, mode)


def build_dict(
    token_streams: Iterable[Sequence[str]],
    max_size: int = 0,
    cutoff: int = 0,
    reserved: Sequence[str] = (),
) -> List[str]:
    """Frequency-descending word list (ties broken alphabetically so the
    output is deterministic). reserved words head the list regardless of
    frequency; cutoff drops words seen fewer times; max_size bounds the
    total length (reserved included)."""
    counts: Dict[str, int] = {}
    for toks in token_streams:
        for t in toks:
            counts[t] = counts.get(t, 0) + 1
    for r in reserved:
        counts.pop(r, None)
    words = sorted(counts, key=lambda w: (-counts[w], w))
    if cutoff:
        words = [w for w in words if counts[w] >= cutoff]
    out = list(reserved) + words
    return out[:max_size] if max_size else out


def save_dict(words: Sequence[str], path: str) -> None:
    with open(path, "w") as f:
        f.write("\n".join(words) + "\n")


def load_dict(path: str) -> Dict[str, int]:
    """word -> id from a one-word-per-line file (id = line number).
    Memoized for the process lifetime: configs and provider hooks both
    resolve the same dict at startup (a 30k-word file parses once)."""
    return dict(_load_dict_cached(os.path.abspath(path)))


@_functools_lru_cache(maxsize=16)
def _load_dict_cached(path: str):
    with open(path) as f:
        return tuple((w.strip(), i) for i, w in enumerate(f) if w.strip())


def read_labeled_lines(path: str) -> Iterator[Tuple[int, List[str]]]:
    """Yield (label, words) from '<label>\\t<text>' lines; tolerates the
    reference's double-tab separator and skips malformed lines."""
    with open_maybe_gz(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t", 1)
            if len(parts) != 2:
                continue
            label, text = parts[0], parts[1].lstrip("\t")
            try:
                yield int(label), text.split()
            except ValueError:
                continue


def write_labeled_lines(samples: Iterable[Tuple[int, Sequence[str]]], path: str) -> int:
    n = 0
    with open(path, "w") as f:
        for label, words in samples:
            f.write(f"{label}\t{' '.join(words)}\n")
            n += 1
    return n


def labeled_samples_or_synth(file_name: str, synth_fn, n: int):
    """The demos' file-list dispatch: an entry that exists on disk is read
    as a converted '<label>\\t<text>' corpus; anything else is a seed
    token for the demo's synthetic generator synth_fn(seed, n)."""
    if os.path.exists(file_name):
        yield from read_labeled_lines(file_name)
    else:
        yield from synth_fn(file_name, n)


def resolve_word_dict(dict_path: str, fallback_vocab: Sequence[str]) -> Dict[str, int]:
    """word->id map: the converter-written dict file when a path is given,
    else enumerate the demo's synthetic vocabulary."""
    if dict_path:
        return load_dict(dict_path)
    return {w: i for i, w in enumerate(fallback_vocab)}


def read_parallel_lines(path: str) -> Iterator[Tuple[List[str], List[str]]]:
    """Yield (source_words, target_words) from '<src>\\t<trg>' lines."""
    with open_maybe_gz(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) != 2:
                continue
            yield parts[0].split(), parts[1].split()
