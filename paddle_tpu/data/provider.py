"""@provider — the user data-ingestion contract.

API-compatible with the reference's PyDataProvider2
(/root/reference/python/paddle/trainer/PyDataProvider2.py:33-190): a user
function yields one sample at a time (list/dict of slot values); the
decorator attaches input-type declarations and behavior knobs. The runtime
side (feeder.py) pulls samples, shuffles, batches and pads them into
Argument pytrees.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Union

__all__ = [
    "SequenceType",
    "DataType",
    "CacheType",
    "InputType",
    "dense_slot",
    "sparse_non_value_slot",
    "sparse_value_slot",
    "index_slot",
    "dense_vector",
    "sparse_binary_vector",
    "sparse_vector",
    "integer_value",
    "dense_vector_sequence",
    "dense_vector_sub_sequence",
    "sparse_binary_vector_sequence",
    "sparse_binary_vector_sub_sequence",
    "sparse_vector_sequence",
    "sparse_vector_sub_sequence",
    "integer_value_sequence",
    "integer_value_sub_sequence",
    "integer_sequence",
    "provider",
]


class SequenceType:
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class DataType:
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class InputType:
    __slots__ = ["dim", "seq_type", "type"]

    def __init__(self, dim: int, seq_type: int, tp: int):
        self.dim = dim
        self.seq_type = seq_type
        self.type = tp

    def __repr__(self):
        return f"InputType(dim={self.dim}, seq_type={self.seq_type}, type={self.type})"


def dense_slot(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def sparse_non_value_slot(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseNonValue)


def sparse_value_slot(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseValue)


def index_slot(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Index)


dense_vector = dense_slot
sparse_binary_vector = sparse_non_value_slot
sparse_vector = sparse_value_slot
integer_value = index_slot


def dense_vector_sequence(dim):
    return dense_vector(dim, seq_type=SequenceType.SEQUENCE)


def dense_vector_sub_sequence(dim):
    return dense_vector(dim, seq_type=SequenceType.SUB_SEQUENCE)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, seq_type=SequenceType.SEQUENCE)


def sparse_binary_vector_sub_sequence(dim):
    return sparse_binary_vector(dim, seq_type=SequenceType.SUB_SEQUENCE)


def sparse_vector_sequence(dim):
    return sparse_vector(dim, seq_type=SequenceType.SEQUENCE)


def sparse_vector_sub_sequence(dim):
    return sparse_vector(dim, seq_type=SequenceType.SUB_SEQUENCE)


def integer_value_sequence(dim):
    return integer_value(dim, seq_type=SequenceType.SEQUENCE)


def integer_value_sub_sequence(dim):
    return integer_value(dim, seq_type=SequenceType.SUB_SEQUENCE)


def integer_sequence(dim):
    return index_slot(dim, seq_type=SequenceType.SEQUENCE)


class _ProviderSettings:
    """The `settings` object handed to init_hook/process (attribute bag)."""

    def __init__(self):
        self.input_types = None
        self.should_shuffle = None
        self.pool_size = -1
        self.sort_by_length = False
        self.logger = None

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


def provider(
    input_types=None,
    should_shuffle: Optional[bool] = None,
    pool_size: int = -1,
    min_pool_size: int = -1,
    can_over_batch_size: bool = True,
    calc_batch_size: Optional[Callable] = None,
    cache: int = CacheType.NO_CACHE,
    init_hook: Optional[Callable] = None,
    sort_by_length: bool = False,
    **outter_kwargs,
):
    """Decorate a sample generator ``fn(settings, filename)``.

    The decorated object exposes the declaration (`input_types`, flags) and
    an ``open(filename)`` iterator used by the runtime feeder.

    ``sort_by_length`` is a TPU-native extension (doc/divergences.md): the
    training feeder length-sorts each shuffle pool before slicing batches
    (batch ORDER stays shuffled), so a batch's padded length is set by
    similar-length neighbors instead of the pool max — the static-shape
    answer to the reference's no-padding SequenceToBatch packing
    (SequenceToBatch.h:41). Test/generation order is never changed.
    """

    def deco(fn):
        class PyDataProvider2:
            # attributes inspected by the feeder
            pass

        p = PyDataProvider2()
        p.generator_fn = fn
        p.input_types = input_types
        # None = decided by the consumer: shuffle for training, ordered for
        # test/gen (reference PyDataProvider2 semantics)
        p.should_shuffle = should_shuffle
        p.pool_size = pool_size
        p.min_pool_size = min_pool_size
        p.can_over_batch_size = can_over_batch_size
        p.calc_batch_size = calc_batch_size
        p.cache = cache
        p.sort_by_length = sort_by_length
        p.init_hook = init_hook
        p.outter_kwargs = outter_kwargs
        p.name = fn.__name__

        def init(**kwargs):
            settings = _ProviderSettings()
            settings.input_types = p.input_types
            settings.should_shuffle = p.should_shuffle
            settings.pool_size = p.pool_size
            settings.sort_by_length = p.sort_by_length
            import logging

            settings.logger = logging.getLogger("paddle_tpu.data")
            if init_hook is not None:
                # the runtime injects file_list/is_train (reference
                # PyDataProvider2.py:161-178 contract); hooks without a
                # **kwargs catch-all only receive the names they declare
                import inspect

                sig = inspect.signature(init_hook)
                if not any(
                    p.kind == p.VAR_KEYWORD for p in sig.parameters.values()
                ):
                    kwargs = {k: v for k, v in kwargs.items() if k in sig.parameters}
                init_hook(settings, **kwargs)
            if settings.input_types is None:
                raise ValueError(
                    f"provider {fn.__name__}: input_types not declared "
                    "(pass to @provider or set in init_hook)"
                )
            return settings

        p.init = init

        functools.update_wrapper(p.__class__, fn, updated=[])
        return p

    return deco
