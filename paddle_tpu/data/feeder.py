"""Batch assembly and the host-side data pipeline.

Replaces the reference's DataProvider/DoubleBuffer machinery
(/root/reference/paddle/gserver/dataproviders/DataProvider.h:59,245,286 and
PyDataProvider2.cpp:176 scanners): pulls samples from a @provider
generator, shuffles in a pool, packs padded numpy batches (the scanner
role), and prefetches asynchronously on a background thread so the TPU step
never waits on Python.

Padding uses *bucketed* sequence lengths (next power-of-two-ish) so jit
recompiles are bounded — the TPU replacement for the reference's ragged
no-padding layout.
"""

from __future__ import annotations

import ctypes
import itertools
import json
import queue
import random
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from paddle_tpu.graph.argument import Argument
from paddle_tpu.data.provider import DataType, SequenceType
from paddle_tpu.native import ptr
from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import spans as obs_spans
from paddle_tpu.proto import DataConfig
from paddle_tpu.resilience import BadSampleError, DataStallError
from paddle_tpu.resilience.faultinject import fault_point
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger
from paddle_tpu.utils.retry import RetryPolicy


def bucket_length(n: int, multiple: int = 8) -> int:
    """Round up to limit distinct padded shapes: next multiple of
    ``multiple`` below 64, else next power of two."""
    n = max(n, 1)
    if n <= 64:
        return ((n + multiple - 1) // multiple) * multiple
    p = 64
    while p < n:
        p *= 2
    return p


def _flat_i32(seqs, total: int) -> np.ndarray:
    return np.fromiter(itertools.chain.from_iterable(seqs), dtype=np.int32, count=total)


class BatchAssembler:
    """Packs a list of samples (per @provider input_types) into Arguments.

    The packing hot loops (the reference's C++ field scanners,
    PyDataProvider2.cpp:611-865) run in the native datapath library when it
    is available — ctypes calls release the GIL, so the prefetch thread
    packs the next batch while the main thread runs Python — and fall back
    to NumPy loops otherwise.
    """

    def __init__(self, input_types: Sequence, slot_names: Sequence[str]):
        from paddle_tpu.native import get_lib

        self._native = get_lib()
        if isinstance(input_types, dict):
            self.slot_names = list(input_types.keys())
            self.input_types = [input_types[k] for k in self.slot_names]
        else:
            self.input_types = list(input_types)
            self.slot_names = list(slot_names)
        assert len(self.input_types) == len(self.slot_names), (
            f"provider declares {len(self.input_types)} slots but model has "
            f"input layers {self.slot_names}"
        )

    def assemble(self, samples: List[Sequence[Any]]) -> Dict[str, Argument]:
        # samples are positional lists/tuples or dicts keyed by slot name
        # (both are legal @provider yields, ref PyDataProvider2.py docs)
        out: Dict[str, Argument] = {}
        for i, (name, tp) in enumerate(zip(self.slot_names, self.input_types)):
            values = [s[name] if isinstance(s, dict) else s[i] for s in samples]
            out[name] = self._slot(values, tp)
        return out

    def _slot(self, values: List[Any], tp) -> Argument:
        if tp.seq_type == SequenceType.NO_SEQUENCE:
            return self._scalar_slot(values, tp)
        if tp.seq_type == SequenceType.SEQUENCE:
            return self._seq_slot(values, tp)
        return self._subseq_slot(values, tp)

    # ---- scanners (roles of Dense/Index/Sparse*Scanner in the reference)

    def _dense_row(self, v, tp) -> np.ndarray:
        return np.asarray(v, dtype=np.float32).reshape(tp.dim)

    def _sparse_row(self, v, tp, with_value: bool) -> np.ndarray:
        row = np.zeros((tp.dim,), dtype=np.float32)
        if with_value:
            for idx, val in v:
                row[int(idx)] = float(val)
        else:
            idx = np.asarray(v, dtype=np.int64)
            row[idx] = 1.0
        return row

    def _row(self, v, tp) -> np.ndarray:
        if tp.type == DataType.Dense:
            return self._dense_row(v, tp)
        if tp.type == DataType.SparseNonValue:
            return self._sparse_row(v, tp, with_value=False)
        if tp.type == DataType.SparseValue:
            return self._sparse_row(v, tp, with_value=True)
        raise ValueError(f"unsupported slot type {tp.type}")

    # -- native marshalling helpers

    def _split_sparse(self, rows, tp):
        """Flatten sparse rows → (indices[i64], values[f32]|None, counts[i32])."""
        counts = np.asarray([len(r) for r in rows], dtype=np.int32)
        total = int(counts.sum())
        if tp.type == DataType.SparseValue:
            idx = np.fromiter(
                (int(p[0]) for r in rows for p in r), dtype=np.int64, count=total
            )
            val = np.fromiter(
                (float(p[1]) for r in rows for p in r), dtype=np.float32, count=total
            )
            return idx, val, counts
        idx = np.fromiter(
            (int(i) for r in rows for i in r), dtype=np.int64, count=total
        )
        return idx, None, counts

    @staticmethod
    def _check_bounds(idx: np.ndarray, dim: int) -> None:
        # the C packers don't bounds-check; a bad index must fail here like
        # the NumPy fallback would, not corrupt the batch buffer
        if idx.size and (idx.min() < 0 or idx.max() >= dim):
            bad = idx[(idx < 0) | (idx >= dim)][0]
            raise IndexError(f"sparse index {int(bad)} out of range [0, {dim})")

    def _native_sparse_rows(self, rows, tp) -> np.ndarray:
        lib = self._native
        idx, val, counts = self._split_sparse(rows, tp)
        self._check_bounds(idx, tp.dim)
        out = np.empty((len(rows), tp.dim), dtype=np.float32)
        lib.pt_pack_sparse_rows(
            ptr(idx, ctypes.c_int64),
            ptr(val, ctypes.c_float) if val is not None else None,
            ptr(counts, ctypes.c_int32),
            len(rows),
            tp.dim,
            ptr(out, ctypes.c_float),
        )
        return out

    def _scalar_slot(self, values, tp) -> Argument:
        if tp.type == DataType.Index:
            return Argument(ids=np.asarray(values, dtype=np.int32))
        if self._native is not None and tp.type in (
            DataType.SparseNonValue,
            DataType.SparseValue,
        ):
            return Argument(value=self._native_sparse_rows(values, tp))
        rows = np.stack([self._row(v, tp) for v in values])
        return Argument(value=rows)

    def _seq_slot(self, values, tp) -> Argument:
        B = len(values)
        lengths = np.asarray([len(v) for v in values], dtype=np.int32)
        T = bucket_length(int(lengths.max()) if B else 1)
        lib = self._native
        if tp.type == DataType.Index:
            if lib is not None:
                flat = _flat_i32(values, int(lengths.sum()))
                ids = np.empty((B, T), dtype=np.int32)
                lib.pt_pack_index_seq(
                    ptr(flat, ctypes.c_int32), ptr(lengths, ctypes.c_int32),
                    B, T, ptr(ids, ctypes.c_int32),
                )
                return Argument(ids=ids, seq_lengths=lengths)
            ids = np.zeros((B, T), dtype=np.int32)
            for b, seq in enumerate(values):
                ids[b, : len(seq)] = np.asarray(seq, dtype=np.int32)
            return Argument(ids=ids, seq_lengths=lengths)
        if lib is not None and tp.type == DataType.Dense:
            blocks = [
                np.asarray(seq, dtype=np.float32).reshape(len(seq), tp.dim)
                for seq in values
            ]
            flat = np.concatenate(blocks) if blocks else np.empty((0, tp.dim), np.float32)
            flat = np.ascontiguousarray(flat)
            val = np.empty((B, T, tp.dim), dtype=np.float32)
            lib.pt_pack_dense_seq(
                ptr(flat, ctypes.c_float), ptr(lengths, ctypes.c_int32),
                B, T, tp.dim, ptr(val, ctypes.c_float),
            )
            return Argument(value=val, seq_lengths=lengths)
        if lib is not None and tp.type in (DataType.SparseNonValue, DataType.SparseValue):
            steps = [row for seq in values for row in seq]
            idx, sval, step_counts = self._split_sparse(steps, tp)
            self._check_bounds(idx, tp.dim)
            val = np.empty((B, T, tp.dim), dtype=np.float32)
            lib.pt_pack_sparse_seq(
                ptr(idx, ctypes.c_int64),
                ptr(sval, ctypes.c_float) if sval is not None else None,
                ptr(step_counts, ctypes.c_int32),
                ptr(lengths, ctypes.c_int32),
                B, T, tp.dim, ptr(val, ctypes.c_float),
            )
            return Argument(value=val, seq_lengths=lengths)
        val = np.zeros((B, T, tp.dim), dtype=np.float32)
        for b, seq in enumerate(values):
            for t, item in enumerate(seq):
                val[b, t] = self._row(item, tp)
        return Argument(value=val, seq_lengths=lengths)

    def _subseq_slot(self, values, tp) -> Argument:
        B = len(values)
        num_subs = np.asarray([len(v) for v in values], dtype=np.int32)
        S = max(int(num_subs.max()) if B else 1, 1)
        sub_lens = np.zeros((B, S), dtype=np.int32)
        for b, sample in enumerate(values):
            for s, sub in enumerate(sample):
                sub_lens[b, s] = len(sub)
        T = bucket_length(int(sub_lens.max()))
        if tp.type == DataType.Index:
            if self._native is not None:
                total = int(sub_lens.sum())
                flat = _flat_i32(
                    (sub for sample in values for sub in sample), total
                )
                ids = np.empty((B, S, T), dtype=np.int32)
                self._native.pt_pack_index_subseq(
                    ptr(flat, ctypes.c_int32), ptr(sub_lens, ctypes.c_int32),
                    B, S, T, ptr(ids, ctypes.c_int32),
                )
                return Argument(ids=ids, seq_lengths=num_subs, sub_seq_lengths=sub_lens)
            ids = np.zeros((B, S, T), dtype=np.int32)
            for b, sample in enumerate(values):
                for s, sub in enumerate(sample):
                    ids[b, s, : len(sub)] = np.asarray(sub, dtype=np.int32)
            return Argument(ids=ids, seq_lengths=num_subs, sub_seq_lengths=sub_lens)
        val = np.zeros((B, S, T, tp.dim), dtype=np.float32)
        for b, sample in enumerate(values):
            for s, sub in enumerate(sample):
                for t, item in enumerate(sub):
                    val[b, s, t] = self._row(item, tp)
        return Argument(value=val, seq_lengths=num_subs, sub_seq_lengths=sub_lens)


class MultiDataProvider:
    """Ratio-mixed composition of sub-providers (ref: MultiDataProvider,
    /root/reference/paddle/gserver/dataproviders/MultiDataProvider.h:22):
    each pass draws samples from every sub-provider's stream in proportion
    to its DataConfig.data_ratio, through one shared shuffle/batch path.
    All sub-providers must declare the same slot layout."""

    def __init__(self, subs: List["DataProvider"], ratios: List[int],
                 async_prefetch: bool = True):
        assert subs and len(subs) == len(ratios)
        self.subs = subs
        self.ratios = [max(int(r), 1) for r in ratios]
        self.async_prefetch = async_prefetch
        base = subs[0]
        self.batch_size = base.batch_size
        self.assembler = base.assembler

        def layout(p):
            return [(t.type, t.dim, t.seq_type) for t in p.assembler.input_types]

        for i, sub in enumerate(subs[1:], 1):
            assert layout(sub) == layout(base), (
                f"multi data provider: sub-provider {i} slot layout "
                f"{layout(sub)} != {layout(base)}"
            )
        self._base = base

    def batches(self) -> Iterator[Dict[str, Argument]]:
        # interleave ratio-sized runs from each sub-stream into the base
        # provider's shuffle/batch machinery
        def mixed_samples():
            its = [iter(sub._samples()) for sub in self.subs]
            live = [True] * len(its)
            while any(live):
                for i, it in enumerate(its):
                    if not live[i]:
                        continue
                    for _ in range(self.ratios[i]):
                        try:
                            yield next(it)
                        except StopIteration:
                            live[i] = False
                            break

        if self.async_prefetch:
            yield from self._base._prefetched(
                self._base._batch_lists_from(mixed_samples())
            )
        else:
            yield from self._base._batches_from(mixed_samples())


class DataProvider:
    """Pass-oriented batch iterator over a @provider object.

    getNextBatch analog (/root/reference/paddle/gserver/dataproviders/
    DataProvider.h:313) with shuffle pool and async double-buffering.
    """

    def __init__(
        self,
        provider_obj,
        file_list: List[str],
        batch_size: int,
        slot_names: Sequence[str],
        provider_kwargs: Optional[Dict] = None,
        async_prefetch: bool = True,
        seed: int = 1,
        drop_last: bool = False,
        for_test: bool = False,
        stall_timeout: Optional[float] = None,
        max_bad_samples: Optional[int] = None,
        retry: Optional[RetryPolicy] = None,
        packer_threads: Optional[int] = None,
        prefetch_depth: Optional[int] = None,
    ):
        from paddle_tpu.utils.flags import FLAGS

        self.provider = provider_obj
        self.file_list = file_list
        self.batch_size = batch_size
        # resilience knobs: explicit argument > global flag
        self.stall_timeout = (
            float(FLAGS.data_stall_timeout) if stall_timeout is None else float(stall_timeout)
        )
        self.max_bad_samples = (
            int(FLAGS.max_bad_samples) if max_bad_samples is None else int(max_bad_samples)
        )
        # packing-stage parallelism (doc/performance.md "Zero-stall
        # host"): N pool threads run BatchAssembler.assemble (the native
        # C packers release the GIL) feeding an order-preserving queue
        # of prefetch_depth packed batches; 1 keeps the classic single
        # prefetch thread
        self.packer_threads = max(1, int(
            FLAGS.data_packer_threads if packer_threads is None else packer_threads
        ))
        self.prefetch_depth = max(1, int(
            FLAGS.prefetch_depth if prefetch_depth is None else prefetch_depth
        ))
        self.retry = retry if retry is not None else RetryPolicy.from_flags(FLAGS)
        self._bad_samples = 0
        # sample-granular watchdog heartbeat (see _watched_get): a
        # provider legitimately spending minutes filling a big shuffle
        # pool IS making progress and must not trip the stall timeout
        self._progress = time.monotonic()
        init_kwargs = dict(provider_kwargs or {})
        # runtime-injected hook kwargs (reference PyDataProvider2 contract):
        # user args from the config take precedence if they collide
        init_kwargs.setdefault("is_train", not for_test)
        init_kwargs.setdefault("file_list", list(file_list))
        self.settings = provider_obj.init(**init_kwargs)
        self.assembler = BatchAssembler(self.settings.input_types, slot_names)
        self.async_prefetch = async_prefetch
        self.rng = random.Random(seed)
        self.drop_last = drop_last
        # should_shuffle=None in the provider means: shuffle in training,
        # keep order for test/gen (matches the reference trainer)
        shuffle = self.settings.should_shuffle
        self.shuffle = (not for_test) if shuffle is None else bool(shuffle)
        # length-sorted bucketing (TPU-native @provider extension, see
        # data/provider.py): only ever applied on the shuffled training
        # path — test/generation sample order must never change
        self.sort_by_length = (
            self.shuffle and bool(getattr(self.settings, "sort_by_length", False))
        )
        self._cache: Optional[List] = None
        self._use_cache = getattr(provider_obj, "cache", 0) == 1

    # -- sample stream

    def _samples(self) -> Iterator[Sequence[Any]]:
        if self._use_cache and self._cache is not None:
            yield from self._cache
            return
        collect = [] if self._use_cache else None
        for fname in self.file_list:
            for sample in self._iter_file(fname):
                if not isinstance(sample, (list, tuple, dict)):
                    sample = [sample]
                if self.max_bad_samples > 0 and not self._sample_ok(sample, fname):
                    continue
                if collect is not None:
                    collect.append(sample)
                yield sample
        if collect is not None:
            self._cache = collect

    def _iter_file(self, fname: str) -> Iterator[Any]:
        """One file's samples through the shared RetryPolicy: a transient
        error from the user generator (flaky shared FS, a remote source
        hiccup) re-opens the generator and fast-forwards past the samples
        already yielded. Exactly-once delivery holds for generators that
        yield the same sequence on every open of the same file (true of
        every provider in this repo — shuffling happens downstream in the
        pool); a generator with INTERNAL nondeterministic order may
        duplicate or drop samples across a retry. Fast-forwarding also
        re-runs the generator's side effects from the start of the
        file."""
        yielded = 0
        state = None
        failed_at = -1
        while True:
            it = self.provider.generator_fn(self.settings, fname)
            try:
                skip = yielded
                for sample in it:
                    if skip > 0:
                        skip -= 1
                        # fast-forward IS progress: without a heartbeat a
                        # long replay after a late-file retry would trip
                        # the stall watchdog mid-recovery
                        self._progress = time.monotonic()
                        continue
                    fault_point("provider.yield", info=fname)
                    yield sample
                    yielded += 1
                return
            except self.retry.retry_on as e:
                # the attempt/deadline budget covers one failure BURST:
                # successful progress since the last failure earns a fresh
                # budget, so two isolated hiccups minutes apart on a huge
                # file don't add up to "exhausted"
                if state is None or yielded > failed_at:
                    state = self.retry.begin(f"provider {self.provider.name}({fname})")
                failed_at = yielded
                state.retry(e)  # sleeps, or re-raises when exhausted

    def _sample_ok(self, sample, fname: str) -> bool:
        """Bounded bad-sample budget (``--max_bad_samples``): a sample
        that cannot be assembled is skipped and logged instead of
        poisoning its whole batch, up to the budget — then fail loudly.
        Validation (a one-sample assembly) only runs when the budget is
        enabled, so the default path pays nothing."""
        try:
            self.assembler.assemble([sample])
            return True
        except Exception as e:
            self._bad_samples += 1
            obs.registry().counter("data.bad_samples").inc()
            if self._bad_samples > self.max_bad_samples:
                raise BadSampleError(
                    f"provider {self.provider.name}: {self._bad_samples} malformed "
                    f"samples exceeds --max_bad_samples={self.max_bad_samples} "
                    f"(last, from {fname!r}: {e})"
                ) from e
            if self._bad_samples <= 5 or self._bad_samples % 100 == 0:
                logger.warning(
                    "skipping malformed sample %d/%d from %s: %s",
                    self._bad_samples, self.max_bad_samples, fname, e,
                )
            return False

    def batches(self) -> Iterator[Dict[str, Argument]]:
        """One pass of batches (shuffled within the pool)."""
        if self.async_prefetch:
            yield from self._prefetched(self._batch_lists_from(self._samples()))
        else:
            yield from self._batches_sync()

    def _prefetched(self, batch_lists) -> Iterator[Dict[str, Argument]]:
        """The async pipeline over a raw batch-list stream — always the
        packer-pool pipeline: with ``packer_threads=1`` a one-worker
        pool IS the classic double-buffer (one thread packs ahead of
        the consumer through a bounded queue), so a single
        implementation carries the order, watchdog, fault-site, and
        telemetry contracts for every thread count."""
        yield from self._pool_packed(batch_lists)

    def _batches_sync(self) -> Iterator[Dict[str, Argument]]:
        yield from self._batches_from(self._samples())

    def _batches_from(self, samples) -> Iterator[Dict[str, Argument]]:
        for batch in self._batch_lists_from(samples):
            yield self.assembler.assemble(batch)

    def _batch_lists_from(self, samples) -> Iterator[List]:
        """The sequential half of batching: shuffle pool, length sort,
        batch slicing — yields raw SAMPLE LISTS so the CPU-heavy
        ``assemble`` can run wherever the caller wants (inline, one
        prefetch thread, or the packer pool)."""
        pool_size = self.settings.pool_size
        if pool_size is None or pool_size <= 0:
            pool_size = 10000 * max(1, self.batch_size // 128 + 1)
        pool: List = []
        for sample in samples:
            self._progress = time.monotonic()  # heartbeat: per SAMPLE
            pool.append(sample)
            if len(pool) >= pool_size:
                yield from self._drain(pool, final=False)
        yield from self._drain(pool, final=True)

    def _sample_len(self, sample) -> int:
        """Padded-cost key for length sorting. SEQUENCE slots cost their
        length; SUB_SEQUENCE slots pad to [S, T] (see _subseq_slot), so
        their key is the padded AREA S·max(sub length) — sorting by
        subsequence count alone would group samples with wildly different
        sub-lengths and deliver no padding reduction."""
        cost = 0
        for i, (name, tp) in enumerate(
            zip(self.assembler.slot_names, self.assembler.input_types)
        ):
            if tp.seq_type == SequenceType.NO_SEQUENCE:
                continue
            v = sample[name] if isinstance(sample, dict) else sample[i]
            if tp.seq_type == SequenceType.SUB_SEQUENCE:
                cost = max(cost, len(v) * max((len(s) for s in v), default=0))
            else:
                cost = max(cost, len(v))
        return cost

    def _drain(self, pool: List, final: bool) -> Iterator[List]:
        """Slice the (shuffled/sorted) pool into raw batch sample lists."""
        if self.shuffle:
            self.rng.shuffle(pool)
        if self.sort_by_length:
            # shuffle-then-stable-sort: similar-length samples become
            # batch neighbors (tight padding), equal-length runs stay
            # randomly ordered, and the BATCH order is re-shuffled below
            # so the pass still visits lengths in random order
            pool.sort(key=self._sample_len)
            batches = []
            while len(pool) >= self.batch_size:
                batches.append(pool[: self.batch_size])
                del pool[: self.batch_size]
            self.rng.shuffle(batches)
            yield from batches
            # the remainder (the longest leftovers) mixes into the next drain
        else:
            # keep a remainder in the pool between drains so shuffling
            # mixes across pool boundaries
            while len(pool) >= self.batch_size:
                batch = pool[: self.batch_size]
                del pool[: self.batch_size]
                yield batch
        if final and pool and not self.drop_last:
            yield list(pool)
            pool.clear()

    def _watched_get(self, fetch, beat: List[float], worker, q, age_gauge) -> Any:
        """One watchdog-guarded wait for a pipeline item.

        ``fetch(timeout_or_None)`` must return the item or raise
        ``queue.Empty`` / ``TimeoutError`` on a bounded wait that came
        up empty. Shared by the pool consumer's two wait points (queue
        get, future result) so the stall-detection rule cannot drift:
        when the consumer has waited ``stall_timeout`` seconds AND
        nothing in the pipeline made progress in that window (not a
        batch handed over — ``beat`` — nor one raw sample pulled —
        ``self._progress``), raise a diagnosable DataStallError instead
        of hanging. 0 disables the watchdog. ``age_gauge`` is resolved
        once by the caller — this runs twice per batch on the consumer
        hot path and must not pay a locked registry lookup each time."""
        timeout = self.stall_timeout
        if not timeout or timeout <= 0:
            return fetch(None)
        wait_start = cc.monotonic()
        while True:
            try:
                return fetch(min(timeout / 4.0, 1.0))
            except (queue.Empty, TimeoutError, _FutureTimeout):
                now = cc.monotonic()
                # progress = a batch handed over (beat) OR a raw sample
                # pulled (self._progress): pool-filling counts as
                # progress, only true dead air trips
                last = max(beat[0], self._progress)
                age_gauge.set(now - last)
                if now - wait_start >= timeout and now - last >= timeout:
                    raise DataStallError(
                        f"data pipeline stalled: no batch for "
                        f"{now - wait_start:.1f}s (stall timeout "
                        f"{timeout:g}s; provider "
                        f"{getattr(self.provider, 'name', '?')}; "
                        f"prefetch worker "
                        f"{'alive' if worker.is_alive() else 'dead'}, "
                        f"last progress {now - last:.1f}s ago, "
                        f"queue depth {q.qsize()}). Raise "
                        f"--data_stall_timeout or fix the provider."
                    )

    def _pool_packed(self, batch_lists: Iterator[List]) -> Iterator[Dict[str, Argument]]:
        """N-thread packing stage (``--data_packer_threads``): a
        dispatcher thread runs the sequential pool/shuffle half and
        submits each raw batch to a thread pool whose workers run
        ``BatchAssembler.assemble`` (the native C packers release the
        GIL, so packs genuinely overlap); completed batches flow to the
        consumer through an order-preserving queue bounded at
        ``--prefetch_depth``. With one packer this IS the classic
        DoubleBuffer analog: one thread packs ahead of the consumer
        through a bounded queue. A provider that blocks forever (dead
        NFS mount, a generator stuck on a socket) used to hang the
        training loop inside ``q.get()`` — which also blocked SIGTERM
        preemption handling, the worst possible failure on a pod; the
        consumer polls via ``_watched_get`` and raises a diagnosable
        DataStallError (worker liveness, queue depth, stall age)
        instead. The ``provider.stall`` fault site and bad-sample
        budget (upstream in ``_samples``) keep their old semantics."""
        from concurrent.futures import ThreadPoolExecutor

        q = cc.Queue(maxsize=self.prefetch_depth)
        sentinel = object()
        err: List[BaseException] = []
        beat = [cc.monotonic()]
        busy = [0]
        busy_lock = cc.Lock()
        busy_hist = obs.registry().histogram("data.pack_threads_busy")

        def pack(batch):
            with busy_lock:
                busy[0] += 1
                n_busy = busy[0]
            try:
                busy_hist.observe(float(n_busy))
                out = self.assembler.assemble(batch)
                beat[0] = cc.monotonic()  # a finished pack IS progress
                return out
            finally:
                with busy_lock:
                    busy[0] -= 1

        pool = ThreadPoolExecutor(
            max_workers=self.packer_threads, thread_name_prefix="pt-data-pack"
        )

        def dispatcher():
            try:
                for batch in batch_lists:
                    fault_point("provider.stall")
                    beat[0] = cc.monotonic()
                    # the bounded put is the backpressure: at most
                    # prefetch_depth packed/packing batches run ahead
                    q.put(pool.submit(pack, batch))
            except BaseException as e:  # propagate into the consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = cc.Thread(
            target=dispatcher, daemon=True, name="pt-data-prefetch"
        )
        t.start()
        wait_counter = obs.registry().counter("data.prefetch_wait_s")
        age_gauge = obs.registry().gauge("data.heartbeat_age_s")

        def fetch_future(to):
            return q.get(timeout=to) if to is not None else q.get()

        try:
            while True:
                wait_t0 = time.perf_counter()
                fut = self._watched_get(fetch_future, beat, t, q, age_gauge)
                if fut is not sentinel:
                    # the future is already executing (pool order =
                    # submission order), so this wait is short — but a
                    # packer wedged inside a bad native call must still
                    # trip the watchdog, not hang the step loop
                    item = self._watched_get(
                        lambda to: fut.result(timeout=to), beat, t, q,
                        age_gauge,
                    )
                else:
                    item = sentinel
                waited = time.perf_counter() - wait_t0
                wait_counter.inc(waited)
                age_gauge.set(0.0)
                if waited > 1e-3:
                    obs_spans.record_perf("data/prefetch_wait", wait_t0, waited)
                if item is sentinel:
                    break
                yield item
            if err:
                raise err[0]
        finally:
            pool.shutdown(wait=False, cancel_futures=True)


def create_data_provider(
    data_config: DataConfig,
    batch_size: int,
    slot_names: Sequence[str],
    async_prefetch: bool = True,
    seed: int = 1,
    for_test: bool = False,
    stall_timeout: Optional[float] = None,
    max_bad_samples: Optional[int] = None,
    retry: Optional[RetryPolicy] = None,
    packer_threads: Optional[int] = None,
    prefetch_depth: Optional[int] = None,
) -> DataProvider:
    """Instantiate from a DataConfig (define_py_data_sources2 output).

    ``stall_timeout`` / ``max_bad_samples`` / ``retry`` override the
    global flags (--data_stall_timeout / --max_bad_samples /
    --io_retry_*) for this provider; ``packer_threads`` /
    ``prefetch_depth`` override --data_packer_threads /
    --prefetch_depth. None inherits the flag."""
    import importlib
    import os
    import sys

    resilience_kw = dict(
        stall_timeout=stall_timeout, max_bad_samples=max_bad_samples, retry=retry,
        packer_threads=packer_threads, prefetch_depth=prefetch_depth,
    )
    if data_config.type == "multi":
        subs = [
            create_data_provider(
                sub, batch_size, slot_names,
                async_prefetch=False, seed=seed + i, for_test=for_test,
                **resilience_kw,
            )
            for i, sub in enumerate(data_config.sub_data_configs)
        ]
        return MultiDataProvider(
            subs,
            [s.data_ratio for s in data_config.sub_data_configs],
            async_prefetch=async_prefetch,
        )
    with open(data_config.files) as f:
        file_list = [line.strip() for line in f if line.strip()]
    if data_config.type == "bin":
        # binary shards (ProtoDataProvider role, paddle_tpu.data.binary)
        from paddle_tpu.data.binary import BinaryProvider

        assert file_list, f"{data_config.files}: empty shard list"
        return DataProvider(
            BinaryProvider(file_list[0]),
            file_list,
            batch_size,
            slot_names,
            async_prefetch=async_prefetch,
            seed=seed,
            for_test=for_test,
            **resilience_kw,
        )
    assert data_config.type in ("py2", "py"), f"unsupported data type {data_config.type!r}"
    # the provider module conventionally sits next to the config / file
    # list (reference: PyDataProvider2.cpp loads the module by name with
    # the config dir importable); make cwd + the list dir importable.
    search = [os.path.dirname(os.path.abspath(data_config.files)), os.getcwd()]
    from paddle_tpu.config.config_parser import evict_shadowed_modules

    for p in search:
        evict_shadowed_modules(p)
    added = [p for p in search if p not in sys.path]
    sys.path[:0] = added
    try:
        module = importlib.import_module(data_config.load_data_module)
    finally:
        for p in added:
            sys.path.remove(p)
    provider_obj = getattr(module, data_config.load_data_object)
    kwargs = json.loads(data_config.load_data_args) if data_config.load_data_args else {}
    return DataProvider(
        provider_obj,
        file_list,
        batch_size,
        slot_names,
        provider_kwargs=kwargs,
        async_prefetch=async_prefetch,
        seed=seed,
        for_test=for_test,
        **resilience_kw,
    )
