"""Learning-rate schedules.

Reference: /root/reference/paddle/parameter/LearningRateScheduler.cpp —
registered by name: constant, poly, exp, discexp, linear, manual,
pass_manual. ``t`` is the number of samples processed (the reference's
numSamplesProcessed), so schedules are batch-size independent.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.proto import OptimizationConfig


def learning_rate_at(opt: OptimizationConfig, num_samples_processed) -> jnp.ndarray:
    lr = opt.learning_rate
    a, b = opt.learning_rate_decay_a, opt.learning_rate_decay_b
    t = num_samples_processed
    sched = opt.learning_rate_schedule
    if sched in ("constant", ""):
        return jnp.asarray(lr)
    if sched == "poly":
        return lr * jnp.power(1.0 + a * t, -b)
    if sched == "caffe_poly":
        return lr * jnp.power(1.0 - t / a, b)
    if sched == "exp":
        return lr * jnp.power(a, t / b)
    if sched == "discexp":
        return lr * jnp.power(a, jnp.floor(t / b))
    if sched == "linear":
        return jnp.maximum(lr - a * t, b)
    if sched == "manual":
        # "seg1:lr1,seg2:lr2,..." — segment boundaries in samples
        segs = []
        for part in opt.learning_rate_args.split(","):
            boundary, _, rate = part.partition(":")
            segs.append((float(boundary), float(rate)))
        out = jnp.asarray(segs[-1][1])
        for boundary, rate in reversed(segs[:-1]):
            out = jnp.where(t < boundary, rate, out)
        return out
    raise ValueError(f"unknown learning_rate_schedule {sched!r}")
