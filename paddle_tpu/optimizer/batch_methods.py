"""Whole-data batch algorithms: L-BFGS and OWL-QN.

TPU-native realization of the reference's batch-algorithm mode
(``Trainer::trainOnePassBatch``, /root/reference/paddle/trainer/
Trainer.cpp:492, selected by ``algorithm=owlqn``): one pass = one
full-dataset gradient, one quasi-Newton update, accept/reject by
backtracking line search. The reference runs this through the pserver's
distributed vector service (``ParameterServer2::doOperation``,
ParameterServer2.cpp:1222-1359: BFGS two-loop ops, ``OP_MAKE_STEEPEST_DESC_DIR``
pseudo-gradient, ``OP_FIX_DIR_SIGNS`` / ``OP_FIX_OMEGA_SIGNS`` orthant
projection, cost-improvement line search driven by the trainers).
Here the whole thing is host-side pytree math between jitted full-data
gradient sweeps — there is no parameter server to shard vectors across,
and the O(params) two-loop recursion is negligible next to the jitted
data sweeps.

Hyperparameters follow the reference settings (config_parser.py:2941-2947):
``c1`` (Armijo sufficient-decrease), ``backoff`` (step shrink factor),
``max_backoff`` (line-search trials), ``owlqn_steps`` (history size),
``l1weight``/``l2weight`` (OWL-QN regularization; l1 drives the
pseudo-gradient/orthant machinery, l2 folds into cost+gradient).

Note: ``backoff`` here is the line search's NUMERICAL step-shrink
factor, unrelated to failure handling — transient-I/O retry backoff
lives in ``paddle_tpu.utils.retry.RetryPolicy`` (doc/resilience.md).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, Tuple

import numpy as np

Params = Dict[str, np.ndarray]


def _tmap(f, *trees: Params) -> Params:
    return {k: f(*(t[k] for t in trees)) for k in trees[0]}


def _dot(a: Params, b: Params) -> float:
    return float(sum(np.vdot(a[k], b[k]) for k in a))


class BatchMethod:
    """L-BFGS / OWL-QN driver over numpy pytrees.

    Usage per pass (the trainer owns data sweeps)::

        method.record_grad(grad)          # completes the previous (s, y)
        d = method.direction(params, grad)
        accepted, new_params, f = method.line_search(
            params, cost, grad, d, eval_cost)

    ``eval_cost(params) -> float`` and the ``cost`` argument are the RAW
    full-data cost; :meth:`regularized` adds the l1/l2 terms on both
    sides of the comparison internally.
    """

    def __init__(
        self,
        method: str = "lbfgs",          # lbfgs | owlqn
        history: int = 10,              # owlqn_steps
        c1: float = 1e-4,
        backoff: float = 0.5,
        max_backoff: int = 5,
        l1weight: float = 0.0,          # owlqn only
        l2weight: float = 0.0,
        learning_rate: float = 1.0,     # first-pass step scale
    ):
        assert method in ("lbfgs", "owlqn"), method
        self.method = method
        self.c1 = c1
        self.backoff = backoff
        self.max_backoff = max(1, int(max_backoff))
        self.l1 = float(l1weight) if method == "owlqn" else 0.0
        self.l2 = float(l2weight)
        self.lr = learning_rate
        self._hist: deque = deque(maxlen=max(1, int(history)))  # (s, y, 1/y·s)
        self._pending = None
        self.n_accepted = 0

    # ------------------------------------------------------------ pieces

    def regularized(self, cost: float, params: Params) -> float:
        """Full objective = data cost + l2/2·‖x‖² (+ l1·‖x‖₁ for owlqn)."""
        if self.l2:
            cost += 0.5 * self.l2 * _dot(params, params)
        if self.l1:
            cost += self.l1 * float(sum(np.abs(v).sum() for v in params.values()))
        return cost

    def _smooth_grad(self, params: Params, grad: Params) -> Params:
        return _tmap(lambda g, x: g + self.l2 * x, grad, params) if self.l2 else grad

    def _pseudo_grad(self, params: Params, grad: Params) -> Params:
        """OWL-QN steepest-descent direction source (OP_MAKE_STEEPEST_DESC_DIR):
        subgradient of l1·‖x‖₁ chosen minimal in magnitude at x=0."""
        l1 = self.l1

        def pg(g, x):
            return np.where(
                x != 0,
                g + l1 * np.sign(x),
                np.where(g + l1 < 0, g + l1, np.where(g - l1 > 0, g - l1, 0.0)),
            )

        return _tmap(pg, grad, params)

    def effective_grad(self, params: Params, grad: Params) -> Params:
        g = self._smooth_grad(params, grad)
        return self._pseudo_grad(params, g) if self.l1 else g

    def direction(self, params: Params, grad: Params) -> Params:
        """Two-loop L-BFGS recursion on the (pseudo-)gradient."""
        g = self.effective_grad(params, grad)
        q = _tmap(np.copy, g)
        alphas = []
        for s, y, rho in reversed(self._hist):
            a = rho * _dot(s, q)
            q = _tmap(lambda qv, yv: qv - a * yv, q, y)
            alphas.append(a)
        if self._hist:
            s, y, _ = self._hist[-1]
            gamma = _dot(s, y) / max(_dot(y, y), 1e-30)
            q = _tmap(lambda v: gamma * v, q)
        for (s, y, rho), a in zip(self._hist, reversed(alphas)):
            b = rho * _dot(y, q)
            q = _tmap(lambda qv, sv: qv + (a - b) * sv, q, s)
        d = _tmap(np.negative, q)
        if self.l1:
            # OP_FIX_DIR_SIGNS: drop components that leave the descent orthant
            d = _tmap(lambda dv, gv: np.where(dv * gv < 0, dv, 0.0), d, g)
        return d

    def _project_orthant(self, x_new: Params, x: Params, g: Params) -> Params:
        """OP_FIX_OMEGA_SIGNS: clip coordinates that crossed zero out of the
        chosen orthant (sign of x, or of -pseudo-grad where x was 0)."""

        def proj(xn, xo, gv):
            orth = np.where(xo != 0, np.sign(xo), -np.sign(gv))
            return np.where(xn * orth < 0, 0.0, xn)

        return _tmap(proj, x_new, x, g)

    # -------------------------------------------------------- line search

    def line_search(
        self,
        params: Params,
        cost: float,
        grad: Params,
        direction: Params,
        eval_cost: Callable[[Params], float],
    ) -> Tuple[bool, Params, float]:
        """Backtracking Armijo search along ``direction``.

        Returns (accepted, new_params, new_cost). On accept the history
        is updated with s = Δx and y = Δgrad is deferred to
        :meth:`record_grad` (the caller computes the gradient at the new
        point during the next pass sweep anyway — matching the
        reference, which pays one gradient sweep per pass)."""
        g = self.effective_grad(params, grad)
        f0 = self.regularized(cost, params)
        gd = _dot(g, direction)
        if gd >= 0:  # not a descent direction: reset stale curvature
            self._hist.clear()
            direction = _tmap(np.negative, g)
            gd = _dot(g, direction)
            if gd >= 0:  # zero gradient — converged
                return False, params, f0
        # with curvature history the two-loop direction already carries the
        # inverse-Hessian scale: the natural step is 1. Only the first
        # (steepest-descent) step needs tempering — by learning_rate and
        # the gradient magnitude.
        t = 1.0 if self._hist else min(self.lr, 1.0 / max(np.sqrt(-gd), 1e-12))
        for _ in range(self.max_backoff):
            x_new = _tmap(lambda xv, dv: xv + t * dv, params, direction)
            if self.l1:
                x_new = self._project_orthant(x_new, params, g)
            # sufficient decrease against the REALIZED displacement — after
            # orthant projection the step is shorter than t*d, and judging
            # it by the unprojected t*gd would spuriously reject
            gdelta = _dot(g, _tmap(np.subtract, x_new, params))
            if gdelta >= 0:  # projection killed the descent — shrink
                t *= self.backoff
                continue
            f_new = self.regularized(eval_cost(x_new), x_new)
            if np.isfinite(f_new) and f_new <= f0 + self.c1 * gdelta:
                self._pending = (params, grad, x_new)
                self.n_accepted += 1
                return True, x_new, f_new
            t *= self.backoff
        return False, params, f0

    def on_reject(self) -> bool:
        """Called by the driver after a rejected pass. Clears stale
        curvature so the next pass retries as tempered steepest descent;
        returns False when that retry would be identical to the pass
        that just failed (history was already empty) — i.e. converged or
        stuck, stop training."""
        had_history = bool(self._hist)
        self._hist.clear()
        return had_history

    def record_grad(self, new_grad: Params) -> None:
        """Complete the accepted step's (s, y) curvature pair with the
        gradient measured at the new point."""
        if getattr(self, "_pending", None) is None:
            return
        x_old, g_old, x_new = self._pending
        self._pending = None
        s = _tmap(np.subtract, x_new, x_old)
        y = _tmap(
            np.subtract,
            self._smooth_grad(x_new, new_grad),
            self._smooth_grad(x_old, g_old),
        )
        ys = _dot(y, s)
        if ys > 1e-10 * max(_dot(y, y), 1e-30):  # curvature condition
            self._hist.append((s, y, 1.0 / ys))
