from paddle_tpu.optimizer.updater import Updater
from paddle_tpu.optimizer.schedules import learning_rate_at

__all__ = ["Updater", "learning_rate_at"]
