"""Row-sparse gradients — the sparse-at-scale embedding path.

The reference's first-class strength is high-dimensional sparse training:
embedding rows are prefetched before forward from the *input ids*
(/root/reference/paddle/trainer/TrainerInternal.cpp:91-95 →
GradientMachine::prefetch), gradients live as sparse rows
(paddle/math/SparseRowMatrix.h:31), and the pserver applies per-row
updates (paddle/pserver/ParameterServer2.cpp:352,572).

TPU-native redesign: a gradient for a ``sparse_update`` table is a
``RowSparseGrad`` — the flat occurrence ids from the batch plus one
gradient row per occurrence, both STATIC shapes O(batch·seq), never the
dense [V, D] scatter jax.grad would produce. The machine computes it by
differentiating w.r.t. the *gathered rows* (the prefetch analog); the
updater dedupes occurrences with a sort + segment-sum and scatters
per-row optimizer updates back with out-of-bounds drop — O(N·D) compute
and memory per step regardless of vocabulary size. On a mesh, the table
(and its optimizer slots) shard over rows; XLA partitions the
gather/scatter into ICI collectives (the SPMD replacement for the sparse
pserver's remote row push/pull).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


@jax.tree_util.register_pytree_node_class
class RowSparseGrad:
    """Gradient of a [V, D] table as occurrence rows.

    ids:  [N] int32 — row index per occurrence (duplicates allowed;
          padded positions contribute zero rows and are harmless)
    rows: [N, D] — d(loss)/d(table[ids[n]]) per occurrence
    nrows: static V, for densification and bounds
    """

    def __init__(self, ids: Array, rows: Array, nrows: int):
        self.ids = ids
        self.rows = rows
        self.nrows = nrows

    def tree_flatten(self):
        return (self.ids, self.rows), self.nrows

    @classmethod
    def tree_unflatten(cls, nrows, children):
        ids, rows = children
        return cls(ids, rows, nrows)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.nrows, self.rows.shape[-1])

    def to_dense(self) -> Array:
        """Materialize the dense [V, D] gradient (tests / small-model API
        introspection only — defeats the point at scale)."""
        out = jnp.zeros((self.nrows, self.rows.shape[-1]), self.rows.dtype)
        return out.at[self.ids].add(self.rows)

    def __repr__(self):
        return (
            f"RowSparseGrad(ids={self.ids.shape}, rows={self.rows.shape}, "
            f"nrows={self.nrows})"
        )


def dedupe(ids: Array, rows: Array, nrows: int):
    """Sum duplicate occurrences: returns (uid, g_rows, valid) all [N]-sized.

    uid[k] is the k-th distinct row index (positions past the distinct
    count hold the sentinel ``nrows`` — out of bounds, so scatters with
    mode='drop' ignore them); g_rows[k] is the summed gradient for uid[k].
    Static shapes throughout: N never shrinks, which is what lets this
    run under jit on TPU.
    """
    N = ids.shape[0]
    order = jnp.argsort(ids)
    ids_s = ids[order]
    rows_s = rows[order]
    first = jnp.concatenate([jnp.ones((1,), bool), ids_s[1:] != ids_s[:-1]])
    seg = jnp.cumsum(first) - 1                       # occurrence → distinct slot
    g_rows = jax.ops.segment_sum(rows_s, seg, num_segments=N)
    k = jnp.sum(first)
    valid = jnp.arange(N) < k
    uid_full = jax.ops.segment_max(ids_s, seg, num_segments=N)
    uid = jnp.where(valid, uid_full, nrows)
    return uid, g_rows, valid


def touched_row_bytes(grad: RowSparseGrad) -> Tuple[int, int]:
    """(gather_bytes, scatter_bytes) one step moved for this table:
    gather pays per OCCURRENCE (the prefetch fetches per-id), scatter
    per distinct row (the updater dedupes first). Host-side accounting
    for the ``kind=sparse`` telemetry record — reads shapes/dtypes
    only, never device data."""
    import numpy as np

    n = int(grad.ids.shape[0])
    row_bytes = int(grad.rows.shape[-1]) * grad.rows.dtype.itemsize
    uniq = int(np.unique(np.asarray(grad.ids)).size) if n else 0
    return n * row_bytes, uniq * row_bytes
