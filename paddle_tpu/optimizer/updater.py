"""Parameter updater — the full reference optimizer family as one jittable
update function.

Reference counterparts: /root/reference/paddle/parameter/
FirstOrderOptimizer.h:24-340 (Sgd/Adagrad/AdaDelta/RMSProp/DecayedAdagrad/
Adam/Adamax + OptimizerWithGradientClipping), OptimizerWithRegularizer.h
(L1/L2 decay), AverageOptimizer.h (parameter averaging), and the
ParameterUpdaterBase protocol (ParameterUpdaterBase.h). Where the reference
composes decorator objects around per-block CPU loops, here everything is
one pure function over the parameter pytree — XLA fuses the whole update
into a single kernel per parameter.

Per-parameter attributes honored (ParameterConfig): learning_rate scale,
momentum, decay_rate (L2), decay_rate_l1, gradient_clipping_threshold,
is_static.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.optimizer.schedules import learning_rate_at
from paddle_tpu.optimizer.sparse import RowSparseGrad, dedupe
from paddle_tpu.proto import ModelConfig, OptimizationConfig, ParameterConfig

Array = jax.Array
Params = Dict[str, Array]


class UpdaterState(NamedTuple):
    step: Array                      # int32 batch counter
    num_samples: Array               # float, samples processed (lr schedules)
    slots: Dict[str, Dict[str, Array]]   # per-param optimizer buffers
    # sliding-window parameter averaging (AverageOptimizer.h:24,99): the
    # current window's running sum/count plus the previous full window
    # (the SUM1+SUM2 / SUM3 double-buffer collapsed to two buckets);
    # average = (sum + old_sum) / (count + old_count)
    avg_sum: Optional[Params]
    avg_count: Array
    avg_old_sum: Optional[Params] = None
    avg_old_count: Optional[Array] = None


class Updater:
    def __init__(self, opt: OptimizationConfig, model: ModelConfig,
                 init_model_path: str = ""):
        self.opt = opt
        # pruning-mask search root (reference ctor falls back to
        # --init_model_path); the trainer passes its resolved path
        self.init_model_path = init_model_path
        self.param_configs: Dict[str, ParameterConfig] = {p.name: p for p in model.parameters}
        self.method = opt.learning_method
        self.averaging = opt.average_window > 0
        # window limits (AverageOptimizer ctor + isAverageWindowTooLong):
        # the current window closes once it holds >= min(max_average_window,
        # numUpdates * average_window) batches (and >= min_window)
        self.avg_frac = float(opt.average_window)
        self.max_window = float(opt.max_average_window)
        self.min_window = float(min(10000, opt.max_average_window))
        # StaticPruningHook (ParameterUpdaterHook.cpp:37): masks loaded
        # lazily (need shapes) in init_state; gradients are masked every
        # step, values once via apply_init_hooks
        self._prune_files = {
            name: h.purning_mask_filename
            for name, cfg in self.param_configs.items()
            for h in cfg.update_hooks
            if h.type in ("pruning", "static_pruning")
        }
        self._masks: Dict[str, Array] = {}

    # ------------------------------------------------------------- state

    def _slot_names(self):
        m = self.method
        if m in ("momentum", "sparse_momentum", "sgd"):
            return ["mom"]
        if m == "adagrad":
            return ["accum"]
        if m == "decayed_adagrad":
            return ["accum"]
        if m == "rmsprop":
            return ["accum_g2", "accum_g"]
        if m == "adadelta":
            return ["accum_g2", "accum_dx"]
        if m == "adam":
            return ["m", "v"]
        if m == "adamax":
            return ["m", "u"]
        if m in ("lbfgs", "owlqn"):
            # whole-data batch methods (algorithm=owlqn): curvature history
            # lives host-side in BatchMethod; no per-batch slots
            return []
        raise ValueError(f"unknown learning_method {m!r}")

    def _load_masks(self, params: Params) -> None:
        if not self._prune_files or self._masks:
            return
        from paddle_tpu.optimizer.hooks import resolve_mask
        from paddle_tpu.utils.flags import FLAGS

        root = self.init_model_path or FLAGS.init_model_path
        for name, fn in self._prune_files.items():
            cfg = self.param_configs[name]
            assert not cfg.sparse_update, (
                f"{name}: pruning hook is not supported together with "
                "sparse_update (the row-sparse gradient path)"
            )
            self._masks[name] = jnp.asarray(
                resolve_mask(fn, params[name].shape, root),
                params[name].dtype,
            )

    def apply_init_hooks(self, params: Params) -> Params:
        """StaticPruningHook::init — mask parameter values once at
        startup (call after init/restore)."""
        self._load_masks(params)
        if not self._masks:
            return params
        return {
            k: (v * self._masks[k] if k in self._masks else v)
            for k, v in params.items()
        }

    def init_state(self, params: Params) -> UpdaterState:
        self._load_masks(params)
        slots = {}
        for name, p in params.items():
            cfg = self.param_configs.get(name)
            if cfg is not None and cfg.is_static:
                slots[name] = {}
                continue
            slots[name] = {s: jnp.zeros_like(p) for s in self._slot_names()}
            if cfg is not None and cfg.sparse_update and p.ndim >= 2:
                # per-row last-touched step for lazy regularizer catch-up
                # (OptimizerWithRegularizerSparse.h:124 semantics)
                slots[name]["t_last"] = jnp.zeros((p.shape[0],), jnp.int32)
        avg_sum = {k: jnp.zeros_like(v) for k, v in params.items()} if self.averaging else None
        avg_old = {k: jnp.zeros_like(v) for k, v in params.items()} if self.averaging else None
        return UpdaterState(
            step=jnp.zeros((), jnp.int32),
            num_samples=jnp.zeros((), jnp.float32),
            slots=slots,
            avg_sum=avg_sum,
            avg_count=jnp.zeros((), jnp.float32),
            avg_old_sum=avg_old,
            avg_old_count=jnp.zeros((), jnp.float32),
        )

    # ------------------------------------------------------------- update

    def __call__(
        self, params: Params, grads: Params, state: UpdaterState, batch_size
    ) -> Tuple[Params, UpdaterState]:
        opt = self.opt
        t = state.step + 1
        num_samples = state.num_samples + batch_size
        base_lr = learning_rate_at(opt, num_samples)
        new_params: Params = {}
        new_slots: Dict[str, Dict[str, Array]] = {}
        for name, w in params.items():
            cfg = self.param_configs.get(name)
            if cfg is None or cfg.is_static or name not in grads:
                new_params[name] = w
                new_slots[name] = state.slots.get(name, {})
                continue
            g = grads[name]
            if name in self._masks and not isinstance(g, RowSparseGrad):
                # StaticPruningHook::update — pruned weights get no gradient
                g = g * self._masks[name]
            clip = cfg.gradient_clipping_threshold or opt.gradient_clipping_threshold
            lr = base_lr * (cfg.learning_rate if cfg.learning_rate else 1.0)
            if isinstance(g, RowSparseGrad):
                w2, slots2 = self._apply_sparse_indexed(
                    cfg, w, g, state.slots[name], lr, t, clip
                )
                new_params[name] = w2
                new_slots[name] = slots2
                continue
            if clip and clip > 0:
                g = jnp.clip(g, -clip, clip)
            if cfg.sparse_update and g.ndim >= 2:
                w2, slots2 = self._apply_sparse_rows(cfg, w, g, state.slots[name], lr, t)
            else:
                # L2 regularization — reference folds decay into the gradient
                # (OptimizerWithRegularizer / sgdUpdate)
                if cfg.decay_rate:
                    g = g + cfg.decay_rate * w
                w2, slots2 = self._apply_method(cfg, w, g, state.slots[name], lr, t)
                # L1 regularization: proximal soft-threshold after the step
                if cfg.decay_rate_l1:
                    thresh = lr * cfg.decay_rate_l1
                    w2 = jnp.sign(w2) * jnp.maximum(jnp.abs(w2) - thresh, 0.0)
            new_params[name] = w2
            new_slots[name] = slots2
        avg_sum, avg_count = state.avg_sum, state.avg_count
        avg_old_sum, avg_old_count = state.avg_old_sum, state.avg_old_count
        if self.averaging:
            cur = {k: avg_sum[k] + new_params[k] for k in new_params}
            n_acc = avg_count + 1.0
            # close the window when it's grown past the configured span
            # (isAverageWindowTooLong): the full window becomes the "old"
            # bucket and a fresh one starts accumulating
            limit = jnp.minimum(self.max_window, t.astype(jnp.float32) * self.avg_frac)
            shift = (n_acc >= self.min_window) & (n_acc >= limit)
            avg_old_sum = {k: jnp.where(shift, cur[k], avg_old_sum[k]) for k in cur}
            avg_old_count = jnp.where(shift, n_acc, avg_old_count)
            avg_sum = {k: jnp.where(shift, jnp.zeros_like(cur[k]), cur[k]) for k in cur}
            avg_count = jnp.where(shift, 0.0, n_acc)
        return new_params, UpdaterState(
            t, num_samples, new_slots, avg_sum, avg_count, avg_old_sum, avg_old_count
        )

    def _apply_sparse_indexed(self, cfg, w, sg: RowSparseGrad, slots, lr, t, clip):
        """Row-sparse update from a RowSparseGrad — O(N·D) in the batch's
        occurrence count N, independent of vocabulary size V. Same
        semantics as _apply_sparse_rows (SparseRowCpuMatrix::sgdUpdate +
        OptimizerWithRegularizerSparse lazy catch-up) but driven by ids
        instead of a dense-gradient row scan: dedupe occurrences by
        sort + segment-sum, gather only the touched parameter/slot rows,
        run the optimizer method on those rows, scatter back (sentinel
        ids drop out of bounds)."""
        V = w.shape[0]
        uid, g_rows, valid = dedupe(sg.ids, sg.rows.reshape(sg.ids.shape[0], -1), V)
        # rows whose aggregate gradient is exactly zero (e.g. ids at padded
        # positions) stay frozen, matching the dense path's any(g != 0)
        # touched-row detection and the reference's sparse semantics
        valid = valid & jnp.any(g_rows != 0, axis=1)
        uid = jnp.where(valid, uid, V)
        if clip and clip > 0:  # clip the aggregated gradient, as the dense path does
            g_rows = jnp.clip(g_rows, -clip, clip)
        uid_c = jnp.minimum(uid, V - 1)               # safe gather index
        vmask = valid[:, None]
        t_last = slots.get("t_last")
        inner = {k: v for k, v in slots.items() if k != "t_last"}
        w_rows = w[uid_c]                             # [N, D]
        inner_rows = {k: v[uid_c] for k, v in inner.items()}
        tl_rows = t_last[uid_c] if t_last is not None else jnp.zeros_like(uid_c)
        elapsed = jnp.maximum(t - 1 - tl_rows, 0).astype(w.dtype)[:, None]
        g = g_rows
        if cfg.decay_rate:
            decay = jnp.power(1.0 - lr * cfg.decay_rate, elapsed)
            w_rows = w_rows * decay
            g = g + cfg.decay_rate * w_rows
        if cfg.decay_rate_l1:
            thresh = lr * cfg.decay_rate_l1 * elapsed
            w_rows = jnp.sign(w_rows) * jnp.maximum(jnp.abs(w_rows) - thresh, 0.0)
        w2_rows, inner2_rows = self._apply_method(cfg, w_rows, g, inner_rows, lr, t)
        if cfg.decay_rate_l1:
            thresh = lr * cfg.decay_rate_l1
            w2_rows = jnp.sign(w2_rows) * jnp.maximum(jnp.abs(w2_rows) - thresh, 0.0)
        # invalid (sentinel) entries scatter out of bounds and are dropped
        w_new = w.at[uid].set(jnp.where(vmask, w2_rows, 0.0), mode="drop")
        slots_new = {
            k: inner[k].at[uid].set(jnp.where(vmask, inner2_rows[k], 0.0), mode="drop")
            for k in inner
        }
        if t_last is not None:
            slots_new["t_last"] = t_last.at[uid].set(t, mode="drop")
        return w_new, slots_new

    def _apply_sparse_rows(self, cfg, w, g, slots, lr, t):
        """Row-sparse update (SparseRowCpuMatrix::sgdUpdate +
        OptimizerWithRegularizerSparse semantics, /root/reference/paddle/
        math/SparseRowMatrix.h:31, parameter/OptimizerWithRegularizer.h:124):

        Only rows touched by this batch advance — optimizer state for
        untouched rows is frozen, and regularization they missed is applied
        lazily ("catch-up") the next time the row is touched. Touched rows
        are detected from the exact-zero gradient rows the embedding
        scatter-add produces; on a sharded table each chip masks its own
        rows, which is the SPMD replacement for the sparse pserver's
        per-row remote updates."""
        row_mask = jnp.any(g != 0, axis=tuple(range(1, g.ndim)))  # [V]
        rm = row_mask.reshape((-1,) + (1,) * (g.ndim - 1))
        t_last = slots.get("t_last")
        inner = {k: v for k, v in slots.items() if k != "t_last"}
        elapsed = jnp.maximum(t - 1 - t_last, 0).astype(w.dtype)  # missed batches
        w_base = w
        if cfg.decay_rate:
            # compound the missed per-batch L2 decays, then fold the current
            # step's decay into the gradient as the dense path does
            decay = jnp.power(1.0 - lr * cfg.decay_rate, elapsed)
            w_base = w * jnp.where(row_mask, decay, 1.0).reshape(rm.shape)
            g = g + cfg.decay_rate * w_base * rm
        if cfg.decay_rate_l1:
            thresh = (lr * cfg.decay_rate_l1 * elapsed).reshape(rm.shape) * rm
            w_base = jnp.sign(w_base) * jnp.maximum(jnp.abs(w_base) - thresh, 0.0)
        w2, inner2 = self._apply_method(cfg, w_base, g, inner, lr, t)
        if cfg.decay_rate_l1:
            thresh = lr * cfg.decay_rate_l1
            w2 = jnp.sign(w2) * jnp.maximum(jnp.abs(w2) - thresh, 0.0)
        w_new = jnp.where(rm, w2, w)
        slots_new = {k: jnp.where(rm, inner2[k], inner[k]) for k in inner}
        slots_new["t_last"] = jnp.where(row_mask, t, t_last)
        return w_new, slots_new

    def _apply_method(self, cfg, w, g, slots, lr, t):
        m = self.method
        opt = self.opt
        eps = opt.ada_epsilon
        rou = opt.ada_rou
        if m in ("momentum", "sparse_momentum", "sgd"):
            mom = cfg.momentum
            v = mom * slots["mom"] - lr * g
            return w + v, {"mom": v}
        if m == "adagrad":
            accum = slots["accum"] + g * g
            return w - lr * g / (jnp.sqrt(accum) + eps), {"accum": accum}
        if m == "decayed_adagrad":
            accum = rou * slots["accum"] + (1.0 - rou) * g * g
            return w - lr * g / jnp.sqrt(accum + eps), {"accum": accum}
        if m == "rmsprop":
            g2 = rou * slots["accum_g2"] + (1.0 - rou) * g * g
            g1 = rou * slots["accum_g"] + (1.0 - rou) * g
            return (
                w - lr * g / jnp.sqrt(g2 - g1 * g1 + eps),
                {"accum_g2": g2, "accum_g": g1},
            )
        if m == "adadelta":
            g2 = rou * slots["accum_g2"] + (1.0 - rou) * g * g
            dx = -jnp.sqrt((slots["accum_dx"] + eps) / (g2 + eps)) * g
            accum_dx = rou * slots["accum_dx"] + (1.0 - rou) * dx * dx
            return w + lr * dx, {"accum_g2": g2, "accum_dx": accum_dx}
        if m == "adam":
            b1, b2 = opt.adam_beta1, opt.adam_beta2
            aeps = opt.adam_epsilon
            mt = b1 * slots["m"] + (1.0 - b1) * g
            vt = b2 * slots["v"] + (1.0 - b2) * g * g
            tf = t.astype(jnp.float32)
            mhat = mt / (1.0 - jnp.power(b1, tf))
            vhat = vt / (1.0 - jnp.power(b2, tf))
            return w - lr * mhat / (jnp.sqrt(vhat) + aeps), {"m": mt, "v": vt}
        if m == "adamax":
            b1, b2 = opt.adam_beta1, opt.adam_beta2
            mt = b1 * slots["m"] + (1.0 - b1) * g
            ut = jnp.maximum(b2 * slots["u"], jnp.abs(g))
            tf = t.astype(jnp.float32)
            return (
                w - (lr / (1.0 - jnp.power(b1, tf))) * mt / (ut + 1e-12),
                {"m": mt, "u": ut},
            )
        raise ValueError(f"unknown learning_method {m!r}")

    # ----------------------------------------------------------- averaging

    def averaged_params(self, params: Params, state: UpdaterState) -> Params:
        """Apply-parameter-averaging view for testing (AverageOptimizer
        apply()/restore(): average = (SUM1+SUM2+SUM3) / (numAccumulates +
        oldNumAccumulates) — here (sum + old_sum) / (count + old_count))."""
        if not self.averaging or state.avg_sum is None:
            return params
        old_sum = state.avg_old_sum
        old_count = (
            state.avg_old_count
            if state.avg_old_count is not None
            else jnp.zeros((), jnp.float32)
        )
        count = jnp.maximum(state.avg_count + old_count, 1.0)
        return {
            k: (state.avg_sum[k] + (old_sum[k] if old_sum is not None else 0.0)) / count
            for k in params
        }
