"""Parameter updater hooks — the StaticPruningHook.

Reference: /root/reference/paddle/parameter/ParameterUpdaterHook.cpp:37.
A user-supplied bitmask file defines which weights are enabled; ``init``
masks the parameter VALUE once at startup, ``update`` masks the GRADIENT
every step — so pruned weights start at zero and never receive updates
(momentum/adam statistics of a masked gradient stay zero; L1/L2 decay of
an exactly-zero weight is zero).

Mask file format v0 (StaticMaskHeader, bit-exact with the reference):
packed little-endian ``uint32 version; uint64 size`` header, then
ceil(size/8) bytes of MSB-first bits, 1 = weight enabled. ``.npy`` files
holding a 0/1 array are also accepted (TPU-era convenience).
"""

from __future__ import annotations

import os
import struct
from typing import Optional

import numpy as np

_HEADER = struct.Struct("<IQ")


def write_mask_file(path: str, mask: np.ndarray) -> None:
    """Write a v0 bitmask file (1 = enabled); mask may be any shape."""
    flat = np.asarray(mask).reshape(-1) != 0
    n = flat.size
    data = bytearray(_HEADER.pack(0, n))
    buf = 0
    for i, bit in enumerate(flat):
        buf = (buf << 1) | int(bit)
        if i % 8 == 7:
            data.append(buf)
            buf = 0
    if n % 8:
        data.append(buf << (8 - n % 8))  # low bits of the end byte are zero
    with open(path, "wb") as f:
        f.write(bytes(data))


def load_mask_file(path: str) -> np.ndarray:
    """Read a mask file (v0 bitmask or .npy) → flat bool array."""
    if path.endswith(".npy"):
        return np.load(path).reshape(-1) != 0
    with open(path, "rb") as f:
        raw = f.read()
    version, size = _HEADER.unpack_from(raw)
    assert version == 0, f"{path}: unsupported mask version {version}"
    bits = np.unpackbits(np.frombuffer(raw, np.uint8, offset=_HEADER.size))
    assert bits.size >= size, f"{path}: truncated mask ({bits.size} < {size})"
    return bits[:size] != 0


def resolve_mask(mask_filename: str, shape, init_model_path: str = "") -> np.ndarray:
    """Locate and load a pruning mask, reshaped to the parameter's shape.

    Search order matches the reference StaticPruningHook ctor: the path as
    given, then relative to --init_model_path."""
    path = mask_filename
    if not os.path.exists(path) and init_model_path:
        path = os.path.join(init_model_path, mask_filename)
    assert os.path.exists(path), (
        f"cannot load pruning mask {mask_filename!r} (also searched "
        f"init_model_path {init_model_path!r})"
    )
    flat = load_mask_file(path)
    n = int(np.prod(shape))
    assert flat.size == n, (
        f"pruning mask {path} has {flat.size} bits but parameter has {n} weights"
    )
    return flat.reshape(shape)
