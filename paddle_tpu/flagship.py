"""Flagship bench/dryrun model builders, shared by bench.py and
__graft_entry__.py so neither entry point depends on the other
(reference role: the benchmark configs under demo/ driven by
paddle/trainer/Trainer.cpp's train path).
"""

from __future__ import annotations

import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def flagship_config(dict_dim=1000, emb_dim=64, hidden=64, classes=2, mesh_shape=""):
    """Stacked-LSTM text classifier (the sentiment-demo shape) built via the
    DSL; the secondary bench flagship next to ResNet."""
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        AdamOptimizer,
        MaxPooling,
        ParamAttr,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        outputs,
        pooling_layer,
        settings,
        simple_lstm,
    )

    with fresh_context() as ctx:
        settings(
            batch_size=32,
            learning_rate=1e-3,
            learning_method=AdamOptimizer(),
            mesh_shape=mesh_shape or None,
        )
        words = data_layer(name="words", size=dict_dim)
        emb = embedding_layer(input=words, size=emb_dim, param_attr=ParamAttr(name="emb"))
        lstm = simple_lstm(input=emb, size=hidden)
        pool = pooling_layer(input=lstm, pooling_type=MaxPooling())
        output = fc_layer(input=pool, size=classes, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=classes)
        outputs(classification_cost(input=output, label=label))
        return ctx.finalize()


def example_batch(dict_dim=1000, B=8, T=32, classes=2, seed=0):
    from paddle_tpu.graph import make_ids, make_seq

    rng = np.random.RandomState(seed)
    ids = rng.randint(0, dict_dim, (B, T)).astype(np.int32)
    lengths = rng.randint(T // 2, T + 1, (B,)).astype(np.int32)
    labels = rng.randint(0, classes, (B,)).astype(np.int32)
    return {
        "words": make_seq(None, lengths, ids=ids),
        "label": make_ids(labels),
    }


def nmt_config(vocab=30000, dim=512, dtype="float32", batch_size=64,
               is_generating=False, **gen_kwargs):
    """seqToseq NMT attention encoder-decoder, the BASELINE.md north-star
    workload #2 — the same model the demo config builds (reference
    demo/seqToseq/seqToseq_net.py:65-181). is_generating=True builds the
    beam-search generation graph (gen.conf path); gen_kwargs (beam_size,
    max_length, ...) pass through to gru_encoder_decoder."""
    import importlib.util

    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import AdamOptimizer, settings

    from paddle_tpu.config.config_parser import _ensure_compat_path

    _ensure_compat_path()  # the demo imports `paddle.trainer_config_helpers`
    spec = importlib.util.spec_from_file_location(
        "seqToseq_net_bench", os.path.join(REPO, "demo", "seqToseq", "seqToseq_net.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with fresh_context() as ctx:
        settings(
            batch_size=batch_size,
            learning_rate=1e-3,
            learning_method=AdamOptimizer(),
            dtype=dtype,
        )
        mod.gru_encoder_decoder(
            source_dict_dim=vocab,
            target_dict_dim=vocab,
            is_generating=is_generating,
            word_vector_dim=dim,
            encoder_size=dim,
            decoder_size=dim,
            **gen_kwargs,
        )
        return ctx.finalize()


def nmt_gen_config(vocab=30000, dim=512, beam_size=3, max_length=32,
                   dtype="float32", batch_size=64):
    """The seqToseq generation graph at bench shapes (see nmt_config)."""
    return nmt_config(vocab=vocab, dim=dim, dtype=dtype,
                      batch_size=batch_size, is_generating=True,
                      beam_size=beam_size, max_length=max_length)


def nmt_gen_batch(vocab=30000, B=8, T=32, seed=0):
    """Source-only batch for the generation graph."""
    from paddle_tpu.graph import make_seq

    rng = np.random.RandomState(seed)
    ids = rng.randint(2, vocab, (B, T)).astype(np.int32)
    lengths = rng.randint(max(T // 2, 1), T + 1, (B,)).astype(np.int32)
    return {"source_language_word": make_seq(None, lengths, ids=ids)}


def nmt_batch(vocab=30000, B=8, T=32, seed=0):
    from paddle_tpu.graph import make_seq

    rng = np.random.RandomState(seed)

    def seq():
        ids = rng.randint(2, vocab, (B, T)).astype(np.int32)
        lengths = rng.randint(max(T // 2, 1), T + 1, (B,)).astype(np.int32)
        return ids, lengths

    src, src_len = seq()
    trg, trg_len = seq()
    nxt = np.roll(trg, -1, axis=1)
    return {
        "source_language_word": make_seq(None, src_len, ids=src),
        "target_language_word": make_seq(None, trg_len, ids=trg),
        "target_language_next_word": make_seq(None, trg_len, ids=nxt),
    }


def resnet_config(layer_num=50, img_size=224, classes=1000):
    from paddle_tpu.config import parse_config_at

    return parse_config_at(
        os.path.join(REPO, "demo", "model_zoo", "resnet", "resnet.py"),
        f"layer_num={layer_num},img_size={img_size},num_classes={classes}",
    )


def make_image_batch(B, img_size, classes, seed=0):
    from paddle_tpu.graph import make_dense, make_ids

    rng = np.random.RandomState(seed)
    return {
        "input": make_dense(rng.randn(B, 3 * img_size * img_size).astype(np.float32)),
        "label": make_ids(rng.randint(0, classes, (B,)).astype(np.int32)),
    }
