"""Flagship bench/dryrun model builders, shared by bench.py and
__graft_entry__.py so neither entry point depends on the other
(reference role: the benchmark configs under demo/ driven by
paddle/trainer/Trainer.cpp's train path).
"""

from __future__ import annotations

import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def flagship_config(dict_dim=1000, emb_dim=64, hidden=64, classes=2, mesh_shape=""):
    """Stacked-LSTM text classifier (the sentiment-demo shape) built via the
    DSL; the secondary bench flagship next to ResNet."""
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        AdamOptimizer,
        MaxPooling,
        ParamAttr,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        outputs,
        pooling_layer,
        settings,
        simple_lstm,
    )

    with fresh_context() as ctx:
        settings(
            batch_size=32,
            learning_rate=1e-3,
            learning_method=AdamOptimizer(),
            mesh_shape=mesh_shape or None,
        )
        words = data_layer(name="words", size=dict_dim)
        emb = embedding_layer(input=words, size=emb_dim, param_attr=ParamAttr(name="emb"))
        lstm = simple_lstm(input=emb, size=hidden)
        pool = pooling_layer(input=lstm, pooling_type=MaxPooling())
        output = fc_layer(input=pool, size=classes, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=classes)
        outputs(classification_cost(input=output, label=label))
        return ctx.finalize()


def example_batch(dict_dim=1000, B=8, T=32, classes=2, seed=0):
    from paddle_tpu.graph import make_ids, make_seq

    rng = np.random.RandomState(seed)
    ids = rng.randint(0, dict_dim, (B, T)).astype(np.int32)
    lengths = rng.randint(T // 2, T + 1, (B,)).astype(np.int32)
    labels = rng.randint(0, classes, (B,)).astype(np.int32)
    return {
        "words": make_seq(None, lengths, ids=ids),
        "label": make_ids(labels),
    }


def resnet_config(layer_num=50, img_size=224, classes=1000):
    from paddle_tpu.config import parse_config_at

    return parse_config_at(
        os.path.join(REPO, "demo", "model_zoo", "resnet", "resnet.py"),
        f"layer_num={layer_num},img_size={img_size},num_classes={classes}",
    )


def make_image_batch(B, img_size, classes, seed=0):
    from paddle_tpu.graph import make_dense, make_ids

    rng = np.random.RandomState(seed)
    return {
        "input": make_dense(rng.randn(B, 3 * img_size * img_size).astype(np.float32)),
        "label": make_ids(rng.randint(0, classes, (B,)).astype(np.int32)),
    }
