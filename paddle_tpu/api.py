"""Embedding API — drive the framework from user Python programs.

The role of the reference's SWIG binding
(/root/reference/paddle/api/PaddleAPI.h:92-799 and
paddle/py_paddle/util.py): load a parsed config, build a machine, run
forward/forwardBackward from numpy data, read/write parameters, and run
beam-search generation — without the Trainer CLI. No SWIG here: the
framework is already Python, so this module is a thin numpy-faced wrapper
over GradientMachine/Updater/checkpoint.

Typical prediction flow (mirrors demo/sentiment/predict.py against the
reference):

    conf = parse_config("trainer_config.py", "is_predict=1")
    machine = GradientMachine.createFromConfigProto(conf.model_config)
    machine.loadParameters("./output/pass-00009")
    conv = DataProviderConverter([integer_value_sequence(dict_dim)],
                                 machine.input_layer_names())
    out = machine.forwardTest(conv([[word_ids], [word_ids2]]))
    prob = out[0]["value"]
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from paddle_tpu.data.feeder import BatchAssembler
from paddle_tpu.graph.argument import Argument
from paddle_tpu.graph.machine import GradientMachine as _CoreMachine
from paddle_tpu.proto import ModelConfig, OptimizationConfig
from paddle_tpu.utils.logging import logger

__all__ = [
    "initPaddle",
    "GradientMachine",
    "DataProviderConverter",
    "SequenceGenerator",
]


def initPaddle(*args: str) -> None:
    """Process-level init (ref: swig_paddle.initPaddle). Flags in
    ``--name=value`` form; unknown names are ignored."""
    from paddle_tpu.utils.flags import FLAGS

    FLAGS.parse(list(args))
    if not FLAGS.use_tpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


class DataProviderConverter:
    """samples → feed dict of Arguments (ref: py_paddle
    DataProviderWrapperConverter / dataprovider_converter.py:22-56).

    ``input_types`` are the @provider slot declarations; ``slot_names``
    the data-layer names in config input order.
    """

    def __init__(self, input_types: Sequence, slot_names: Sequence[str]):
        self.assembler = BatchAssembler(input_types, slot_names)

    def __call__(self, samples: List[Sequence[Any]]) -> Dict[str, Argument]:
        return self.assembler.assemble(samples)


class GradientMachine:
    """Numpy-faced machine wrapper (ref: PaddleAPI.h:626 GradientMachine)."""

    def __init__(self, model_config: ModelConfig, params=None, seed: int = 1):
        self._core = _CoreMachine(model_config)
        self.model_config = model_config
        self.params = params if params is not None else self._core.init_params(seed=seed)
        self._fwd_test = None

    # -- construction ----------------------------------------------------

    @classmethod
    def createFromConfigProto(cls, model_config: ModelConfig, seed: int = 1):
        return cls(model_config, seed=seed)

    @classmethod
    def createFromConfigFile(cls, config_file: str, config_args: str = ""):
        from paddle_tpu.config import parse_config

        conf = parse_config(config_file, config_args)
        return cls(conf.model_config)

    # -- parameters ------------------------------------------------------

    def loadParameters(self, path: str) -> None:
        """Load parameters from a checkpoint dir (pass-NNNNN), a save_dir
        containing pass dirs (latest wins), or a merged-model .npz."""
        import jax.numpy as jnp

        from paddle_tpu.trainer import checkpoint as ckpt

        if os.path.isfile(path):  # merged model (cli merge_model output)
            with np.load(path, allow_pickle=False) as z:
                loaded = {
                    k: jnp.asarray(z[k]) for k in z.files if k != "__config_json__"
                }
            for name in self.params:
                assert name in loaded, f"parameter {name!r} missing from {path}"
            self.params = {k: loaded[k] for k in self.params}
        else:
            if not ckpt.has_params_tree(path):
                latest = ckpt.latest_pass(path)
                assert latest is not None, f"no checkpoint under {path}"
                path = os.path.join(path, ckpt.PASS_FMT % latest)
            # fallback=False: an inference embedding asked for THIS
            # checkpoint — never quarantine it or silently substitute an
            # older pass (verification still fails loudly on corruption)
            self.params, _, _ = ckpt.load_checkpoint(
                path, None, expected_params=self.params, fallback=False
            )
        self._fwd_test = None

    def saveParameters(self, save_dir: str, pass_id: int = 0) -> None:
        from paddle_tpu.trainer import checkpoint as ckpt

        ckpt.save_checkpoint(save_dir, pass_id, self.params)

    def getParameterNames(self) -> List[str]:
        return sorted(self.params.keys())

    def getParameter(self, name: str) -> np.ndarray:
        return np.asarray(self.params[name])

    def setParameter(self, name: str, value) -> None:
        import jax.numpy as jnp

        cur = self.params[name]
        arr = jnp.asarray(value, dtype=cur.dtype)
        assert arr.shape == cur.shape, f"{name}: {arr.shape} != {cur.shape}"
        self.params[name] = arr
        self._fwd_test = None

    # -- inference -------------------------------------------------------

    def input_layer_names(self) -> List[str]:
        return list(self.model_config.input_layer_names)

    def output_layer_names(self) -> List[str]:
        return list(self._core.network.output_layer_names)

    def _feed(self, in_args) -> Dict[str, Argument]:
        """Normalize a feed: dict keyed by data-layer names, a positionally
        keyed dict ("0", "1", ... from DataProviderWrapperConverter), or a
        list of Arguments in config input order."""
        names = self.input_layer_names()
        if isinstance(in_args, dict):
            if any(n in in_args for n in names):
                return in_args
            # positional string keys → input order
            return {n: in_args[str(i)] for i, n in enumerate(names) if str(i) in in_args}
        return {n: a for n, a in zip(names, in_args)}

    def forwardTest(self, in_args) -> List[Dict[str, np.ndarray]]:
        """Forward in test mode; one dict per output layer with numpy
        ``value`` / ``id`` / ``sequence_lengths`` entries (the shape of the
        reference's Arguments-out-to-numpy conversion, util.py:136)."""
        in_args = self._feed(in_args)
        if self._fwd_test is None:
            core = self._core

            def fwd(params, args):
                outputs, _ = core.forward(params, args, pass_type="test", rng=None)
                return outputs

            self._fwd_test = jax.jit(fwd)
        outputs = self._fwd_test(self.params, in_args)
        result = []
        for name in self.output_layer_names():
            arg = outputs[name]
            entry: Dict[str, np.ndarray] = {}
            if arg.value is not None:
                entry["value"] = np.asarray(arg.value)
            if arg.ids is not None:
                entry["id"] = np.asarray(arg.ids)
            if arg.seq_lengths is not None:
                entry["sequence_lengths"] = np.asarray(arg.seq_lengths)
            result.append(entry)
        return result

    def forwardBackward(self, in_args: Dict[str, Argument], rng=None):
        """One loss+gradient evaluation (custom training loops, ref:
        PaddleAPI.h GradientMachine::forwardBackward). Returns
        (loss: float, grads: dict name→numpy)."""
        grad_fn = self._core.grad_fn()
        loss, grads, _, _ = grad_fn(self.params, in_args, rng)
        # row-sparse embedding grads densify for this numpy API (small
        # models only; training never materializes them)
        dense = {
            k: np.asarray(v.to_dense() if hasattr(v, "to_dense") else v)
            for k, v in grads.items()
        }
        return float(loss), dense

    # -- generation ------------------------------------------------------

    def asSequenceGenerator(
        self,
        dict_file: str = "",
        begin_token: Optional[int] = None,
        end_token: Optional[int] = None,
        max_length: Optional[int] = None,
        beam_size: Optional[int] = None,
    ) -> "SequenceGenerator":
        """Overrides (when given) are written into the generator sub-model
        config before the generation graph is traced — same knobs the
        reference SWIG API exposes (PaddleAPI.h:775)."""
        return SequenceGenerator(
            self, dict_file,
            begin_token=begin_token, end_token=end_token,
            max_length=max_length, beam_size=beam_size,
        )

    def asDecodeEngine(self, slots: int = 8, prompt_tokens: int = 32,
                       queue_cap: int = 0, request_timeout_s: float = 60.0,
                       decode_block=1, registry=None,
                       pipeline: bool = True, fused_step: bool = False,
                       spec_tokens="0", slot_dtype: str = "f32"):
        """The continuous-batching engine over this machine's generator
        graph (doc/serving.md) — the concurrent-use superset of
        :class:`SequenceGenerator`: submit() from any thread, slot-based
        greedy decode (beam_size=1 semantics, token-for-token equal to
        ``generate`` at beam 1), admission/eviction per iteration.
        Returns an UNstarted :class:`paddle_tpu.serving.Engine`; call
        ``.start()`` (pays the compiles) and ``.drain()`` when done."""
        from paddle_tpu.serving.frontend import build_engine

        return build_engine(
            self._core, self.params, slots=slots,
            prompt_tokens=prompt_tokens, queue_cap=queue_cap,
            request_timeout_s=request_timeout_s, decode_block=decode_block,
            registry=registry, pipeline=pipeline, fused_step=fused_step,
            spec_tokens=spec_tokens, slot_dtype=slot_dtype,
        )


def _feed_signature(in_args):
    """Best-effort batch-shape signature of a feed — what jit retraces
    on. Unhashable/unreadable feeds collapse to one bucket (only the
    first call is then flagged cold_start, the pre-signature behavior)."""
    try:
        parts = []
        items = (sorted(in_args.items()) if isinstance(in_args, dict)
                 else enumerate(in_args))
        for name, arg in items:
            for field in ("ids", "value", "seq_lengths"):
                v = getattr(arg, field, None)
                if v is not None:
                    parts.append((str(name), field, tuple(np.asarray(v).shape)))
        return tuple(parts)
    except Exception:
        return None


def _feed_batch_size(in_args) -> int:
    """Sample count of a feed, from any input's leading dimension —
    best-effort, at least 1 (one error record beats none)."""
    try:
        for arg in (in_args.values() if isinstance(in_args, dict) else in_args):
            for field in ("seq_lengths", "ids", "value"):
                v = getattr(arg, field, None)
                if v is not None:
                    return max(int(np.asarray(v).shape[0]), 1)
    except Exception:
        pass
    return 1


def _prompt_token_counts(in_args) -> List[int]:
    """Per-sample prompt token counts from a feed's first sequence input
    (its seq_lengths column); best-effort — a dense-only feed yields
    an empty list and the request records fall back to 0."""
    try:
        for arg in (in_args.values() if isinstance(in_args, dict) else in_args):
            sl = getattr(arg, "seq_lengths", None)
            if sl is not None:
                return [int(x) for x in np.asarray(sl).reshape(-1)]
    except Exception:
        pass
    return []


class SequenceGenerator:
    """Beam-search generation façade (ref: PaddleAPI.h:775 and
    ISequenceResults). Works on configs whose sub-model declares a
    generator (beam_search in the DSL).

    One call = one static run-to-completion cohort. For CONCURRENT use
    — many callers, mixed lengths, latency targets — the continuous-
    batching engine subsumes this API at beam_size=1:
    ``machine.asDecodeEngine(...).start()`` then ``submit()`` per
    request (doc/serving.md; greedy outputs are token-for-token equal,
    pinned by tests/test_engine.py). This class keeps its PR-8
    one-cohort request-record contract unchanged."""

    def __init__(
        self,
        machine: GradientMachine,
        dict_file: str = "",
        begin_token: Optional[int] = None,
        end_token: Optional[int] = None,
        max_length: Optional[int] = None,
        beam_size: Optional[int] = None,
    ):
        self.machine = machine
        self.words: Optional[List[str]] = None
        if dict_file:
            with open(dict_file) as f:
                self.words = [line.rstrip("\n") for line in f]
        # apply overrides to a private copy of the model config so they
        # never leak into the machine (or later generators); a dedicated
        # core machine traces from the copy, sharing the live params
        import copy

        model_cfg = machine.model_config
        if any(x is not None for x in (begin_token, end_token, max_length, beam_size)):
            model_cfg = copy.deepcopy(machine.model_config)
        subs = [s for s in model_cfg.sub_models if s.generator is not None]
        assert subs, "config declares no generator sub-model (beam_search)"
        self.sub = subs[0]
        group_cfg = next(
            (l for l in model_cfg.layers if l.name == self.sub.name), None
        )
        if max_length is not None:
            self.sub.generator.max_num_frames = int(max_length)
        if beam_size is not None and group_cfg is not None:
            group_cfg.beam_size = int(beam_size)
            self.sub.generator.beam_size = int(beam_size)
        if begin_token is not None and group_cfg is not None:
            group_cfg.bos_id = int(begin_token)
        if end_token is not None and group_cfg is not None:
            group_cfg.eos_id = int(end_token)
        self._core = (
            machine._core if model_cfg is machine.model_config else _CoreMachine(model_cfg)
        )
        self._fwd = None
        self._seen_sigs: set = set()

    def generate(self, in_args: Dict[str, Argument]) -> List[List[Dict[str, Any]]]:
        """Returns, per input sample, a list of beams:
        ``{"ids": [...], "score": float, "words": [...]}`` sorted best-first.

        When telemetry is configured (``observability.metrics.configure``),
        every call emits one ``kind=request`` record per input sample —
        the call is one batch cohort, each sample a zero-queue-wait
        request (doc/observability.md "Serving telemetry") — so even
        embedding-API generation carries request-level latency evidence."""
        import time as _time

        from paddle_tpu.observability import metrics as _metrics
        from paddle_tpu.observability import serving as _serving

        # all instrumentation bookkeeping (feed signature, prompt lens)
        # is gated like log_oneshot itself: the telemetry-off hot path
        # pays nothing
        telemetry = _metrics.enabled()
        # cold_start marks any call that pays a jit trace+compile: the
        # first one, AND any new batch-shape signature (jit retraces per
        # shape) — steady-state latency aggregations must be able to
        # split both out
        sig = _feed_signature(in_args) if telemetry else None
        cold_start = telemetry and (
            self._fwd is None or sig not in self._seen_sigs
        )
        if self._fwd is None:
            core = self._core

            def fwd(params, args):
                outputs, _ = core.forward(params, args, pass_type="gen", rng=None)
                return outputs

            self._fwd = jax.jit(fwd)
        prompt_lens = _prompt_token_counts(in_args) if telemetry else []
        t0 = _time.perf_counter()
        try:
            outputs = jax.block_until_ready(
                self._fwd(self.machine.params, in_args)
            )
        except Exception:
            if telemetry:
                # even a dense-only feed (no seq_lengths → empty
                # prompt_lens) must leave error evidence: size the
                # cohort from the feed
                _serving.log_oneshot(
                    prompt_lens, [], _time.perf_counter() - t0,
                    beam_size=self.sub.generator.beam_size,
                    outcome="error",
                    n=len(prompt_lens) or _feed_batch_size(in_args),
                    cold_start=cold_start,
                )
            raise
        service_s = _time.perf_counter() - t0
        if telemetry:
            # only a SUCCESSFUL forward warms the signature: a failed
            # trace/compile isn't cached by jit, so the retry pays the
            # compile again and must be flagged cold_start again
            self._seen_sigs.add(sig)
        group = self.sub.name
        best = outputs[group]
        beams = outputs.get(f"{group}@beams")
        if beams is not None:
            beam_ids = np.asarray(beams.ids)               # [B, K, T]
            scores = np.asarray(beams.value)               # [B, K]
            lens = np.asarray(beams.sub_seq_lengths)       # [B, K]
        else:
            beam_ids = np.asarray(best.ids)[:, None]       # [B, 1, T]
            scores = np.zeros(beam_ids.shape[:2], np.float32)
            lens = np.asarray(best.seq_lengths)[:, None]
        results = []
        for b in range(beam_ids.shape[0]):
            sample = []
            for k in range(beam_ids.shape[1]):
                ids = [int(i) for i in beam_ids[b, k, : lens[b, k]]]
                entry: Dict[str, Any] = {"ids": ids, "score": float(scores[b, k])}
                if self.words is not None:
                    entry["words"] = [
                        self.words[i] if 0 <= i < len(self.words) else "<unk>"
                        for i in ids
                    ]
                sample.append(entry)
            sample.sort(key=lambda e: -e["score"])
            results.append(sample)
        # gen_tokens counts the BEST beam's tokens — taken from the
        # sorted results the caller receives, not raw beam slot 0 (the
        # forward may return beams in non-score order)
        _serving.log_oneshot(
            prompt_lens if len(prompt_lens) == len(results)
            else [0] * len(results),
            [len(sample[0]["ids"]) if sample else 0 for sample in results],
            service_s, beam_size=self.sub.generator.beam_size,
            cold_start=cold_start,
        )
        return results
