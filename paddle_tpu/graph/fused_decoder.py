"""Template matcher wiring the fused attention-GRU decoder kernel
(ops/pallas_attention_gru) into the recurrent-group scan.

A training-time recurrent group whose step graph is EXACTLY the
attention-decoder template built by
trainer_config_helpers.networks.simple_attention + gru_step_layer
(the reference's demo/seqToseq decoder, networks.py:943 +
GruStepLayer.cpp) is lowered to one Pallas launch instead of a
lax.scan of ~10 layers per step:

    memory(gru) -> [transform -> expand -> combine -> softmax
                    -> scaling -> pooling] -> mixed(din) -> gru_step

Anything that deviates — extra layers, other activations, dropout,
error clipping, sequence memories, unhoisted in-link consumers, shapes
the kernel gates out — falls back to the scan with identical
semantics. The matcher runs only when OptimizationConfig.pallas_decoder
is set (a separate knob from pallas_rnn: this kernel must not become a
default before a measured A/B win).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_AGENT_TYPES = ("agent", "sequence_agent", "scatter_agent", "gather_agent")


def _clean(cfg) -> bool:
    """No semantics outside the template on an in-scan layer."""
    return cfg.drop_rate == 0.0 and cfg.error_clipping_threshold == 0


def _single_proj(cfg, want_type: str):
    """The layer's single input if it is a `want_type` projection."""
    if len(cfg.inputs) != 1:
        return None
    ic = cfg.inputs[0]
    if ic.proj_conf is None or ic.proj_conf.type != want_type:
        return None
    return ic


def match_decoder(network, sub, ctx, statics, skip, pro_plan) -> Optional[Dict[str, Any]]:
    """Returns the extraction plan, or None when the group is not the
    attention-GRU decoder template (every bail is silent — the scan path
    is always a correct fallback)."""
    if not ctx.is_training or sub.reversed:
        return None
    if ctx.mesh is not None:
        from paddle_tpu.parallel.mesh import data_only_extent

        # a pallas custom call has no GSPMD partitioning rule; under a
        # purely data-parallel mesh the decoder runs per-shard via
        # shard_map (run_fused_decoder) — anything else takes the scan
        if data_only_extent(ctx.mesh) is None:
            return None
    on_tpu = jax.default_backend() == "tpu"
    force_interpret = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET") == "1"
    if not (on_tpu or force_interpret):
        return None
    if len(sub.memories) != 1 or sub.memories[0].is_sequence:
        return None
    mem = sub.memories[0]
    lm = network.layer_map
    step_layers = [
        lm[n]
        for n in sub.layer_names
        if n not in skip and lm[n].type not in _AGENT_TYPES
    ]
    by_name = {l.name: l for l in step_layers}
    if len(step_layers) != 8 or not all(_clean(l) for l in step_layers):
        return None

    # anchor: the gru_step owning the memory
    gru = next((l for l in step_layers if l.type == "gru_step"), None)
    if gru is None or gru.name != mem.layer_name or len(gru.inputs) != 2:
        return None
    if gru.inputs[1].input_layer_name != mem.link_name:
        return None
    D = gru.size

    din = by_name.get(gru.inputs[0].input_layer_name)
    if din is None or din.type != "mixed" or din.size != 3 * D:
        return None
    if din.active_type not in ("", "linear"):
        return None
    # every din input except the context projection must be hoisted
    hoisted = set(pro_plan.get(din.name, ()))
    ctx_idx = [i for i in range(len(din.inputs)) if i not in hoisted]
    if len(ctx_idx) != 1:
        return None
    ctx_ic = din.inputs[ctx_idx[0]]
    if ctx_ic.proj_conf is None or ctx_ic.proj_conf.type != "fc":
        return None

    pooling = by_name.get(ctx_ic.input_layer_name)
    if (
        pooling is None
        or pooling.type != "average"
        or (pooling.average_strategy or "average") != "sum"
        or pooling.trans_type == "seq"
        or pooling.active_type not in ("", "linear")
        or len(pooling.inputs) != 1
    ):
        return None

    scaling = by_name.get(pooling.inputs[0].input_layer_name)
    if scaling is None or scaling.type != "scaling" or len(scaling.inputs) != 2:
        return None
    sm_name, ev_link = (
        scaling.inputs[0].input_layer_name,
        scaling.inputs[1].input_layer_name,
    )
    if ev_link not in statics:
        return None

    sm = by_name.get(sm_name)
    if (
        sm is None
        or sm.type != "fc"
        or sm.size != 1
        or sm.active_type != "sequence_softmax"
        or sm.bias_parameter_name
        or len(sm.inputs) != 1
    ):
        return None

    combine = by_name.get(sm.inputs[0].input_layer_name)
    if (
        combine is None
        or combine.type != "mixed"
        or combine.active_type != "tanh"
        or combine.size != D
        or len(combine.inputs) != 2
    ):
        return None
    comb_srcs = []
    for ic in combine.inputs:
        if ic.proj_conf is None or ic.proj_conf.type != "identity":
            return None
        comb_srcs.append(ic.input_layer_name)

    expand = next(
        (by_name[n] for n in comb_srcs if n in by_name and by_name[n].type == "expand"),
        None,
    )
    ep_link = next((n for n in comb_srcs if n in statics), None)
    if expand is None or ep_link is None or ep_link == ev_link:
        return None
    if not expand.inputs or expand.inputs[0].input_layer_name not in by_name:
        return None

    transform = by_name.get(expand.inputs[0].input_layer_name)
    if (
        transform is None
        or transform.type != "mixed"
        or transform.active_type not in ("", "linear")
        or transform.size != D
    ):
        return None
    tr_ic = _single_proj(transform, "fc")
    if tr_ic is None or tr_ic.input_layer_name != mem.link_name:
        return None

    # the whole template accounted for?
    template = {gru.name, din.name, pooling.name, scaling.name, sm.name,
                combine.name, expand.name, transform.name}
    if template != set(by_name):
        return None
    # in-links may only feed the hoisted din inputs
    in_link_names = {l.link_name for l in sub.in_links}
    for l in step_layers:
        for i, ic in enumerate(l.inputs):
            if ic.input_layer_name in in_link_names and not (
                l.name == din.name and i in hoisted
            ):
                return None

    gru_acts = (gru.active_type or "tanh", gru.active_gate_type or "sigmoid")
    if gru_acts != ("tanh", "sigmoid"):
        return None
    return dict(
        gru=gru, din=din, transform=transform, combine=combine, softmax=sm,
        ctx_ic=ctx_ic, tr_ic=tr_ic, ep_link=ep_link, ev_link=ev_link, D=D,
    )


def run_fused_decoder(network, sub, ctx, statics, plan, pro_feeds,
                      boot_carry, mask_bt) -> Optional[Array]:
    """Build kernel operands from the matched plan and run it. Returns
    the RAW per-step GRU output stream [T, B, D], or None when shapes
    fail the kernel gate (caller falls back to the scan)."""
    from paddle_tpu.ops import pallas_attention_gru as pag

    D = plan["D"]
    gru, din = plan["gru"], plan["din"]
    ep_arg = statics[plan["ep_link"]]
    ev_arg = statics[plan["ev_link"]]
    if ep_arg.value is None or ev_arg.value is None or not ep_arg.is_seq:
        return None
    B, Te = ep_arg.value.shape[0], ep_arg.value.shape[1]
    E = ev_arg.value.shape[2]
    xw = pro_feeds.get(din.name)
    if xw is None or ep_arg.value.shape[2] != D:
        return None
    Td = xw.shape[0]
    dtype = xw.dtype
    if dtype not in (jnp.float32, jnp.bfloat16):
        return None
    interpret = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET") == "1"
    data_extent = None
    if ctx.mesh is not None:
        from paddle_tpu.parallel.mesh import data_only_extent

        data_extent = data_only_extent(ctx.mesh)
        if data_extent is None or B % data_extent:
            return None
    B_local = B // (data_extent or 1)
    # the lane-alignment/VMEM gate is a Mosaic-compile constraint; the
    # interpreter (CPU parity tests) takes any shape
    if not interpret and not pag.supported(B_local, Te, D, E,
                                           jnp.dtype(dtype).itemsize):
        return None

    wa = ctx.param(plan["tr_ic"].input_parameter_name).reshape(D, D)
    v = ctx.param(plan["softmax"].inputs[0].input_parameter_name).reshape(D, 1)
    wctx = ctx.param(plan["ctx_ic"].input_parameter_name).reshape(E, 3 * D)
    wg = ctx.param(gru.inputs[0].input_parameter_name).reshape(D, 3 * D)

    f32 = jnp.float32
    ba = jnp.zeros((1, D), dtype)
    if plan["transform"].bias_parameter_name:
        ba = ba + ctx.param(plan["transform"].bias_parameter_name).reshape(1, D)
    if plan["combine"].bias_parameter_name:
        ba = ba + ctx.param(plan["combine"].bias_parameter_name).reshape(1, D)
    if din.bias_parameter_name:
        xw = xw + ctx.param(din.bias_parameter_name).reshape(1, 1, 3 * D).astype(dtype)
    if gru.bias_parameter_name:
        xw = xw + ctx.param(gru.bias_parameter_name).reshape(1, 1, 3 * D).astype(dtype)

    ep = jnp.swapaxes(ep_arg.value, 0, 1)                     # [Te, B, D]
    ev = jnp.swapaxes(ev_arg.value, 0, 1)                     # [Te, B, E]
    em = jnp.swapaxes(ep_arg.seq_mask(), 0, 1)[:, :, None].astype(dtype)
    dmask = jnp.swapaxes(mask_bt, 0, 1)[:, :, None].astype(dtype)
    h0 = boot_carry.astype(dtype)

    operands = (ep, ev, em, xw.astype(dtype), dmask, h0,
                wa, ba.astype(wa.dtype), v.reshape(1, D), wctx, wg)
    if data_extent is None:
        return pag.fused_attention_gru(*operands, ("tanh", "sigmoid"),
                                       interpret)
    # purely data-parallel mesh: per-shard execution (each shard's batch
    # rows are independent decodes); weights replicated, batch dims
    # sharded (the version-compat lives in parallel/mesh.py).
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.mesh import replicated_specs, shard_map_compat

    def shard_fn(ep_l, ev_l, em_l, xw_l, dm_l, h0_l, *ws):
        return pag.fused_attention_gru(ep_l, ev_l, em_l, xw_l, dm_l, h0_l,
                                       *ws, ("tanh", "sigmoid"), interpret)

    seq_spec = P(None, "data")
    in_specs = (seq_spec,) * 5 + (P("data"),) + replicated_specs(*operands[6:])
    return shard_map_compat(
        shard_fn, ctx.mesh, in_specs=in_specs, out_specs=seq_spec
    )(*operands)
