"""Recurrent-group executor (analog of RecurrentGradientMachine).

Compiles a recurrent sub-model (/root/reference/paddle/gserver/
gradientmachines/RecurrentGradientMachine.cpp) into a ``lax.scan`` over the
padded time axis: scatter/gather agents become per-step slices, memory
links become scan carries, and generation becomes greedy/beam search under
``lax.while_loop`` (see paddle_tpu.ops.beam_search).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext
from paddle_tpu.proto import LayerConfig


def forward_recurrent_group(network, cfg: LayerConfig, ctx: LayerContext) -> None:
    raise NotImplementedError(
        "recurrent_layer_group execution lands with the sequence-machinery "
        "stage (SURVEY.md §7 step 6)"
    )
