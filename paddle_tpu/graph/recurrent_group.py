"""Recurrent-group executor — the RecurrentGradientMachine analog.

Reference: /root/reference/paddle/gserver/gradientmachines/
RecurrentGradientMachine.cpp (1174 LoC). There, the engine clones the
sub-network per timestep (resizeOrCreateFrames :296), scatters sorted
ragged sequences into frames via Scatter/GatherAgentLayers, walks frames
forward then backward, and implements generation as an imperative beam
search (:717, :1114).

TPU-native formulation:
- training/eval: ONE ``lax.scan`` over the padded time axis. Scatter
  agents become per-step slices of [B, T, D]; memory links become scan
  carries (masked so padding passes state through); gather agents are the
  stacked scan outputs. XLA unrolls nothing — one compiled step reused T
  times, backward derived by jax.grad through the scan.
- generation: a fixed-length ``lax.scan`` over max_num_frames implementing
  batched beam search with static shapes (beam reindexing via
  take_along_axis, finished-beam masking) — the replacement for the
  pointer-chasing beamSearch loop.

Sub-sequence (nested) groups and sequence-valued memories raise
NotImplementedError for now (tracked divergence).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, forward_layer, register_layer
from paddle_tpu.ops.activations import apply_activation
from paddle_tpu.proto import LayerConfig, SubModelConfig

Array = jax.Array


@register_layer(
    "agent",
    "sequence_agent",
    "scatter_agent",
    "sequence_scatter_agent",
    "gather_agent",
    "sequence_gather_agent",
)
def _agent_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    raise RuntimeError(
        f"agent layer {cfg.name!r} executed outside a recurrent group — "
        "agents are fed by the group executor"
    )


def forward_recurrent_group(network, cfg: LayerConfig, ctx: LayerContext) -> None:
    sub = network.submodel_map.get(cfg.name)
    assert sub is not None, f"no sub-model named {cfg.name!r}"
    if sub.generator is not None:
        _generate(network, cfg, sub, ctx)
    else:
        _forward_scan(network, cfg, sub, ctx)


# ------------------------------------------------------------- training


def _is_int_carry(x: Array) -> bool:
    return jnp.issubdtype(x.dtype, jnp.integer)


def _carry_to_arg(carry: Array) -> Argument:
    if _is_int_carry(carry):
        return Argument(ids=carry)
    return Argument(value=carry)


def _resolve_outer(sub: SubModelConfig, name: str) -> str:
    """Map an in-group agent name back to the outer layer feeding it."""
    for link in list(sub.static_links) + list(sub.in_links):
        if link.link_name == name:
            return link.layer_name
    return name


def _memory_boot(network, mem, ctx: LayerContext, batch: int, dtype, sub: SubModelConfig) -> Array:
    size = network.layer_map[mem.link_name].size
    if mem.boot_layer_name:
        boot = ctx.outputs[_resolve_outer(sub, mem.boot_layer_name)].value
    elif mem.boot_with_const_id >= 0:
        boot = jnp.full((batch,), mem.boot_with_const_id, jnp.int32)
        return boot
    else:
        boot = jnp.zeros((batch, size), dtype)
    if mem.boot_bias_parameter_name:
        boot = boot + ctx.param(mem.boot_bias_parameter_name).reshape(-1)
        boot = apply_activation(mem.boot_bias_active_type, boot)
    return boot


def _run_submodel_step(
    network,
    sub: SubModelConfig,
    ctx: LayerContext,
    fed: Dict[str, Argument],
    rng: Optional[Array],
) -> Dict[str, Argument]:
    """Run the sub-model's layers once with pre-fed agent outputs."""
    step_ctx = LayerContext(
        params=ctx.params,
        model=ctx.model,
        pass_type=ctx.pass_type,
        rng=rng,
        states=ctx.states,
        dtype=ctx.dtype,
        mesh=ctx.mesh,
    )
    step_ctx.outputs.update(fed)
    for name in sub.layer_names:
        lcfg = network.layer_map[name]
        if lcfg.name in step_ctx.outputs:
            continue
        ins = [
            network._lookup_input(step_ctx, ic.input_layer_name, ic.input_layer_argument)
            for ic in lcfg.inputs
        ]
        forward_layer(lcfg, ins, step_ctx)
    # NOTE: state updates produced inside the scan body (batch_norm moving
    # stats) would be scan tracers — propagating them out would leak.
    # Running statistics are not updated inside recurrent groups
    # (divergence; the reference shares this limitation in practice since
    # BN inside a step sees per-frame batches).
    return step_ctx.outputs


def _forward_scan(network, cfg: LayerConfig, sub: SubModelConfig, ctx: LayerContext) -> None:
    for link in sub.in_links:
        if link.has_subseq:
            raise NotImplementedError("nested (sub-sequence) recurrent groups not yet supported")
    assert sub.in_links, f"recurrent group {cfg.name} has no sequence inputs"
    first = ctx.outputs[sub.in_links[0].layer_name]
    assert first.is_seq, f"in-link {sub.in_links[0].layer_name!r} is not a sequence"
    lengths = first.seq_lengths
    B, T = first.batch_size, first.max_len
    mask_bt = first.seq_mask()  # [B, T]

    # time-major stacked in-link slices
    xs_vals: Dict[str, Array] = {}
    xs_ids: Dict[str, Array] = {}
    for link in sub.in_links:
        arg = ctx.outputs[link.layer_name]
        if arg.value is not None:
            xs_vals[link.link_name] = jnp.swapaxes(arg.value, 0, 1)  # [T, B, D]
        if arg.ids is not None:
            xs_ids[link.link_name] = jnp.swapaxes(arg.ids, 0, 1)  # [T, B]

    statics: Dict[str, Argument] = {
        link.link_name: ctx.outputs[link.layer_name] for link in sub.static_links
    }

    memories = list(sub.memories)
    for mem in memories:
        if mem.is_sequence:
            raise NotImplementedError("sequence-valued memories not yet supported")
    # carry dtype must match the traced computation (x64 gradient checks
    # promote everything), so follow the data rather than ctx.dtype
    carry_dtype = first.value.dtype if first.value is not None else ctx.dtype
    init_carries = tuple(
        _memory_boot(network, mem, ctx, B, carry_dtype, sub) for mem in memories
    )
    out_links = list(sub.out_links)
    base_rng = ctx.rng

    def step(carries, inp):
        x_v, x_i, m_t, t_idx = inp
        fed: Dict[str, Argument] = {}
        for name, v in x_v.items():
            fed[name] = Argument(value=v, ids=x_i.get(name))
        for name, i in x_i.items():
            if name not in fed:
                fed[name] = Argument(ids=i)
        for name, arg in statics.items():
            fed[name] = arg
        for mem, carry in zip(memories, carries):
            fed[mem.link_name] = _carry_to_arg(carry)
        rng = jax.random.fold_in(base_rng, t_idx) if base_rng is not None else None
        outs = _run_submodel_step(network, sub, ctx, fed, rng)
        new_carries = []
        m = m_t[:, None]
        for mem, old in zip(memories, carries):
            out_arg = outs[mem.layer_name]
            new = out_arg.value if not _is_int_carry(old) else out_arg.ids
            keep = m > 0 if new.ndim == 2 else m_t > 0
            new_carries.append(jnp.where(keep, new, old))
        ys = tuple(outs[l.layer_name].value * m for l in out_links)
        return tuple(new_carries), ys

    xs = (
        xs_vals,
        xs_ids,
        jnp.swapaxes(mask_bt, 0, 1),
        jnp.arange(T, dtype=jnp.int32),
    )
    _, ys = jax.lax.scan(step, init_carries, xs, reverse=bool(sub.reversed))
    for link, y in zip(out_links, ys):
        ctx.outputs[link.link_name] = Argument(
            value=jnp.swapaxes(y, 0, 1), seq_lengths=lengths
        )
    # the group layer itself exposes the first out-link
    if out_links:
        ctx.outputs[cfg.name] = ctx.outputs[out_links[0].link_name]


# ------------------------------------------------------------ generation


def _expand_beams(arg: Argument, K: int) -> Argument:
    """Tile an Argument's batch dim by the beam width: [B, ...] → [B*K, ...]."""

    def rep(x):
        return None if x is None else jnp.repeat(x, K, axis=0)

    return Argument(
        value=rep(arg.value),
        ids=rep(arg.ids),
        seq_lengths=rep(arg.seq_lengths),
        sub_seq_lengths=rep(arg.sub_seq_lengths),
        weight=rep(arg.weight),
    )


def _generate(network, cfg: LayerConfig, sub: SubModelConfig, ctx: LayerContext) -> None:
    """Batched beam search (ref: RecurrentGradientMachine::beamSearch
    :1114 and oneWaySearch :786 — greedy is beam_size=1)."""
    gen = sub.generator
    K = max(int(cfg.beam_size or gen.beam_size), 1)
    L = int(gen.max_num_frames)
    assert L > 0, "generator needs max_num_frames (beam_search max_length)"
    bos, eos = int(cfg.bos_id), int(cfg.eos_id)

    # batch size from any static link or boot layer
    B = None
    statics: Dict[str, Argument] = {}
    for link in sub.static_links:
        arg = ctx.outputs[link.layer_name]
        statics[link.link_name] = _expand_beams(arg, K)
        B = arg.batch_size if B is None else B
    memories = list(sub.memories)
    boots = []
    for mem in memories:
        if mem.is_sequence:
            raise NotImplementedError("sequence-valued memories in generation")
        if mem.boot_layer_name and B is None:
            B = ctx.outputs[mem.boot_layer_name].batch_size
    assert B is not None, f"generation group {cfg.name}: cannot infer batch size"
    gen_dtype = ctx.dtype
    for arg in statics.values():
        if arg.value is not None:
            gen_dtype = arg.value.dtype
            break
    for mem in memories:
        boots.append(_memory_boot(network, mem, ctx, B, gen_dtype, sub))
    # expand memories across beams: [B, D] → [B*K, D]
    carries0 = tuple(
        jnp.repeat(b, K, axis=0) for b in boots
    )

    if sub.in_links:
        raise NotImplementedError(
            f"generation group {cfg.name}: plain sequence inputs are not "
            "supported during generation — wrap encoder outputs in "
            "StaticInput(..., is_seq=True)"
        )
    # the feed agent for previously generated ids (created by beam_search())
    predict_agent = f"__generated_id@{cfg.name}"
    assert predict_agent in network.layer_map, "generation group missing the generated-id agent"
    score_layer = sub.out_links[0].layer_name

    neg_inf = jnp.asarray(-1e30, gen_dtype)
    init_state = (
        carries0,
        jnp.full((B * K,), bos, jnp.int32),                  # prev token per beam
        jnp.concatenate(                                      # cum log prob [B, K]
            [jnp.zeros((B, 1), gen_dtype), jnp.full((B, K - 1), neg_inf, gen_dtype)], axis=1
        )
        if K > 1
        else jnp.zeros((B, 1), gen_dtype),
        jnp.zeros((B, K), bool),                              # finished
        jnp.zeros((B, K, L), jnp.int32),                      # token history
        jnp.zeros((B, K), jnp.int32),                         # lengths
    )
    base_rng = ctx.rng

    def step(state, t_idx):
        carries, prev_tok, cum, finished, history, lens = state
        fed: Dict[str, Argument] = {predict_agent: Argument(ids=prev_tok)}
        for name, arg in statics.items():
            fed[name] = arg
        for mem, carry in zip(memories, carries):
            fed[mem.link_name] = _carry_to_arg(carry)
        rng = jax.random.fold_in(base_rng, t_idx) if base_rng is not None else None
        outs = _run_submodel_step(network, sub, ctx, fed, rng)
        probs = outs[score_layer].value  # [B*K, V]
        V = probs.shape[-1]
        logp = jnp.log(jnp.clip(probs, 1e-20, None)).reshape(B, K, V)
        fin = finished[:, :, None]
        # finished beams may only "emit" eos with no score change; every
        # other candidate is dead (-inf, not the clip floor, else a
        # finished beam's V-1 ghosts can outrank live continuations)
        eos_onehot = jax.nn.one_hot(eos, V, dtype=logp.dtype)
        logp = jnp.where(fin, jnp.where(eos_onehot[None, None, :] > 0, 0.0, neg_inf), logp)
        total = cum[:, :, None] + logp  # [B, K, V]
        flat = total.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(flat, K)  # [B, K]
        beam_idx = top_idx // V                        # [B, K]
        token = (top_idx % V).astype(jnp.int32)        # [B, K]
        # advance memories with this step's outputs, then reindex by the
        # selected beams
        flat_sel = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)  # [B*K]
        stepped = tuple(
            outs[mem.layer_name].ids if _is_int_carry(old) else outs[mem.layer_name].value
            for mem, old in zip(memories, carries)
        )
        # finished beams freeze their state
        fin_flat = finished.reshape(-1)
        frozen = tuple(
            jnp.where(fin_flat[:, None] if new.ndim == 2 else fin_flat, old, new)
            for old, new in zip(carries, stepped)
        )
        new_carries = tuple(c[flat_sel] for c in frozen)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        lens = jnp.take_along_axis(lens, beam_idx, axis=1)
        history = jnp.take_along_axis(history, beam_idx[:, :, None], axis=1)
        history = history.at[:, :, t_idx].set(jnp.where(finished, eos, token))
        lens = jnp.where(finished, lens, lens + 1)
        finished = finished | (token == eos)
        return (
            new_carries,
            token.reshape(-1),
            top_scores,
            finished,
            history,
            lens,
        ), None

    state, _ = jax.lax.scan(step, init_state, jnp.arange(L, dtype=jnp.int32))
    _, _, scores, finished, history, lens = state
    # best beam per sample (beams are kept sorted by top_k, but normalize
    # defensively by picking argmax score)
    best = jnp.argmax(scores, axis=1)  # [B]
    best_tokens = jnp.take_along_axis(history, best[:, None, None], axis=1)[:, 0]  # [B, L]
    best_lens = jnp.take_along_axis(lens, best[:, None], axis=1)[:, 0]
    ctx.outputs[cfg.name] = Argument(ids=best_tokens, seq_lengths=best_lens)
    ctx.outputs[f"{cfg.name}@beams"] = Argument(
        ids=history, value=scores, seq_lengths=jnp.full((B,), K, jnp.int32),
        sub_seq_lengths=lens,
    )
    ctx.outputs[score_layer] = ctx.outputs[cfg.name]
