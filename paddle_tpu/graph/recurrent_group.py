"""Recurrent-group executor — the RecurrentGradientMachine analog.

Reference: /root/reference/paddle/gserver/gradientmachines/
RecurrentGradientMachine.cpp (1174 LoC). There, the engine clones the
sub-network per timestep (resizeOrCreateFrames :296), scatters sorted
ragged sequences into frames via Scatter/GatherAgentLayers, walks frames
forward then backward, and implements generation as an imperative beam
search (:717, :1114).

TPU-native formulation:
- training/eval: ONE ``lax.scan`` over the padded time axis. Scatter
  agents become per-step slices of [B, T, D]; memory links become scan
  carries (masked so padding passes state through); gather agents are the
  stacked scan outputs. XLA unrolls nothing — one compiled step reused T
  times, backward derived by jax.grad through the scan.
- generation: a ``lax.while_loop`` bounded by max_num_frames implementing
  batched beam search with static shapes (beam reindexing via
  take_along_axis, finished-beam masking) that exits as soon as every
  beam has finished — the replacement for the pointer-chasing beamSearch
  loop. Groups with real sequence in-links generate one step per input
  frame (per-step conditioning); nested in-links feed one whole
  subsequence per step.
- nested (sub-sequence) groups: the outer scan steps over SUBSEQUENCES
  ([B, S, T, D] in-links feed [B, T, D] sequence frames, ref
  createInFrameInfo hasSubseq branch :564); an inner recurrent group in
  the step body scans the tokens — scan-in-scan, still one compiled step.
- sequence-valued memories (memory(is_seq=True), ref createMemoryFrameInfo
  :622): the carry is a whole padded sequence (value, lengths), booted
  from a sequence layer, so step s can read step s-1's full output
  sequence (hierarchical RNN decoders).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import (LayerContext, TimeMajorLogits, forward_layer,
                                   register_layer)
from paddle_tpu.ops.activations import apply_activation
from paddle_tpu.proto import LayerConfig, SubModelConfig

Array = jax.Array


@register_layer(
    "agent",
    "sequence_agent",
    "scatter_agent",
    "sequence_scatter_agent",
    "gather_agent",
    "sequence_gather_agent",
)
def _agent_layer(cfg: LayerConfig, inputs: List[Argument], ctx: LayerContext) -> Argument:
    raise RuntimeError(
        f"agent layer {cfg.name!r} executed outside a recurrent group — "
        "agents are fed by the group executor"
    )


def forward_recurrent_group(network, cfg: LayerConfig, ctx: LayerContext) -> None:
    sub = network.submodel_map.get(cfg.name)
    assert sub is not None, f"no sub-model named {cfg.name!r}"
    if sub.generator is not None:
        _generate(network, cfg, sub, ctx)
    else:
        _forward_scan(network, cfg, sub, ctx)


# ------------------------------------------------------------- training


def _is_int_carry(x: Array) -> bool:
    return jnp.issubdtype(x.dtype, jnp.integer)


def _carry_to_arg(carry: Array) -> Argument:
    if _is_int_carry(carry):
        return Argument(ids=carry)
    return Argument(value=carry)


def _resolve_outer(sub: SubModelConfig, name: str) -> str:
    """Map an in-group agent name back to the outer layer feeding it."""
    for link in list(sub.static_links) + list(sub.in_links):
        if link.link_name == name:
            return link.layer_name
    return name


def _scope_lookup(ctx: LayerContext, name: str) -> Argument:
    """Group-entry name resolution: this scope, then enclosing scopes.

    Used ONLY for in-links, static links, and memory boot layers — the
    references a nested group may legitimately make to layers outside its
    enclosing group (reference: agent layers connect across frames).
    """
    c = ctx
    while c is not None:
        if name in c.outputs:
            return c.outputs[name]
        c = c.parent
    raise KeyError(f"layer output {name!r} not found in any enclosing scope")


def _memory_boot(network, mem, ctx: LayerContext, batch: int, dtype, sub: SubModelConfig) -> Array:
    size = network.layer_map[mem.link_name].size
    if mem.boot_layer_name:
        boot = _scope_lookup(ctx, _resolve_outer(sub, mem.boot_layer_name)).value
    elif mem.boot_with_const_id >= 0:
        boot = jnp.full((batch,), mem.boot_with_const_id, jnp.int32)
        return boot
    else:
        boot = jnp.zeros((batch, size), dtype)
    if mem.boot_bias_parameter_name:
        boot = boot + ctx.param(mem.boot_bias_parameter_name).reshape(-1)
        boot = apply_activation(mem.boot_bias_active_type, boot)
    return boot


def _run_submodel_step(
    network,
    sub: SubModelConfig,
    ctx: LayerContext,
    fed: Dict[str, Argument],
    rng: Optional[Array],
    skip: frozenset = frozenset(),
    mixed_prologue: Optional[Dict[str, Any]] = None,
) -> Dict[str, Argument]:
    """Run the sub-model's layers once with pre-fed agent outputs.
    ``skip`` names epilogue layers hoisted out of the scan;
    ``mixed_prologue`` maps a mixed layer to (skip_input_indices,
    precomputed [B, out] slice) for projections hoisted BEFORE the scan
    (see _plan_prologue)."""
    step_ctx = LayerContext(
        params=ctx.params,
        model=ctx.model,
        pass_type=ctx.pass_type,
        rng=rng,
        states=ctx.states,
        dtype=ctx.dtype,
        mesh=ctx.mesh,
        compute_dtype=ctx.compute_dtype,
        no_cast_inputs=ctx.no_cast_inputs,
        scan_unroll=ctx.scan_unroll,
        mixed_prologue=mixed_prologue,
    )
    # the parent link lets an inner group's ENTRY resolution (static
    # links, boot layers, nested in-links) see outer-scope layers without
    # making them resolvable as ordinary layer inputs — a step referencing
    # an outer sequence without StaticInput still fails loudly
    step_ctx.parent = ctx
    step_ctx.outputs.update(fed)
    for name in sub.layer_names:
        lcfg = network.layer_map[name]
        if lcfg.name in step_ctx.outputs or lcfg.name in skip:
            continue
        if lcfg.type == "recurrent_layer_group":
            # nested group: the inner executor scans the tokens of this
            # step's subsequence (scan-in-scan)
            forward_recurrent_group(network, lcfg, step_ctx)
            continue
        ins = [
            network._lookup_input(step_ctx, ic.input_layer_name, ic.input_layer_argument)
            for ic in lcfg.inputs
        ]
        forward_layer(lcfg, ins, step_ctx)
    # NOTE: state updates produced inside the scan body (batch_norm moving
    # stats) would be scan tracers — propagating them out would leak.
    # Running statistics are not updated inside recurrent groups
    # (divergence; the reference shares this limitation in practice since
    # BN inside a step sees per-frame batches).
    return step_ctx.outputs


def _memory_feed_arg(mem, carry) -> Argument:
    """Turn a scan/beam carry back into the Argument fed to the step
    (shared by training and generation)."""
    if mem.is_sequence:
        v, sl = carry
        return (
            Argument(ids=v, seq_lengths=sl)
            if _is_int_carry(v)
            else Argument(value=v, seq_lengths=sl)
        )
    return _carry_to_arg(carry)


def _advance_seq_memory(mem, old, out_arg: Argument, Tm: int, n_rows: int):
    """New (value, lengths) for a sequence memory from the linked layer's
    step output, padded/clamped to the FIXED capacity Tm (the boot
    sequence's padded length — XLA carries need static shapes, so a
    carried sequence cannot grow past the boot's capacity; pad the boot
    to the maximum length the step may produce; see doc/divergences.md).
    Callers apply their own keep-mask (per-sample in training, per-beam
    in generation)."""
    old_v, _ = old
    new_v = out_arg.ids if _is_int_carry(old_v) else out_arg.value
    assert new_v.ndim == old_v.ndim, (
        f"sequence memory {mem.layer_name!r}: linked layer must "
        "produce a sequence frame"
    )
    new_v = _pad_time(new_v, Tm)
    if out_arg.seq_lengths is not None:
        new_l = jnp.minimum(out_arg.seq_lengths, Tm)
    else:
        new_l = jnp.full((n_rows,), Tm, jnp.int32)
    return new_v, new_l


def _pad_time(x: Array, T: int) -> Array:
    """Pad or slice axis 1 to exactly T (static shapes for scan carries)."""
    if x.shape[1] == T:
        return x
    if x.shape[1] > T:
        return jax.lax.slice_in_dim(x, 0, T, axis=1)
    pad = [(0, 0), (0, T - x.shape[1])] + [(0, 0)] * (x.ndim - 2)
    return jnp.pad(x, pad)


def _memory_boot_seq(network, mem, ctx: LayerContext, sub: SubModelConfig):
    """Boot a sequence-valued memory (createMemoryFrameInfo seqFlag branch,
    ref RecurrentGradientMachine.cpp:622): the boot layer MUST be a
    sequence; the carry is its padded (value-or-ids, lengths) pair."""
    assert mem.boot_layer_name, (
        f"sequence memory for {mem.layer_name!r} needs a sequence boot layer "
        "(reference: 'boot layer must be a sequence when is_sequence = true')"
    )
    boot = _scope_lookup(ctx, _resolve_outer(sub, mem.boot_layer_name))
    assert boot.is_seq, (
        f"boot layer {mem.boot_layer_name!r} of sequence memory is not a sequence"
    )
    v = boot.value if boot.value is not None else boot.ids
    return (v, boot.seq_lengths)


# layer types that are pure per-row functions of their inputs (no
# sequence/time semantics, no randomness) — safe to re-apply on stacked
# [T*B, D] rows after the scan instead of per step inside it
_HOISTABLE_TYPES = frozenset({"fc", "mixed", "addto", "slope_intercept", "concat"})


def _plan_epilogue(network, sub: SubModelConfig):
    """Split the step graph into (inside, epilogue) for training scans.

    Layers that only feed the group's out-links — never a memory, never
    another inside layer — and are pure per-row ops can run ONCE on the
    stacked scan outputs instead of once per step. The classic win is an
    NMT decoder's vocab-softmax projection: inside the scan it re-reads
    the [D, V] weight from HBM every step and multiplies [B, D] rows;
    hoisted it is a single [T*B, D] x [D, V] matmul. Returns
    (epilogue: ordered layer names, frontier: inside outputs the epilogue
    reads), or None when nothing can be hoisted.
    """
    layer_map = network.layer_map
    names = [n for n in sub.layer_names if n in layer_map]
    name_set = set(names)
    for n in names:
        if layer_map[n].type == "recurrent_layer_group":
            return None  # nested groups: keep everything inside
    # consumers within the step graph
    consumers: Dict[str, set] = {n: set() for n in names}
    for n in names:
        for ic in layer_map[n].inputs:
            if ic.input_layer_name in consumers:
                consumers[ic.input_layer_name].add(n)
    # everything a memory reads must stay inside (the carry depends on it)
    inside_roots = {m.layer_name for m in sub.memories if m.layer_name in name_set}
    must_inside = set()
    stack = list(inside_roots)
    while stack:
        n = stack.pop()
        if n in must_inside:
            continue
        must_inside.add(n)
        for ic in layer_map[n].inputs:
            if ic.input_layer_name in name_set:
                stack.append(ic.input_layer_name)
    out_names = {l.layer_name for l in sub.out_links}

    def hoistable(n):
        lc = layer_map[n]
        return (
            lc.type in _HOISTABLE_TYPES
            and lc.drop_rate == 0.0
            and n not in must_inside
        )

    # reverse-topological growth: a layer joins the epilogue when every
    # step-graph consumer already did (out-link layers additionally have
    # the implicit "out" consumer, which the epilogue serves)
    epilogue: list = []
    in_epi: set = set()
    for n in reversed(names):
        if not hoistable(n):
            continue
        if not consumers[n] and n not in out_names:
            continue  # dead layer — leave it alone
        if all(c in in_epi for c in consumers[n]):
            in_epi.add(n)
            epilogue.append(n)
    epilogue.reverse()
    if not any(n in out_names for n in epilogue):
        return None  # hoisting pays only when an out-link moves out
    # frontier: non-epilogue values the epilogue reads (inside layers or
    # fed agents)
    frontier: list = []
    for n in epilogue:
        for ic in layer_map[n].inputs:
            src = ic.input_layer_name
            if src not in in_epi and src not in frontier:
                frontier.append(src)
    return epilogue, frontier


def _plan_prologue(network, sub: SubModelConfig, epilogue: frozenset):
    """Projection PROLOGUE hoisting: the input-side dual of the epilogue.

    A mixed layer inside the scan often sums a carry-dependent projection
    (attention context) with projections of plain scan inputs (the NMT
    decoder's target-word projection, reference seqToseq_net.py:120-124).
    The scan-input projections are time-parallel: compute them ONCE
    outside the scan as a single [T, B, D] x [D, out] matmul (full MXU
    tiles, one weight read) and feed the per-step slices in as extra scan
    inputs; the step's mixed layer starts its sum from the precomputed
    slice and skips those projection inputs.

    Returns {mixed_layer_name: (input_index, ...)} naming the
    weight-bearing projections (fc/trans_fc) whose source is a plain
    non-subseq in-link agent. Epilogue layers are excluded (they already
    run outside the scan).
    """
    layer_map = network.layer_map
    in_links = {l.link_name for l in sub.in_links if not l.has_subseq}
    plan = {}
    for n in sub.layer_names:
        lc = layer_map.get(n)
        if lc is None or lc.type != "mixed" or n in epilogue:
            continue
        idxs = tuple(
            idx
            for idx, ic in enumerate(lc.inputs)
            if ic.proj_conf is not None
            and ic.proj_conf.type in ("fc", "trans_fc")
            and ic.input_layer_name in in_links
        )
        if idxs:
            plan[n] = idxs
    return plan


def _forward_scan(network, cfg: LayerConfig, sub: SubModelConfig, ctx: LayerContext) -> None:
    assert sub.in_links, f"recurrent group {cfg.name} has no sequence inputs"
    nested = any(link.has_subseq for link in sub.in_links)
    if nested:
        # outer scan over SUBSEQUENCES: [B, S, T, ...] in-links feed
        # [B, T, ...] sequence frames (createInFrameInfo hasSubseq :564)
        ref_link = next(l for l in sub.in_links if l.has_subseq)
        first = _scope_lookup(ctx, ref_link.layer_name)
        assert first.is_nested_seq, (
            f"in-link {ref_link.layer_name!r} marked has_subseq but is not nested"
        )
    else:
        first = _scope_lookup(ctx, sub.in_links[0].layer_name)
        assert first.is_seq, f"in-link {sub.in_links[0].layer_name!r} is not a sequence"
    lengths = first.seq_lengths          # [B]: valid timesteps / subsequences
    B, T = first.batch_size, first.max_len
    mask_bt = first.seq_mask()           # [B, T] (T = S for nested groups)

    # time-major stacked in-link slices; nested links also stack their
    # per-subsequence lengths so each frame is a real sequence Argument
    xs_vals: Dict[str, Array] = {}
    xs_ids: Dict[str, Array] = {}
    xs_sublens: Dict[str, Array] = {}
    for link in sub.in_links:
        arg = _scope_lookup(ctx, link.layer_name)
        if arg.value is not None:
            xs_vals[link.link_name] = jnp.swapaxes(arg.value, 0, 1)
        if arg.ids is not None:
            xs_ids[link.link_name] = jnp.swapaxes(arg.ids, 0, 1)
        if link.has_subseq:
            assert arg.sub_seq_lengths is not None
            xs_sublens[link.link_name] = jnp.swapaxes(arg.sub_seq_lengths, 0, 1)  # [S, B]

    statics: Dict[str, Argument] = {
        link.link_name: _scope_lookup(ctx, link.layer_name) for link in sub.static_links
    }

    memories = list(sub.memories)
    # carry dtype must match the traced computation (x64 gradient checks
    # promote everything), so follow the data rather than ctx.dtype
    carry_dtype = first.value.dtype if first.value is not None else ctx.dtype
    init_carries = []
    seq_mem_T: Dict[int, int] = {}
    for i, mem in enumerate(memories):
        if mem.is_sequence:
            v, sl = _memory_boot_seq(network, mem, ctx, sub)
            seq_mem_T[i] = v.shape[1]
            init_carries.append((v, sl))
        else:
            init_carries.append(_memory_boot(network, mem, ctx, B, carry_dtype, sub))
    init_carries = tuple(init_carries)
    out_links = list(sub.out_links)
    base_rng = ctx.rng

    # epilogue hoisting: pure per-row suffix layers (e.g. the NMT vocab
    # projection) run ONCE on stacked scan outputs instead of per step —
    # one [T*B, D] x [D, V] matmul instead of T weight re-reads. Only for
    # flat groups whose hoisted layers never read a sequence-valued feed.
    plan = None if nested else _plan_epilogue(network, sub)
    if plan is not None:
        # a hoisted layer must never read a sequence-VALUED feed — its
        # per-step input would be [B, T2, D] with lengths the frontier
        # capture can't carry
        seq_feeds = {m.link_name for m in sub.memories if m.is_sequence}
        seq_feeds |= {l.link_name for l in sub.in_links if l.has_subseq}
        seq_feeds |= {l.link_name for l in sub.static_links if l.has_subseq}
        if any(f in seq_feeds for f in plan[1]):
            plan = None
    epilogue, frontier = plan if plan is not None else ([], [])
    # loop-invariant static feeds are rebuilt outside the scan (tiling a
    # [B, D] static T times as scan output would waste memory)
    dyn_frontier = [f for f in frontier if f not in statics]
    skip = frozenset(epilogue)
    inside_out_links = [l for l in out_links if l.layer_name not in skip]

    # prologue hoisting: time-parallel projections of plain scan inputs
    # computed once outside the scan (see _plan_prologue)
    pro_plan = {} if nested else _plan_prologue(network, sub, skip)
    pro_feeds: Dict[str, Array] = {}
    if pro_plan:
        from paddle_tpu.layers.core import apply_projection

        for lname, idxs in pro_plan.items():
            lc = network.layer_map[lname]
            acc = None
            for idx in idxs:
                ic = lc.inputs[idx]
                # the SAME projection code the in-scan path uses, applied
                # to the [T, B, D] time-major stacked in-link in one matmul
                y = apply_projection(
                    ic.proj_conf, ic, Argument(value=xs_vals[ic.input_layer_name]), ctx
                )
                acc = y if acc is None else acc + y
            pro_feeds[lname] = acc

    # fused attention-GRU decoder (OptimizationConfig.pallas_decoder):
    # when the step graph is exactly the simple_attention + gru_step
    # template, the whole time loop runs as ONE pallas launch with the
    # encoder states VMEM-resident per batch block (ops/
    # pallas_attention_gru); the hoisted epilogue then consumes the
    # same raw frontier stream the scan would have produced
    fused_ys = None
    if not nested and ctx.pallas_decoder:
        from paddle_tpu.graph.fused_decoder import match_decoder, run_fused_decoder

        fplan = match_decoder(network, sub, ctx, statics, skip, pro_plan)
        if fplan is not None:
            gname = fplan["gru"].name
            frontier_ok = all(f == gname for f in dyn_frontier)
            links_ok = all(l.layer_name == gname for l in inside_out_links)
            if frontier_ok and links_ok:
                try:
                    fused_ys = run_fused_decoder(
                        network, sub, ctx, statics, fplan, pro_feeds,
                        init_carries[0], mask_bt,
                    )
                except Exception as exc:  # noqa: BLE001 — any compile
                    # failure (VMEM overflow on an untested shape, a
                    # Mosaic lowering bug) must not kill the step: the
                    # unfused scan below computes the same function
                    import logging

                    logging.getLogger("paddle_tpu.graph").warning(
                        "fused decoder kernel failed for %s — falling "
                        "back to the unfused scan: %s", sub.name, exc)
                    fused_ys = None

    def step(carries, inp):
        x_v, x_i, x_sl, m_t, t_idx, x_pro = inp
        fed: Dict[str, Argument] = {}
        for link in sub.in_links:
            name = link.link_name
            fed[name] = Argument(
                value=x_v.get(name),
                ids=x_i.get(name),
                seq_lengths=x_sl.get(name),
            )
        for name, arg in statics.items():
            fed[name] = arg
        for i, (mem, carry) in enumerate(zip(memories, carries)):
            fed[mem.link_name] = _memory_feed_arg(mem, carry)
        rng = jax.random.fold_in(base_rng, t_idx) if base_rng is not None else None
        mixed_pro = {
            lname: (pro_plan[lname], x_pro[lname]) for lname in x_pro
        }
        outs = _run_submodel_step(
            network, sub, ctx, fed, rng, skip=skip, mixed_prologue=mixed_pro
        )
        new_carries = []
        m = m_t[:, None]
        for i, (mem, old) in enumerate(zip(memories, carries)):
            out_arg = outs[mem.layer_name]
            if mem.is_sequence:
                old_v, old_l = old
                new_v, new_l = _advance_seq_memory(mem, old, out_arg, seq_mem_T[i], B)
                keep = m_t > 0
                keep_v = keep.reshape((B,) + (1,) * (new_v.ndim - 1))
                new_carries.append(
                    (jnp.where(keep_v, new_v, old_v), jnp.where(keep, new_l, old_l))
                )
            else:
                new = out_arg.value if not _is_int_carry(old) else out_arg.ids
                keep = m > 0 if new.ndim == 2 else m_t > 0
                new_carries.append(jnp.where(keep, new, old))
        ys = []
        for l in inside_out_links:
            out_arg = outs[l.layer_name]
            if out_arg.value.ndim >= 3 and out_arg.seq_lengths is not None:
                # sequence frame (inner-group output): nested result
                # (mask cast keeps bf16 outputs bf16)
                ys.append(
                    (
                        out_arg.value * m_t[:, None, None].astype(out_arg.value.dtype),
                        (out_arg.seq_lengths * m_t.astype(jnp.int32)),
                    )
                )
            else:
                ys.append((out_arg.value * m.astype(out_arg.value.dtype), None))
        # frontier values for the hoisted epilogue — UNMASKED (the final
        # out-link mask is applied after the epilogue, matching the
        # masked-inside semantics exactly)
        fr = tuple((outs[f].value, outs[f].ids) for f in dyn_frontier)
        return tuple(new_carries), (tuple(ys), fr)

    xs = (
        xs_vals,
        xs_ids,
        xs_sublens,
        jnp.swapaxes(mask_bt, 0, 1),
        jnp.arange(T, dtype=jnp.int32),
        pro_feeds,
    )
    if fused_ys is not None:
        # same (ys, frs) pytree the scan would produce: masked out-link
        # streams + raw frontier values for the hoisted epilogue
        m3 = jnp.swapaxes(mask_bt, 0, 1)[:, :, None].astype(fused_ys.dtype)
        ys = [(fused_ys * m3, None) for _ in inside_out_links]
        frs = tuple((fused_ys, None) for _ in dyn_frontier)
    else:
        _, (ys, frs) = jax.lax.scan(
            step, init_carries, xs, reverse=bool(sub.reversed), unroll=ctx.scan_unroll
        )
    for link, (y, y_lens) in zip(inside_out_links, ys):
        if y_lens is not None:
            # [S, B, T, D] → nested [B, S, T, D] with per-subseq lengths
            ctx.outputs[link.link_name] = Argument(
                value=jnp.swapaxes(y, 0, 1),
                seq_lengths=lengths,
                sub_seq_lengths=jnp.swapaxes(y_lens, 0, 1),
            )
        else:
            ctx.outputs[link.link_name] = Argument(
                value=jnp.swapaxes(y, 0, 1), seq_lengths=lengths
            )
    if epilogue:
        _run_epilogue(
            network, ctx, epilogue, dyn_frontier, frs, statics, out_links,
            B, T, mask_bt, lengths,
        )
    # the group layer itself exposes the first out-link (logits alias
    # included, so a cost wired to the group name keeps the fused path)
    if out_links:
        ctx.outputs[cfg.name] = ctx.outputs[out_links[0].link_name]
        if out_links[0].link_name in ctx.logits:
            ctx.logits[cfg.name] = ctx.logits[out_links[0].link_name]


def _run_epilogue(network, ctx, epilogue, dyn_frontier, frs, statics,
                  out_links, B, T, mask_bt, lengths):
    """Apply hoisted per-row layers once to the stacked scan outputs."""
    epi_ctx = LayerContext(
        params=ctx.params,
        model=ctx.model,
        pass_type=ctx.pass_type,
        rng=None,  # epilogue layers are rng-free by construction
        states=ctx.states,
        dtype=ctx.dtype,
        mesh=ctx.mesh,
        compute_dtype=ctx.compute_dtype,
        no_cast_inputs=ctx.no_cast_inputs,
        scan_unroll=ctx.scan_unroll,
    )
    for name, (v, ids) in zip(dyn_frontier, frs):
        # [T, B, ...] → rows [T*B, ...]
        flat_v = None if v is None else v.reshape((-1,) + v.shape[2:])
        flat_i = None if ids is None else ids.reshape((-1,) + ids.shape[2:])
        epi_ctx.outputs[name] = Argument(value=flat_v, ids=flat_i)
    for name, arg in statics.items():
        # loop-invariant feeds: tile the [B, ...] value across the T rows

        def tile(x):
            if x is None:
                return None
            return jnp.broadcast_to(x[None], (T,) + x.shape).reshape(
                (-1,) + x.shape[1:]
            )

        if name not in epi_ctx.outputs:
            epi_ctx.outputs[name] = Argument(value=tile(arg.value), ids=tile(arg.ids))
    layer_map = network.layer_map
    for name in epilogue:
        lcfg = layer_map[name]
        ins = [
            network._lookup_input(epi_ctx, ic.input_layer_name, ic.input_layer_argument)
            for ic in lcfg.inputs
        ]
        forward_layer(lcfg, ins, epi_ctx)
    hoisted = {l.layer_name for l in out_links} & set(epilogue)
    mask = mask_bt[..., None]
    for link in out_links:
        if link.layer_name not in hoisted:
            continue
        flat = epi_ctx.outputs[link.layer_name].value          # [T*B, D]
        y = jnp.swapaxes(flat.reshape((T, B) + flat.shape[1:]), 0, 1)
        y = y * mask.astype(y.dtype)
        ctx.outputs[link.link_name] = Argument(value=y, seq_lengths=lengths)
        z = epi_ctx.logits.get(link.layer_name)
        if z is not None:
            # re-publish the hoisted layer's pre-softmax logits under the
            # out-link name so the fused cross-entropy path survives the
            # hoist (the probabilities' transpose is then DCE-able when
            # only the loss consumes this link). Published FLAT in the
            # projection's [T*B, V] layout: transposing the V-sized
            # tensor to [B, T, V] here forced a full relayout copy on
            # TPU (layers/base.py TimeMajorLogits) — the CE consumer
            # transposes only the [T, B] per-step costs instead.
            ctx.logits[link.link_name] = TimeMajorLogits(z, T, B)


# ------------------------------------------------------------ generation


def _expand_beams(arg: Argument, K: int) -> Argument:
    """Tile an Argument's batch dim by the beam width: [B, ...] → [B*K, ...]."""

    def rep(x):
        return None if x is None else jnp.repeat(x, K, axis=0)

    return Argument(
        value=rep(arg.value),
        ids=rep(arg.ids),
        seq_lengths=rep(arg.seq_lengths),
        sub_seq_lengths=rep(arg.sub_seq_lengths),
        weight=rep(arg.weight),
    )


def _generate(network, cfg: LayerConfig, sub: SubModelConfig, ctx: LayerContext) -> None:
    """Batched beam search (ref: RecurrentGradientMachine::beamSearch
    :1114 and oneWaySearch :786 — greedy is beam_size=1)."""
    gen = sub.generator
    K = max(int(cfg.beam_size or gen.beam_size), 1)
    L = int(gen.max_num_frames)
    assert L > 0, "generator needs max_num_frames (beam_search max_length)"
    bos, eos = int(cfg.bos_id), int(cfg.eos_id)

    # batch size from any static link or boot layer
    B = None
    statics: Dict[str, Argument] = {}
    for link in sub.static_links:
        arg = _scope_lookup(ctx, link.layer_name)
        statics[link.link_name] = _expand_beams(arg, K)
        B = arg.batch_size if B is None else B
    # real sequence in-links: generation consumes one input frame per step
    # (per-step conditioning — each generated token sees x_t next to the
    # fed-back embedding; sequence length follows the input). A NESTED
    # in-link ([B, S, T, ...] sub-sequences) feeds one whole subsequence
    # per generated step — the step sub-network sees it as a flat
    # sequence, mirroring training's outer-scan-over-subsequences
    # (createInFrameInfo hasSubseq branch) at generation time.
    in_xs_v: Dict[str, Array] = {}
    in_xs_i: Dict[str, Array] = {}
    in_xs_l: Dict[str, Array] = {}  # nested links: per-step inner lengths
    in_lengths = None
    L_in = None
    for link in sub.in_links:
        arg = _scope_lookup(ctx, link.layer_name)
        if link.has_subseq:
            assert arg.is_nested_seq and arg.is_seq, (
                f"generation in-link {link.layer_name!r} marked has_subseq "
                "needs a nested sequence with OUTER lengths "
                "(seq_lengths = subsequence count per sample)"
            )
        else:
            assert arg.is_seq, (
                f"generation in-link {link.layer_name!r} must be a sequence "
                "(wrap whole-sequence conditions in StaticInput(..., is_seq=True))"
            )
        B = arg.batch_size if B is None else B
        # axis 1 is the generation axis either way: frames (flat) or
        # subsequences (nested)
        L_in = arg.max_len if L_in is None else min(L_in, arg.max_len)
        # generation ends at the SHORTEST in-link per sample — a longer
        # link's frames past that point would be padding, not conditioning
        in_lengths = (
            arg.seq_lengths
            if in_lengths is None
            else jnp.minimum(in_lengths, arg.seq_lengths)
        )
        ex = _expand_beams(arg, K)  # [B*K, T|S, ...]
        if ex.value is not None:
            in_xs_v[link.link_name] = jnp.swapaxes(ex.value, 0, 1)  # [T|S, B*K, ...]
        if ex.ids is not None:
            in_xs_i[link.link_name] = jnp.swapaxes(ex.ids, 0, 1)
        if link.has_subseq:
            in_xs_l[link.link_name] = jnp.swapaxes(ex.sub_seq_lengths, 0, 1)  # [S, B*K]
    if L_in is not None:
        L = min(L, L_in)

    memories = list(sub.memories)
    for mem in memories:
        if mem.boot_layer_name and B is None:
            B = _scope_lookup(ctx, mem.boot_layer_name).batch_size
    assert B is not None, f"generation group {cfg.name}: cannot infer batch size"
    gen_dtype = ctx.dtype
    for arg in statics.values():
        if arg.value is not None:
            gen_dtype = arg.value.dtype
            break
    if gen_dtype == ctx.dtype:
        for v in in_xs_v.values():
            gen_dtype = v.dtype
            break
    # boot memories (unexpanded [B, ...] first — the decode-step capture
    # below wants them per SAMPLE, not per beam), then expand across
    # beams: [B, ...] → [B*K, ...]. Sequence-valued memories (seqFlag
    # branch of createMemoryFrameInfo, ref RecurrentGradientMachine.cpp:
    # 740-744) carry a (padded sequence, lengths) pair so step s reads
    # step s-1's FULL output sequence — hierarchical decoders at
    # generation time.
    boots = []
    seq_mem_T: Dict[int, int] = {}
    for i, mem in enumerate(memories):
        if mem.is_sequence:
            v, sl = _memory_boot_seq(network, mem, ctx, sub)
            seq_mem_T[i] = v.shape[1]
            boots.append((v, sl))
        else:
            boots.append(_memory_boot(network, mem, ctx, B, gen_dtype, sub))

    # the feed agent for previously generated ids (created by beam_search())
    predict_agent = f"__generated_id@{cfg.name}"
    assert predict_agent in network.layer_map, "generation group missing the generated-id agent"
    score_layer = sub.out_links[0].layer_name

    if ctx.gen_capture is not None:
        # per-step decoder seam (graph/decode_step.py): the serving
        # engine's prefill runs the graph up to here — encoder outputs
        # (static links) and memory boots, per sample — and takes over
        # the decode loop itself, one slot-batched step per launch.
        # Outputs are zero placeholders: a capture forward exists only
        # for its captured side channel.
        ctx.gen_capture.update(
            group=cfg.name,
            statics={link.link_name: _scope_lookup(ctx, link.layer_name)
                     for link in sub.static_links},
            boots=list(boots),
            batch=B,
            dtype=gen_dtype,
        )
        zeros = Argument(ids=jnp.zeros((B, L), jnp.int32),
                         seq_lengths=jnp.zeros((B,), jnp.int32))
        ctx.outputs[cfg.name] = zeros
        ctx.outputs[f"{cfg.name}@beams"] = Argument(
            ids=jnp.zeros((B, K, L), jnp.int32),
            value=jnp.zeros((B, K), gen_dtype),
            seq_lengths=jnp.full((B,), K, jnp.int32),
            sub_seq_lengths=jnp.zeros((B, K), jnp.int32),
        )
        ctx.outputs[score_layer] = ctx.outputs[cfg.name]
        return

    carries0 = []
    for mem, boot in zip(memories, boots):
        if mem.is_sequence:
            v, sl = boot
            carries0.append((jnp.repeat(v, K, axis=0), jnp.repeat(sl, K, axis=0)))
        else:
            carries0.append(jnp.repeat(boot, K, axis=0))
    carries0 = tuple(carries0)

    neg_inf = jnp.asarray(-1e30, gen_dtype)
    init_state = (
        carries0,
        jnp.full((B * K,), bos, jnp.int32),                  # prev token per beam
        jnp.concatenate(                                      # cum log prob [B, K]
            [jnp.zeros((B, 1), gen_dtype), jnp.full((B, K - 1), neg_inf, gen_dtype)], axis=1
        )
        if K > 1
        else jnp.zeros((B, 1), gen_dtype),
        # an empty in-link sequence is finished before step 0 (no frame to
        # condition on) — generates length 0, not one garbage token
        (
            jnp.zeros((B, K), bool)
            if in_lengths is None
            else jnp.broadcast_to((in_lengths <= 0)[:, None], (B, K))
        ),
        jnp.zeros((B, K, L), jnp.int32),                      # token history
        jnp.zeros((B, K), jnp.int32),                         # lengths
    )
    base_rng = ctx.rng

    def step(state, inp):
        t_idx, x_v, x_i, x_l = inp
        carries, prev_tok, cum, finished, history, lens = state
        fed: Dict[str, Argument] = {predict_agent: Argument(ids=prev_tok)}
        for link in sub.in_links:
            fed[link.link_name] = Argument(
                value=x_v.get(link.link_name),
                ids=x_i.get(link.link_name),
                # nested links feed one whole subsequence per step
                seq_lengths=x_l.get(link.link_name),
            )
        for name, arg in statics.items():
            fed[name] = arg
        for mem, carry in zip(memories, carries):
            fed[mem.link_name] = _memory_feed_arg(mem, carry)
        rng = jax.random.fold_in(base_rng, t_idx) if base_rng is not None else None
        outs = _run_submodel_step(network, sub, ctx, fed, rng)
        probs = outs[score_layer].value  # [B*K, V]
        V = probs.shape[-1]
        logp = jnp.log(jnp.clip(probs, 1e-20, None)).reshape(B, K, V)
        fin = finished[:, :, None]
        # finished beams may only "emit" eos with no score change; every
        # other candidate is dead (-inf, not the clip floor, else a
        # finished beam's V-1 ghosts can outrank live continuations)
        eos_onehot = jax.nn.one_hot(eos, V, dtype=logp.dtype)
        logp = jnp.where(fin, jnp.where(eos_onehot[None, None, :] > 0, 0.0, neg_inf), logp)
        total = cum[:, :, None] + logp  # [B, K, V]
        flat = total.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(flat, K)  # [B, K]
        beam_idx = top_idx // V                        # [B, K]
        token = (top_idx % V).astype(jnp.int32)        # [B, K]
        # advance memories with this step's outputs (finished beams freeze
        # their state), then reindex by the selected beams
        flat_sel = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)  # [B*K]
        fin_flat = finished.reshape(-1)

        def freeze(old, new):
            keep = fin_flat.reshape((-1,) + (1,) * (new.ndim - 1))
            return jnp.where(keep if new.ndim > 1 else fin_flat, old, new)

        new_carries = []
        for i, (mem, old) in enumerate(zip(memories, carries)):
            out_arg = outs[mem.layer_name]
            if mem.is_sequence:
                old_v, old_l = old
                new_v, new_l = _advance_seq_memory(mem, old, out_arg, seq_mem_T[i], B * K)
                new_carries.append(
                    (freeze(old_v, new_v)[flat_sel], freeze(old_l, new_l)[flat_sel])
                )
            else:
                new = out_arg.ids if _is_int_carry(old) else out_arg.value
                new_carries.append(freeze(old, new)[flat_sel])
        new_carries = tuple(new_carries)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        lens = jnp.take_along_axis(lens, beam_idx, axis=1)
        history = jnp.take_along_axis(history, beam_idx[:, :, None], axis=1)
        history = history.at[:, :, t_idx].set(jnp.where(finished, eos, token))
        lens = jnp.where(finished, lens, lens + 1)
        finished = finished | (token == eos)
        if in_lengths is not None:
            # real in-links bound the generation: a sequence ends with its
            # last input frame even without eos
            finished = finished | ((t_idx + 1) >= in_lengths[:, None])
        return (
            new_carries,
            token.reshape(-1),
            top_scores,
            finished,
            history,
            lens,
        ), None

    # while_loop instead of a fixed-L scan: generation stops as soon as
    # every beam of every sample has finished (eos / in-link exhausted) —
    # with the default max_length=500 and typical outputs of tens of
    # tokens this is the difference between L steps and ~longest-output
    # steps per batch. Generation is never differentiated, so while_loop's
    # no-reverse-AD limitation does not bite.
    in_v = {k: v[:L] for k, v in in_xs_v.items()}
    in_i = {k: v[:L] for k, v in in_xs_i.items()}
    in_l = {k: v[:L] for k, v in in_xs_l.items()}

    def cond(carry):
        t, state = carry
        return (t < L) & ~jnp.all(state[3])  # state[3] = finished [B, K]

    def body(carry):
        t, state = carry
        inp = (
            t,
            {k: v[t] for k, v in in_v.items()},
            {k: v[t] for k, v in in_i.items()},
            {k: v[t] for k, v in in_l.items()},
        )
        state, _ = step(state, inp)
        return t + 1, state

    _, state = jax.lax.while_loop(cond, body, (jnp.asarray(0, jnp.int32), init_state))
    _, _, scores, finished, history, lens = state
    # best beam per sample (beams are kept sorted by top_k, but normalize
    # defensively by picking argmax score)
    best = jnp.argmax(scores, axis=1)  # [B]
    best_tokens = jnp.take_along_axis(history, best[:, None, None], axis=1)[:, 0]  # [B, L]
    best_lens = jnp.take_along_axis(lens, best[:, None], axis=1)[:, 0]
    ctx.outputs[cfg.name] = Argument(ids=best_tokens, seq_lengths=best_lens)
    ctx.outputs[f"{cfg.name}@beams"] = Argument(
        ids=history, value=scores, seq_lengths=jnp.full((B,), K, jnp.int32),
        sub_seq_lengths=lens,
    )
    ctx.outputs[score_layer] = ctx.outputs[cfg.name]
