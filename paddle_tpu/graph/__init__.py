from paddle_tpu.graph.argument import Argument, make_dense, make_ids, make_seq
from paddle_tpu.graph.network import Network
from paddle_tpu.graph.machine import GradientMachine

__all__ = ["Argument", "make_dense", "make_ids", "make_seq", "Network", "GradientMachine"]
