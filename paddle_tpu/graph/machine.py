"""GradientMachine — parameters + jitted forward/loss/grad over a model.

TPU-native replacement for the reference's ``GradientMachine`` family
(/root/reference/paddle/gserver/gradientmachines/GradientMachine.h:73):
``forward``/``backward`` over stateful layers become pure functions of a
parameter pytree; ``MultiGradientMachine``'s thread-ring data parallelism
is subsumed by sharding the same functions over a mesh (see
paddle_tpu.parallel). Gradients come from jax.grad of the summed cost
outputs — replacing every hand-written Layer::backward.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.graph.network import Network
from paddle_tpu.layers.base import LayerContext
from paddle_tpu.ops.init import init_parameter
from paddle_tpu.proto import ModelConfig, ParameterConfig

Array = jax.Array
Params = Dict[str, Array]


def compute_dtype_of(opt_config) -> Optional[Any]:
    """Resolve OptimizationConfig.dtype ('float32'|'bfloat16') to the
    narrow compute dtype, or None for plain f32 training."""
    name = getattr(opt_config, "dtype", "float32") or "float32"
    if name in ("bfloat16", "bf16"):
        return jnp.bfloat16
    if name in ("float32", "fp32", ""):
        return None
    raise ValueError(f"unsupported OptimizationConfig.dtype {name!r}")


class GradientMachine:
    def __init__(self, model: ModelConfig, dtype=jnp.float32, compute_dtype=None,
                 scan_unroll: int = 1, pallas_rnn: bool = False,
                 pallas_flat: bool = False,
                 conv_s2d: bool = False, conv_stats_mode: str = "",
                 pallas_decoder: bool = False):
        self.model = model
        self.network = Network(model)
        self.dtype = dtype
        # mixed precision: master params stay `dtype`; activations/matmuls
        # run in `compute_dtype` (bf16 on the MXU). None = everything in
        # `dtype` (see LayerContext.compute_dtype for the cast rules).
        self.compute_dtype = None if compute_dtype == jnp.float32 else compute_dtype
        # lax.scan unroll factor for recurrent layers/groups
        # (OptimizationConfig.scan_unroll)
        self.scan_unroll = max(1, int(scan_unroll))
        # recurrent layers via the fused Pallas kernels (ops/pallas_lstm)
        self.pallas_rnn = bool(pallas_rnn)
        # their transpose-free batch-major interface (A/B knob)
        self.pallas_flat = bool(pallas_flat)
        # stem conv space-to-depth rewrite (layers/vision.py)
        self.conv_s2d = bool(conv_s2d)
        # fused attention-GRU decoder groups (ops/pallas_attention_gru)
        self.pallas_decoder = bool(pallas_decoder)
        # fused 1x1-conv + BN-statistics mode ("gram" | "pallas" | "")
        self.conv_stats_mode = str(conv_stats_mode or "")
        if self.conv_stats_mode not in ("", "gram", "pallas"):
            # an unknown value would silently disable the feature and
            # poison the very A/B measurement the knob exists for
            raise ValueError(
                f"conv_stats_mode must be '', 'gram' or 'pallas', "
                f"got {conv_stats_mode!r}"
            )
        self.mesh = None  # set by the trainer when running on a mesh
        self.param_configs: Dict[str, ParameterConfig] = {p.name: p for p in model.parameters}
        # data layers whose every consumer is a cost layer carry targets/
        # labels/weights, not features — exempt them from the bf16 input
        # cast so loss math sees un-rounded values (code-review finding)
        data_names = {l.name for l in model.layers if l.type == "data"}
        consumers: Dict[str, set] = {}
        for layer in model.layers:
            for ic in layer.inputs:
                if ic.input_layer_name in data_names:
                    consumers.setdefault(ic.input_layer_name, set()).add(layer.type)
        costish = self.COST_TYPES | {"classification_error", "lambda_cost"}
        self.no_cast_inputs = frozenset(
            n for n, types in consumers.items() if types and types <= costish
        )

    # ------------------------------------------------------------- params

    def init_params(self, seed: int = 1) -> Params:
        rng = jax.random.PRNGKey(seed)
        params: Params = {}
        for i, (name, cfg) in enumerate(sorted(self.param_configs.items())):
            params[name] = init_parameter(jax.random.fold_in(rng, i), cfg, self.dtype)
        return params

    def trainable_mask(self) -> Dict[str, bool]:
        return {n: not c.is_static for n, c in self.param_configs.items()}

    # ------------------------------------------------------------ forward

    def forward(
        self,
        params: Params,
        in_args: Dict[str, Argument],
        pass_type: str = "test",
        rng: Optional[Array] = None,
        table_overrides=None,
        gen_capture=None,
    ) -> Tuple[Dict[str, Argument], Dict[str, Array]]:
        """Run the graph; returns (all layer outputs, state updates).

        ``gen_capture``: a dict sink making generator groups capture their
        prepared decode inputs instead of running the beam loop — the
        serving engine's prefill seam (graph/decode_step.py)."""
        ctx = LayerContext(
            params=params, model=self.model, pass_type=pass_type, rng=rng,
            dtype=self.dtype, mesh=self.mesh, table_overrides=table_overrides,
            compute_dtype=self.compute_dtype, no_cast_inputs=self.no_cast_inputs,
            scan_unroll=self.scan_unroll, pallas_rnn=self.pallas_rnn,
            pallas_flat=self.pallas_flat,
            conv_s2d=self.conv_s2d, conv_stats_mode=self.conv_stats_mode,
            pallas_decoder=self.pallas_decoder, gen_capture=gen_capture,
        )
        self.network.forward(ctx, in_args)
        return ctx.outputs, ctx.state_updates

    def output_args(self, outputs: Dict[str, Argument]) -> Dict[str, Argument]:
        return {n: outputs[n] for n in self.network.output_layer_names}

    # --------------------------------------------------------------- loss

    # layer types whose output is a differentiable per-sample cost — only
    # these contribute to the training loss (a prediction output like
    # maxid can legally sit next to the cost in output_layer_names)
    COST_TYPES = frozenset(
        {
            "multi-class-cross-entropy",
            "multi_class_cross_entropy_with_selfnorm",
            "square_error",
            "multi_binary_label_cross_entropy",
            "soft_binary_class_cross_entropy",
            "rank-cost",
            "huber",
            "lambda_cost",
            "ctc",
            "crf",
            "nce",
            "hsigmoid",
        }
    )

    def has_cost(self) -> bool:
        layer_map = self.network.layer_map
        return any(
            layer_map[n].type in self.COST_TYPES
            for n in self.network.output_layer_names
            if n in layer_map
        )

    def cost_layer_names(self):
        layer_map = self.network.layer_map
        return [
            n
            for n in self.network.output_layer_names
            if n in layer_map and layer_map[n].type in self.COST_TYPES
        ]

    def total_cost(self, outputs: Dict[str, Argument]) -> Array:
        """Mean per-sample cost summed across cost-layer outputs.

        The analog of Argument::sumCosts over the out args
        (/root/reference/paddle/parameter/Argument.h:168), normalized by
        batch size so gradients are per-sample means.
        """
        layer_map = self.network.layer_map
        total = None
        for name in self.network.output_layer_names:
            cfg = layer_map.get(name)
            if cfg is None or cfg.type not in self.COST_TYPES:
                continue
            arg = outputs[name]
            c = jnp.mean(arg.value[:, 0])
            total = c if total is None else total + c
        if total is None:
            raise ValueError("no cost outputs among output layers")
        return total

    def loss_fn(
        self,
        params: Params,
        in_args: Dict[str, Argument],
        rng: Optional[Array] = None,
        pass_type: str = "train",
    ):
        outputs, state_updates = self.forward(params, in_args, pass_type, rng)
        return self.total_cost(outputs), (outputs, state_updates)

    # --------------------------------------------------- sparse prefetch

    def sparse_prefetch_plan(self):
        """Which sparse_update tables can take the row-sparse gradient path.

        The analog of GradientMachine::prefetch (/root/reference/paddle/
        trainer/TrainerInternal.cpp:91-95): sparse rows are identified from
        the *input ids*, before forward. A table qualifies when every use
        of the parameter is a table projection reading ids straight from a
        data layer (the reference has the same reach — it prefetches from
        inArgs only). Returns [(param_name, data_layer_name)]; parameters
        used any other way fall back to the dense-gradient row-scan path.
        """
        sparse_names = {
            n for n, c in self.param_configs.items() if c.sparse_update and not c.is_static
        }
        if not sparse_names:
            return []
        layer_map = self.network.layer_map
        uses: Dict[str, list] = {n: [] for n in sparse_names}
        for layer in self.model.layers:
            for ic in layer.inputs:
                pn = ic.input_parameter_name
                if pn not in sparse_names:
                    continue
                src = layer_map.get(ic.input_layer_name)
                ok = (
                    ic.proj_conf is not None
                    and ic.proj_conf.type == "table"
                    and src is not None
                    and src.type == "data"
                )
                uses[pn].append((ic.input_layer_name, ok))
            if layer.bias_parameter_name in sparse_names:
                uses[layer.bias_parameter_name].append(("", False))
        plan = []
        for pn, sites in sorted(uses.items()):
            if sites and all(ok for _, ok in sites):
                plan.extend((pn, ln) for ln, _ in sites)
        return plan

    def grad_fn(self, remat: str = "none", sparse: bool = True):
        """Returns f(params, in_args, rng) → (loss, grads, outputs, state_updates).

        Gradients for prefetchable sparse_update tables come back as
        RowSparseGrad (ids + occurrence rows, O(batch·seq) not O(V)) —
        see paddle_tpu.optimizer.sparse; everything else is dense.
        ``sparse=False`` forces dense gradients everywhere (needed when
        gradients must be accumulated across batches — RowSparseGrad
        shapes vary per batch).

        ``remat="full"`` (OptimizationConfig.remat) wraps the loss in
        jax.checkpoint: backward recomputes the forward instead of
        storing activations — the HBM-for-FLOPs trade."""
        plan = self.sparse_prefetch_plan() if sparse else []
        loss_fn = self.loss_fn
        if remat == "full":
            loss_fn = jax.checkpoint(loss_fn)
        elif remat not in ("", "none"):
            raise ValueError(f"unsupported remat mode {remat!r}")

        def f(params: Params, in_args: Dict[str, Argument], rng: Optional[Array]):
            if not plan:
                (loss, (outputs, state_updates)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, in_args, rng)
            else:
                loss, grads, outputs, state_updates = self._sparse_value_and_grad(
                    plan, params, in_args, rng, remat=remat
                )
            # static parameters get no gradient
            for n, cfg in self.param_configs.items():
                if cfg.is_static and n in grads:
                    grads[n] = jnp.zeros_like(grads[n])
            return loss, grads, outputs, state_updates

        return f

    def _sparse_value_and_grad(self, plan, params, in_args, rng, remat="none"):
        from paddle_tpu.optimizer.sparse import RowSparseGrad

        sparse_pnames = {pn for pn, _ in plan}
        # prefetch: gather the occurrence rows OUTSIDE autodiff and make
        # them the differentiable leaves; the table itself is frozen
        rows_in = {}
        for pn, dname in plan:
            ids = in_args[dname].ids
            rows_in[(pn, dname)] = jnp.take(params[pn], ids, axis=0)
        dense_params = {k: v for k, v in params.items() if k not in sparse_pnames}
        frozen = {k: jax.lax.stop_gradient(params[k]) for k in sparse_pnames}

        def loss2(dense_params, rows):
            full = dict(dense_params, **frozen)
            outputs, state_updates = self.forward(
                full, in_args, "train", rng, table_overrides=rows
            )
            return self.total_cost(outputs), (outputs, state_updates)

        if remat == "full":
            loss2 = jax.checkpoint(loss2)
        (loss, (outputs, state_updates)), (dgrads, rgrads) = jax.value_and_grad(
            loss2, argnums=(0, 1), has_aux=True
        )(dense_params, rows_in)
        grads: Dict[str, Any] = dict(dgrads)
        by_param: Dict[str, list] = {}
        for (pn, dname), rg in rgrads.items():
            ids = in_args[dname].ids.reshape(-1)
            by_param.setdefault(pn, []).append((ids, rg.reshape(ids.shape[0], -1)))
        for pn, pieces in by_param.items():
            ids = jnp.concatenate([i for i, _ in pieces])
            rows = jnp.concatenate([r for _, r in pieces])
            grads[pn] = RowSparseGrad(ids=ids, rows=rows, nrows=params[pn].shape[0])
        return loss, grads, outputs, state_updates

    # --------------------------------------------------- gradient checking

    def check_gradient(
        self,
        params: Params,
        in_args: Dict[str, Argument],
        epsilon: float = 1e-4,
        max_entries: int = 20,
        rng: Optional[Array] = None,
        rtol: float = 5e-2,
    ) -> Dict[str, float]:
        """Finite-difference check, the analog of Trainer::checkGradient
        (/root/reference/paddle/trainer/Trainer.cpp:313-387) and the
        test_LayerGrad methodology. Returns max relative diff per param.

        Runs in float64 (the reference's WITH_DOUBLE gradient-check mode) —
        fp32 finite differences are too noisy for small gradients.
        """
        saved = self.compute_dtype
        self.compute_dtype = None  # bf16 forward would swamp the FD signal
        # jax >= 0.4.37 removed the jax.enable_x64 alias; the context
        # manager lives (and always lived) in jax.experimental
        from jax.experimental import enable_x64

        try:
            with enable_x64():
                return self._check_gradient_x64(params, in_args, epsilon, max_entries, rng, rtol)
        finally:
            self.compute_dtype = saved

    def _check_gradient_x64(self, params, in_args, epsilon, max_entries, rng, rtol):
        import numpy as np

        cast = lambda x: x.astype(jnp.float64) if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) else x
        params = {k: cast(v) for k, v in params.items()}
        in_args = jax.tree_util.tree_map(cast, in_args)
        loss = jax.jit(lambda p: self.loss_fn(p, in_args, rng)[0])
        grads = jax.jit(jax.grad(lambda p: self.loss_fn(p, in_args, rng)[0]))(params)
        report = {}
        key = jax.random.PRNGKey(0)
        for name, g in grads.items():
            if self.param_configs[name].is_static:
                continue
            flat = np.asarray(g).ravel()
            n = flat.size
            key, sub = jax.random.split(key)
            idxs = np.asarray(jax.random.choice(sub, n, (min(max_entries, n),), replace=False))
            worst = 0.0
            base = np.asarray(params[name]).ravel()
            for i in idxs:
                p_plus = dict(params)
                v = base.copy()
                v[i] += epsilon
                p_plus[name] = jnp.asarray(v.reshape(params[name].shape))
                v2 = base.copy()
                v2[i] -= epsilon
                p_minus = dict(params)
                p_minus[name] = jnp.asarray(v2.reshape(params[name].shape))
                num = (float(loss(p_plus)) - float(loss(p_minus))) / (2 * epsilon)
                ana = float(flat[i])
                denom = max(abs(num), abs(ana), 1e-6)
                worst = max(worst, abs(num - ana) / denom)
            report[name] = worst
        return report
