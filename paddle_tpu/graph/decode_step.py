"""Per-step decoder seam — slot-batched greedy decode for serving.

``_generate`` (recurrent_group.py) runs the whole beam-search loop as
one ``lax.while_loop`` launch: correct for single-shot generation, but
a continuous-batching server (paddle_tpu/serving/) needs the loop OPEN
— admit and evict sequences at every iteration boundary. This module
is that opening:

- :func:`plan_of` inspects a generation graph and returns a
  :class:`GenPlan` when the generator group is *slot-decodable*:
  statics-only conditioning (the seqToseq attention decoder shape —
  encoder outputs as StaticInput, a GeneratedInput token feed), plain
  (non-sequence) memories. Anything else returns the reason — the
  engine refuses loudly and the static path stays available.
- :func:`capture_prefill` runs the machine forward with the
  ``gen_capture`` sink: the encoder executes normally, the generator
  group stores its prepared decode inputs (static-link Arguments,
  unexpanded memory boots) and skips the loop. This is the *prefill*:
  everything a new sequence needs before its first decode step.
- :func:`make_greedy_step` builds one decode step over a [B, ...] slot
  batch: feed the previous tokens, run the group's step sub-network
  once, take the argmax token, advance the memory carries with
  finished-slot freezing. Shape-polymorphic in B; semantically the
  K=1 path of ``_generate``'s beam step (pinned by the golden test in
  tests/test_engine.py), so the engine subsumes ``SequenceGenerator``
  for beam_size=1.

The fused attention-GRU kernel exposes the matching single-step math as
``ops.pallas_attention_gru.attention_gru_step`` — the seam a future
TPU-fused serve_decode kernel plugs into without changing the engine.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.graph.recurrent_group import (
    _memory_feed_arg,
    _run_submodel_step,
)
from paddle_tpu.layers.base import LayerContext

Array = jax.Array


@dataclasses.dataclass
class GenPlan:
    """Static description of a slot-decodable generator group."""

    group: str                 # the recurrent_layer_group layer name
    sub: Any                   # SubModelConfig
    predict_agent: str         # the generated-id feed agent
    score_layer: str           # out-link producing the [B, V] probs
    bos: int
    eos: int
    max_length: int            # generator max_num_frames
    memories: List[Any]
    static_links: List[str]    # link names, capture/state dict keys


def plan_of(machine) -> Tuple[Optional[GenPlan], str]:
    """(plan, "") when the machine's generation graph supports
    slot-batched per-step decode, else (None, reason). Gates mirror the
    state the engine can hold in fixed-shape slot buffers: statics-only
    conditioning and flat (non-sequence) memory carries."""
    subs = [s for s in machine.model.sub_models if s.generator is not None]
    if not subs:
        return None, "config declares no generator sub-model (beam_search)"
    sub = subs[0]
    if sub.in_links:
        return None, (
            "generator group has sequence in-links (per-step input "
            "conditioning) — slot decode supports statics-only groups"
        )
    if any(m.is_sequence for m in sub.memories):
        return None, (
            "generator group carries a sequence-valued memory — slot "
            "decode supports flat carries only"
        )
    cfg = machine.network.layer_map.get(sub.name)
    if cfg is None:
        return None, f"no group layer named {sub.name!r}"
    predict_agent = f"__generated_id@{sub.name}"
    if predict_agent not in machine.network.layer_map:
        return None, "generation group missing the generated-id agent"
    if not sub.out_links:
        return None, "generator group has no out-links"
    return GenPlan(
        group=sub.name,
        sub=sub,
        predict_agent=predict_agent,
        score_layer=sub.out_links[0].layer_name,
        bos=int(cfg.bos_id),
        eos=int(cfg.eos_id),
        max_length=int(sub.generator.max_num_frames),
        memories=list(sub.memories),
        static_links=[l.link_name for l in sub.static_links],
    ), ""


def _static_tree(statics: Dict[str, Argument]) -> Dict[str, Dict[str, Array]]:
    """Argument dict → a plain jax pytree (absent fields omitted, so the
    tree structure is a pure function of the model, not of None leaves)."""
    out: Dict[str, Dict[str, Array]] = {}
    for name, arg in statics.items():
        d: Dict[str, Array] = {}
        for f in ("value", "ids", "seq_lengths", "sub_seq_lengths"):
            v = getattr(arg, f)
            if v is not None:
                d[f] = v
        out[name] = d
    return out


def _static_args(tree: Dict[str, Dict[str, Array]]) -> Dict[str, Argument]:
    return {
        name: Argument(
            value=d.get("value"), ids=d.get("ids"),
            seq_lengths=d.get("seq_lengths"),
            sub_seq_lengths=d.get("sub_seq_lengths"),
        )
        for name, d in tree.items()
    }


def capture_prefill(machine, plan: GenPlan, params, in_args):
    """Run the full graph (encoder + boots) in capture mode; returns
    ``(statics_tree, carries)`` for the feed's batch — the per-sequence
    decode state the engine scatters into free slots. jit-safe: the
    captured values are tracers of the enclosing trace."""
    cap: Dict[str, Any] = {}
    machine.forward(params, in_args, pass_type="gen", rng=None,
                    gen_capture=cap)
    assert cap.get("group") == plan.group, (
        f"capture ran group {cap.get('group')!r}, planned {plan.group!r}"
    )
    return _static_tree(cap["statics"]), tuple(cap["boots"])


def make_greedy_step(machine, plan: GenPlan):
    """Build ``step(params, statics_tree, carries, prev_tok, finished)
    -> (new_carries, token, new_finished)`` — one greedy decode step for
    every slot row. Finished rows freeze their carries and emit ``eos``
    (score-free), exactly the K=1 semantics of ``_generate``'s beam
    step, so greedy engine output matches ``SequenceGenerator`` with
    beam_size=1 token for token."""
    network = machine.network
    eos = plan.eos

    def step(params, statics_tree, carries, prev_tok, finished):
        ctx = LayerContext(
            params=params, model=machine.model, pass_type="gen", rng=None,
            dtype=machine.dtype, compute_dtype=machine.compute_dtype,
            no_cast_inputs=machine.no_cast_inputs,
            scan_unroll=machine.scan_unroll,
        )
        fed: Dict[str, Argument] = {plan.predict_agent: Argument(ids=prev_tok)}
        fed.update(_static_args(statics_tree))
        for mem, carry in zip(plan.memories, carries):
            fed[mem.link_name] = _memory_feed_arg(mem, carry)
        outs = _run_submodel_step(network, plan.sub, ctx, fed, None)
        probs = outs[plan.score_layer].value                      # [B, V]
        # argmax of log-probs == argmax of probs; the clip only matters
        # for the beam path's score arithmetic — kept for bit-parity of
        # tie behavior with _generate's top_k(K=1)
        logp = jnp.log(jnp.clip(probs, 1e-20, None))
        token = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        token = jnp.where(finished, eos, token)
        new_carries = []
        for mem, old in zip(plan.memories, carries):
            out_arg = outs[mem.layer_name]
            new = (out_arg.ids
                   if jnp.issubdtype(old.dtype, jnp.integer)
                   else out_arg.value)
            keep = finished.reshape((-1,) + (1,) * (new.ndim - 1))
            new_carries.append(
                jnp.where(keep if new.ndim > 1 else finished, old, new)
            )
        new_finished = finished | (token == eos)
        return tuple(new_carries), token, new_finished

    return step
