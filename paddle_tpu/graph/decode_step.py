"""Per-step decoder seam — slot-batched greedy decode for serving.

``_generate`` (recurrent_group.py) runs the whole beam-search loop as
one ``lax.while_loop`` launch: correct for single-shot generation, but
a continuous-batching server (paddle_tpu/serving/) needs the loop OPEN
— admit and evict sequences at every iteration boundary. This module
is that opening:

- :func:`plan_of` inspects a generation graph and returns a
  :class:`GenPlan` when the generator group is *slot-decodable*:
  statics-only conditioning (the seqToseq attention decoder shape —
  encoder outputs as StaticInput, a GeneratedInput token feed), plain
  (non-sequence) memories. Anything else returns the reason — the
  engine refuses loudly and the static path stays available.
- :func:`capture_prefill` runs the machine forward with the
  ``gen_capture`` sink: the encoder executes normally, the generator
  group stores its prepared decode inputs (static-link Arguments,
  unexpanded memory boots) and skips the loop. This is the *prefill*:
  everything a new sequence needs before its first decode step.
- :func:`make_greedy_step` builds one decode step over a [B, ...] slot
  batch: feed the previous tokens, run the group's step sub-network
  once, take the argmax token, advance the memory carries with
  finished-slot freezing. Shape-polymorphic in B; semantically the
  K=1 path of ``_generate``'s beam step (pinned by the golden test in
  tests/test_engine.py), so the engine subsumes ``SequenceGenerator``
  for beam_size=1.

The fused attention-GRU kernel exposes the matching single-step math as
``ops.pallas_attention_gru.attention_gru_step`` — and behind
``--serve_fused_step`` this module WIRES it in: :func:`plan_fused_step`
template-matches the generation step graph against the attention-GRU
decoder shape (the serve-side sibling of graph/fused_decoder.py's
training matcher) and extracts the weights; :func:`make_greedy_step`
then builds the step from ``attention_gru_step`` plus the embedding
lookup and the output softmax — one tight function instead of a
layer-by-layer graph walk, golden-pinned token-for-token against the
unfused step (tests/test_engine.py). Any deviation from the template
refuses with the reason; the unfused step is always a correct fallback.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.graph.argument import Argument
from paddle_tpu.graph.recurrent_group import (
    _memory_feed_arg,
    _run_submodel_step,
)
from paddle_tpu.layers.base import LayerContext

Array = jax.Array


@dataclasses.dataclass
class GenPlan:
    """Static description of a slot-decodable generator group."""

    group: str                 # the recurrent_layer_group layer name
    sub: Any                   # SubModelConfig
    predict_agent: str         # the generated-id feed agent
    score_layer: str           # out-link producing the [B, V] probs
    bos: int
    eos: int
    max_length: int            # generator max_num_frames
    memories: List[Any]
    static_links: List[str]    # link names, capture/state dict keys


def plan_of(machine) -> Tuple[Optional[GenPlan], str]:
    """(plan, "") when the machine's generation graph supports
    slot-batched per-step decode, else (None, reason). Gates mirror the
    state the engine can hold in fixed-shape slot buffers: statics-only
    conditioning and flat (non-sequence) memory carries."""
    subs = [s for s in machine.model.sub_models if s.generator is not None]
    if not subs:
        return None, "config declares no generator sub-model (beam_search)"
    sub = subs[0]
    if sub.in_links:
        return None, (
            "generator group has sequence in-links (per-step input "
            "conditioning) — slot decode supports statics-only groups"
        )
    if any(m.is_sequence for m in sub.memories):
        return None, (
            "generator group carries a sequence-valued memory — slot "
            "decode supports flat carries only"
        )
    cfg = machine.network.layer_map.get(sub.name)
    if cfg is None:
        return None, f"no group layer named {sub.name!r}"
    predict_agent = f"__generated_id@{sub.name}"
    if predict_agent not in machine.network.layer_map:
        return None, "generation group missing the generated-id agent"
    if not sub.out_links:
        return None, "generator group has no out-links"
    return GenPlan(
        group=sub.name,
        sub=sub,
        predict_agent=predict_agent,
        score_layer=sub.out_links[0].layer_name,
        bos=int(cfg.bos_id),
        eos=int(cfg.eos_id),
        max_length=int(sub.generator.max_num_frames),
        memories=list(sub.memories),
        static_links=[l.link_name for l in sub.static_links],
    ), ""


def _static_tree(statics: Dict[str, Argument]) -> Dict[str, Dict[str, Array]]:
    """Argument dict → a plain jax pytree (absent fields omitted, so the
    tree structure is a pure function of the model, not of None leaves)."""
    out: Dict[str, Dict[str, Array]] = {}
    for name, arg in statics.items():
        d: Dict[str, Array] = {}
        for f in ("value", "ids", "seq_lengths", "sub_seq_lengths"):
            v = getattr(arg, f)
            if v is not None:
                d[f] = v
        out[name] = d
    return out


def _static_args(tree: Dict[str, Dict[str, Array]]) -> Dict[str, Argument]:
    return {
        name: Argument(
            value=d.get("value"), ids=d.get("ids"),
            seq_lengths=d.get("seq_lengths"),
            sub_seq_lengths=d.get("sub_seq_lengths"),
        )
        for name, d in tree.items()
    }


def capture_prefill(machine, plan: GenPlan, params, in_args):
    """Run the full graph (encoder + boots) in capture mode; returns
    ``(statics_tree, carries)`` for the feed's batch — the per-sequence
    decode state the engine scatters into free slots. jit-safe: the
    captured values are tracers of the enclosing trace."""
    cap: Dict[str, Any] = {}
    machine.forward(params, in_args, pass_type="gen", rng=None,
                    gen_capture=cap)
    assert cap.get("group") == plan.group, (
        f"capture ran group {cap.get('group')!r}, planned {plan.group!r}"
    )
    return _static_tree(cap["statics"]), tuple(cap["boots"])


# layer types that are wiring, not computation, in a step submodel
_AGENT_TYPES = ("agent", "sequence_agent", "scatter_agent", "gather_agent")


# ------------------------------------------------ reduced-precision slot state
#
# ``--serve_slot_dtype=bf16`` halves per-slot HBM by STORING slot state
# (GRU carries + captured statics) in bfloat16 while every step still
# COMPUTES in f32: the backend upcasts statics once per launch and
# carries before every micro-step, and downcasts only what it stores
# back. This is a *storage* plan — it deliberately does NOT relax the
# f32-compute refusal below: a model that computes in bf16 rounds
# differently per layer and the greedy argmax could silently diverge
# from the golden-parity contract, whereas store-rounding is a bounded,
# tested perturbation of the carry between steps.
SLOT_STORE_DTYPES: Dict[str, Optional[str]] = {"f32": None, "bf16": "bfloat16"}

# Parity tolerance gate per slot dtype: the max fraction of emitted
# token positions allowed to differ from the f32-stored reference on the
# seeded parity workloads (tests/test_speculative.py). f32 storage is
# bit-exact by construction; bf16 carry rounding may flip near-tie
# argmax tokens, and past this rate the plan is considered broken.
SLOT_PARITY_TOL: Dict[str, float] = {"f32": 0.0, "bf16": 0.05}


def plan_slot_dtype(slot_dtype: str) -> Tuple[Optional[Dict[str, Any]], str]:
    """(mixed-precision storage plan, "") for a ``--serve_slot_dtype``
    spelling, else (None, reason). The plan names the storage dtype
    (None = store in the model dtype, the PR-12 behavior) and the parity
    tolerance the golden tests gate on."""
    if slot_dtype not in SLOT_STORE_DTYPES:
        return None, (
            f"unknown slot dtype {slot_dtype!r} "
            f"(supported: {tuple(SLOT_STORE_DTYPES)})"
        )
    return {
        "store_dtype": SLOT_STORE_DTYPES[slot_dtype],
        "parity_tol": SLOT_PARITY_TOL[slot_dtype],
    }, ""


def plan_fused_step(machine, plan: GenPlan, slot_dtype: str = "f32"):
    """(extraction dict, "") when the generation step graph is EXACTLY
    the attention-GRU decoder template (simple_attention + gru_step +
    softmax out — the seqToseq shape graph/fused_decoder.py matches on
    the training side), else (None, reason). The dict carries every
    parameter name and static-link key the fused step needs, plus the
    :func:`plan_slot_dtype` storage plan for ``slot_dtype`` (the
    store-bf16/compute-f32 extension past the f32 refusal below — the
    refusal itself is about COMPUTE dtype and is unchanged); refusals
    are loud because ``--serve_fused_step`` is an explicit request."""
    slot_plan, why = plan_slot_dtype(slot_dtype)
    if slot_plan is None:
        return None, why
    sub = plan.sub
    lm = machine.network.layer_map
    # the fused step computes in f32; under a reduced compute dtype the
    # unfused graph walk rounds differently per layer and near-tie
    # argmax tokens could silently diverge from the parity contract —
    # refuse instead (the flag is an explicit request, never a guess).
    # compute_dtype=None means "everything in the model dtype"
    eff = machine.compute_dtype if machine.compute_dtype is not None else (
        machine.dtype)
    if jnp.dtype(eff) != jnp.float32:
        return None, (
            f"fused step supports float32 compute only (model computes "
            f"in {jnp.dtype(eff).name})"
        )
    if len(plan.memories) != 1:
        return None, "fused step needs exactly one flat memory carry"
    mem = plan.memories[0]
    layers = [lm[n] for n in sub.layer_names if lm[n].type not in _AGENT_TYPES]
    by_name = {l.name: l for l in layers}
    if len(layers) != 10:
        return None, (
            f"step graph has {len(layers)} layers — not the attention-GRU "
            "decoder template (embedding/attention/din/gru/out)"
        )
    if not all(l.drop_rate == 0.0 and l.error_clipping_threshold == 0
               for l in layers):
        return None, "step layers carry dropout/error-clipping"
    gru = next((l for l in layers if l.type == "gru_step"), None)
    if gru is None or gru.name != mem.layer_name or len(gru.inputs) != 2:
        return None, "no gru_step layer owning the memory"
    if gru.inputs[1].input_layer_name != mem.link_name:
        return None, "gru_step's second input is not the memory link"
    acts = (gru.active_type or "tanh", gru.active_gate_type or "sigmoid")
    if acts != ("tanh", "sigmoid"):
        return None, f"gru activations {acts} != ('tanh', 'sigmoid')"
    D = gru.size
    din = by_name.get(gru.inputs[0].input_layer_name)
    if (din is None or din.type != "mixed" or din.size != 3 * D
            or din.active_type not in ("", "linear") or len(din.inputs) != 2
            or any(ic.proj_conf is None or ic.proj_conf.type != "fc"
                   for ic in din.inputs)):
        return None, "gru input is not a linear mixed of two fc projections"
    emb = ctx_ic = word_ic = None
    for ic in din.inputs:
        src = by_name.get(ic.input_layer_name)
        if (src is not None and src.type == "mixed" and len(src.inputs) == 1
                and src.inputs[0].proj_conf is not None
                and src.inputs[0].proj_conf.type == "table"
                and src.inputs[0].input_layer_name == plan.predict_agent):
            emb, word_ic = src, ic
        else:
            ctx_ic = ic
    if emb is None or ctx_ic is None or emb.bias_parameter_name:
        return None, "no bias-free generated-word embedding feeding the gru"
    pooling = by_name.get(ctx_ic.input_layer_name)
    if (pooling is None or pooling.type != "average"
            or (pooling.average_strategy or "average") != "sum"
            or pooling.active_type not in ("", "linear")
            or len(pooling.inputs) != 1):
        return None, "context is not a sum-pooled attention readout"
    scaling = by_name.get(pooling.inputs[0].input_layer_name)
    if scaling is None or scaling.type != "scaling" or len(scaling.inputs) != 2:
        return None, "no attention scaling layer"
    sm = by_name.get(scaling.inputs[0].input_layer_name)
    ev_link = scaling.inputs[1].input_layer_name
    if ev_link not in plan.static_links:
        return None, "attention values are not a static link"
    if (sm is None or sm.type != "fc" or sm.size != 1
            or sm.active_type != "sequence_softmax"
            or sm.bias_parameter_name or len(sm.inputs) != 1):
        return None, "no sequence-softmax attention scorer"
    combine = by_name.get(sm.inputs[0].input_layer_name)
    if (combine is None or combine.type != "mixed"
            or combine.active_type != "tanh" or combine.size != D
            or len(combine.inputs) != 2
            or any(ic.proj_conf is None or ic.proj_conf.type != "identity"
                   for ic in combine.inputs)):
        return None, "no tanh combine of expanded transform + projection"
    comb_srcs = [ic.input_layer_name for ic in combine.inputs]
    expand = next((by_name[n] for n in comb_srcs
                   if n in by_name and by_name[n].type == "expand"), None)
    ep_link = next((n for n in comb_srcs if n in plan.static_links), None)
    if expand is None or ep_link is None or ep_link == ev_link:
        return None, "combine does not mix an expand with a static link"
    if not expand.inputs or expand.inputs[0].input_layer_name not in by_name:
        return None, "expand input missing from the step graph"
    transform = by_name.get(expand.inputs[0].input_layer_name)
    if (transform is None or transform.type != "mixed"
            or transform.active_type not in ("", "linear")
            or transform.size != D or len(transform.inputs) != 1
            or transform.inputs[0].proj_conf is None
            or transform.inputs[0].proj_conf.type != "fc"
            or transform.inputs[0].input_layer_name != mem.link_name):
        return None, "attention transform is not an fc of the decoder memory"
    out = by_name.get(plan.score_layer)
    if (out is None or out.type != "mixed" or out.active_type != "softmax"
            or len(out.inputs) != 1 or out.inputs[0].proj_conf is None
            or out.inputs[0].proj_conf.type != "fc"
            or out.inputs[0].input_layer_name != gru.name):
        return None, "score layer is not a softmax fc of the gru output"
    template = {gru.name, din.name, emb.name, pooling.name, scaling.name,
                sm.name, combine.name, expand.name, transform.name, out.name}
    if template != set(by_name):
        return None, "extra layers outside the attention-GRU template"
    return dict(
        D=D, E=pooling.size, word_dim=emb.size, vocab=out.size,
        ep_link=ep_link, ev_link=ev_link,
        emb_param=emb.inputs[0].input_parameter_name,
        word_param=word_ic.input_parameter_name,
        wctx_param=ctx_ic.input_parameter_name,
        wa_param=transform.inputs[0].input_parameter_name,
        v_param=sm.inputs[0].input_parameter_name,
        wg_param=gru.inputs[0].input_parameter_name,
        out_w_param=out.inputs[0].input_parameter_name,
        out_b_param=out.bias_parameter_name or "",
        ba_params=[p for p in (transform.bias_parameter_name,
                               combine.bias_parameter_name) if p],
        xw_bias_params=[p for p in (din.bias_parameter_name,
                                    gru.bias_parameter_name) if p],
        **slot_plan,
    ), ""


def _make_fused_step(machine, plan: GenPlan, fp: Dict[str, Any]):
    """The ``--serve_fused_step`` step body: the parity-tested
    ``ops.pallas_attention_gru.attention_gru_step`` math plus the
    embedding lookup and the output softmax, from the weights
    :func:`plan_fused_step` extracted. Finished-row semantics are
    identical to the unfused step (eos emission, frozen carries)."""
    from paddle_tpu.ops.pallas_attention_gru import attention_gru_step

    eos = plan.eos
    D, E, W, V = fp["D"], fp["E"], fp["word_dim"], fp["vocab"]

    def step(params, statics_tree, carries, prev_tok, finished):
        ctx = LayerContext(
            params=params, model=machine.model, pass_type="gen", rng=None,
            dtype=machine.dtype, compute_dtype=machine.compute_dtype,
            no_cast_inputs=machine.no_cast_inputs,
            scan_unroll=machine.scan_unroll,
        )
        f32 = jnp.float32
        p = ctx.param
        ep_d = statics_tree[fp["ep_link"]]
        ev_d = statics_tree[fp["ev_link"]]
        ep = jnp.swapaxes(ep_d["value"], 0, 1).astype(f32)   # [Te, B, D]
        ev = jnp.swapaxes(ev_d["value"], 0, 1).astype(f32)   # [Te, B, E]
        Te = ep.shape[0]
        lens = ep_d.get("seq_lengths")
        if lens is None:
            em = jnp.ones(ep.shape[:2] + (1,), f32)
        else:
            em = (jnp.arange(Te)[:, None] < lens[None, :]).astype(f32)[
                :, :, None]                                  # [Te, B, 1]
        emb = p(fp["emb_param"]).reshape(-1, W)[prev_tok]    # [B, W]
        xw = jax.lax.dot(
            emb.astype(f32), p(fp["word_param"]).reshape(W, 3 * D).astype(f32)
        )
        for name in fp["xw_bias_params"]:
            xw = xw + p(name).reshape(1, 3 * D).astype(f32)
        ba = jnp.zeros((1, D), f32)
        for name in fp["ba_params"]:
            ba = ba + p(name).reshape(1, D).astype(f32)
        (h,) = carries
        h_new = attention_gru_step(
            h.astype(f32), ep, ev, em, xw,
            p(fp["wa_param"]).reshape(D, D).astype(f32), ba,
            p(fp["v_param"]).reshape(1, D).astype(f32),
            p(fp["wctx_param"]).reshape(E, 3 * D).astype(f32),
            p(fp["wg_param"]).reshape(D, 3 * D).astype(f32),
        )                                                    # [B, D] f32
        logits = jax.lax.dot(
            h_new, p(fp["out_w_param"]).reshape(D, V).astype(f32)
        )
        if fp["out_b_param"]:
            logits = logits + p(fp["out_b_param"]).reshape(1, V).astype(f32)
        probs = jax.nn.softmax(logits, axis=-1)
        # same argmax arithmetic as the unfused step — tie behavior and
        # the clip floor must not diverge between the two paths
        logp = jnp.log(jnp.clip(probs, 1e-20, None))
        token = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        token = jnp.where(finished, eos, token)
        old = carries[0]
        keep = finished.reshape((-1,) + (1,) * (h_new.ndim - 1))
        new_h = jnp.where(keep, old, h_new.astype(old.dtype))
        new_finished = finished | (token == eos)
        return (new_h,), token, new_finished

    return step


def make_greedy_step(machine, plan: GenPlan,
                     fused_plan: Optional[Dict[str, Any]] = None):
    """Build ``step(params, statics_tree, carries, prev_tok, finished)
    -> (new_carries, token, new_finished)`` — one greedy decode step for
    every slot row. Finished rows freeze their carries and emit ``eos``
    (score-free), exactly the K=1 semantics of ``_generate``'s beam
    step, so greedy engine output matches ``SequenceGenerator`` with
    beam_size=1 token for token. With ``fused_plan`` (from
    :func:`plan_fused_step`, the ``--serve_fused_step`` path) the step
    is the extracted attention-GRU math instead of the graph walk —
    token-parity-pinned against this default."""
    if fused_plan is not None:
        return _make_fused_step(machine, plan, fused_plan)
    network = machine.network
    eos = plan.eos

    def step(params, statics_tree, carries, prev_tok, finished):
        ctx = LayerContext(
            params=params, model=machine.model, pass_type="gen", rng=None,
            dtype=machine.dtype, compute_dtype=machine.compute_dtype,
            no_cast_inputs=machine.no_cast_inputs,
            scan_unroll=machine.scan_unroll,
        )
        fed: Dict[str, Argument] = {plan.predict_agent: Argument(ids=prev_tok)}
        fed.update(_static_args(statics_tree))
        for mem, carry in zip(plan.memories, carries):
            fed[mem.link_name] = _memory_feed_arg(mem, carry)
        outs = _run_submodel_step(network, plan.sub, ctx, fed, None)
        probs = outs[plan.score_layer].value                      # [B, V]
        # argmax of log-probs == argmax of probs; the clip only matters
        # for the beam path's score arithmetic — kept for bit-parity of
        # tie behavior with _generate's top_k(K=1)
        logp = jnp.log(jnp.clip(probs, 1e-20, None))
        token = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        token = jnp.where(finished, eos, token)
        new_carries = []
        for mem, old in zip(plan.memories, carries):
            out_arg = outs[mem.layer_name]
            new = (out_arg.ids
                   if jnp.issubdtype(old.dtype, jnp.integer)
                   else out_arg.value)
            keep = finished.reshape((-1,) + (1,) * (new.ndim - 1))
            new_carries.append(
                jnp.where(keep if new.ndim > 1 else finished, old, new)
            )
        new_finished = finished | (token == eos)
        return tuple(new_carries), token, new_finished

    return step
