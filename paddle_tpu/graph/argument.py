"""Argument — the universal inter-layer value type.

TPU-native redesign of the reference's ``Argument``
(/root/reference/paddle/parameter/Argument.h:32): there, a batch is a
ragged concatenation of variable-length sequences with
``sequenceStartPositions`` / ``subSequenceStartPositions`` index vectors and
no padding. XLA wants static shapes, so here a batch is a **padded dense
array plus a lengths vector**; masking (not ragged indexing) removes the
padding's influence. Nested (sub-)sequences get a second padded axis.

Shapes:
- non-sequence:   value [B, D]            ids [B]
- sequence:       value [B, T, D]         ids [B, T]        seq_lengths [B]
- nested seq:     value [B, S, T, D]      ids [B, S, T]     sub_seq_lengths [B, S]
                  (seq_lengths [B] = number of valid subsequences per sample)

All fields are optional; a layer populates what it produces. ``Argument``
is a pytree so whole batches flow through jit/pjit/scan.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

Array = jax.Array


@struct.dataclass
class Argument:
    value: Optional[Array] = None
    ids: Optional[Array] = None
    seq_lengths: Optional[Array] = None       # int32 [B]
    sub_seq_lengths: Optional[Array] = None   # int32 [B, S]
    # per-sample weight (reference: Argument::weight used by cost layers)
    weight: Optional[Array] = None

    # ---- static-shape helpers -------------------------------------------

    @property
    def is_seq(self) -> bool:
        return self.seq_lengths is not None

    @property
    def is_nested_seq(self) -> bool:
        return self.sub_seq_lengths is not None

    @property
    def batch_size(self) -> int:
        ref = self.value if self.value is not None else self.ids
        assert ref is not None, "empty Argument"
        return ref.shape[0]

    @property
    def max_len(self) -> int:
        ref = self.value if self.value is not None else self.ids
        assert ref is not None and ref.ndim >= 2
        return ref.shape[1]

    def seq_mask(self, dtype=jnp.float32) -> Array:
        """[B, T] mask of valid timesteps (1 inside the sequence)."""
        assert self.seq_lengths is not None
        ref = self.value if self.value is not None else self.ids
        T = ref.shape[1]
        pos = jnp.arange(T, dtype=jnp.int32)[None, :]
        return (pos < self.seq_lengths[:, None]).astype(dtype)

    def sub_seq_mask(self, dtype=jnp.float32) -> Array:
        """[B, S, T] mask for nested sequences."""
        assert self.sub_seq_lengths is not None
        ref = self.value if self.value is not None else self.ids
        T = ref.shape[2]
        pos = jnp.arange(T, dtype=jnp.int32)[None, None, :]
        return (pos < self.sub_seq_lengths[:, :, None]).astype(dtype)

def make_dense(value: Array, weight: Optional[Array] = None) -> Argument:
    return Argument(value=jnp.asarray(value), weight=weight)


def make_ids(ids: Array) -> Argument:
    return Argument(ids=jnp.asarray(ids, dtype=jnp.int32))


def make_seq(value: Optional[Array], lengths: Array, ids: Optional[Array] = None) -> Argument:
    return Argument(
        value=None if value is None else jnp.asarray(value),
        ids=None if ids is None else jnp.asarray(ids, dtype=jnp.int32),
        seq_lengths=jnp.asarray(lengths, dtype=jnp.int32),
    )


def degrade_sequence(arg: Argument) -> Argument:
    """Nested sequence → plain sequence over subsequences.

    Reference semantics (`Argument::degradeSequence`,
    /root/reference/paddle/parameter/Argument.cpp:513): treat each
    subsequence as one unit. Here [B, S, T, D] stays put; the caller uses
    ``sub_seq_lengths`` directly — this helper just strips nesting metadata
    for layers that operate per-subsequence after a reduction over T.
    """
    return Argument(
        value=arg.value, ids=arg.ids, seq_lengths=arg.seq_lengths, weight=arg.weight
    )
