"""Network — topological execution of a layer graph.

TPU-native replacement for the reference's ``NeuralNetwork``
(/root/reference/paddle/gserver/gradientmachines/NeuralNetwork.cpp:230,279):
there, stateful Layer objects run hand-written forward then reverse-order
backward; here the whole walk happens inside a traced function, jax.grad
derives the backward, and XLA fuses across layer boundaries.

Sub-models: a ``recurrent_layer_group`` layer in the parent list hands off
to the recurrent-group executor (paddle_tpu.graph.recurrent_group), the
analog of RecurrentGradientMachine.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, forward_layer
from paddle_tpu.proto import LayerConfig, ModelConfig, SubModelConfig


class Network:
    """Executable view of (a sub-model of) a ModelConfig."""

    def __init__(self, model: ModelConfig, submodel: Optional[SubModelConfig] = None):
        self.model = model
        self.layer_map: Dict[str, LayerConfig] = {l.name: l for l in model.layers}
        self.submodel_map: Dict[str, SubModelConfig] = {s.name: s for s in model.sub_models}
        if submodel is None and model.sub_models:
            submodel = self.submodel_map.get("root")
        self.submodel = submodel
        if submodel is not None:
            names = list(submodel.layer_names)
            if submodel.name == "root":
                # multi_nn (ref MultiNetwork, gradientmachines/MultiNetwork.h:
                # 25): plain non-recurrent sub-models are independent
                # sub-networks trained jointly — execute their layers after
                # the root's (each depends only on its own data layers)
                for s in model.sub_models:
                    if s.name != "root" and not s.is_recurrent_layer_group:
                        names.extend(n for n in s.layer_names if n not in names)
        else:
            names = [l.name for l in model.layers]
        self.layers: List[LayerConfig] = [self.layer_map[n] for n in names]
        if submodel is not None:
            self.input_layer_names = list(submodel.input_layer_names)
            self.output_layer_names = list(submodel.output_layer_names)
        else:
            self.input_layer_names = list(model.input_layer_names)
            self.output_layer_names = list(model.output_layer_names)

    def forward(self, ctx: LayerContext, in_args: Dict[str, Argument]) -> Dict[str, Argument]:
        """Run all layers; returns ctx.outputs (every layer's output)."""
        for cfg in self.layers:
            if cfg.name in ctx.outputs:
                continue
            if cfg.type == "data":
                if cfg.name not in in_args:
                    raise KeyError(f"no data fed for input layer {cfg.name!r}")
                forward_layer(cfg, [in_args[cfg.name]], ctx)
            elif cfg.type == "recurrent_layer_group":
                from paddle_tpu.graph.recurrent_group import forward_recurrent_group

                forward_recurrent_group(self, cfg, ctx)
            else:
                ins = [self._lookup_input(ctx, ic.input_layer_name, ic.input_layer_argument)
                       for ic in cfg.inputs]
                forward_layer(cfg, ins, ctx)
        return ctx.outputs

    def _lookup_input(self, ctx: LayerContext, name: str, arg_name: str = "") -> Argument:
        if not name:
            # parameter-only input slot (e.g. batch_norm moving stats)
            return Argument()
        key = f"{name}@{arg_name}" if arg_name else name
        if key not in ctx.outputs:
            raise KeyError(
                f"layer output {key!r} not available; computed: {sorted(ctx.outputs)}"
            )
        return ctx.outputs[key]
