"""Package build (role of the reference's cmake root + paddle/scripts/
docker + deb packaging, re-designed as one Python wheel).

Static metadata lives in pyproject.toml. This file contributes what the
declarative config cannot express:

- the compat-shim package-dir mapping: ``compat/paddle`` and
  ``compat/py_paddle`` install under their reference import names, so
  `from paddle.trainer_config_helpers import *` and
  `import py_paddle.swig_paddle` work unmodified after `pip install`;
- a best-effort prebuild of the native datapath library
  (paddle_tpu/native/datapath.cc → _datapath.so) into the wheel. The
  runtime loader (paddle_tpu/native/__init__.py) prefers the bundled
  library, falls back to build-on-first-import, then to the NumPy
  paths — a missing toolchain at either build or run time never breaks
  the install.
"""

import hashlib
import os
import shutil
import subprocess
import sys

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution

# single source of truth for the datapath compile line (the loader's
# build-on-first-import path uses the same helper, so the wheel-bundled
# library can never be compiled with different flags than a cache build)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from paddle_tpu.native import build_command  # noqa: E402


def _have_cxx() -> bool:
    return shutil.which(os.environ.get("CXX", "g++")) is not None


class BuildPyWithDatapath(build_py):
    def run(self):
        super().run()
        if not _have_cxx():
            self.announce("no C++ compiler; datapath prebuild skipped — "
                          "the runtime builds or falls back on first import",
                          level=3)
            return
        src = os.path.join("paddle_tpu", "native", "datapath.cc")
        out = os.path.join(self.build_lib, "paddle_tpu", "native", "_datapath.so")
        try:
            subprocess.run(build_command(src, out), check=True,
                           capture_output=True, timeout=300)
            # stamp the source hash so the runtime loader rejects a
            # bundle that no longer matches datapath.cc (an ABI check
            # alone would let a stale-but-compatible binary shadow an
            # edited source)
            with open(src, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()
            with open(out.replace(".so", ".hash"), "w") as f:
                f.write(digest + "\n")
        except Exception as e:  # noqa: BLE001 — optional artifact
            self.announce(f"datapath prebuild skipped ({e}); the runtime "
                          "will build or fall back on first import", level=3)


class DatapathDistribution(Distribution):
    """A wheel that carries the arch-specific _datapath.so must not be
    tagged py3-none-any — pip would install an x86-64 binary on arm64,
    where CDLL fails and the prebuild benefit is silently lost. When a
    compiler is present (so the prebuild will run) the wheel is declared
    platform-specific; without one it stays pure and the runtime's
    build-on-first-import / NumPy fallback chain applies. (If the
    compile itself fails the wheel is tagged platform-specific without
    the .so — over-restrictive but harmless; the runtime chain still
    applies.)"""

    def has_ext_modules(self):
        return _have_cxx()


try:
    from wheel.bdist_wheel import bdist_wheel as _bdist_wheel

    class BdistWheelCtypes(_bdist_wheel):
        """The bundled library is ctypes-loaded — no CPython ABI — so the
        wheel must stay py3-none-<plat>, not cp3X-cp3X-<plat>: an
        interpreter-specific tag would lock out other supported Python
        versions (requires-python >= 3.10) for no reason."""

        def get_tag(self):
            python, abi, plat = super().get_tag()
            if self.root_is_pure:
                return python, abi, plat
            return "py3", "none", plat

    _wheel_cmdclass = {"bdist_wheel": BdistWheelCtypes}
except ImportError:  # pragma: no cover - wheel not installed
    _wheel_cmdclass = {}


setup(
    packages=find_packages(include=["paddle_tpu*"]) + [
        "paddle",
        "paddle.trainer",
        "paddle.trainer_config_helpers",
        "paddle.utils",
        "py_paddle",
    ],
    package_dir={
        "": ".",
        "paddle": "compat/paddle",
        "py_paddle": "compat/py_paddle",
    },
    cmdclass={"build_py": BuildPyWithDatapath, **_wheel_cmdclass},
    distclass=DatapathDistribution,
)
