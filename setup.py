"""Package build (role of the reference's cmake root + paddle/scripts/
docker + deb packaging, re-designed as one Python wheel).

Static metadata lives in pyproject.toml. This file contributes what the
declarative config cannot express:

- the compat-shim package-dir mapping: ``compat/paddle`` and
  ``compat/py_paddle`` install under their reference import names, so
  `from paddle.trainer_config_helpers import *` and
  `import py_paddle.swig_paddle` work unmodified after `pip install`;
- a best-effort prebuild of the native datapath library
  (paddle_tpu/native/datapath.cc → _datapath.so) into the wheel. The
  runtime loader (paddle_tpu/native/__init__.py) prefers the bundled
  library, falls back to build-on-first-import, then to the NumPy
  paths — a missing toolchain at either build or run time never breaks
  the install.
"""

import os
import shutil
import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution


def _have_cxx() -> bool:
    return shutil.which(os.environ.get("CXX", "g++")) is not None


class BuildPyWithDatapath(build_py):
    def run(self):
        super().run()
        if not _have_cxx():
            self.announce("no C++ compiler; datapath prebuild skipped — "
                          "the runtime builds or falls back on first import",
                          level=3)
            return
        src = os.path.join("paddle_tpu", "native", "datapath.cc")
        out = os.path.join(self.build_lib, "paddle_tpu", "native", "_datapath.so")
        try:
            subprocess.run(
                [os.environ.get("CXX", "g++"), "-O3", "-shared", "-fPIC",
                 "-std=c++17", "-o", out, src],
                check=True, capture_output=True, timeout=300,
            )
        except Exception as e:  # noqa: BLE001 — optional artifact
            self.announce(f"datapath prebuild skipped ({e}); the runtime "
                          "will build or fall back on first import", level=3)


class DatapathDistribution(Distribution):
    """A wheel that carries the arch-specific _datapath.so must not be
    tagged py3-none-any — pip would install an x86-64 binary on arm64,
    where CDLL fails and the prebuild benefit is silently lost. When a
    compiler is present (so the prebuild will run) the wheel is declared
    platform-specific; without one it stays pure and the runtime's
    build-on-first-import / NumPy fallback chain applies."""

    def has_ext_modules(self):
        return _have_cxx()


setup(
    packages=find_packages(include=["paddle_tpu*"]) + [
        "paddle",
        "paddle.trainer",
        "paddle.trainer_config_helpers",
        "paddle.utils",
        "py_paddle",
    ],
    package_dir={
        "": ".",
        "paddle": "compat/paddle",
        "py_paddle": "compat/py_paddle",
    },
    cmdclass={"build_py": BuildPyWithDatapath},
    distclass=DatapathDistribution,
)
