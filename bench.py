"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: ResNet-50 training throughput (imgs/sec/chip), the
north-star workload from BASELINE.md. `python bench.py lstm` runs the
secondary LSTM-classifier tokens/sec bench. vs_baseline is measured
against benchmarks/targets.json when present (the reference publishes no
numbers — BASELINE.md); absent a recorded target it reports 1.0.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _jit_train_step(tc):
    import jax

    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.optimizer import Updater

    gm = GradientMachine(tc.model_config)
    updater = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=1)
    opt_state = updater.init_state(params)
    grad_fn = gm.grad_fn()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, bs):
        loss, grads, outputs, state_updates = grad_fn(params, batch, None)
        new_params, new_opt = updater(params, grads, opt_state, bs)
        for k, v in state_updates.items():
            new_params[k] = v
        return new_params, new_opt, loss

    return step, params, opt_state


def _time_steps(step, params, opt_state, batch, bs, steps, warmup):
    # sync via host readback: on the axon TPU platform block_until_ready
    # returns before execution finishes, but a device→host transfer of the
    # loss (which transitively depends on every step) cannot
    loss = None
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch, bs)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch, bs)
    float(loss)
    return time.perf_counter() - t0


def resnet_config(layer_num=50, img_size=224, classes=1000):
    from paddle_tpu.config import parse_config_at

    return parse_config_at(
        os.path.join(REPO, "demo", "model_zoo", "resnet", "resnet.py"),
        f"layer_num={layer_num},img_size={img_size},num_classes={classes}",
    )


def make_image_batch(B, img_size, classes, seed=0):
    import numpy as np

    from paddle_tpu.graph import make_dense, make_ids

    rng = np.random.RandomState(seed)
    return {
        "input": make_dense(rng.randn(B, 3 * img_size * img_size).astype(np.float32)),
        "label": make_ids(rng.randint(0, classes, (B,)).astype(np.int32)),
    }


def bench_resnet50(B=64, img_size=224, classes=1000, steps=20, warmup=3):
    import jax.numpy as jnp

    tc = resnet_config(50, img_size, classes)
    tc.opt_config.batch_size = B
    step, params, opt_state = _jit_train_step(tc)
    batch = make_image_batch(B, img_size, classes)
    dt = _time_steps(step, params, opt_state, batch, jnp.asarray(float(B)), steps, warmup)
    return B * steps / dt


def bench_lstm_classifier(B=256, T=64, steps=20, warmup=3):
    import jax.numpy as jnp

    from __graft_entry__ import _example_batch, _flagship_config

    tc = _flagship_config(dict_dim=10000, emb_dim=256, hidden=512, classes=2)
    tc.opt_config.batch_size = B
    step, params, opt_state = _jit_train_step(tc)
    batch = _example_batch(dict_dim=10000, B=B, T=T)
    dt = _time_steps(step, params, opt_state, batch, jnp.asarray(float(B)), steps, warmup)
    return B * T * steps / dt


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    targets_path = os.path.join(REPO, "benchmarks", "targets.json")
    targets = {}
    if os.path.exists(targets_path):
        with open(targets_path) as f:
            targets = json.load(f)

    if which not in ("resnet", "lstm"):
        print(f"unknown benchmark {which!r}: expected 'resnet' or 'lstm'", file=sys.stderr)
        return 2
    if which == "lstm":
        value = bench_lstm_classifier()
        metric, unit, tkey = ("lstm_classifier_train_tokens_per_sec", "tokens/s",
                              "lstm_classifier_tokens_per_sec")
    else:
        # CPU smoke runs can't push 224px ResNet: shrink AND rename the
        # metric so a toy run can never masquerade as the flagship number
        import jax

        on_tpu = jax.default_backend() not in ("cpu",)
        if on_tpu:
            value = bench_resnet50()
            metric, unit, tkey = ("resnet50_train_imgs_per_sec_per_chip", "imgs/s",
                                  "resnet50_imgs_per_sec")
        else:
            value = bench_resnet50(B=16, img_size=32, classes=16, steps=5, warmup=2)
            metric, unit, tkey = ("resnet50_cpu_smoke_imgs_per_sec", "imgs/s", None)

    target = targets.get(tkey) if tkey else None
    vs_baseline = value / target if target else 1.0
    print(json.dumps({
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
