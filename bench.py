"""Benchmark harness — prints ONE JSON line for the driver.

Runs the flagship training step on the available accelerator and reports
throughput. vs_baseline is measured/target against the north-star proxy
recorded in benchmarks/targets.json (the reference publishes no numbers —
BASELINE.md); until a measured CUDA reference exists, targets are the
driver-defined proxies.
"""

from __future__ import annotations

import json
import os
import time


def bench_lstm_classifier(B=256, T=64, steps=20, warmup=3):
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _example_batch, _flagship_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.optimizer import Updater

    tc = _flagship_config(dict_dim=10000, emb_dim=256, hidden=512, classes=2)
    gm = GradientMachine(tc.model_config)
    updater = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=1)
    opt_state = updater.init_state(params)
    grad_fn = gm.grad_fn()

    @jax.jit
    def step(params, opt_state, batch, bs):
        loss, grads, outputs, _ = grad_fn(params, batch, None)
        new_params, new_opt = updater(params, grads, opt_state, bs)
        return new_params, new_opt, loss

    batch = _example_batch(dict_dim=10000, B=B, T=T)
    bs = jnp.asarray(float(B))
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch, bs)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch, bs)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    tokens_per_sec = B * T * steps / dt
    return tokens_per_sec


def main():
    tokens_per_sec = bench_lstm_classifier()
    targets_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "targets.json")
    target = None
    if os.path.exists(targets_path):
        with open(targets_path) as f:
            target = json.load(f).get("lstm_classifier_tokens_per_sec")
    vs_baseline = tokens_per_sec / target if target else 1.0
    print(
        json.dumps(
            {
                "metric": "lstm_classifier_train_tokens_per_sec",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
