"""Benchmark harness — prints ONE JSON line for the driver, always.

Headline metric: ResNet-50 bf16 training throughput (imgs/sec/chip), the
north-star workload from BASELINE.md. The default run ("all") also times
the two sequence flagships — the stacked-LSTM classifier and the seqToseq
NMT attention encoder-decoder (demo/seqToseq, reference
demo/seqToseq/seqToseq_net.py:65-181) — and reports them in the same JSON
line under "legs", plus an MFU figure (see benchmarks/mfu.py: analytic
model matmul FLOPs from a jaxpr walk of the step / wall-clock / chip
peak).
`python bench.py resnet|lstm|nmt` runs a single leg. vs_baseline is
measured against benchmarks/targets.json when present (the reference
publishes no numbers — BASELINE.md; targets are clearly-labeled estimates,
and the JSON carries `baseline_kind` so an estimate can never masquerade
as a measured reference ratio).

On TPU all legs train in bf16 mixed precision (f32 master weights) —
the production configuration; `PADDLE_TPU_BENCH_DTYPE=float32` forces
full precision for A/B runs. Set PADDLE_TPU_BENCH_TRACE_DIR to capture an
xplane trace of the headline timed window.

Hardening (the round-1 failure mode): the environment pre-registers an
accelerator plugin whose backend init can raise UNAVAILABLE or hang.
We therefore (1) probe the backend in a SUBPROCESS with a timeout, and
only let this process touch the accelerator if the probe proved it
initializes; (2) otherwise force the CPU platform via
paddle_tpu.utils.backend_guard; (3) wrap main in a catch-all that emits
a parseable JSON line with an "error" field rather than a traceback.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# How long the subprocess backend probe may take before we give up on the
# accelerator and fall back to CPU. First TPU init can take ~40s; leave slack.
PROBE_TIMEOUT_S = float(os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT", "180"))
BENCH_DTYPE = os.environ.get("PADDLE_TPU_BENCH_DTYPE", "bfloat16")
TRACE_DIR = os.environ.get("PADDLE_TPU_BENCH_TRACE_DIR", "")
# which leg's trace window to trace when TRACE_DIR is set: the resnet
# headline always traces; "lstm"/"nmt" trace that leg instead (one trace
# per run keeps the xplane dirs unambiguous)
TRACE_LEG = os.environ.get("PADDLE_TPU_BENCH_TRACE_LEG", "")
# fuse k optimizer steps into one device launch (lax.fori_loop over the
# jitted step) — amortizes per-launch dispatch latency, which dominates
# the small recurrent legs through the remote tunnel (device busy ~60%
# on the lstm leg at k=1). Throughput semantics are unchanged: the same
# batch is consumed per step either way, and the JSON reports the knob.
# parsed leniently here; validated in main() so a bad value still flows
# through the child's catch-all into the guaranteed bench_failed JSON line
# instead of killing the supervisor before any JSON is printed
_SPL_RAW = os.environ.get("PADDLE_TPU_BENCH_STEPS_PER_LAUNCH", "1")
_SPL_ENV_SET = "PADDLE_TPU_BENCH_STEPS_PER_LAUNCH" in os.environ
try:
    STEPS_PER_LAUNCH = int(_SPL_RAW)
except ValueError:
    STEPS_PER_LAUNCH = 0  # out of range; rejected in main()


def _leg_spl(default: int = 1) -> int:
    """Per-leg fused-launch factor: an explicit env value wins (A/B
    control); otherwise the leg's measured-best default applies."""
    return STEPS_PER_LAUNCH if _SPL_ENV_SET else default


def _leg_extras(spl=1, rnn_leg=False, **kw):
    """Per-leg JSON extras; tags the knobs that are active. The
    pallas_rnn tag only goes on legs that HAVE recurrent layers —
    default-on _pallas_on() would otherwise stamp conv-only legs
    (resnet) with a knob that cannot affect them, polluting the
    measured-row provenance in measured_tpu.json."""
    if spl > 1:
        kw["steps_per_launch"] = spl
    if rnn_leg and _pallas_on():
        kw["pallas_rnn"] = True
    if os.environ.get("PADDLE_TPU_BENCH_S2D") == "1":
        kw["conv_s2d"] = True
    if rnn_leg and _pallas_decoder_on():
        kw["pallas_decoder"] = True
    if rnn_leg and os.environ.get("PADDLE_TPU_PALLAS_FLAT") == "1":
        kw["pallas_flat"] = True
    return kw


def _jit_train_step(tc, spl=1):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.machine import compute_dtype_of
    from paddle_tpu.optimizer import Updater

    # A/B knobs for the recurrent legs (no-op for ResNet: no scans)
    env_unroll = os.environ.get("PADDLE_TPU_BENCH_UNROLL")
    if env_unroll:
        tc.opt_config.scan_unroll = int(env_unroll)
    if _pallas_on():
        tc.opt_config.pallas_rnn = True
    if os.environ.get("PADDLE_TPU_BENCH_S2D") == "1":
        tc.opt_config.conv_s2d = True
    if _conv_stats_mode():
        tc.opt_config.conv_stats_mode = _conv_stats_mode()
    if _pallas_decoder_on():
        tc.opt_config.pallas_decoder = True

    gm = GradientMachine(tc.model_config, compute_dtype=compute_dtype_of(tc.opt_config),
                         scan_unroll=tc.opt_config.scan_unroll,
                         pallas_rnn=tc.opt_config.pallas_rnn,
                         conv_s2d=tc.opt_config.conv_s2d,
                         conv_stats_mode=tc.opt_config.conv_stats_mode,
                         pallas_decoder=tc.opt_config.pallas_decoder)
    updater = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=1)
    opt_state = updater.init_state(params)
    grad_fn = gm.grad_fn(remat=tc.opt_config.remat)

    def one_step(params, opt_state, batch, bs):
        loss, grads, outputs, state_updates = grad_fn(params, batch, None)
        new_params, new_opt = updater(params, grads, opt_state, bs)
        for k, v in state_updates.items():
            new_params[k] = v
        return new_params, new_opt, loss

    if spl > 1:

        def multi(params, opt_state, batch, bs):
            def body(_, carry):
                p, o, _loss = carry
                p2, o2, loss = one_step(p, o, batch, bs)
                return p2, o2, loss.astype(jnp.float32)

            init = (params, opt_state, jnp.zeros((), jnp.float32))
            return jax.lax.fori_loop(0, spl, body, init)

        step = jax.jit(multi, donate_argnums=(0, 1))
    else:
        step = jax.jit(one_step, donate_argnums=(0, 1))
    # one_step is returned for FLOP counting: always the per-step
    # computation, so _time_steps' explicit ×spl stays correct however
    # the fused fori lowers
    return step, params, opt_state, one_step


def _time_steps(step, params, opt_state, batch, bs, steps, warmup, trace=False, spl=1,
                count_fn=None):
    """Returns (elapsed seconds, flops-per-LAUNCH or None, compile-info
    dict) — a launch is ``spl`` fused optimizer steps, and the elapsed
    time likewise covers ``steps`` launches, so callers must treat both
    as per-launch. The compile info (``trace_s``/``compile_s``/
    ``compile_cache_hit``) rides each leg's JSON extras into the
    ``kind=bench`` record, so BENCH_*.json carries compile cost and the
    persistent cache's effect is measured run over run.

    FLOPs are analytic MODEL matmul FLOPs from a jaxpr walk of
    ``count_fn`` (the per-step function) — NOT XLA's cost analysis, which
    counts scan/while bodies once regardless of trip count and so
    understated the recurrent legs' MFU several-fold in round 4 (and
    cannot see inside pallas_call custom calls at all). See
    paddle_tpu/ops/kernel_flops.py. Cost analysis remains the fallback
    when no count_fn is given."""
    import jax

    from benchmarks.mfu import flops_of_compiled
    from paddle_tpu.observability.compile_log import cache_probe
    from paddle_tpu.ops.kernel_flops import capture as kernel_flops_capture
    from paddle_tpu.ops.kernel_flops import train_step_flops

    flops = None
    compile_info = {}
    if count_fn is not None:
        try:
            flops = train_step_flops(count_fn, params, opt_state, batch, bs)
        except Exception:
            flops = None
    # AOT-compile ONCE and drive the loop with the same executable the
    # cost analysis describes (jit dispatch would compile a second time).
    # The capture collects analytic FLOP counts recorded by any fused
    # Pallas kernels traced inside the step — the cost-analysis fallback
    # cannot see into a pallas_call custom call
    try:
        hit_probe = cache_probe()
        t0 = time.perf_counter()
        with kernel_flops_capture() as kernel_log:
            lowered = step.lower(params, opt_state, batch, bs)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        compile_info["trace_s"] = round(t1 - t0, 4)
        compile_info["compile_s"] = round(time.perf_counter() - t1, 4)
        hit = hit_probe()
        if hit is not None:
            compile_info["compile_cache_hit"] = hit
        # static memory plan of this leg's one launch group — BENCH_*.json
        # carries a memory trajectory alongside throughput, and `paddle
        # compare` judges footprint growth (doc/observability.md)
        from paddle_tpu.observability.memory import memory_analysis_of

        mem = memory_analysis_of(compiled)
        if mem:
            compile_info["static_mem_bytes"] = mem["mem_total_bytes"]
        if flops is None:
            flops = flops_of_compiled(compiled)
            if flops is not None and kernel_log:
                flops += sum(kernel_log)
        # per-launch basis: count_fn counts ONE step, and XLA's cost
        # analysis counts a fori body once (verified empirically), so
        # both bases scale by the fused-launch factor
        if flops is not None:
            flops *= spl
        step = compiled
    except Exception:
        if flops is not None:
            flops *= spl  # still per-launch on the jit dispatch path
    # sync via host readback: on the axon TPU platform block_until_ready
    # returns before execution finishes, but a device→host transfer of the
    # loss (which transitively depends on every step) cannot
    import contextlib

    loss = None
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch, bs)
    float(loss)
    tracer = (
        jax.profiler.trace(TRACE_DIR) if trace and TRACE_DIR else contextlib.nullcontext()
    )
    with tracer:  # exception-safe: a failing step still finalizes the trace
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, batch, bs)
        float(loss)
        dt = time.perf_counter() - t0
    # live HBM peak over the timed run (allocator cumulative peak —
    # host-side C call, no device sync); absent on backends without
    # allocator stats (CPU), same degradation as the kind=memory records
    from paddle_tpu.observability.memory import device_memory_stats

    stats = device_memory_stats()
    if stats and stats.get("peak_bytes_in_use"):
        compile_info["peak_hbm_bytes"] = stats["peak_bytes_in_use"]
    return dt, flops, compile_info


def _mfu_of(flops, dt, steps):
    import jax

    from benchmarks.mfu import mfu

    kind = jax.devices()[0].device_kind
    m = mfu(flops, dt / steps, kind)
    return (round(m, 4) if m is not None else None), kind


def _is_oom(e) -> bool:
    """True only for memory-exhaustion failures. Anything else (a shape
    bug, a bad rewrite, a lowering error) must FAIL the leg loudly rather
    than silently stepping the ladder down and reporting a healthy-looking
    number for a different configuration.

    The base classifier is the ONE shared OOM matcher
    (observability/memory.py — what routes a training death to the
    oom_report.json pre-mortem and EXIT_OOM); the bench ladder adds the
    looser bare-'oom' token on top, acceptable only HERE because this
    predicate runs inside a leg where memory exhaustion is the expected
    failure mode — the trainer-wide catch must not inherit it."""
    from paddle_tpu.observability.memory import is_oom_error

    return is_oom_error(e) or "oom" in f"{type(e).__name__}: {e}".lower()


def _pallas_on() -> bool:
    """Tri-state PADDLE_TPU_BENCH_PALLAS_RNN: '1' forces the fused
    kernels, '0' forces the scan path, unset defaults to ON for
    accelerator runs and OFF for CPU smoke — measured default
    (2026-08-01 03:27Z follow-up session): pallas lstm 10.57M vs 5.67M
    tok/s at k=8 (1.86x, MFU 0.507), decision-table flip."""
    v = os.environ.get("PADDLE_TPU_BENCH_PALLAS_RNN")
    if v is not None:
        return v == "1"
    import jax

    return jax.default_backend() != "cpu"


def _pallas_decoder_on() -> bool:
    """Tri-state PADDLE_TPU_BENCH_PALLAS_DECODER: '1' runs matching
    attention-GRU decoder groups as one fused Pallas launch
    (ops/pallas_attention_gru), '0'/unset keeps the lax.scan — off
    pending a measured A/B win on hardware (first compile ever)."""
    return os.environ.get("PADDLE_TPU_BENCH_PALLAS_DECODER") == "1"


def _conv_stats_mode() -> str:
    """PADDLE_TPU_BENCH_CONV_STATS: 'gram' computes BN statistics from
    the 1x1 conv's input side (pure XLA — colsum + Gram algebra),
    'pallas' uses the fused matmul kernel (measured end-to-end loser:
    layout-boundary copies, see doc/performance.md), '1' aliases gram,
    '0'/'' force off. Unset = off pending a measured A/B win."""
    v = os.environ.get("PADDLE_TPU_BENCH_CONV_STATS", "")
    if v == "1":
        return "gram"
    if v in ("gram", "pallas"):
        return v
    return ""


def _knob_fallback(is_on, env_var, tag_key, fallback_label):
    """Decorator factory for optional-kernel legs: if the leg fails with
    the knob on — a Mosaic rejection, a VMEM miss in the real compiler,
    anything — rerun it with the knob forced off instead of forfeiting
    the A/B leg's budget, and tag the JSON so the fallback can never
    masquerade as a win for the kernel."""

    def deco(leg_fn):
        @functools.wraps(leg_fn)
        def wrapped(*args, **kwargs):
            if not is_on():
                return leg_fn(*args, **kwargs)
            try:
                return leg_fn(*args, **kwargs)
            except Exception as e:
                err = f"{type(e).__name__}: {str(e)[:300]}"
                sys.stderr.write(f"{tag_key} leg failed, retrying on "
                                 f"{fallback_label}: {err}\n")
                orig = os.environ.get(env_var)
                os.environ[env_var] = "0"
                try:
                    value, extras = leg_fn(*args, **kwargs)
                except Exception as e2:
                    # keep the original diagnosis in the parseable record,
                    # not just stderr — the rerun's error alone would
                    # lose it
                    raise RuntimeError(
                        f"{type(e2).__name__}: {str(e2)[:300]} "
                        f"(rerun on {fallback_label} after {tag_key} "
                        f"failure: {err})"
                    ) from e2
                finally:
                    if orig is None:
                        del os.environ[env_var]
                    else:
                        os.environ[env_var] = orig
                extras = dict(extras or {})
                extras[tag_key] = f"FELL BACK to {fallback_label} ({err})"
                return value, extras

        return wrapped

    return deco


_pallas_fallback = _knob_fallback(
    lambda: _pallas_on(), "PADDLE_TPU_BENCH_PALLAS_RNN",
    "pallas_rnn", "the scan path")
_conv_stats_fallback = _knob_fallback(
    lambda: bool(_conv_stats_mode()), "PADDLE_TPU_BENCH_CONV_STATS",
    "conv_stats", "the XLA path")
_pallas_decoder_fallback = _knob_fallback(
    _pallas_decoder_on, "PADDLE_TPU_BENCH_PALLAS_DECODER",
    "pallas_decoder", "the scan path")


def _try_ladder(configs, run_one):
    """Run the first ladder configuration that survives an OOM-class
    failure; any other error re-raises immediately. The successful rung's
    extras gain a "skipped_rungs" list recording each rung stepped past
    and why, so the JSON never hides that a smaller configuration ran.

    Rungs are (batch, remat) tuples; once a rung OOMs, later rungs with
    the same remat mode and an equal-or-larger batch are skipped without
    compiling — they strictly dominate the failed rung's memory, and the
    ladder is no longer monotonically descending (256 leads on measured
    throughput), so a guaranteed-OOM 512 could otherwise burn a full
    compile after 256 already failed."""
    skipped = []
    oomed = []  # (batch, ...) rungs that hit OOM
    for i, cfg in enumerate(configs):
        # rung = (batch,) or (batch, remat, ...): dominate = same
        # non-batch knobs with an equal-or-larger batch
        dom = next((o for o in oomed if o[1:] == cfg[1:] and cfg[0] >= o[0]), None)
        if dom is not None and i < len(configs) - 1:
            skipped.append({"rung": list(cfg),
                            "error": f"skipped: memory-dominates OOMed rung {list(dom)}"})
            continue
        try:
            value, extras = run_one(*cfg)
        except Exception as e:
            if i == len(configs) - 1 or not _is_oom(e):
                raise
            oomed.append(cfg)
            skipped.append({"rung": list(cfg), "error": f"{type(e).__name__}: {str(e)[:200]}"})
            continue
        if skipped:
            extras = dict(extras or {}, skipped_rungs=skipped)
        return value, extras
    raise AssertionError("empty ladder")


@_conv_stats_fallback
def bench_resnet50(B=None, img_size=224, classes=1000, steps=20, warmup=3, trace=True,
                   dtype=None):
    """Headline leg. Without an explicit B, tries a (batch, remat)
    ladder led by the measured-fastest rung (B=256 — past it the BN-stat
    and residual bandwidth grows faster than MXU fill; 2026-08-01 batch
    A/B in benchmarks/RESULTS.md), stepping to other plain sizes on OOM
    and only then to remat rungs (the +33% recompute FLOPs often beats
    halving B), keeping the first configuration that runs.
    PADDLE_TPU_BENCH_RESNET_B pins a size."""
    import jax.numpy as jnp

    from paddle_tpu.flagship import make_image_batch, resnet_config

    env_b = os.environ.get("PADDLE_TPU_BENCH_RESNET_B")
    env_remat = os.environ.get("PADDLE_TPU_BENCH_RESNET_REMAT", "none")
    if env_b:
        ladder = [(int(env_b), env_remat)]
    elif B:
        ladder = [(B, "none")]
    else:
        # 256 leads — measured (2026-08-01 03:43Z batch A/B): 2201 imgs/s
        # at B=256 vs 2082 at 512 and 1957 at 768; past 256 the BN-stat
        # and residual bandwidth grows faster than MXU fill. ALL plain
        # rungs come before ANY remat rung — if a plain rung OOMs a
        # smaller plain rung must win, not a remat one whose +33%
        # recompute would silently replace the mfu headline with
        # hw_flops_util
        sizes = (256, 512, 128, 64)
        ladder = [(b, "none") for b in sizes] + [(b, "full") for b in sizes]

    def run_one(b, remat):
        tc = resnet_config(50, img_size, classes)
        tc.opt_config.batch_size = b
        tc.opt_config.dtype = dtype or BENCH_DTYPE
        tc.opt_config.remat = remat
        spl = _leg_spl(1)  # long compute-bound steps: fusing launches is noise
        step, params, opt_state, one_step = _jit_train_step(tc, spl)
        batch = make_image_batch(b, img_size, classes)
        dt, flops, cinfo = _time_steps(
            step, params, opt_state, batch, jnp.asarray(float(b)), steps, warmup,
            trace=trace and TRACE_LEG in ("", "resnet"), spl=spl, count_fn=one_step,
        )
        m, kind = _mfu_of(flops, dt, steps)
        extras = _leg_extras(spl=spl, device_kind=kind, dtype=tc.opt_config.dtype, batch=b,
                             **cinfo)
        if _conv_stats_mode():
            extras["conv_stats"] = _conv_stats_mode()
        if remat == "none":
            extras["mfu"] = m
        else:
            # remat recompute FLOPs are in the executed count, so this
            # is hardware-FLOPs utilization, NOT model-FLOPs (MFU would
            # be overstated ~33%) — different key, never comparable
            extras["remat"] = remat
            extras["hw_flops_util"] = m
        return b * steps * spl / dt, extras

    return _try_ladder(ladder, run_one)


@_pallas_fallback
def bench_lstm_classifier(B=256, T=64, steps=20, warmup=3, dtype=None):
    import jax.numpy as jnp

    from paddle_tpu.flagship import example_batch, flagship_config

    import jax

    B = int(os.environ.get("PADDLE_TPU_BENCH_LSTM_B", 0)) or B
    tc = flagship_config(dict_dim=10000, emb_dim=256, hidden=512, classes=2)
    tc.opt_config.batch_size = B
    tc.opt_config.dtype = dtype or BENCH_DTYPE
    # measured-best default: k=8 fused launches on the accelerator (5.55M
    # vs 4.31M tok/s at k=1 — this leg is dispatch-latency-bound); plain
    # single launches on the CPU smoke path
    spl = _leg_spl(8 if jax.default_backend() != "cpu" else 1)
    step, params, opt_state, one_step = _jit_train_step(tc, spl)
    batch = example_batch(dict_dim=10000, B=B, T=T)
    dt, flops, cinfo = _time_steps(
        step, params, opt_state, batch, jnp.asarray(float(B)), steps, warmup,
        trace=TRACE_LEG == "lstm", spl=spl, count_fn=one_step,
    )
    m, _ = _mfu_of(flops, dt, steps)
    extras = _leg_extras(spl=spl, rnn_leg=True, mfu=m, dtype=tc.opt_config.dtype,
                         **cinfo)
    return B * T * steps * spl / dt, extras


@_pallas_fallback
@_pallas_decoder_fallback
def bench_nmt(B=None, T=32, vocab=30000, dim=512, steps=10, warmup=2, dtype=None):
    """seqToseq NMT attention encoder-decoder train step; tokens/sec counts
    target (decoder) tokens — BASELINE.md north-star workload #2. Without
    an explicit B, walks a 448/384/256/128/64 batch ladder on OOM (448
    measured fastest 2026-08-01: 599.6k tok/s MFU 0.4102; 512 breaks
    the fused GRU kernel's hardware compile); an
    explicit B or PADDLE_TPU_BENCH_NMT_B pins a size, matching
    bench_resnet50's PADDLE_TPU_BENCH_RESNET_B."""
    import jax.numpy as jnp

    from paddle_tpu.flagship import nmt_batch, nmt_config

    def run_one(b):
        import jax

        tc = nmt_config(vocab=vocab, dim=dim, dtype=dtype or BENCH_DTYPE)
        tc.opt_config.batch_size = b
        # measured default (2026-08-01 03:26Z session): k=8 419.9k tok/s
        # vs k=1 373.3k = 1.125x — decision-table flip; CPU smoke stays k=1
        spl = _leg_spl(8 if jax.default_backend() != "cpu" else 1)
        step, params, opt_state, one_step = _jit_train_step(tc, spl)
        batch = nmt_batch(vocab=vocab, B=b, T=T)
        dt, flops, cinfo = _time_steps(
            step, params, opt_state, batch, jnp.asarray(float(b)), steps, warmup,
            trace=TRACE_LEG == "nmt", spl=spl, count_fn=one_step,
        )
        m, _ = _mfu_of(flops, dt, steps)
        extras = _leg_extras(spl=spl, rnn_leg=True, mfu=m, dtype=tc.opt_config.dtype,
                             tokens="target", batch=b, **cinfo)
        return b * T * steps * spl / dt, extras

    env_b = os.environ.get("PADDLE_TPU_BENCH_NMT_B")
    if env_b:
        ladder = [(int(env_b),)]
    else:
        # 448 leads — measured (2026-08-01 06:08Z, post flat-logits):
        # 599.6k tok/s MFU 0.4102 vs 587.4k at 384 and 554.6k at 256;
        # at 512 the fused GRU kernel's hardware compile fails (falls
        # back to scan), so 448 is the largest kernel-clean batch
        ladder = [(B,)] if B else [(448,), (384,), (256,), (128,), (64,)]
    return _try_ladder(ladder, run_one)


def bench_nmt_gen(B=None, T=32, vocab=30000, dim=512, beam_size=3,
                  max_length=32, steps=10, warmup=2, dtype=None):
    """seqToseq beam-search generation throughput: generated (best-beam)
    tokens/sec — the reference's gen.conf workload (SURVEY hard part #1's
    beam search under XLA's static-shape regime). Forward-only; no MFU
    (the decode while-loop is dispatch/latency-bound, not matmul-bound,
    and its trip count is data-dependent)."""
    import jax
    import numpy as np

    from paddle_tpu.flagship import nmt_gen_batch, nmt_gen_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.machine import compute_dtype_of

    def run_one(b):
        tc = nmt_gen_config(vocab=vocab, dim=dim, beam_size=beam_size,
                            max_length=max_length, dtype=dtype or BENCH_DTYPE,
                            batch_size=b)
        gm = GradientMachine(tc.model_config,
                             compute_dtype=compute_dtype_of(tc.opt_config))
        params = gm.init_params(seed=1)
        batch = nmt_gen_batch(vocab=vocab, B=b, T=T)
        group = next(s.name for s in tc.model_config.sub_models
                     if s.generator is not None)

        def fwd(params, batch):
            outputs, _ = gm.forward(params, batch, pass_type="gen", rng=None)
            best = outputs[group]
            return best.ids, best.seq_lengths

        fwd = jax.jit(fwd)
        ids, lens = fwd(params, batch)
        jax.block_until_ready((ids, lens))
        for _ in range(warmup - 1):
            ids, lens = fwd(params, batch)
        jax.block_until_ready((ids, lens))
        tracing = TRACE_DIR and TRACE_LEG == "gen"
        if tracing:
            jax.profiler.start_trace(TRACE_DIR)
        # count generated tokens EVERY timed step (the lens readback is
        # also the per-step device sync): the old once-after-loop
        # `tokens * steps / dt` assumed every step produced identical
        # trip counts — data-dependent decode lengths (early-EOS beams)
        # would silently skew the headline
        t0 = time.perf_counter()
        tokens = 0.0
        for _ in range(steps):
            ids, lens = fwd(params, batch)
            tokens += float(np.asarray(lens).sum())  # sync via readback
        dt = time.perf_counter() - t0
        if tracing:
            jax.profiler.stop_trace()
        extras = _leg_extras(beam_size=beam_size, max_length=max_length,
                             dtype=tc.opt_config.dtype, batch=b,
                             tokens="best-beam generated")
        return tokens / dt, extras

    env_b = os.environ.get("PADDLE_TPU_BENCH_GEN_B")
    if env_b:
        ladder = [(int(env_b),)]
    else:
        # 512 leads — measured (2026-08-01 07:08Z batch sweep): decode is
        # dispatch-bound per step, so tokens/s scales with batch until
        # the MXU fills: 800.6 (64) / 1557.6 (128) / 2450.3 (256) /
        # 3114.4 (512) tok/s at beam=3
        ladder = [(B,)] if B else [(512,), (256,), (128,), (64,)]
    return _try_ladder(ladder, run_one)


def _serve_sweep_static(gm, params, registry, *, group, rates, B, T,
                        n_requests, seed, timeout_s, queue_cap, beam_size,
                        prompt_fn, budget_fn, make_seq):
    """The PR-8 static engine: run-to-completion micro-batch cohorts
    over the jitted full-generation launch, virtual-clock driver.
    Returns (sweep doc, measured capacity req/s)."""
    import jax
    import numpy as np

    from paddle_tpu.observability import serving

    def fwd(params, batch):
        outputs, _ = gm.forward(params, batch, pass_type="gen", rng=None)
        best = outputs[group]
        return best.ids, best.seq_lengths

    fwd = jax.jit(fwd)
    sig_key = (B, T)  # ONE signature: every cohort pads to it

    serving_now = [False]  # warmup/calibration launches stay out of the
    # roofline totals: they serve no requests, and the rung windows'
    # launches/exec_s must reconcile with the serve_gen roofline row

    def launch_fn(requests):
        # pad-to-signature: a fixed [B, T] int32 batch regardless of
        # cohort size or prompt lengths — empty slots replay a 1-token
        # dummy prompt whose output is discarded. The signature never
        # changes, so CompileRegistry reuse keeps recompiles at 0.
        ids = np.full((B, T), 2, dtype=np.int32)
        lengths = np.ones((B,), dtype=np.int32)
        for i, r in enumerate(requests):
            p = np.asarray(r.prompt, dtype=np.int32)[:T]
            ids[i, : len(p)] = p
            lengths[i] = max(len(p), 1)
        batch = {"source_language_word": make_seq(None, lengths, ids=ids)}
        t0 = time.perf_counter()
        _out_ids, out_lens = registry.call(
            serving.SERVE_GROUP, sig_key, fwd, params, batch
        )
        lens_np = np.asarray(out_lens)  # device sync via readback
        dt = time.perf_counter() - t0
        if serving_now[0]:
            registry.note_exec(serving.SERVE_GROUP, sig_key, dt)
        # delivered tokens cap at the request's output budget (mixed-
        # length workloads) — run-to-completion still PAID max_length
        # decode steps for the whole cohort, which is the A/B's point
        return [
            int(lens_np[i]) if r.max_new is None
            else min(int(lens_np[i]), r.max_new)
            for i, r in enumerate(requests)
        ], dt

    # warmup: the ONE compile (kind=compile record, recompiles=0), then
    # a clean measured launch to calibrate capacity for the rate ladder
    prng = np.random.RandomState(seed)
    warm = [serving.Request(rid=f"warm-{i}", t_enqueue=0.0,
                            prompt=prompt_fn(prng, i))
            for i in range(B)]
    launch_fn(warm)
    # the warmup launch paid the compile but isn't roofline-counted:
    # discard the pending compile-cost deduction so it can't zero the
    # first RUNG launch's exec time instead
    registry.drop_pending(serving.SERVE_GROUP, sig_key)
    # median of 3: one descheduled calibration launch would otherwise
    # halve the whole auto-rate ladder (A/B runs pin rates anyway)
    service_s = sorted(launch_fn(warm)[1] for _ in range(3))[1]
    capacity_rps = B / max(service_s, 1e-6)
    serving_now[0] = True
    if not rates:
        rates = [round(f * capacity_rps, 4) for f in (0.25, 0.5, 1.0, 2.0)]

    doc = serving.run_sweep(
        launch_fn, rates, n_requests=n_requests, seed=seed, max_batch=B,
        timeout_s=timeout_s, queue_cap=queue_cap, beam_size=beam_size,
        prompt_fn=prompt_fn, budget_fn=budget_fn, engine="static",
    )
    return doc, capacity_rps


def _serve_sweep_continuous(gm, params, registry, *, rates, B, T,
                            max_length, n_requests, seed, timeout_s,
                            queue_cap, decode_block, prompt_fn, budget_fn,
                            pipeline=True, fused_step=False,
                            shed_policy="off", replicas=(1,),
                            transport="pipe", spec_tokens="0",
                            slot_dtype="f32"):
    """The continuous-batching engine (paddle_tpu/serving/) on the SAME
    seeded workload, driven open-loop in wall-clock time. ``pipeline``
    selects the overlapped dispatch/collect loop vs the serial PR-12
    loop (PADDLE_TPU_BENCH_SERVE_PIPELINE — the overlap A/B's subject).
    ``replicas`` is the fleet-size LADDER (PADDLE_TPU_BENCH_SERVE_
    REPLICAS): each size N > 1 runs the whole rate sweep through
    ``drive_fleet_rung`` — N engines behind the router's own
    least-loaded scoring — so the scaling curve (goodput vs replicas,
    router overhead share) is measured, not assumed.

    ``transport`` (PADDLE_TPU_BENCH_SERVE_TRANSPORT=pipe|tcp) selects
    the submit path: ``pipe`` is the direct in-process call; ``tcp``
    fronts every engine with an :class:`EngineSocketServer` on a
    loopback ephemeral port and drives it through a framed
    :class:`SocketEngineClient`, so JSON serialization + the socket
    round trip land in the measured ``router_share`` — the
    pipe-vs-tcp A/B `paddle compare` judges. tcp routes EVERY rung
    (n == 1 included) through the fleet driver: the single-engine
    drive_rung path has no client seam.

    ``spec_tokens`` (PADDLE_TPU_BENCH_SERVE_SPEC, "0" = off) is the
    speculative draft-length ladder and ``slot_dtype``
    (PADDLE_TPU_BENCH_SERVE_SLOT_DTYPE) the slot-state storage dtype —
    doc/serving.md "Speculative decode" / "Reduced-precision slot
    state". With speculation on, the calibration pass's emitted
    sequences seed every engine's draft table before rung 0 (the
    calibration launches are already excluded from rung telemetry via
    ``backend.serving``), so the first measured rung isn't penalized
    by draft-table cold start — the same discipline that keeps warmup
    compiles out of the measurement. Returns (sweep doc, measured
    capacity req/s of ONE replica)."""
    import numpy as np

    from paddle_tpu.observability import serving
    from paddle_tpu.serving import Engine, drive_rung
    from paddle_tpu.serving.fleet import drive_fleet_rung
    from paddle_tpu.serving.jax_backend import JaxDecodeBackend

    replicas = tuple(replicas) or (1,)
    n_max = max(replicas)
    backend = JaxDecodeBackend(
        gm, params, slots=B, prompt_tokens=T, max_length=max_length,
        decode_block=decode_block, registry=registry, pipeline=pipeline,
        fused_step=fused_step, spec_tokens=spec_tokens,
        slot_dtype=slot_dtype,
    )
    backend.warmup()  # compiles land now; Engine.start()'s call re-runs
    # two cheap no-slot launches (idempotent semantically)
    # capacity calibration without request records OR roofline exec
    # (the static leg's serving_now rule): drive the backend directly —
    # B full-length sequences back to back, like the static leg's
    # full-batch launch
    backend.serving = False
    prng = np.random.RandomState(seed)
    warm = [serving.Request(rid=f"warm-{i}", t_enqueue=0.0,
                            prompt=prompt_fn(prng, i))
            for i in range(B)]
    t0 = time.perf_counter()
    backend.admit(list(range(B)), warm, [max_length] * B)
    # calibration emits real greedy tokens — keep them: with
    # speculation on they seed the draft tables below, so rung 0 sees
    # a warm table (the launches themselves stay out of rung telemetry
    # via backend.serving)
    cal_seqs = [[] for _ in range(B)]
    done = False
    while not done:
        out = backend.step()
        toks = np.asarray(out.tokens)
        lives = np.asarray(out.live)
        for u in range(toks.shape[0]):
            for b in range(B):
                if lives[u, b]:
                    cal_seqs[b].append(int(toks[u, b]))
        done = bool(out.finished.all())
    capacity_rps = B / max(time.perf_counter() - t0, 1e-6)
    backend.serving = True
    if not rates:
        rates = [round(f * capacity_rps, 4) for f in (0.25, 0.5, 1.0, 2.0)]

    # replica 0 owns the shared CompileRegistry; the extra fleet
    # backends compile identical signatures and would only double-count
    # the compile/roofline telemetry
    backends = [backend] + [
        JaxDecodeBackend(
            gm, params, slots=B, prompt_tokens=T, max_length=max_length,
            decode_block=decode_block, registry=None, pipeline=pipeline,
            fused_step=fused_step, spec_tokens=spec_tokens,
            slot_dtype=slot_dtype,
        )
        for _ in range(1, n_max)
    ]
    engines = [
        Engine(b, queue_cap=queue_cap, request_timeout_s=timeout_s,
               pipeline=pipeline, shed_policy=shed_policy,
               replica=(f"replica-{i}" if n_max > 1 else "")).start()
        for i, b in enumerate(backends)
    ]
    draft_seeded = 0
    if backend.spec_blocks:
        for e in engines:
            draft_seeded = e.seed_draft(cal_seqs)
    servers, clients = [], []
    if transport == "tcp":
        # the real wire, loopback: every engine behind a framed socket
        # server, driven by a framed client — serialization + syscall
        # cost lands inside the router_s stopwatch
        from paddle_tpu.serving.transport import (EngineSocketServer,
                                                  SocketEngineClient)

        for e in engines:
            srv = EngineSocketServer(e, "127.0.0.1:0")
            srv.start()
            servers.append(srv)
        for srv in servers:
            c = SocketEngineClient(srv.address)
            c.start()
            clients.append(c)
    try:
        windows = []
        rung = 0
        for n in replicas:
            for rate in rates:
                reqs = serving.schedule_requests(
                    float(rate), n_requests, seed + rung, rung=rung,
                    prompt_fn=prompt_fn, budget_fn=budget_fn,
                )
                if n_max <= 1 and transport != "tcp":
                    # no fleet anywhere in the ladder: the PR-13 single-
                    # engine path, byte-identical records
                    w = drive_rung(engines[0], reqs, rate_rps=float(rate),
                                   rung=rung)
                else:
                    # n == 1 rungs also go through the fleet driver so
                    # the baseline carries replicas=1 (and pays the
                    # same routing overhead) — the scaling curve's x=1
                    # point must be measured under the same discipline
                    w = drive_fleet_rung(
                        engines[:n], reqs, rate_rps=float(rate), rung=rung,
                        clients=clients[:n] if clients else None)
                windows.append(w)
                rung += 1
    finally:
        for c in clients:
            c.close()
        for srv in servers:
            srv.close()
        for e in engines:
            e.drain(timeout=600.0)
    # the knee belongs to ONE ladder: with a fleet-size sweep, report
    # the LARGEST fleet's (its capacity is the headline the sweep asks
    # about); mixed-size windows would fake an early knee
    knee_windows = [w for w in windows
                    if int(w.get("replicas") or 1) == n_max]
    return ({"rungs": windows,
             "knee_rps": serving.saturation_knee(knee_windows),
             # per-slot device state bytes (weights excluded) — the
             # honest bf16-vs-f32 footprint stamp `paddle compare`
             # judges as slot_bytes
             "slot_bytes": backend.slot_state_bytes(),
             "draft_seeded": draft_seeded},
            capacity_rps)


def bench_serve(B=None, T=None, vocab=None, dim=None, beam_size=None,
                max_length=None, n_requests=None, rates=None, seed=None,
                run_dir=None, timeout_s=None, queue_cap=None, dtype=None,
                engine=None, mixed_len=None, decode_block=None,
                pipeline=None, fused_step=None, replicas=None):
    """Offered-load serving leg (doc/observability.md "Serving
    telemetry"): a deterministic seeded open-loop arrival process at a
    sweep of offered loads drives one of TWO engines over the seqToseq
    generator (``--engine`` / PADDLE_TPU_BENCH_SERVE_ENGINE):

    - ``static`` (default, the PR-8 path): a dynamic micro-batch
      aggregator over the jitted full beam-search generation launch —
      run-to-completion cohorts of up to B, padded to ONE signature so
      the ``serve_gen`` launch group never recompiles after warmup.
    - ``continuous``: the slot-based continuous-batching engine
      (paddle_tpu/serving/, doc/serving.md) on the SAME seeded arrival
      schedule, prompts and budgets — ``serve_prefill``/``serve_decode``
      launch groups, one signature each, driven in wall-clock time.

    Emits per-request ``kind=request`` records and per-rung
    ``kind=serve_window`` rollups (``engine`` stamped on both) into
    ``run_dir`` (PADDLE_TPU_BENCH_SERVE_DIR), the run dir `paddle
    serve-report` renders. Headline: best goodput (generated tok/s)
    across rungs; extras carry per-rung p50/p99 latency and TTFT vs
    offered load plus the saturation knee. With
    PADDLE_TPU_BENCH_SERVE_MIXED_LEN=1 every request draws a seeded
    heavy-tailed output budget (most short, a tail at max_length) — the
    mixed-length workload where run-to-completion batching pays
    max_length for every cohort and iteration-level scheduling shows
    its goodput win; `paddle compare` of a static vs a continuous run
    on pinned PADDLE_TPU_BENCH_SERVE_RATES is the A/B.

    Without PADDLE_TPU_BENCH_SERVE_RATES (comma-separated req/s), the
    rungs are calibrated from a measured full-batch, full-length
    serving pass: 0.25x / 0.5x / 1x / 2x the back-to-back capacity, so
    the sweep brackets the knee on any backend."""
    import jax
    import numpy as np

    from paddle_tpu.flagship import nmt_gen_config
    from paddle_tpu.graph import GradientMachine, make_seq
    from paddle_tpu.graph.machine import compute_dtype_of
    from paddle_tpu.observability import metrics as obsm
    from paddle_tpu.observability import serving
    from paddle_tpu.observability.compile_log import CompileRegistry

    on_cpu = jax.default_backend() == "cpu"
    env = os.environ.get
    engine = engine or env("PADDLE_TPU_BENCH_SERVE_ENGINE", "static")
    if engine not in ("static", "continuous"):
        raise ValueError(f"unknown serve engine {engine!r}: expected "
                         "'static' or 'continuous'")
    B = int(env("PADDLE_TPU_BENCH_SERVE_B", 0)) or B or (4 if on_cpu else 64)
    T = T or (8 if on_cpu else 32)
    vocab = vocab or (200 if on_cpu else 30000)
    dim = dim or (32 if on_cpu else 512)
    beam_size = beam_size or (2 if on_cpu else 3)
    max_length = max_length or (8 if on_cpu else 32)
    n_requests = (int(env("PADDLE_TPU_BENCH_SERVE_REQUESTS", 0))
                  or n_requests or (32 if on_cpu else 256))
    seed = int(env("PADDLE_TPU_BENCH_SERVE_SEED", "0")) if seed is None else seed
    if mixed_len is None:
        mixed_len = env("PADDLE_TPU_BENCH_SERVE_MIXED_LEN", "0") == "1"
    if decode_block is None:
        # the decode-block LADDER (an int or "1,2,4,8"): one compiled
        # serve_decode signature covers every rung, the engine's
        # adaptive policy picks per iteration (doc/serving.md)
        decode_block = (env("PADDLE_TPU_BENCH_SERVE_BLOCK", "")
                        or ("1,2,4,8" if on_cpu else "1,2,4"))
    if pipeline is None:
        pip_env = env("PADDLE_TPU_BENCH_SERVE_PIPELINE", "")
        if pip_env:
            pipeline = pip_env != "off"
        else:
            # overlap needs somewhere to overlap INTO: on a TPU the
            # device runs beside the host; on a CPU backend "device"
            # work shares the host's cores, so a 1-core box can only
            # lose to speculation+context-switching (measured −10..−27%
            # goodput — doc/performance.md "Pipelined decode"). Count
            # the cores this process may actually USE — a cgroup/
            # affinity-limited container on a big host is still 1-core
            try:
                cores = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                cores = os.cpu_count() or 1
            pipeline = (not on_cpu) or cores > 1
    if fused_step is None:
        fused_step = env("PADDLE_TPU_BENCH_SERVE_FUSED", "0") == "1"
    # overload defense for the shed-on-vs-off A/B
    # (PADDLE_TPU_BENCH_SERVE_SHED=off|deadline|brownout, continuous
    # engine only — the static driver has no admission estimator)
    shed_policy = env("PADDLE_TPU_BENCH_SERVE_SHED", "off")
    # 0 is a LEGAL deadline (drop everything not admitted immediately)
    # — None, not falsiness, is the unset sentinel
    if timeout_s is None:
        t_env = env("PADDLE_TPU_BENCH_SERVE_TIMEOUT")
        timeout_s = float(t_env) if t_env is not None else 60.0
    queue_cap = (int(env("PADDLE_TPU_BENCH_SERVE_QUEUE_CAP", 0))
                 if queue_cap is None else queue_cap)
    run_dir = run_dir or env("PADDLE_TPU_BENCH_SERVE_DIR",
                             os.path.join(REPO, "output", "bench_serve"))
    obsm.configure(run_dir)

    tc = nmt_gen_config(vocab=vocab, dim=dim, beam_size=beam_size,
                        max_length=max_length, dtype=dtype or BENCH_DTYPE,
                        batch_size=B)
    gm = GradientMachine(tc.model_config,
                         compute_dtype=compute_dtype_of(tc.opt_config))
    params = gm.init_params(seed=1)
    group = next(s.name for s in tc.model_config.sub_models
                 if s.generator is not None)
    registry = CompileRegistry(device_kind=jax.devices()[0].device_kind)

    def prompt_fn(rng, i):
        return rng.randint(2, vocab, size=int(rng.randint(1, T + 1))).tolist()

    budget_fn = None
    if mixed_len:
        # heavy-tailed output budgets (real serving is mostly-short with
        # a long tail): ~90% draw 1..max(L/8, 1) tokens, ~10% the full
        # max_length — run-to-completion pays max_length for EVERY
        # cohort regardless, which is exactly the A/B's subject
        short = max(max_length // 8, 1)

        def budget_fn(rng, i):
            if rng.rand() < 0.1:
                return max_length
            return 1 + int(rng.randint(0, short))

    rates_env = env("PADDLE_TPU_BENCH_SERVE_RATES", "")
    if rates_env:
        rates = [float(r) for r in rates_env.split(",") if r.strip()]
    # the fleet-size ladder (--replicas=N or "1,2,4"): each size runs
    # the whole rate sweep through the in-process fleet driver
    # (serving/fleet.drive_fleet_rung), continuous engine only — the
    # static driver has no router seam to measure
    if replicas is None:
        rep_env = env("PADDLE_TPU_BENCH_SERVE_REPLICAS", "")
        replicas = ([int(r) for r in rep_env.split(",") if r.strip()]
                    if rep_env else [1])
    elif isinstance(replicas, int):
        replicas = [replicas]
    replicas = [max(int(n), 1) for n in replicas] or [1]
    if max(replicas) > 1 and engine != "continuous":
        raise ValueError(
            "PADDLE_TPU_BENCH_SERVE_REPLICAS needs "
            "PADDLE_TPU_BENCH_SERVE_ENGINE=continuous (the static "
            "driver has no fleet)")
    # the submit path A/B (doc/serving.md "Cross-host fleet"): pipe is
    # the in-process call, tcp fronts every engine with a loopback
    # framed-socket server so the wire cost is measured
    transport = env("PADDLE_TPU_BENCH_SERVE_TRANSPORT", "pipe")
    if transport not in ("pipe", "tcp"):
        raise ValueError(f"unknown serve transport {transport!r}: "
                         "expected 'pipe' or 'tcp'")
    if transport == "tcp" and engine != "continuous":
        raise ValueError(
            "PADDLE_TPU_BENCH_SERVE_TRANSPORT=tcp needs "
            "PADDLE_TPU_BENCH_SERVE_ENGINE=continuous (the static "
            "driver has no socket seam)")
    # speculative decode + slot-state precision (doc/serving.md): the
    # spec-on-vs-off and bf16-vs-f32 A/Bs, continuous engine only
    from paddle_tpu.serving.backend import (parse_slot_dtype,
                                            parse_spec_tokens)

    spec_tokens = env("PADDLE_TPU_BENCH_SERVE_SPEC", "0")
    slot_dtype = parse_slot_dtype(
        env("PADDLE_TPU_BENCH_SERVE_SLOT_DTYPE", "f32"))
    if parse_spec_tokens(spec_tokens) and engine != "continuous":
        raise ValueError(
            "PADDLE_TPU_BENCH_SERVE_SPEC needs "
            "PADDLE_TPU_BENCH_SERVE_ENGINE=continuous (the static "
            "driver has no draft seam)")
    if slot_dtype != "f32" and engine != "continuous":
        raise ValueError(
            "PADDLE_TPU_BENCH_SERVE_SLOT_DTYPE needs "
            "PADDLE_TPU_BENCH_SERVE_ENGINE=continuous (slot state is "
            "the continuous engine's)")

    if engine == "continuous":
        doc, capacity_rps = _serve_sweep_continuous(
            gm, params, registry, rates=rates, B=B, T=T,
            max_length=max_length, n_requests=n_requests, seed=seed,
            timeout_s=timeout_s, queue_cap=queue_cap,
            decode_block=decode_block, prompt_fn=prompt_fn,
            budget_fn=budget_fn, pipeline=bool(pipeline),
            fused_step=bool(fused_step), shed_policy=shed_policy,
            replicas=tuple(replicas), transport=transport,
            spec_tokens=spec_tokens, slot_dtype=slot_dtype,
        )
        beam_size = 1  # the engine decodes greedily (doc/serving.md)
    else:
        doc, capacity_rps = _serve_sweep_static(
            gm, params, registry, group=group, rates=rates, B=B, T=T,
            n_requests=n_requests, seed=seed, timeout_s=timeout_s,
            queue_cap=queue_cap, beam_size=beam_size, prompt_fn=prompt_fn,
            budget_fn=budget_fn, make_seq=make_seq,
        )
    registry.emit_roofline()
    # run_end must be the serve stream's LAST record (after the
    # kind=bench headline — doc/observability.md). When the bench-record
    # mirror will land in THIS stream (PADDLE_TPU_BENCH_METRICS_DIR
    # unset → main() defaults it to run_dir, or explicitly equal), the
    # caller emits run_end after the mirror through the same reused
    # writer; when the mirror goes elsewhere, close the stream here
    # while this leg's writer is still installed (re-opening later
    # would append a second run_start with a re-anchored `t`)
    mdir = env("PADDLE_TPU_BENCH_METRICS_DIR", "")
    if mdir and os.path.abspath(mdir) != os.path.abspath(run_dir):
        obsm.emit("run_end", status="completed")
    obsm.flush()

    rungs = [
        {
            "offered_rps": w.get("offered_rps"),
            "arrived": w.get("arrived"),
            "completed": w.get("completed"),
            "rejected": w.get("rejected"),
            "timeouts": w.get("timeouts"),
            "shed": w.get("shed", 0),
            "errors": w.get("errors", 0),
            # overload-defense rates ride the archived artifact so
            # `paddle compare` can judge shed/error growth without the
            # telemetry run dir (zero-filled there for older artifacts)
            "shed_rate": (round((w.get("shed", 0) or 0)
                                / float(w["arrived"]), 6)
                          if w.get("arrived") else 0.0),
            "error_rate": (round((w.get("errors", 0) or 0)
                                 / float(w["arrived"]), 6)
                           if w.get("arrived") else 0.0),
            "p50_ms": round((w.get("latency") or {}).get("p50", 0.0) * 1e3, 3),
            "p99_ms": round((w.get("latency") or {}).get("p99", 0.0) * 1e3, 3),
            "ttft_p50_ms": round((w.get("ttft") or {}).get("p50", 0.0) * 1e3, 3),
            "ttft_p99_ms": round((w.get("ttft") or {}).get("p99", 0.0) * 1e3, 3),
            "queue_wait_share": w.get("queue_wait_share"),
            "occupancy_mean": round((w.get("occupancy") or {}).get("mean", 0.0), 3),
            "goodput_tok_s": w.get("goodput_tok_s"),
            "engine": w.get("engine", engine),
            # pipeline mode rides every rung record (continuous engine
            # only): `paddle compare` joins on (engine, pipeline,
            # offered load), so a pipelined-vs-blocking A/B compares
            # mode-to-mode instead of landing in only_a/only_b
            **({"pipeline": w["pipeline"]}
               if isinstance(w.get("pipeline"), str) else {}),
            # fleet rungs: the size joins the compare key ((engine,
            # pipeline, replicas, offered load)) and the measured
            # router overhead share rides the artifact
            **({"replicas": int(w["replicas"])}
               if isinstance(w.get("replicas"), int) else {}),
            **({"router_share": w["router_share"]}
               if isinstance(w.get("router_share"), (int, float)) else {}),
            # pipe|tcp — compare joins pipe-vs-tcp rungs on offered
            # load and judges router_share across the wire
            **({"transport": w["transport"]}
               if isinstance(w.get("transport"), str) else {}),
            # speculation config + per-rung draft acceptance: spec
            # ("4"/"2,4"/"off") and slot_dtype join the compare key;
            # accept_rate rides so an archived artifact carries the
            # spec A/B's explanatory variable (zero when no verify
            # launch ran — compare zero-fills old artifacts the same)
            **({"spec": w["spec"]}
               if isinstance(w.get("spec"), str) else {}),
            **({"slot_dtype": w["slot_dtype"]}
               if isinstance(w.get("slot_dtype"), str) else {}),
            **({"accept_rate": w["accept_rate"]}
               if isinstance(w.get("accept_rate"), (int, float)) else {}),
        }
        for w in doc["rungs"]
    ]
    best = max((w.get("goodput_tok_s", 0.0) for w in doc["rungs"]), default=0.0)
    extras = _leg_extras(
        batch=B, beam_size=beam_size, max_length=max_length,
        dtype=tc.opt_config.dtype, n_requests=n_requests, engine=engine,
        mixed_len=bool(mixed_len), capacity_rps=round(capacity_rps, 3),
        knee_rps=doc.get("knee_rps"), rungs=rungs, run_dir=run_dir,
        tokens=("greedy generated" if engine == "continuous"
                else "best-beam generated"),
    )
    if engine == "continuous":
        # the headline stamps the pipeline mode + ladder so an archived
        # BENCH_*.json says WHAT was measured (and compare joins on it)
        extras["pipeline"] = "on" if pipeline else "off"
        extras["decode_blocks"] = str(decode_block)
        extras["transport"] = transport
        # speculation + slot-dtype headline stamps: spec=K|off and the
        # storage dtype say WHAT was measured; slot_bytes is the
        # memory_analysis-honest per-slot footprint compare judges
        spec_ladder = parse_spec_tokens(spec_tokens)
        extras["spec"] = (",".join(str(k) for k in spec_ladder)
                          if spec_ladder else "off")
        extras["slot_dtype"] = slot_dtype
        if isinstance(doc.get("slot_bytes"), int):
            extras["slot_bytes"] = doc["slot_bytes"]
        if doc.get("draft_seeded"):
            extras["draft_seeded"] = doc["draft_seeded"]
        if max(replicas) > 1:
            extras["replicas"] = ",".join(str(n) for n in replicas)
        if fused_step:
            extras["fused_step"] = True
        if shed_policy != "off":
            extras["shed_policy"] = shed_policy
    # memory trajectory for the serve leg too: the sweep's live HBM
    # peak (absent on stat-less backends) and the serve_gen group's
    # static plan from its one compile
    from paddle_tpu.observability.memory import device_memory_stats

    stats = device_memory_stats()
    if stats and stats.get("peak_bytes_in_use"):
        extras["peak_hbm_bytes"] = stats["peak_bytes_in_use"]
    static_rows = registry.static_memory_rows()
    if static_rows:
        extras["static_mem_bytes"] = static_rows[0]["mem_total_bytes"]
    return best, extras


def bench_feeder(B=128, dim=512, n_batches=40, max_threads=None,
                 repeats=3):
    """Input-pipeline microbenchmark (no train step): packed samples/s
    and bytes/s through ``BatchAssembler`` + the prefetch pipeline, with
    1 vs N packer threads (``--data_packer_threads``). Device-free by
    construction — it measures exactly the host packing stage the
    zero-stall work parallelized, so regressions in the feeder can't
    hide behind device time. Samples are pre-built numpy sequences
    (varied lengths, so bucketing and padding run for real) and the
    shuffle pool is active, matching the training-path shape of the
    work. Emitted through the same ``kind=bench`` metrics schema as
    every other leg, so ``BENCH_*.json`` tracks input-pipeline
    throughput run over run."""
    import numpy as np

    from paddle_tpu.data.feeder import DataProvider
    from paddle_tpu.native import get_lib
    from paddle_tpu.data.provider import (
        dense_vector_sequence, integer_value, provider,
    )

    B = int(os.environ.get("PADDLE_TPU_BENCH_FEEDER_B", 0)) or B
    n = max_threads or int(os.environ.get("PADDLE_TPU_BENCH_FEEDER_THREADS", "2"))
    rng = np.random.default_rng(0)
    # lengths 100-128 all bucket to T=128: realistic padding work with a
    # high C-packer share (the measured sweet spot for exposing packing
    # parallelism — shorter/raggeder mixes shift time into GIL-held
    # Python prep and understate the pool). Only B*4 UNIQUE samples,
    # cycled: assemble re-packs them identically each time, and holding
    # every sample of every batch resident (~1.2 GB at the defaults)
    # would OOM-risk small CI containers for no extra signal
    uniq = B * 4
    samples = [
        (rng.standard_normal((int(rng.integers(100, 129)), dim)).astype(np.float32),
         int(i % 2))
        for i in range(uniq)
    ]

    @provider(input_types={"x": dense_vector_sequence(dim),
                           "y": integer_value(2)},
              pool_size=B * 8)
    def synth(settings, file_name):
        for i in range(B * n_batches):
            yield samples[i % uniq]

    def one_pass(threads):
        dp = DataProvider(
            synth, ["mem"], B, ["x", "y"],
            packer_threads=threads, prefetch_depth=4,
            stall_timeout=300.0, seed=1,
        )
        t0 = time.perf_counter()
        n_samples = n_bytes = 0
        for batch in dp.batches():
            n_samples += int(np.asarray(batch["y"].ids).shape[0])
            n_bytes += sum(
                getattr(f, "nbytes", 0)
                for a in batch.values()
                for f in (a.value, a.ids, a.seq_lengths)
                if f is not None
            )
        return n_samples, n_bytes, time.perf_counter() - t0

    one_pass(1)  # warm the native lib + allocator
    results = {}
    for threads in sorted({1, n}):
        best = min((one_pass(threads) for _ in range(repeats)),
                   key=lambda r: r[2])
        results[threads] = best
    ns, nb, dt = results[n]
    rate = ns / dt
    rate1 = results[1][0] / results[1][2]
    return rate, {
        "packer_threads": n,
        "batch": B,
        "dim": dim,
        "bytes_per_sec": round(nb / dt, 1),
        "samples_per_sec_1thread": round(rate1, 1),
        "speedup_vs_1thread": round(rate / rate1, 3) if n > 1 else 1.0,
        "native_datapath": get_lib() is not None,
    }


def bench_sparse(V=100_000, D=64, B=4096, steps=20, warmup=3, dtype=None):
    """Row-sharded sparse-embedding step microbenchmark (doc/sparse.md):
    touched-rows/s through one gather → per-row adagrad → scatter-drop
    update step — the exact kernel sequence the ``sparse_update`` table
    path runs, built from the same ``optimizer.sparse.dedupe`` the
    updater uses. Ids are a hot-set-skewed mix (80 % of occurrences
    from 1 % of rows, the CTR-shaped distribution), so the dedupe and
    the unique-row rate measure something real. Alongside the headline
    it measures the gather's own share of the step (a second
    gather-only jit over the same ids) and stamps ``static_mem_bytes``
    + the roofline bucket — gather-dominated steps must classify
    memory-bound on any known chip."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.observability import costs
    from paddle_tpu.optimizer.sparse import dedupe

    dt = jnp.dtype(dtype or "float32")
    rng = np.random.default_rng(0)
    hot = max(V // 100, 1)
    n_hot = int(B * 0.8)
    ids_batches = [
        jnp.asarray(np.concatenate([
            rng.integers(0, hot, size=n_hot),
            rng.integers(0, V, size=B - n_hot),
        ]).astype(np.int32))
        for _ in range(4)
    ]
    table = jnp.asarray(rng.standard_normal((V, D)), dtype=dt)
    acc = jnp.zeros((V, D), dtype=dt)  # per-row adagrad accumulator

    def step(table, acc, ids):
        rows = jnp.take(table, ids, axis=0)
        loss = 0.5 * jnp.mean(rows * rows)
        grads = rows / (ids.shape[0] * D)
        uid, g_rows, _valid = dedupe(ids, grads, V)
        safe = jnp.clip(uid, 0, V - 1)
        acc_rows = jnp.take(acc, safe, axis=0) + g_rows * g_rows
        update = g_rows / (jnp.sqrt(acc_rows) + 1e-6)
        table = table.at[uid].add(-0.1 * update, mode="drop")
        acc = acc.at[uid].max(acc_rows, mode="drop")
        return table, acc, loss

    def gather_only(table, ids):
        return jnp.take(table, ids, axis=0).sum()

    jstep = jax.jit(step, donate_argnums=(0, 1))
    jgather = jax.jit(gather_only)
    extras = {"vocab": V, "dim": D, "batch": B, "steps": steps}
    step_fn = jstep
    try:
        # AOT-compile once and TIME the same executable, so the
        # static-memory/roofline analysis does not pay a second compile
        # of an identical step graph (jit's own cache would)
        compiled = jstep.lower(table, acc, ids_batches[0]).compile()
        step_fn = compiled
        ma = compiled.memory_analysis()
        if ma is not None:
            extras["static_mem_bytes"] = int(
                getattr(ma, "temp_size_in_bytes", 0)
                + getattr(ma, "argument_size_in_bytes", 0)
                + getattr(ma, "output_size_in_bytes", 0)
            )
        ca = costs.cost_analysis_of(compiled)
        if ca and ca.get("bytes_accessed"):
            intensity = ca.get("flops", 0.0) / ca["bytes_accessed"]
            extras["roofline_class"] = costs.classify(
                intensity, jax.devices()[0].device_kind
            )
    except Exception:
        pass  # AOT-less backends: headline still measured below

    def time_fn(fn, *state):
        # every fn returns (carried_state..., last_result): the carry
        # threads donated buffers, the tail is only blocked on at the end
        for i in range(warmup):
            state = fn(*state, ids_batches[i % len(ids_batches)])[:-1]
        t0 = time.perf_counter()
        out = state
        for i in range(steps):
            out = fn(*out[: len(state)], ids_batches[i % len(ids_batches)])
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    t_step = time_fn(step_fn, table, acc)
    t_gather = time_fn(lambda t, ids: (t, jgather(t, ids)),
                       jnp.asarray(rng.standard_normal((V, D)), dtype=dt))
    rows_per_sec = B * steps / max(t_step, 1e-9)
    uniq = np.mean([
        np.unique(np.asarray(ids)).size / B for ids in ids_batches
    ])
    extras.update({
        "sparse_gather_share": round(min(t_gather / max(t_step, 1e-9), 1.0), 4),
        "unique_row_rate": round(float(uniq), 4),
        "step_ms": round(t_step / steps * 1e3, 3),
    })
    return rows_per_sec, extras


def _load_last_measured():
    """Newest committed real-TPU rows (benchmarks/measured_tpu.json,
    refreshed by append_results.py after every measurement session).
    Embedded under "last_measured" whenever this run falls back to CPU
    smoke, so the driver's bench artifact always carries the best
    available hardware evidence — clearly labeled as prior-window
    measurements, never mixed into the live numbers."""
    path = os.path.join(REPO, "benchmarks", "measured_tpu.json")
    try:
        with open(path) as f:
            rows = json.load(f).get("rows")
        if not rows:
            return None
        return {"note": "prior-window real-TPU measurements (this run fell "
                        "back to CPU); see benchmarks/RESULTS.md",
                **rows}
    except Exception:
        return None


def _emit(metric, value, unit, vs_baseline, **extra):
    line = {
        "metric": metric,
        "value": round(float(value), 1),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 3),
    }
    line.update({k: v for k, v in extra.items() if v is not None})
    print(json.dumps(line))
    _emit_metrics_record(line)


def _emit_metrics_record(line):
    """Mirror each result line into a run-telemetry stream
    (PADDLE_TPU_BENCH_METRICS_DIR): the BENCH_*.json payload and live
    run telemetry then share ONE schema — `paddle metrics --tail` and
    any jsonl tooling read bench sessions unchanged
    (doc/observability.md, kind="bench")."""
    path = os.environ.get("PADDLE_TPU_BENCH_METRICS_DIR", "")
    if not path:
        return
    try:
        from paddle_tpu.observability import metrics as obs

        obs.configure(path)
        obs.emit("bench", **line)
        obs.flush()
    except Exception as e:  # telemetry must never fail the bench
        print(f"# bench metrics record failed: {e}", file=sys.stderr)


def main():
    if STEPS_PER_LAUNCH < 1:
        raise ValueError(
            "PADDLE_TPU_BENCH_STEPS_PER_LAUNCH must be an integer >= 1, "
            f"got {_SPL_RAW!r}"
        )
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which not in ("all", "resnet", "lstm", "nmt", "gen", "serve", "feeder",
                     "sparse"):
        print(
            f"unknown benchmark {which!r}: expected 'all', 'resnet', 'lstm', "
            "'nmt', 'gen', 'serve', 'feeder' or 'sparse'",
            file=sys.stderr,
        )
        return 2

    if which == "feeder":
        # host-only leg: never touches the accelerator — force the CPU
        # platform so merely importing the data path can't wedge on a
        # pre-registered plugin backend, and skip the probe entirely
        from paddle_tpu.utils.backend_guard import ensure_cpu_mesh

        ensure_cpu_mesh(1)
        value, extras = bench_feeder()
        _emit("feeder_pack_samples_per_sec", value, "samples/s", 1.0,
              backend="host", baseline_kind="none", **extras)
        return 0

    targets_path = os.path.join(REPO, "benchmarks", "targets.json")
    targets = {}
    if os.path.exists(targets_path):
        with open(targets_path) as f:
            targets = json.load(f)

    # Decide the backend BEFORE this process touches jax: probe in a
    # subprocess (can't hang us), fall back to forced CPU on any failure.
    from paddle_tpu.utils.backend_guard import ensure_cpu_mesh, probe_backend

    backend = probe_backend(timeout_s=PROBE_TIMEOUT_S)
    on_tpu = backend not in ("", "cpu")
    # early stderr marker: tells the supervisor this child never touches
    # the accelerator, so a timeout may safely terminate it (a child on
    # the TPU path must be abandoned instead — kill-wedge)
    print(f"# backend-decision: {'tpu' if on_tpu else 'cpu'}",
          file=sys.stderr, flush=True)
    if not on_tpu:
        ensure_cpu_mesh(1)

    # persistent compilation cache: repeat measurement sessions skip the
    # slow (remote-tunnel) recompiles of unchanged steps; a cold cache is
    # merely the old speed. Shared helper also drops jax's
    # min-compile-time gate so cache hits are measurable (and measured —
    # _time_steps stamps trace_s/compile_s/compile_cache_hit into every
    # leg's record)
    from paddle_tpu.observability.compile_log import enable_compile_cache

    enable_compile_cache(
        os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache")
    )

    # bf16 on XLA CPU is emulated and slow — CPU fallbacks run f32 so
    # their numbers stay comparable run-to-run
    leg_dtype = None if on_tpu else "float32"
    if which == "lstm":
        value, extras = bench_lstm_classifier(dtype=leg_dtype)
        metric, unit, tkey = (
            "lstm_classifier_train_tokens_per_sec",
            "tokens/s",
            "lstm_classifier_tokens_per_sec",
        )
    elif which == "nmt":
        # CPU has nothing to OOM the ladder down: pin the pre-ladder B=64
        # so the leg stays inside the supervisor budget
        value, extras = bench_nmt(dtype=leg_dtype, **({} if on_tpu else {"B": 64}))
        metric, unit, tkey = ("nmt_train_tokens_per_sec", "tokens/s", "nmt_tokens_per_sec")
    elif which == "gen":
        if on_tpu:
            value, extras = bench_nmt_gen()
            metric = "nmt_gen_tokens_per_sec"
        else:
            value, extras = bench_nmt_gen(
                B=4, T=8, vocab=200, dim=32, max_length=8, steps=2, warmup=1,
                dtype="float32")
            metric = "nmt_gen_cpu_smoke_tokens_per_sec"
        unit, tkey = "tokens/s", None
    elif which == "sparse":
        # sparse-embedding leg (doc/sparse.md): touched-rows/s headline,
        # gather share + static_mem_bytes + roofline bucket in extras —
        # `paddle compare` judges rows/s higher-better and gather share
        # lower-better (_HIGHER_BETTER entries). CPU smoke shrinks the
        # table and renames the metric, same contract as the other legs
        if on_tpu:
            value, extras = bench_sparse()
            metric = "sparse_rows_per_sec"
        else:
            value, extras = bench_sparse(
                V=20_000, D=32, B=1024, steps=8, warmup=2, dtype="float32"
            )
            metric = "sparse_cpu_smoke_rows_per_sec"
        unit, tkey = "rows/s", None
    elif which == "serve":
        # offered-load serving leg: CPU smoke shapes are bench_serve's
        # backend-aware defaults (tiny model, named so a toy run never
        # masquerades as the flagship serving number).
        # `bench.py serve --engine={static,continuous}` picks the
        # engine (PADDLE_TPU_BENCH_SERVE_ENGINE also works) — run one
        # of each on pinned PADDLE_TPU_BENCH_SERVE_RATES and `paddle
        # compare` the two artifacts for the A/B (doc/serving.md)
        eng = None
        for a in sys.argv[2:]:
            if a.startswith("--engine="):
                eng = a.split("=", 1)[1]
        value, extras = bench_serve(dtype=None if on_tpu else "float32",
                                    engine=eng)
        metric = ("serve_goodput_tokens_per_sec" if on_tpu
                  else "serve_cpu_smoke_goodput_tokens_per_sec")
        unit, tkey = "tokens/s", None
        # one schema, one stream: unless the driver already points the
        # bench-record mirror somewhere, land the kind=bench headline in
        # the serve run dir next to its request/serve_window records
        os.environ.setdefault("PADDLE_TPU_BENCH_METRICS_DIR",
                              extras["run_dir"])
    elif on_tpu:
        # headline: bf16 ResNet-50; "all" additionally runs the two
        # sequence flagships (emitted incrementally below)
        value, extras = bench_resnet50()
        metric, unit, tkey = (
            "resnet50_train_imgs_per_sec_per_chip",
            "imgs/s",
            "resnet50_imgs_per_sec",
        )
    else:
        # CPU smoke runs can't push 224px ResNet: shrink AND rename the
        # metric so a toy run can never masquerade as the flagship number
        value, extras = bench_resnet50(B=16, img_size=32, classes=16, steps=5, warmup=2,
                                       trace=False, dtype="float32")
        metric, unit, tkey = ("resnet50_cpu_smoke_imgs_per_sec", "imgs/s", None)

    target = targets.get(tkey) if tkey else None
    vs_baseline = value / target if target else 1.0
    common = dict(backend=backend, baseline_kind="estimated" if target else "none")
    if not on_tpu:
        last_measured = _load_last_measured()
        if last_measured:
            common["last_measured"] = last_measured
    # emit the headline IMMEDIATELY — if a later leg hangs past the
    # supervisor budget, the measured number is already on stdout (the
    # supervisor keeps the LAST parseable line and salvages timed-out
    # child output)
    _emit(metric, value, unit, vs_baseline, **common, **extras)
    sys.stdout.flush()
    if which == "serve":
        # the mirror above landed in the serve stream (same resolved
        # writer — no reconfigure, no second run_start): NOW close it,
        # run_end last, so `paddle metrics --follow` shows the headline
        # before it stops. The other-dir case already closed in
        # bench_serve.
        mdir = os.environ.get("PADDLE_TPU_BENCH_METRICS_DIR", "")
        if mdir and os.path.abspath(mdir) == os.path.abspath(extras["run_dir"]):
            from paddle_tpu.observability import metrics as obsm

            obsm.emit("run_end", status="completed")
            obsm.flush()
    if which == "all":
        if on_tpu:
            leg_specs = [
                ("lstm_classifier_train_tokens_per_sec", bench_lstm_classifier, {}),
                ("nmt_train_tokens_per_sec", bench_nmt, {}),
            ]
        else:
            # tiny lstm/nmt smoke legs: worthless as perf numbers (and
            # named so) but they prove all three flagship train steps
            # compile and run even when the accelerator is unreachable
            leg_specs = [
                ("lstm_cpu_smoke_tokens_per_sec", bench_lstm_classifier,
                 dict(B=8, T=16, steps=3, warmup=1, dtype="float32")),
                ("nmt_cpu_smoke_tokens_per_sec", bench_nmt,
                 dict(B=4, T=8, vocab=200, dim=32, steps=2, warmup=1,
                      dtype="float32")),
            ]
        legs = {}
        for key, fn, kw in leg_specs:
            try:
                v, e = fn(**kw)
                legs[key] = {"value": round(v, 1), "unit": "tokens/s",
                             **{k: x for k, x in (e or {}).items() if x is not None}}
            except Exception as ex:
                legs[key] = {"error": f"{type(ex).__name__}: {ex}"}
            # cumulative re-emit after each leg: always a complete line
            _emit(metric, value, unit, vs_baseline, **common, legs=legs, **extras)
            sys.stdout.flush()
    return 0


def _good_json_line(text):
    """The LAST parseable JSON line that isn't a failure report — the
    child emits the headline first, then cumulative lines as extra legs
    finish, so the last line is the most complete."""
    best = None
    for ln in text.strip().splitlines():
        if ln.startswith("{"):
            try:
                parsed = json.loads(ln)
            except ValueError:
                continue
            if parsed.get("metric") != "bench_failed":
                best = ln
    return best


def _supervise():
    """Run the real bench in a child with a wall-clock budget; if the
    accelerator leg hangs or crashes (round-1 failure modes), retry on
    forced CPU. Guarantees exactly one JSON line and rc=0 no matter what.

    Timed-out children are ABANDONED, never signaled: even a SIGTERM to a
    process hung mid-claim wedges the tunnel for every later claimant
    (including the CPU-retry's probe subprocess). The abandoned child
    finishes its own claim rejection (~25 min) as an orphan while the
    retry proceeds; its partial stdout is salvaged from the incremental
    pipe drain."""
    from paddle_tpu.utils.backend_guard import run_abandoning

    budget = float(os.environ.get("PADDLE_TPU_BENCH_BUDGET", "1500"))
    deadline = time.monotonic() + budget
    attempts = [
        dict(os.environ, PADDLE_TPU_BENCH_CHILD="1"),
        # forced-CPU retry: 1s probe timeout makes the child give up on the
        # accelerator immediately and run the CPU smoke instead
        dict(os.environ, PADDLE_TPU_BENCH_CHILD="1", PADDLE_TPU_BENCH_PROBE_TIMEOUT="1"),
    ]
    last_err = "no attempt ran"
    # a hung accelerator attempt must not starve the forced-CPU retry:
    # reserve enough budget for the CPU smoke to run after a timeout
    RETRY_RESERVE_S = 180.0
    for i, env in enumerate(attempts):
        remaining = deadline - time.monotonic()
        if remaining <= 10:
            break
        attempt_budget = remaining
        if i < len(attempts) - 1 and remaining - RETRY_RESERVE_S > 10:
            attempt_budget = remaining - RETRY_RESERVE_S
        rc, stdout, stderr = run_abandoning(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            timeout_s=attempt_budget,
            env=env,
            # a timed-out child that committed to the CPU path never held
            # the accelerator: stop it so the retry gets uncontended cores
            signal_if=lambda _o, e: "# backend-decision: cpu" in e,
        )
        sys.stderr.write((stderr or "")[-4000:])
        # salvage even on timeout: the child may have emitted the headline
        # before a later leg hung
        line = _good_json_line(stdout or "")
        if line is not None:
            print(line)
            return 0
        if rc is None:
            last_err = f"bench child exceeded its {attempt_budget:.0f}s attempt budget"
        else:
            last_err = (stderr or stdout or "no output")[-500:]
    _emit("bench_failed", 0.0, "none", 0.0, error=last_err)
    return 0


if __name__ == "__main__":
    if os.environ.get("PADDLE_TPU_BENCH_CHILD") == "1":
        try:
            rc = main()
        except Exception as e:  # never leave the driver without a JSON line
            _emit("bench_failed", 0.0, "none", 0.0, error=f"{type(e).__name__}: {e}")
            rc = 0
        sys.exit(rc)
    sys.exit(_supervise())
