"""Benchmark harness — prints ONE JSON line for the driver, always.

Headline metric: ResNet-50 training throughput (imgs/sec/chip), the
north-star workload from BASELINE.md. `python bench.py lstm` runs the
secondary LSTM-classifier tokens/sec bench. vs_baseline is measured
against benchmarks/targets.json when present (the reference publishes no
numbers — BASELINE.md; the targets are clearly-labeled estimates, and
the emitted JSON carries `baseline_kind` so an estimate can never
masquerade as a measured reference ratio).

Hardening (the round-1 failure mode): the environment pre-registers an
accelerator plugin whose backend init can raise UNAVAILABLE or hang.
We therefore (1) probe the backend in a SUBPROCESS with a timeout, and
only let this process touch the accelerator if the probe proved it
initializes; (2) otherwise force the CPU platform via
paddle_tpu.utils.backend_guard; (3) wrap main in a catch-all that emits
a parseable JSON line with an "error" field rather than a traceback.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# How long the subprocess backend probe may take before we give up on the
# accelerator and fall back to CPU. First TPU init can take ~40s; leave slack.
PROBE_TIMEOUT_S = float(os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT", "180"))


def _jit_train_step(tc):
    import jax

    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.optimizer import Updater

    gm = GradientMachine(tc.model_config)
    updater = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=1)
    opt_state = updater.init_state(params)
    grad_fn = gm.grad_fn()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, bs):
        loss, grads, outputs, state_updates = grad_fn(params, batch, None)
        new_params, new_opt = updater(params, grads, opt_state, bs)
        for k, v in state_updates.items():
            new_params[k] = v
        return new_params, new_opt, loss

    return step, params, opt_state


def _time_steps(step, params, opt_state, batch, bs, steps, warmup):
    # sync via host readback: on the axon TPU platform block_until_ready
    # returns before execution finishes, but a device→host transfer of the
    # loss (which transitively depends on every step) cannot
    loss = None
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch, bs)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch, bs)
    float(loss)
    return time.perf_counter() - t0


def bench_resnet50(B=64, img_size=224, classes=1000, steps=20, warmup=3):
    import jax.numpy as jnp

    from paddle_tpu.flagship import make_image_batch, resnet_config

    tc = resnet_config(50, img_size, classes)
    tc.opt_config.batch_size = B
    step, params, opt_state = _jit_train_step(tc)
    batch = make_image_batch(B, img_size, classes)
    dt = _time_steps(step, params, opt_state, batch, jnp.asarray(float(B)), steps, warmup)
    return B * steps / dt


def bench_lstm_classifier(B=256, T=64, steps=20, warmup=3):
    import jax.numpy as jnp

    from paddle_tpu.flagship import example_batch, flagship_config

    tc = flagship_config(dict_dim=10000, emb_dim=256, hidden=512, classes=2)
    tc.opt_config.batch_size = B
    step, params, opt_state = _jit_train_step(tc)
    batch = example_batch(dict_dim=10000, B=B, T=T)
    dt = _time_steps(step, params, opt_state, batch, jnp.asarray(float(B)), steps, warmup)
    return B * T * steps / dt


def _emit(metric, value, unit, vs_baseline, **extra):
    line = {
        "metric": metric,
        "value": round(float(value), 1),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 3),
    }
    line.update(extra)
    print(json.dumps(line))


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    if which not in ("resnet", "lstm"):
        print(f"unknown benchmark {which!r}: expected 'resnet' or 'lstm'", file=sys.stderr)
        return 2

    targets_path = os.path.join(REPO, "benchmarks", "targets.json")
    targets = {}
    if os.path.exists(targets_path):
        with open(targets_path) as f:
            targets = json.load(f)

    # Decide the backend BEFORE this process touches jax: probe in a
    # subprocess (can't hang us), fall back to forced CPU on any failure.
    from paddle_tpu.utils.backend_guard import ensure_cpu_mesh, probe_backend

    backend = probe_backend(timeout_s=PROBE_TIMEOUT_S)
    on_tpu = backend not in ("", "cpu")
    if not on_tpu:
        ensure_cpu_mesh(1)

    if which == "lstm":
        value = bench_lstm_classifier()
        metric, unit, tkey = (
            "lstm_classifier_train_tokens_per_sec",
            "tokens/s",
            "lstm_classifier_tokens_per_sec",
        )
    elif on_tpu:
        value = bench_resnet50()
        metric, unit, tkey = (
            "resnet50_train_imgs_per_sec_per_chip",
            "imgs/s",
            "resnet50_imgs_per_sec",
        )
    else:
        # CPU smoke runs can't push 224px ResNet: shrink AND rename the
        # metric so a toy run can never masquerade as the flagship number
        value = bench_resnet50(B=16, img_size=32, classes=16, steps=5, warmup=2)
        metric, unit, tkey = ("resnet50_cpu_smoke_imgs_per_sec", "imgs/s", None)

    target = targets.get(tkey) if tkey else None
    vs_baseline = value / target if target else 1.0
    _emit(
        metric,
        value,
        unit,
        vs_baseline,
        backend=backend,
        baseline_kind="estimated" if target else "none",
    )
    return 0


def _good_json_line(text):
    """The first parseable JSON line, unless it's only a failure report."""
    for ln in text.strip().splitlines():
        if ln.startswith("{"):
            try:
                parsed = json.loads(ln)
            except ValueError:
                continue
            if parsed.get("metric") != "bench_failed":
                return ln
    return None


def _supervise():
    """Run the real bench in a child with a wall-clock budget; if the
    accelerator leg hangs or crashes (round-1 failure modes), retry on
    forced CPU. Guarantees exactly one JSON line and rc=0 no matter what."""
    import subprocess

    budget = float(os.environ.get("PADDLE_TPU_BENCH_BUDGET", "1500"))
    deadline = time.monotonic() + budget
    attempts = [
        dict(os.environ, PADDLE_TPU_BENCH_CHILD="1"),
        # forced-CPU retry: 1s probe timeout makes the child give up on the
        # accelerator immediately and run the CPU smoke instead
        dict(os.environ, PADDLE_TPU_BENCH_CHILD="1", PADDLE_TPU_BENCH_PROBE_TIMEOUT="1"),
    ]
    last_err = "no attempt ran"
    for env in attempts:
        remaining = deadline - time.monotonic()
        if remaining <= 10:
            break
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                env=env,
                capture_output=True,
                text=True,
                timeout=remaining,
            )
        except subprocess.TimeoutExpired:
            last_err = f"bench child exceeded {remaining:.0f}s remaining budget"
            continue
        sys.stderr.write(out.stderr[-4000:])
        line = _good_json_line(out.stdout)
        if line is not None:
            print(line)
            return 0
        last_err = (out.stderr or out.stdout or "no output")[-500:]
    _emit("bench_failed", 0.0, "none", 0.0, error=last_err)
    return 0


if __name__ == "__main__":
    if os.environ.get("PADDLE_TPU_BENCH_CHILD") == "1":
        try:
            rc = main()
        except Exception as e:  # never leave the driver without a JSON line
            _emit("bench_failed", 0.0, "none", 0.0, error=f"{type(e).__name__}: {e}")
            rc = 0
        sys.exit(rc)
    sys.exit(_supervise())
