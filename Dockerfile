# Container image (role of the reference's paddle/scripts/docker/Dockerfile:
# a reproducible train/serve environment with the CLI on PATH).
#
#   docker build -t paddle-tpu .
#   docker run --rm paddle-tpu paddle version
#
# On a TPU VM, install the TPU-enabled jax wheel instead of the CPU one:
#   docker build --build-arg JAX_EXTRA=tpu -t paddle-tpu .
FROM python:3.11-slim

# g++ lets the wheel prebuild the native datapath library; the runtime
# degrades gracefully without it, so slim deployments may drop this.
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

ARG JAX_EXTRA=""
WORKDIR /src
COPY . .
RUN pip install --no-cache-dir ${JAX_EXTRA:+"jax[${JAX_EXTRA}]"} . \
    && rm -rf /src

WORKDIR /workspace
ENTRYPOINT ["paddle"]
CMD ["version"]
