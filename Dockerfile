# Container image (role of the reference's paddle/scripts/docker/Dockerfile:
# a reproducible train/serve environment with the CLI on PATH).
#
#   docker build -t paddle-tpu .
#   docker run --rm paddle-tpu version
#
# On a TPU VM, install the TPU-enabled jax wheel instead of the CPU one:
#   docker build --build-arg JAX_EXTRA=tpu -t paddle-tpu .
#
# Multi-stage: the wheel is built (with the native datapath prebuild) in
# a throwaway stage, so the runtime image's layers never carry the
# source tree — a COPY'd-then-rm'd tree would still ship in the copy
# layer. .dockerignore keeps .git and trace dirs out of the context.
FROM python:3.11-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY . .
RUN pip install --no-cache-dir build wheel setuptools \
    && python -m build --wheel --no-isolation -o /dist

FROM python:3.11-slim
ARG JAX_EXTRA=""
RUN --mount=type=bind,from=build,source=/dist,target=/dist \
    pip install --no-cache-dir ${JAX_EXTRA:+"jax[${JAX_EXTRA}]"} /dist/*.whl

WORKDIR /workspace
ENTRYPOINT ["paddle"]
CMD ["version"]
