"""Synthetic CoNLL-style SRL corpus.

The reference trains on CoNLL-2005 (ref: demo/semantic_role_labeling/
data/get_data.sh); here sentences are synthesized with a planted tagging
rule — tokens inside a window around the marked predicate get B-ARG/I-ARG
style labels, everything else O — so the tagger has deterministic signal.
"""

import random

WORDS = ["<unk>"] + [f"w{i}" for i in range(199)]
LABELS = ["O", "B-ARG0", "I-ARG0", "B-V", "B-ARG1", "I-ARG1"]


def synth_sentences(seed, n=500):
    """Yield (words, verb_pos) tagged sentences; labels derive from the
    predicate position so the mark feature is informative."""
    rng = random.Random(seed)
    for _ in range(n):
        length = rng.randint(5, 25)
        words = [rng.randrange(1, len(WORDS)) for _ in range(length)]
        verb = rng.randrange(length)
        labels = []
        for i in range(length):
            if i == verb:
                labels.append(LABELS.index("B-V"))
            elif i == verb - 2:
                labels.append(LABELS.index("B-ARG0"))
            elif i == verb - 1:
                labels.append(LABELS.index("I-ARG0"))
            elif i == verb + 1:
                labels.append(LABELS.index("B-ARG1"))
            elif i == verb + 2:
                labels.append(LABELS.index("I-ARG1"))
            else:
                labels.append(LABELS.index("O"))
        yield words, verb, labels
