#!/bin/bash
# Train the deep bi-LSTM SRL tagger (ref: demo/semantic_role_labeling/train.sh).
set -e
cd "$(dirname "$0")"
echo train-seed-1 > train.list
echo test-seed-1 > test.list
paddle train \
  --config=db_lstm.py \
  --save_dir=./output \
  --num_passes=10 \
  --log_period=5
