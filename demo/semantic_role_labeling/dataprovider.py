"""SRL data provider (ref: demo/semantic_role_labeling/dataprovider.py).

Seven aligned integer sequences per sample: word ids, the predicate
broadcast to sentence length, three context-window features, the 0/1
predicate mark, and the target labels.
"""

from paddle.trainer.PyDataProvider2 import *

import common


def hook(settings, **kwargs):
    settings.input_types = [
        integer_value_sequence(len(common.WORDS)),
        integer_value_sequence(len(common.WORDS)),
        integer_value_sequence(len(common.WORDS)),
        integer_value_sequence(len(common.WORDS)),
        integer_value_sequence(len(common.WORDS)),
        integer_value_sequence(2),
        integer_value_sequence(len(common.LABELS)),
    ]


@provider(init_hook=hook)
def process(settings, file_name):
    for words, verb, labels in common.synth_sentences(file_name):
        n = len(words)
        verb_id = words[verb]
        ctx_n1 = words[verb - 1] if verb > 0 else 0
        ctx_p1 = words[verb + 1] if verb < n - 1 else 0
        yield (
            words,
            [verb_id] * n,
            [ctx_n1] * n,
            [words[verb]] * n,
            [ctx_p1] * n,
            [1 if i == verb else 0 for i in range(n)],
            labels,
        )
